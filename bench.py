#!/usr/bin/env python
"""Benchmark: AlexNet training throughput (images/sec/chip) on real hardware.

Prints ONE JSON line:
  {"metric": "alexnet_images_per_sec_per_chip", "value": N,
   "unit": "images/sec", "vs_baseline": N}

Baseline: the reference repo publishes no numbers (BASELINE.md).  We use
500 images/sec as the stand-in for cxxnet-CUDA AlexNet on a 2015-era
high-end GPU (Titan X class, cuDNN-era full fwd+bwd+update; see BASELINE.md
ledger) until a measured reference figure exists.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_IMAGES_PER_SEC = 500.0


def main() -> int:
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.models import alexnet_conf
    from cxxnet_tpu.utils.config import parse_config_string

    batch_size = 256
    conf = alexnet_conf() + f"""
batch_size = {batch_size}
eta = 0.01
momentum = 0.9
wmat:wd = 0.0005
bias:wd = 0.0
metric = error
eval_train = 0
random_type = xavier
compute_type = bfloat16
"""
    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()

    # raw uint8 pixels pre-staged on device: measures the full training
    # step (device-side cast/normalize + fwd + bwd + optimizer) per chip.
    # The dev-harness host link (a ~26MB/s tunnel to the remote chip) is
    # excluded — in production the input pipeline double-buffers H2D behind
    # compute (utils/thread_buffer + update_on_device).
    import jax
    rng = np.random.RandomState(0)
    dev_batches = []
    for i in range(4):
        b = DataBatch(
            rng.randint(0, 256, (batch_size, 3, 227, 227), dtype=np.uint8),
            rng.randint(0, 1000, (batch_size, 1)).astype(np.float32))
        dev_batches.append((trainer._shard_batch(b.data),
                            trainer._shard_batch(b.label, cast=False)))

    # warmup: compile + 3 steps
    for i in range(3):
        trainer.update_on_device(*dev_batches[i % 4])
    jax.device_get(trainer.params['16']['bias'])

    steps = 30
    t0 = time.perf_counter()
    for i in range(steps):
        trainer.update_on_device(*dev_batches[i % 4])
    # force full sync: read back a small param slice
    jax.device_get(trainer.params['16']['bias'])
    dt = time.perf_counter() - t0

    ips = steps * batch_size / dt
    print(json.dumps({
        'metric': 'alexnet_images_per_sec_per_chip',
        'value': round(ips, 1),
        'unit': 'images/sec',
        'vs_baseline': round(ips / BASELINE_IMAGES_PER_SEC, 3),
    }))
    return 0


if __name__ == '__main__':
    sys.exit(main())
