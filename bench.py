#!/usr/bin/env python
"""Benchmark: training throughput (images/sec/chip) on real hardware.

Default (what the driver runs) — AlexNet batch 256, prints ONE JSON line:
  {"metric": "alexnet_images_per_sec_per_chip", "value": N,
   "unit": "images/sec", "vs_baseline": N, "mfu": F, "tflops": T}

Extra modes for the BASELINE.md ledger (same JSON shape):
  python bench.py inception_bn     # Inception-BN batch 128 throughput
  python bench.py googlenet        # GoogLeNet v1 batch 128 throughput
  python bench.py vgg16            # VGG-16 batch 64 throughput
  python bench.py e2e_alexnet      # AlexNet through the FULL data path
                                   #   (imgbin+decode+augment+H2D included)
  python bench.py mnist_tta        # MNIST conv time-to-2%-test-error (sec)
  python bench.py eval_alexnet     # AlexNet EVAL (forward-only) img/s —
                                   #   fc8 Pallas gate A/B in one receipt
  python bench.py transformer      # TransformerLM tokens/sec (GPT-2-small
                                   #   class; beyond-reference family)
  python bench.py decode           # LM inference tokens/sec (KV-cached
                                   #   autoregressive generate)
  python bench.py io               # host input pipeline only (no chip):
                                   #   imgbinx chain + nworker pool sweep
                                   #   (alias: bench_io; BENCH_IO_r01.json)
  python bench.py scan             # SUPERVISED steps/sec A/B: K=4 scanned
                                   #   dispatch vs per-step with the
                                   #   supervisor on (BENCH_SCAN_r01.json)
  python bench.py online           # train-while-serve: steps/sec under
                                   #   live traffic + freshness p50/p99 +
                                   #   swap count (BENCH_ONLINE_r01.json)

``CXXNET_BENCH_CONF_EXTRA`` appends config lines (';'-separated) to every
model bench conf — the execution-plan A/B hook (e.g.
``fuse_blockdiag = auto``, ``conv_lowering = s2d``).

Robustness: the axon tunnel that fronts the TPU chip can wedge or report
UNAVAILABLE for hours.  The backend probe runs in a short-lived
subprocess with a SHORT default budget ($CXXNET_BENCH_BACKEND_WAIT sec,
default 60); on failure the requested mode reruns in a child pinned to
JAX_PLATFORMS=cpu and its receipt is re-emitted tagged
``"platform": "cpu-fallback"`` — the ledger always records a number,
and a CPU number can never pass as per-chip throughput.  On any other
failure the output is still ONE structured JSON line with an "error"
field — never a bare traceback.

MFU: flops per optimizer step come from the compiled executable's own
cost analysis (trainer.train_step_flops); peak chip flops from the device
kind (override with $CXXNET_PEAK_TFLOPS).

Baseline: the reference repo publishes no numbers (BASELINE.md).  We use
500 images/sec as the stand-in for cxxnet-CUDA AlexNet on a 2015-era
high-end GPU (Titan X class, cuDNN-era full fwd+bwd+update; see BASELINE.md
ledger) until a measured reference figure exists.
"""

from __future__ import annotations

import gzip
import json
import os
import struct
import subprocess
import sys
from typing import Optional

# Persistent XLA compilation cache: the AlexNet train-step scan takes
# many minutes to compile over the dev-harness tunnel, and every bench
# mode / A-B experiment repays it from scratch without this.  Must be in
# the environment before jax initializes its backend.
os.environ.setdefault(
    'JAX_COMPILATION_CACHE_DIR',
    os.path.join(os.path.dirname(os.path.abspath(__file__)), '.jax_cache'))
os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS', '2')
import time

import numpy as np

BASELINE_IMAGES_PER_SEC = 500.0          # AlexNet stand-in (see docstring)
BASELINE_INCEPTION_IMAGES_PER_SEC = 130.0  # Inception-BN stand-in, same era
BASELINE_GOOGLENET_IMAGES_PER_SEC = 150.0  # GoogLeNet v1 stand-in, same era
BASELINE_VGG16_IMAGES_PER_SEC = 50.0       # VGG-16 stand-in, same era
BASELINE_MNIST_TTA_SEC = 30.0            # reference MNIST.conf CPU run
BASELINE_TRANSFORMER_TOKENS_PER_SEC = 25000.0  # stand-in: GPT-2-small-class
# fp16 training on a 2019 V100 (no reference number exists — the
# reference framework has no attention; generous like the other stand-ins)

# bf16 peak TFLOP/s by TPU generation — THE table lives in
# cxxnet_tpu/obs/programs.py (the MFU gauge on the train eval line
# divides by the same numbers; _peak_flops below delegates to it)


def _emit(obj: dict) -> None:
    print(json.dumps(obj))


class BackendUnavailable(RuntimeError):
    pass


def _ensure_backend() -> None:
    """Probe the accelerator backend in a fresh subprocess (a wedged
    probe hangs forever, so it gets a hard timeout).  The default budget
    is SHORT (60s, one probe): the BENCH ledger showed five consecutive
    all-error rounds from patient 900s waits on a down tunnel — on
    failure the caller falls back to a tagged CPU run so the ledger
    always records a number.  Set ``CXXNET_BENCH_BACKEND_WAIT`` higher
    to restore the patient exponential-backoff wait."""
    plats = [p.strip() for p in
             os.environ.get('JAX_PLATFORMS', '').split(',') if p.strip()]
    if plats and all(p == 'cpu' for p in plats):
        return                           # explicit CPU-only run: no wait
    budget = float(os.environ.get('CXXNET_BENCH_BACKEND_WAIT', '60'))
    probe_timeout = max(20.0, min(180.0, budget))
    deadline = time.time() + budget
    delay, last_err = 10.0, ''
    while True:
        try:
            r = subprocess.run(
                [sys.executable, '-c',
                 'import jax; d = jax.devices(); print(d[0].platform)'],
                capture_output=True, text=True, timeout=probe_timeout)
            if r.returncode == 0:
                plat = (r.stdout or '').strip().splitlines()[-1:]
                if plat and plat[0] != 'cpu':
                    return
                # jax silently fell back to CPU: the accelerator is NOT
                # up; a CPU number must never pass as per-chip throughput
                last_err = 'jax fell back to CPU (accelerator plugin down)'
            else:
                tail = (r.stderr or '').strip().splitlines()
                last_err = tail[-1] if tail else f'probe rc={r.returncode}'
        except subprocess.TimeoutExpired:
            last_err = (f'backend probe hung >{probe_timeout:.0f}s '
                        '(tunnel wedge)')
        if time.time() + delay > deadline:
            raise BackendUnavailable(
                f'TPU backend unavailable after {budget:.0f}s: {last_err}')
        time.sleep(delay)
        delay = min(delay * 1.7, 120.0)


def _peak_flops() -> float:
    """Peak bf16 FLOP/s of one chip, for the MFU denominator — ONE
    table (``obs/programs.py``) shared with the train eval line's MFU
    gauge, ``CXXNET_PEAK_TFLOPS`` override included."""
    from cxxnet_tpu.obs.programs import peak_flops
    return peak_flops()


def _program_summary() -> Optional[dict]:
    """The ledger's compile summary for the receipt (programs /
    compiles / compile-ms / recompiles) — None when nothing compiled
    in-process (subprocess-driven modes)."""
    from cxxnet_tpu.obs.programs import get_ledger
    led = get_ledger()
    led.entries()                 # force the lazy AOT analysis so the
                                  # receipt's compile_ms_total is real
    s = led.summary()
    return s if s['compiles_total'] else None


def _bench_steps(default: int) -> int:
    """K for the K-vs-1 quotient; floor 2 (K=1 has no quotient)."""
    return max(2, int(os.environ.get('CXXNET_BENCH_STEPS', str(default))))


def _quotient_per_step(run_1, run_k, steps: int):
    """The ledger timing method, in ONE place: warm both compiled loops,
    then 4 reps of each endpoint; per-step seconds is the K-vs-1
    difference quotient of the min wall times.  min over reps because the
    link cost is a constant floor plus positive jitter spikes, so min
    rejects the spikes where a median-of-noisy-quotients cannot.
    Returns (per_step_seconds, t1s)."""
    run_1()                              # compile + warm
    run_k()
    t1s, tks = [], []
    for _ in range(4):
        t0 = time.perf_counter()
        run_1()
        t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_k()
        tks.append(time.perf_counter() - t0)
    return (min(tks) - min(t1s)) / (steps - 1), t1s


def _emit_throughput(metric: str, work_per_step: float, unit: str,
                     baseline: float, step_flops: float, per_step: float,
                     t1s) -> None:
    """The shared ledger JSON payload (value/tflops/mfu/step_ms/
    dispatch_ms/timing keys) — one schema for every model family.

    The A/B experiment knobs ride in the receipt itself (``batch`` from
    ``CXXNET_BENCH_BATCH``, ``conf_extra`` from
    ``CXXNET_BENCH_CONF_EXTRA``; both None on a baseline run), so a
    ledger entry is self-describing — an override run can never be
    mistaken for the default configuration it is measured against.
    ``save_stall_ms_per_step`` is 0.0 here by construction (these loops
    never touch a checkpoint); ``bench_ckpt.py`` measures the nonzero
    sync-vs-async story on the same schema key."""
    import statistics

    rate = work_per_step / per_step
    achieved = step_flops / per_step
    peak = _peak_flops()
    measured = step_flops > 0            # 0 = backend has no cost model
    env_batch = os.environ.get('CXXNET_BENCH_BATCH')
    conf_extra = os.environ.get('CXXNET_BENCH_CONF_EXTRA', '').strip()
    _emit({
        'metric': metric,
        'value': round(rate, 1),
        'unit': unit,
        'vs_baseline': round(rate / baseline, 3),
        'tflops': round(achieved / 1e12, 2) if measured else None,
        'mfu': round(achieved / peak, 4) if measured and peak else None,
        # compiler truth (obs/programs.py): the HLO flops the mfu/tflops
        # figures divide, plus the run's compile ledger — a receipt now
        # says what was compiled, how long compiles took, and whether
        # the recompile sentinel fired during the measurement
        'flops_per_step': round(step_flops) if measured else None,
        'programs': _program_summary(),
        'step_ms': round(per_step * 1e3, 3),
        # wall time of a 1-step dispatch minus the step itself = the pure
        # link/dispatch overhead one un-pipelined update() pays per call
        'dispatch_ms': round(statistics.median(t1s) * 1e3 - per_step * 1e3,
                             1),
        'batch': int(env_batch) if env_batch else None,
        'conf_extra': conf_extra or None,
        'save_stall_ms_per_step': 0.0,
        'timing': 'scan-in-jit K-vs-1 quotient',
    })


def _throughput(conf: str, batch_size: int, shape, metric: str,
                baseline: float) -> int:
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string

    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()

    # raw uint8 pixels pre-staged on device: measures the full training
    # step (device-side cast/normalize + fwd + bwd + optimizer) per chip.
    # The dev-harness host link (a ~26MB/s tunnel to the remote chip) is
    # excluded — in production the input pipeline double-buffers H2D behind
    # compute (utils/thread_buffer + trainer.update's async staging).
    #
    # Timing method: per-step dispatch does NOT pipeline over the remote
    # tunnel (every call costs the ~7 ms link RTT, so per-dispatch loops
    # measure the link, not the chip — BENCH_r02 and earlier carried that
    # contamination).  Instead the whole K-step loop runs on device in ONE
    # dispatch (trainer.compile_multi_step: lax.scan over the params
    # carry), and the per-step time is the K-vs-1 difference quotient,
    # which cancels the constant dispatch/link cost exactly.
    rng = np.random.RandomState(0)
    nstack = 4
    dstack = trainer.shard_batch_stack(
        rng.randint(0, 256, (nstack, batch_size) + shape, dtype=np.uint8))
    lstack = trainer.shard_batch_stack(
        rng.randint(0, 1000, (nstack, batch_size, 1)).astype(np.float32),
        cast=False)

    steps = _bench_steps(30)
    multi_1 = trainer.compile_multi_step(1)
    multi_k = trainer.compile_multi_step(steps)

    def run(fn, n) -> float:
        # fetching the returned device scalar is the only reliable
        # completion barrier over the tunnel (block_until_ready acks early)
        return float(np.asarray(
            trainer.update_n_on_device(fn, dstack, lstack, n)))

    per_step, t1s = _quotient_per_step(
        lambda: run(multi_1, 1), lambda: run(multi_k, steps), steps)
    # AFTER the warm runs: the flops read the ledger entries the loops
    # above just compiled — no throwaway probe program
    step_flops = trainer.train_step_flops(dstack[0], lstack[0])
    _emit_throughput(metric, batch_size, 'images/sec', baseline,
                     step_flops, per_step, t1s)
    return 0


def _bench_batch(default: int) -> int:
    """``CXXNET_BENCH_BATCH`` overrides a bench's default batch size
    (batch-scaling experiments, e.g. GoogLeNet 128 vs 256)."""
    return int(os.environ.get('CXXNET_BENCH_BATCH', default))


def _extra_conf() -> str:
    """``CXXNET_BENCH_CONF_EXTRA`` appends config lines (';'-separated)
    to every model bench conf — the A/B hook for execution-plan knobs
    (e.g. ``fuse_blockdiag = auto`` for the GoogLeNet tower-fusion
    receipt) without a bench.py edit per experiment."""
    extra = os.environ.get('CXXNET_BENCH_CONF_EXTRA', '').strip()
    return (extra.replace(';', '\n') + '\n') if extra else ''


def bench_alexnet() -> int:
    from cxxnet_tpu.models import alexnet_conf
    batch_size = _bench_batch(256)
    conf = alexnet_conf() + f"""
batch_size = {batch_size}
eta = 0.01
momentum = 0.9
wmat:wd = 0.0005
bias:wd = 0.0
metric = error
eval_train = 0
random_type = xavier
compute_type = bfloat16
""" + _extra_conf()
    return _throughput(conf, batch_size, (3, 227, 227),
                       'alexnet_images_per_sec_per_chip',
                       BASELINE_IMAGES_PER_SEC)


def bench_eval_alexnet() -> int:
    """Net-level EVAL (forward-only) throughput on AlexNet, A/B over the
    fc8-class Pallas forward gate in ONE receipt.

    The micro receipt (micro_matmul.json) shows the Pallas forward 4.28x
    over XLA at fc8's non-lane-aligned 256x4096x1000 — this measures
    whether that survives at net level (fc8 is a sub-ms slice of the
    step), which decides if the ``fullc_use_pallas`` auto gate stays.
    ``value`` is the gated (auto) img/s; ``gate_off_images_per_sec`` and
    ``gate_speedup`` carry the A/B."""
    from cxxnet_tpu.models import alexnet_conf
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string

    batch_size = _bench_batch(256)
    conf = alexnet_conf() + f"""
batch_size = {batch_size}
metric = error
eval_train = 0
random_type = xavier
compute_type = bfloat16
""" + _extra_conf()
    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()
    rng = np.random.RandomState(0)
    dstack = trainer.shard_batch_stack(
        rng.randint(0, 256, (4, batch_size, 3, 227, 227), dtype=np.uint8))
    steps = _bench_steps(30)

    # the off leg uses the fullc-only kill switch: CXXNET_PALLAS=0 would
    # also disable the LRN auto winners and credit their delta to this
    # gate
    prev = os.environ.get('CXXNET_FULLC_PALLAS')
    rates = {}
    try:
        for gate, env in (('auto', None), ('off', '0')):
            if env is None:
                os.environ.pop('CXXNET_FULLC_PALLAS', None)
            else:
                os.environ['CXXNET_FULLC_PALLAS'] = env
            # fresh jit objects per gate setting: the env is read at trace
            # time, so reusing a compiled fn would ignore the toggle
            fwd_1 = trainer.compile_multi_forward(1)
            fwd_k = trainer.compile_multi_forward(steps)

            def run(fn):
                return float(np.asarray(fn(trainer.params, dstack)))

            per_step, t1s = _quotient_per_step(
                lambda: run(fwd_1), lambda: run(fwd_k), steps)
            rates[gate] = batch_size / per_step
    finally:
        if prev is None:
            os.environ.pop('CXXNET_FULLC_PALLAS', None)
        else:
            os.environ['CXXNET_FULLC_PALLAS'] = prev
    _emit({
        'metric': 'alexnet_eval_images_per_sec_per_chip',
        'value': round(rates['auto'], 1),
        'unit': 'images/sec',
        'vs_baseline': None,
        'gate_off_images_per_sec': round(rates['off'], 1),
        'gate_speedup': round(rates['auto'] / rates['off'], 4),
        'timing': 'scan-in-jit K-vs-1 quotient, fwd-only',
    })
    return 0


def bench_inception_bn() -> int:
    from cxxnet_tpu.models import inception_bn_conf
    batch_size = _bench_batch(128)
    conf = inception_bn_conf() + f"""
batch_size = {batch_size}
eta = 0.01
momentum = 0.9
metric = error
eval_train = 0
random_type = xavier
compute_type = bfloat16
""" + _extra_conf()
    return _throughput(conf, batch_size, (3, 224, 224),
                       'inception_bn_images_per_sec_per_chip',
                       BASELINE_INCEPTION_IMAGES_PER_SEC)


def bench_googlenet() -> int:
    from cxxnet_tpu.models import googlenet_conf
    batch_size = _bench_batch(128)
    conf = googlenet_conf() + f"""
batch_size = {batch_size}
eta = 0.01
momentum = 0.9
metric = error
eval_train = 0
random_type = xavier
compute_type = bfloat16
""" + _extra_conf()
    return _throughput(conf, batch_size, (3, 224, 224),
                       'googlenet_images_per_sec_per_chip',
                       BASELINE_GOOGLENET_IMAGES_PER_SEC)


def bench_vgg16() -> int:
    from cxxnet_tpu.models import vgg16_conf
    batch_size = _bench_batch(64)
    conf = vgg16_conf() + f"""
batch_size = {batch_size}
eta = 0.01
momentum = 0.9
metric = error
eval_train = 0
random_type = xavier
compute_type = bfloat16
""" + _extra_conf()
    return _throughput(conf, batch_size, (3, 224, 224),
                       'vgg16_images_per_sec_per_chip',
                       BASELINE_VGG16_IMAGES_PER_SEC)


def _transformer_throughput(cfg, batch: int, metric: str,
                            baseline: float) -> int:
    """Tokens/sec of a TransformerLM train step on the current backend,
    timed like _throughput: the whole K-step loop runs on device in one
    dispatch (lax.scan over the params carry, cycling a stacked token
    stack) and the per-step time is the K-vs-1 difference quotient."""
    import jax.numpy as jnp

    from cxxnet_tpu.models import transformer as T

    rng = np.random.RandomState(0)
    params = T.init_params(rng, cfg)
    nstack = 4
    toks = jnp.asarray(rng.randint(
        0, cfg.vocab_size, (nstack, batch, cfg.seq_len)), jnp.int32)
    labs = jnp.asarray(rng.randint(
        0, cfg.vocab_size, (nstack, batch, cfg.seq_len)), jnp.int32)

    steps = _bench_steps(20)
    multi_1 = T.make_multi_train_step(cfg, 1, lr=0.01)
    multi_k = T.make_multi_train_step(cfg, steps, lr=0.01)

    try:
        cost = multi_1.lower(params, toks, labs).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        step_flops = float(cost.get('flops', 0.0)) if cost else 0.0
    except Exception:
        step_flops = 0.0

    def run(fn):
        nonlocal params
        params, loss = fn(params, toks, labs)
        # a device_get is the only reliable completion barrier over the
        # remote tunnel (block_until_ready acks early there)
        return float(np.asarray(loss))

    per_step, t1s = _quotient_per_step(
        lambda: run(multi_1), lambda: run(multi_k), steps)
    _emit_throughput(metric, batch * cfg.seq_len, 'tokens/sec', baseline,
                     step_flops, per_step, t1s)
    return 0


def bench_transformer() -> int:
    """TransformerLM tokens/sec on one chip — the beyond-reference
    flagship family (the reference has no attention anywhere, SURVEY.md
    §5 'long-context: N/A for parity').  GPT-2-small-class decoder:
    8 blocks, d_model 1024, 16 heads, d_ff 4096, causal, bf16.  Times
    the single-device path (``reference_loss`` + scanned SGD) — the
    exact math the 4-axis shard_map step is oracle-tested against
    (tests/test_transformer_parallel.py), but NOT the shard_map program
    itself, which needs a multi-chip mesh to mean anything."""
    import jax.numpy as jnp

    from cxxnet_tpu.models import transformer as T

    batch = _bench_batch(16)
    seq = int(os.environ.get('CXXNET_BENCH_SEQ', '1024'))
    cfg = T.TransformerConfig(
        vocab_size=32768, d_model=1024, num_heads=16, d_ff=4096,
        num_stages=8, seq_len=seq, attn='local', causal=True,
        num_microbatches=1, dtype=jnp.bfloat16)
    return _transformer_throughput(
        cfg, batch, 'transformer_tokens_per_sec_per_chip',
        BASELINE_TRANSFORMER_TOKENS_PER_SEC)


def bench_decode() -> int:
    """Autoregressive decode throughput (tokens/sec/chip) on the
    GPT-2-small-class LM — the inference-side counterpart of
    ``transformer`` (training tok/s).  KV-cached ``transformer.generate``
    runs prefill + the whole decode scan in ONE dispatch; per-token time
    is the K-vs-1 difference quotient over the number of NEW tokens, so
    the dispatch/link cost and the shared prefill cancel."""
    import jax
    import jax.numpy as jnp

    from cxxnet_tpu.models import transformer as T

    batch = _bench_batch(8)
    seq0 = int(os.environ.get('CXXNET_BENCH_SEQ', '128'))
    new_k = _bench_steps(256)
    # exact decode shapes: the K-vs-1 quotient needs each request to cost
    # exactly its own step count — opt out of the generate() size-class
    # bucketing (models/transformer._size_class) so no run is ever
    # rounded up to a larger compiled horizon
    os.environ['CXXNET_GEN_BUCKETS'] = '0'
    cfg = T.TransformerConfig(
        vocab_size=32768, d_model=1024, num_heads=16, d_ff=4096,
        num_stages=8, seq_len=seq0 + new_k, attn='local', causal=True,
        num_microbatches=1, dtype=jnp.bfloat16)
    params = T.init_params(np.random.RandomState(0), cfg)
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, cfg.vocab_size, (batch, seq0)).astype(np.int32)

    def run(n):
        return np.asarray(T.generate(params, prompt, n, cfg))

    per_tok, t1s = _quotient_per_step(lambda: run(1), lambda: run(new_k),
                                      new_k)
    import statistics
    _emit({
        'metric': 'decode_tokens_per_sec_per_chip',
        'value': round(batch / per_tok, 1),
        'unit': 'tokens/sec',
        'vs_baseline': None,
        'batch': batch,
        'prompt_len': seq0,
        'new_tokens': new_k,
        'per_token_ms': round(per_tok * 1e3, 3),
        'dispatch_ms': round(statistics.median(t1s) * 1e3
                             - per_tok * 1e3, 1),
        'timing': 'KV-cached scan, K-vs-1 new-token quotient',
    })
    return 0


def _pack_synthetic_imgbin(tmp: str, n_images: int):
    """Pack a synthetic JPEG imgbin dataset with the in-tree packer;
    returns (list_path, bin_path)."""
    from PIL import Image
    rng = np.random.RandomState(0)
    lst = os.path.join(tmp, 'train.lst')
    with open(lst, 'w') as f:
        for i in range(n_images):
            # low-frequency content (16x16 noise upsampled): natural-
            # photo-like JPEG size/decode cost, unlike raw noise which
            # barely compresses and overstates decode time
            small = rng.randint(0, 255, (16, 16, 3), dtype=np.uint8)
            img = Image.fromarray(small).resize((256, 256),
                                                Image.BILINEAR)
            img.save(os.path.join(tmp, f'{i}.jpg'), quality=85)
            f.write(f'{i}\t{i % 1000}\t{i}.jpg\n')
    binpath = os.path.join(tmp, 'train.bin')
    subprocess.check_call(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      'tools', 'im2bin.py'), lst, tmp, binpath],
        stdout=subprocess.DEVNULL)
    return lst, binpath


def _imgbinx_chain(lst: str, binpath: str, batch_size: int,
                   device_normalize: bool = False):
    """The production input chain: two-stage imgbinx reader -> augment
    (rand crop+mirror) -> batch -> background threadbuffer.
    ``device_normalize`` keeps the decoded uint8 on the wire (half the
    H2D bytes, no host-side cast) and defers (x-mean)*scale to the
    jitted step — the TPU-recommended configuration."""
    chain = [('iter', 'imgbinx'),
             ('image_list', lst),
             ('image_bin', binpath),
             ('shuffle', '1'), ('rand_crop', '1'), ('rand_mirror', '1'),
             ('input_shape', '3,227,227'),
             ('batch_size', str(batch_size)),
             ('round_batch', '1'), ('silent', '1')]
    if device_normalize:
        chain.append(('device_normalize', '1'))
    chain.append(('iter', 'threadbuffer'))
    return chain


def _imgbin_aug_chain(lst: str, binpath: str, batch_size: int,
                      nworker: int):
    """The nworker-sweep chain: imgbin + REAL augmentation (affine warp
    via rotation, random crop, mirror) behind a pooled threadbuffer —
    the per-instance work the ``nworker`` pool (utils/parallel_pool.py)
    exists to parallelize."""
    return [('iter', 'imgbin'),
            ('image_list', lst), ('image_bin', binpath),
            ('shuffle', '1'), ('rand_crop', '1'), ('rand_mirror', '1'),
            ('max_rotate_angle', '15'),
            ('input_shape', '3,224,224'),
            ('batch_size', str(batch_size)),
            ('round_batch', '1'), ('silent', '1'),
            ('iter', 'threadbuffer'),
            ('nworker', str(nworker))]


def bench_io() -> int:
    """HOST-side input-pipeline throughput: imgbin pages -> JPEG decode
    -> augment -> batch -> threadbuffer, no device involved (runs
    anywhere, chip or not).  This is the supply side of the e2e number:
    if bench_io < bench_alexnet img/s, the host pipeline is the e2e
    bottleneck (the reference's iter_thread_imbin_x exists for exactly
    that reason).  Counterpart of the reference's ``test_io=1`` harness
    (cxxnet_main.cpp test_io loop).

    Also sweeps ``nworker`` over an AUGMENTED imgbin stream (affine +
    crop + mirror — the decode+augment cost a real training conf pays)
    and reports batches/sec per worker count plus the n=4 pool
    occupancy: the receipt that justifies (or indicts) the parallel
    decode/augment pool on this host."""
    import tempfile

    from cxxnet_tpu.io.data import create_iterator

    batch_size = _bench_batch(256)
    n_images = int(os.environ.get('CXXNET_E2E_IMAGES', '1024'))
    sweep_images = int(os.environ.get('CXXNET_IO_SWEEP_IMAGES', '256'))
    sweep_batch = int(os.environ.get('CXXNET_IO_SWEEP_BATCH', '32'))

    def rate(it, rounds=2):
        it.init()
        for b in it:                 # warm: page cache, buffers, threads
            pass
        n_done, n_batch, t0 = 0, 0, time.perf_counter()
        for _round in range(rounds):
            for b in it:
                n_done += b.batch_size - b.num_batch_padd
                n_batch += 1
        dt = time.perf_counter() - t0
        return n_done, n_done / dt, n_batch / dt

    with tempfile.TemporaryDirectory() as tmp:
        lst, binpath = _pack_synthetic_imgbin(tmp, n_images)
        n_done, ips, _ = rate(
            create_iterator(_imgbinx_chain(lst, binpath, batch_size)))
        # B-side: uint8 wire (device_normalize) — the host skips the
        # f32 convert + normalize, quantifying that stage's share.  A
        # B-side failure must not discard the completed A-side number.
        try:
            _, ips_u8, _ = rate(
                create_iterator(_imgbinx_chain(lst, binpath, batch_size,
                                               device_normalize=True)))
        except Exception as e:              # noqa: BLE001
            ips_u8 = None
            print(f'uint8-wire side failed: {e!r}', file=sys.stderr)

        # nworker sweep on its own (smaller) augmented dataset: the
        # affine warp makes per-instance cost realistic, so the sweep
        # stays minutes-not-hours on the serial leg
        if sweep_images == n_images:
            slst, sbin = lst, binpath
        else:
            sdir = os.path.join(tmp, 'sweep')
            os.makedirs(sdir, exist_ok=True)
            slst, sbin = _pack_synthetic_imgbin(sdir, sweep_images)
        sweep, occupancy = {}, None
        for nw in (1, 2, 4, 8):
            it = create_iterator(_imgbin_aug_chain(slst, sbin,
                                                   sweep_batch, nw))
            _, sips, bps = rate(it)
            sweep[str(nw)] = {'images_per_sec': round(sips, 1),
                              'batches_per_sec': round(bps, 2)}
            stats = it.pipeline_stats()
            if nw == 4 and stats is not None:
                occupancy = round(stats.get('pool.occupancy'), 3)
    speedup = (sweep['4']['batches_per_sec']
               / max(sweep['1']['batches_per_sec'], 1e-9))
    _emit({
        'metric': 'host_io_images_per_sec',
        'value': round(ips, 1),
        'unit': 'images/sec',
        'vs_baseline': None,
        'images': n_done,
        'uint8_wire_images_per_sec':
            round(ips_u8, 1) if ips_u8 else None,
        'nworker_sweep': sweep,
        'sweep_batch': sweep_batch,
        'speedup_4v1': round(speedup, 2),
        'pool_occupancy_nworker4': occupancy,
        'note': 'imgbinx+decode+augment+threadbuffer, host only; '
                'uint8_wire = same chain under device_normalize=1; '
                'nworker_sweep = augmented (affine+crop+mirror) imgbin '
                'through the parallel decode/augment pool',
    })
    return 0


_SCAN_MLP = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 512
  init_sigma = 0.05
layer[+1:ac1] = relu
layer[+1:do1] = dropout
  threshold = 0.3
layer[+1:fc2] = fullc:fc2
  nhidden = 512
  init_sigma = 0.05
layer[+1:ac2] = relu
layer[+1:fc3] = fullc:fc3
  nhidden = 16
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,256
dev = cpu
eta = 0.05
momentum = 0.9
metric[label] = error
eval_train = 0
"""


def bench_scan() -> int:
    """SUPERVISED steps/sec, scanned K-dispatch vs per-step — the receipt
    that the ExecutionPlan refactor (doc/trainer.md) keeps the
    steps_per_dispatch win under production constraints: both legs run
    the REAL supervised loop (TrainSupervisor watchdog ThreadBuffer,
    anchor + final exact-resume checkpoints, divergence gate armed via
    nan_breaker), differing ONLY in the plan's K.  Final params of the
    two legs are bitwise-asserted in-bench, so the speedup can never be
    bought with a semantics drift.  On a remote-chip tunnel the per-step
    leg pays the link RTT every step and K recovers it; on CPU fallback
    the dispatch overhead is host-call-only, so speedup ~1x is expected
    and the receipt is a trend point, not a chip number."""
    import tempfile

    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.execution import ExecutionPlan
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.runtime.supervisor import (SupervisorConfig,
                                               TrainSupervisor)
    from cxxnet_tpu.utils.config import parse_config_string

    batch_size = _bench_batch(64)
    scan_k = int(os.environ.get('CXXNET_SCAN_K', '4'))
    n_batches = int(os.environ.get('CXXNET_SCAN_BATCHES', '96'))
    # whole windows for a clean A/B, floor of one window (a sub-K request
    # would otherwise round to zero batches and a 0/0 speedup)
    n_batches = max(scan_k, n_batches - n_batches % scan_k)
    conf = _SCAN_MLP + f'batch_size = {batch_size}\n' + _extra_conf()

    rng = np.random.RandomState(0)
    centers = rng.randn(16, 256).astype(np.float32) * 2
    batches = []
    for _ in range(n_batches):
        y = rng.randint(0, 16, batch_size)
        x = centers[y] + 0.3 * rng.randn(batch_size, 256).astype(np.float32)
        batches.append(DataBatch(x.reshape(batch_size, 1, 1, 256),
                                 y[:, None].astype(np.float32)))

    def leg(k, tmp):
        trainer = NetTrainer(parse_config_string(conf))
        trainer.init_model()
        plan = ExecutionPlan.resolve(requested_k=k, strict=True,
                                     silent=True)
        sup = TrainSupervisor(
            trainer, os.path.join(tmp, f'sup_k{k}'),
            SupervisorConfig(batch_deadline=120.0, nan_breaker=3,
                             save_every=0))
        stepper = lambda: plan.round_stepper(trainer, lookahead=0)  # noqa: E731
        factory = lambda s: iter(batches[s % n_batches:])           # noqa: E731
        sup.run(factory, before_step=None, make_stepper=stepper)  # warm
        # min over reps, like _quotient_per_step: scheduler spikes only
        # ever ADD time, so min is the honest steady-state epoch
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            n = sup.run(factory, make_stepper=stepper)
            times.append(time.perf_counter() - t0)
        return n / min(times), trainer

    with tempfile.TemporaryDirectory() as tmp:
        rate_1, t1 = leg(1, tmp)
        rate_k, tk = leg(scan_k, tmp)
    bitwise = all(
        np.array_equal(np.asarray(t1.params[lk][fk]),
                       np.asarray(tk.params[lk][fk]))
        for lk, fields in t1.params.items() for fk in fields)
    if not bitwise:
        raise AssertionError(
            'supervised scanned leg diverged from the per-step leg — '
            'the speedup number would be meaningless')
    import jax
    _emit({
        'metric': 'supervised_scan_steps_per_sec',
        'value': round(rate_k, 1),
        'unit': 'steps/sec',
        # steps/sec is platform-bound: say where it was measured even
        # when the cpu-fallback machinery didn't have to engage (the
        # probe short-circuits on an explicit JAX_PLATFORMS=cpu run)
        'platform': jax.devices()[0].platform,
        'vs_baseline': None,
        'per_step_steps_per_sec': round(rate_1, 1),
        'speedup': round(rate_k / rate_1, 3),
        'k': scan_k,
        'batch': batch_size,
        'steps': n_batches,
        'supervise': 1,
        'bitwise_equal': True,
        'timing': 'min wall over 3 supervised epochs, warm leg discarded',
    })
    return 0


def bench_obs() -> int:
    """Always-on telemetry tax (doc/observability.md): the graftscope
    flight recorder + span instrumentation runs on EVERY production
    path, so its cost must be provably negligible.  Two A/B legs with
    the recorder disabled vs enabled (the only difference — the hub
    object, StatSets, and trace-id counters exist either way):

    * supervised train steps/sec — the real TrainSupervisor loop with
      dispatch/save spans and io.produce events riding each batch,
    * decode tokens/sec — the DecodeService continuous-batching stack
      with per-request lifecycle spans and per-step decode spans.

    Each leg runs back-to-back off/on PAIRS and reports the median of
    per-pair overhead ratios: host noise between bursts spans ±5-10%,
    far above the recorder's true cost, and only the paired ratio
    cancels it.  The decode model is mid-sized (d_model 128) like the
    ``decode`` mode's, not a toy: the span cost is constant per step,
    so a micro model would overstate the relative tax ~10x against any
    production step time.  Acceptance: overhead < 2% on both.  The
    receipt also lands in BENCH_OBS_r01.json (cpu-fallback policy tags
    apply).

    A second pass measures graftwatch on top of an enabled recorder:
    sampler-off vs sampler-on (the ``obs.sample_every`` history thread
    at its production-default 0.25s cadence plus two live SLO specs
    evaluated per tick, one plain and one windowed-rate reduction).
    Same paired-ratio discipline, same legs; receipt BENCH_OBS_r02.json,
    acceptance: the history/SLO tax stays below the recorder acceptance
    bar (< 2% on both legs).  ``CXXNET_OBS_SAMPLE_EVERY=0.05`` stresses
    a 5x cadence — measured ~2% on the host decode leg (each 20 Hz tick
    costs ~1ms of GIL against the pure-host token loop; the bounded
    ``tail_view`` read keeps it flat no matter how large the serving
    distributions grow)."""
    import tempfile

    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.models import transformer as T
    from cxxnet_tpu.nnet.execution import ExecutionPlan
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.obs import get_hub
    from cxxnet_tpu.runtime.supervisor import (SupervisorConfig,
                                               TrainSupervisor)
    from cxxnet_tpu.serve.decode import DecodeService
    from cxxnet_tpu.utils.config import parse_config_string

    hub = get_hub()
    batch_size = _bench_batch(64)
    n_batches = int(os.environ.get('CXXNET_OBS_BATCHES', '192'))
    n_req = int(os.environ.get('CXXNET_OBS_REQUESTS', '32'))
    max_new = int(os.environ.get('CXXNET_OBS_MAX_NEW', '48'))
    reps = int(os.environ.get('CXXNET_OBS_REPS', '6'))
    conf = _SCAN_MLP + f'batch_size = {batch_size}\n' + _extra_conf()

    rng = np.random.RandomState(0)
    centers = rng.randn(16, 256).astype(np.float32) * 2
    batches = []
    for _ in range(n_batches):
        y = rng.randint(0, 16, batch_size)
        x = centers[y] + 0.3 * rng.randn(batch_size, 256).astype(np.float32)
        batches.append(DataBatch(x.reshape(batch_size, 1, 1, 256),
                                 y[:, None].astype(np.float32)))

    def make_train(tmp):
        trainer = NetTrainer(parse_config_string(conf))
        trainer.init_model()
        plan = ExecutionPlan.resolve(requested_k=1, silent=True)
        sup = TrainSupervisor(
            trainer, os.path.join(tmp, 'sup'),
            SupervisorConfig(batch_deadline=120.0, nan_breaker=3,
                             save_every=0))
        stepper = lambda: plan.round_stepper(trainer, lookahead=0)  # noqa: E731
        factory = lambda s: iter(batches[s % n_batches:])           # noqa: E731
        sup.run(factory, make_stepper=stepper)            # warm/compile

        def epoch():
            t0 = time.perf_counter()
            n = sup.run(factory, make_stepper=stepper)
            return n / (time.perf_counter() - t0)
        return epoch, sup

    lm_cfg = T.TransformerConfig(vocab_size=512, d_model=128, num_heads=8,
                                 d_ff=256, num_stages=2, seq_len=64,
                                 attn='local')
    lm_params = T.init_params(np.random.RandomState(0), lm_cfg)
    prompt_rng = np.random.RandomState(7)
    prompts = [prompt_rng.randint(
        0, lm_cfg.vocab_size,
        (1, int(prompt_rng.randint(1, 12)))).astype(np.int32)
        for _ in range(n_req)]

    def make_decode():
        svc = DecodeService(lm_params, lm_cfg, slots=4, pages=96,
                            page_size=8, max_prompt=16,
                            max_new_bound=max_new, deadline=240.0)
        svc.generate(prompts[0], max_new)                 # warm/compile

        def burst():
            t0 = time.perf_counter()
            reqs = [svc.submit_async(p, max_new) for p in prompts]
            toks = 0
            for r in reqs:
                svc.batcher.wait(r)
                toks += len(r.tokens)
            return toks / (time.perf_counter() - t0)
        return burst, svc

    import statistics
    samples = {'train': {False: [], True: []},
               'decode': {False: [], True: []}}
    pair_tax = {'train': [], 'decode': []}
    with tempfile.TemporaryDirectory() as tmp:
        train_epoch, sup = make_train(tmp)
        decode_burst, svc = make_decode()
        try:
            # per-leg back-to-back off/on pairs: only the paired ratio
            # cancels slow host drift, so nothing runs inside a pair.
            # Decode measures first and a full collection precedes each
            # leg: the recorder's only indirect cost is extra gc
            # triggers, and their price scales with how much garbage
            # the OTHER leg left behind — that cross-talk is bench
            # artifact, not recorder tax
            import gc
            for leg, run in (('decode', decode_burst),
                             ('train', train_epoch)):
                gc.collect()
                for i in range(reps):
                    # alternate which state runs first within the pair:
                    # the second slot of a pair is systematically a bit
                    # different (heap growth, cache state), and a fixed
                    # order would book that bias to one state
                    order = (False, True) if i % 2 == 0 else (True, False)
                    rate = {}
                    for state in order:
                        hub.enabled = state
                        # max rate of two runs per slot: scheduler
                        # spikes only ever ADD time, so the better of
                        # two is the honest steady-state sample
                        rate[state] = max(run(), run())
                    samples[leg][False].append(rate[False])
                    samples[leg][True].append(rate[True])
                    pair_tax[leg].append(1.0 - rate[True] / rate[False])
        finally:
            hub.enabled = True
            svc.close(30.0)
            sup.close()

    # --- graftwatch leg: sampler+SLO tax over the enabled recorder ---
    from cxxnet_tpu.obs.history import GaugeSampler, hub_source
    from cxxnet_tpu.obs.slo import SLOEngine, SLOSpec
    sample_every = float(os.environ.get('CXXNET_OBS_SAMPLE_EVERY',
                                        '0.25'))
    s_samples = {'train': {False: [], True: []},
                 'decode': {False: [], True: []}}
    s_pair_tax = {'train': [], 'decode': []}
    with tempfile.TemporaryDirectory() as tmp:
        train_epoch, sup = make_train(tmp)
        decode_burst, svc = make_decode()
        hub.enabled = True
        # real gauges for the sampler to chew on each tick
        hub.register_stats('decode', svc.engine.stats)
        try:
            import gc
            for leg, run in (('decode', decode_burst),
                             ('train', train_epoch)):
                gc.collect()
                for i in range(reps):
                    order = (False, True) if i % 2 == 0 else (True, False)
                    rate = {}
                    for state in order:
                        sampler = None
                        if state:
                            sampler = GaugeSampler(hub_source(hub),
                                                   period=sample_every)
                            eng = SLOEngine(sampler.history)
                            eng.add(SLOSpec.parse(
                                'load', 'decode.requests>=0@1'))
                            eng.add(SLOSpec.parse(
                                'ramp', 'decode.requests.rate>=0@1'))
                            sampler.add_listener(eng.on_tick)
                            sampler.start()
                        try:
                            rate[state] = max(run(), run())
                        finally:
                            if sampler is not None:
                                sampler.close(10.0)
                    s_samples[leg][False].append(rate[False])
                    s_samples[leg][True].append(rate[True])
                    s_pair_tax[leg].append(1.0 - rate[True] / rate[False])
        finally:
            hub.unregister_stats('decode')
            svc.close(30.0)
            sup.close()

    # --- graftprof leg: program-ledger + sentinel tax ----------------
    # off = the ledger's trace-time hook suppressed (set_raw_jit — the
    # dispatch is the plain jit C++ fast path either way), on = the
    # shipped wrap.  Both paths are warmed before pairing so neither
    # leg ever measures a compile.
    from cxxnet_tpu.obs.programs import set_raw_jit
    l_samples = {'train': {False: [], True: []},
                 'decode': {False: [], True: []}}
    l_pair_tax = {'train': [], 'decode': []}
    with tempfile.TemporaryDirectory() as tmp:
        train_epoch, sup = make_train(tmp)
        decode_burst, svc = make_decode()
        hub.enabled = True
        try:
            import gc
            for leg, run in (('decode', decode_burst),
                             ('train', train_epoch)):
                set_raw_jit(True)        # warm the plain-jit twin cache
                run()
                set_raw_jit(False)
                gc.collect()
                for i in range(reps):
                    order = (False, True) if i % 2 == 0 else (True, False)
                    rate = {}
                    for state in order:
                        # state True = ledger wrap ON (the shipped path).
                        # best-of-3 per slot (vs the other passes'
                        # best-of-2): the ledger's true per-dispatch
                        # cost is ~µs against a multi-ms step — an
                        # order of magnitude under the recorder/sampler
                        # taxes — so only the min-wall discipline of
                        # _quotient_per_step keeps scheduler spikes
                        # from swamping it
                        set_raw_jit(not state)
                        try:
                            rate[state] = max(run(), run(), run())
                        finally:
                            set_raw_jit(False)
                    l_samples[leg][False].append(rate[False])
                    l_samples[leg][True].append(rate[True])
                    l_pair_tax[leg].append(1.0 - rate[True] / rate[False])
        finally:
            set_raw_jit(False)
            svc.close(30.0)
            sup.close()

    # direct per-dispatch wrapper cost: the A/B above runs minute-long
    # loops whose run-to-run spread on a shared host is ±5-15% — it can
    # corroborate "no systemic tax rides along" but cannot RESOLVE a
    # µs-scale dispatch delta.  So measure the delta directly: a tiny
    # program behind a conservatively deep pytree (the signature walk
    # is the wrapper's only per-call work and scales with leaf count),
    # wrapped vs raw, median of trials, then convert through each
    # leg's measured step/token wall into the implied steady-state tax.
    # A throwaway ledger keeps the micro program out of /programs.
    import jax.numpy as jnp
    from cxxnet_tpu.obs.programs import (ProgramLedger, get_ledger,
                                         install_ledger)
    micro_led = ProgramLedger()
    prev_led = install_ledger(micro_led)
    try:
        mprog = micro_led.program('bench.micro')
    finally:
        install_ledger(prev_led)
    mtree = {f'l{i}': {'w': jnp.ones((64, 64)), 'b': jnp.ones((64,))}
             for i in range(50)}         # 100 leaves: deeper than any
                                         # real step's dispatch tree
    mwrap = mprog.jit(lambda tree, x: x + tree['l0']['b'][0])
    set_raw_jit(True)
    mwrap(mtree, 0.0).block_until_ready()
    set_raw_jit(False)
    mwrap(mtree, 0.0).block_until_ready()

    def _per_call_us(raw: bool, n: int = 3000) -> float:
        set_raw_jit(raw)
        try:
            t0 = time.perf_counter()
            r = None
            for _ in range(n):
                r = mwrap(mtree, 0.0)
            r.block_until_ready()
            return (time.perf_counter() - t0) / n * 1e6
        finally:
            set_raw_jit(False)
    deltas = sorted(_per_call_us(False) - _per_call_us(True)
                    for _ in range(7))
    wrap_delta_us = max(0.0, deltas[len(deltas) // 2])

    rates = {leg: {st: statistics.median(v) for st, v in legs.items()}
             for leg, legs in samples.items()}
    s_rates = {leg: {st: statistics.median(v) for st, v in legs.items()}
               for leg, legs in s_samples.items()}
    l_rates = {leg: {st: statistics.median(v) for st, v in legs.items()}
               for leg, legs in l_samples.items()}

    def tax(leg):
        return round(statistics.median(pair_tax[leg]), 4)

    def s_tax(leg):
        return round(statistics.median(s_pair_tax[leg]), 4)

    def l_tax(leg):
        return round(statistics.median(l_pair_tax[leg]), 4)

    import jax
    plat = jax.devices()[0].platform
    if plat == 'cpu' and os.environ.get('CXXNET_BENCH_FALLBACK') == '1':
        # the fallback wrapper only rewrites the LAST emitted payload;
        # stamping here keeps BOTH committed receipts self-describing
        plat = 'cpu-fallback'
    # implied steady-state tax per leg: measured per-dispatch delta
    # over each leg's measured per-step / per-token wall.  One dispatch
    # per train step and per decode token is CONSERVATIVE (a K-scanned
    # window dispatches once per K steps; one decode step emits up to
    # `slots` tokens), so the true tax is at or below these
    train_ms = 1e3 / max(l_rates['train'][True], 1e-9)
    tok_ms = 1e3 / max(l_rates['decode'][True], 1e-9)
    implied_train = wrap_delta_us / 1e3 / train_ms
    implied_decode = wrap_delta_us / 1e3 / tok_ms
    ledger_payload = {
        'metric': 'obs_ledger_overhead',
        'value': round(max(implied_train, implied_decode), 5),
        'unit': 'fraction',
        'platform': plat,
        'vs_baseline': None,
        'wrap_dispatch_delta_us': round(wrap_delta_us, 2),
        'train_implied_tax': round(implied_train, 5),
        'decode_implied_tax': round(implied_decode, 5),
        'programs': _program_summary(),
        'train_steps_per_sec_ledger_on': round(l_rates['train'][True], 1),
        'train_steps_per_sec_ledger_off': round(l_rates['train'][False],
                                                1),
        'train_overhead': l_tax('train'),
        'train_tax_pairs': [round(t, 4) for t in l_pair_tax['train']],
        'decode_tokens_per_sec_ledger_on': round(
            l_rates['decode'][True], 1),
        'decode_tokens_per_sec_ledger_off': round(
            l_rates['decode'][False], 1),
        'decode_overhead': l_tax('decode'),
        'decode_tax_pairs': [round(t, 4) for t in l_pair_tax['decode']],
        'acceptance': 'implied steady-state tax < 0.002 on both legs; '
                      'A/B pair medians within the host noise band the '
                      'enclosed pairs demonstrate',
        'receipt_file': 'BENCH_OBS_r03.json',
        'timing': 'headline value = measured per-dispatch wrapper '
                  'delta (tiny program behind a 100-leaf pytree — '
                  'deeper than any real step\'s dispatch tree — the '
                  'shipped wrap vs the hook-suppressed set_raw_jit '
                  'twin; dispatch is the plain jit C++ fast path '
                  'either way, so the delta is one Python frame + the '
                  'flag check; median of 7 trials of 3000 calls) '
                  'divided by each leg\'s measured per-step / '
                  'per-token wall, one dispatch per step/token '
                  'assumed (conservative: scanned windows and '
                  'multi-slot decode dispatch less often).  '
                  f'Corroboration: median of {reps} back-to-back '
                  'off/on pair ratios per leg, best-of-3 runs per slot '
                  '(min-wall), both paths warmed — the end-to-end A/B '
                  'cannot resolve a µs-scale delta through minute-long '
                  'loops on a shared host (the enclosed pairs span the '
                  'noise band) but holds the line against any '
                  'systemic tax.  Compiler truth is harvested at '
                  'trace time + lazy AOT analysis on read, so '
                  'steady-state tax is the wrapper frame alone',
    }
    _write_receipt_file(ledger_payload)
    _emit(ledger_payload)
    sampler_payload = {
        'metric': 'obs_sampler_overhead',
        'value': max(0.0, s_tax('train'), s_tax('decode')),
        'unit': 'fraction',
        'platform': plat,
        'vs_baseline': None,
        'sample_every_s': sample_every,
        'slo_specs': 2,
        'train_steps_per_sec_sampler_on': round(s_rates['train'][True],
                                                1),
        'train_steps_per_sec_sampler_off': round(s_rates['train'][False],
                                                 1),
        'train_overhead': s_tax('train'),
        'train_tax_pairs': [round(t, 4) for t in s_pair_tax['train']],
        'decode_tokens_per_sec_sampler_on': round(
            s_rates['decode'][True], 1),
        'decode_tokens_per_sec_sampler_off': round(
            s_rates['decode'][False], 1),
        'decode_overhead': s_tax('decode'),
        'decode_tax_pairs': [round(t, 4) for t in s_pair_tax['decode']],
        'acceptance': 'overhead < 0.02 on both legs',
        'receipt_file': 'BENCH_OBS_r02.json',
        'timing': f'median of {reps} back-to-back off/on pair ratios '
                  'per leg over an ENABLED recorder; sampler at '
                  f'{sample_every:g}s (the production default) with two '
                  'SLO specs evaluated per tick; negative = below this '
                  'host\'s noise floor',
    }
    _write_receipt_file(sampler_payload)
    _emit(sampler_payload)
    payload = {
        'metric': 'obs_recorder_overhead',
        # a negative per-leg reading means the recorder's cost is below
        # this machine's run-to-run noise floor; the headline is the
        # worst leg clamped at 0 (the raw legs stay in the receipt)
        'value': max(0.0, tax('train'), tax('decode')),
        'unit': 'fraction',
        'platform': plat,
        'vs_baseline': None,
        'train_steps_per_sec_recorder_on': round(rates['train'][True], 1),
        'train_steps_per_sec_recorder_off': round(rates['train'][False], 1),
        'train_overhead': tax('train'),
        'train_tax_pairs': [round(t, 4) for t in pair_tax['train']],
        'decode_tokens_per_sec_recorder_on': round(rates['decode'][True],
                                                   1),
        'decode_tokens_per_sec_recorder_off': round(rates['decode'][False],
                                                    1),
        'decode_overhead': tax('decode'),
        'decode_tax_pairs': [round(t, 4) for t in pair_tax['decode']],
        'acceptance': 'overhead < 0.02 on both legs',
        'batch': batch_size,
        'steps': n_batches,
        'requests': n_req,
        'max_new': max_new,
        'receipt_file': 'BENCH_OBS_r01.json',
        'timing': f'median of {reps} back-to-back off/on pair ratios '
                  'per leg, warm leg discarded; negative = below this '
                  'host\'s noise floor',
    }
    _write_receipt_file(payload)
    _emit(payload)
    return 0


def _write_receipt_file(payload: dict) -> None:
    """Commit a mode's receipt next to the ledger files (the
    ``receipt_file`` key names it); the cpu-fallback wrapper rewrites
    the same file with the tagged payload so the committed receipt is
    always self-describing."""
    name = payload.get('receipt_file')
    if not name:
        return
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(path, 'w') as f:
        json.dump(payload, f, indent=1)
        f.write('\n')


def _q_ms(tracker, name: str, q: float):
    """A tracker quantile in ms, or None when unmeasured — the receipt
    must stay strict JSON (NaN is not)."""
    v = tracker.stats.quantile(name, q)
    return None if v != v else round(v * 1e3, 2)


def bench_online() -> int:
    """Train-while-serve ledger (doc/online.md): the FULL OnlinePipeline —
    supervised trainer publishing a serving checkpoint every
    ``save_every`` steps, colocated engine/batcher/registry hot-swapping
    them under constant-rate traffic — against a train-only supervised
    twin differing ONLY in the serving stack being absent.  Reports
    steps/sec while serving, the serving tax (ratio vs train-only),
    freshness/swap-lag p50/p99, swap count, and the zero-drop counter.
    On CPU the two tasks share cores, so the tax reads high; on a real
    chip the serve forwards interleave into trainer bubbles."""
    import tempfile

    from cxxnet_tpu.io.data import DataBatch, IIterator
    from cxxnet_tpu.nnet.execution import ExecutionPlan
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.online import OnlineConfig, OnlinePipeline
    from cxxnet_tpu.runtime.supervisor import (SupervisorConfig,
                                               TrainSupervisor)
    from cxxnet_tpu.utils.config import parse_config_string

    batch_size = _bench_batch(64)
    n_batches = int(os.environ.get('CXXNET_ONLINE_BATCHES', '96'))
    save_every = int(os.environ.get('CXXNET_ONLINE_SAVE_EVERY', '16'))
    rounds = int(os.environ.get('CXXNET_ONLINE_ROUNDS', '3'))
    conf = _SCAN_MLP + f'batch_size = {batch_size}\n' + _extra_conf()

    rng = np.random.RandomState(0)
    centers = rng.randn(16, 256).astype(np.float32) * 2
    batches = []
    for _ in range(n_batches):
        y = rng.randint(0, 16, batch_size)
        x = centers[y] + 0.3 * rng.randn(batch_size, 256).astype(np.float32)
        batches.append(DataBatch(x.reshape(batch_size, 1, 1, 256),
                                 y[:, None].astype(np.float32)))

    class ListIter(IIterator):
        def __iter__(self):
            return iter(batches)

    def request_rows():
        y = rng.randint(0, 16, 8)
        return (centers[y]
                + 0.3 * rng.randn(8, 256).astype(np.float32)
                ).reshape(8, 1, 1, 256)

    # train-only twin: same supervised loop, no serving stack
    def train_only(tmp):
        trainer = NetTrainer(parse_config_string(conf))
        trainer.init_model()
        plan = ExecutionPlan.resolve(requested_k=1, silent=True)
        sup = TrainSupervisor(
            trainer, os.path.join(tmp, 'train_only'),
            SupervisorConfig(batch_deadline=120.0, nan_breaker=3,
                             save_every=save_every, save_async=1))
        factory = lambda s: iter(batches[s % n_batches:])   # noqa: E731
        sup.run(factory,
                make_stepper=lambda: plan.round_stepper(trainer,
                                                        lookahead=0))
        t0 = time.perf_counter()
        n = 0
        for _ in range(rounds):
            n += sup.run(factory,
                         make_stepper=lambda: plan.round_stepper(
                             trainer, lookahead=0))
        sup.close()
        return n / (time.perf_counter() - t0)

    with tempfile.TemporaryDirectory() as tmp:
        rate_train_only = train_only(tmp)
        trainer = NetTrainer(parse_config_string(conf))
        trainer.init_model()
        pipe = OnlinePipeline(
            trainer, ListIter(),
            lambda: NetTrainer(parse_config_string(
                conf + 'inference_only = 1\n')),
            OnlineConfig(model_dir=os.path.join(tmp, 'online'),
                         save_every=save_every, reload_poll=0.02,
                         buckets=(8,), qps=100.0,
                         watchdog_deadline=120.0, silent=True),
            request_source=request_rows)
        import io as _io
        sink = _io.StringIO()
        try:
            warm = pipe.run(num_rounds=1, out=sink)
            # scope every receipt field to the measured window: drop the
            # warm round's freshness/lag samples and snapshot its counts
            # so the reported swaps/served/dropped are deltas
            pipe.tracker.stats.clear()
            t0 = time.perf_counter()
            summary = pipe.run(num_rounds=rounds, start_round=2, out=sink)
            wall = time.perf_counter() - t0
        finally:
            pipe.close(timeout=30.0)
    steps = rounds * n_batches
    rate = steps / wall
    tr = pipe.tracker
    import jax
    _emit({
        'metric': 'online_steps_per_sec_while_serving',
        'value': round(rate, 1),
        'unit': 'steps/sec',
        'platform': jax.devices()[0].platform,
        'vs_baseline': None,
        'train_only_steps_per_sec': round(rate_train_only, 1),
        'serving_tax': round(1.0 - rate / rate_train_only, 3),
        'freshness_p50_ms': _q_ms(tr, 'freshness_s', 0.5),
        'freshness_p99_ms': _q_ms(tr, 'freshness_s', 0.99),
        'swap_lag_p50_ms': _q_ms(tr, 'swap_lag_s', 0.5),
        'swaps': summary['swaps'] - warm['swaps'],
        'served': summary['served'] - warm['served'],
        'dropped': summary['dropped'] - warm['dropped'],
        'slo_breaches': summary['slo_breaches'] - warm['slo_breaches'],
        'save_every': save_every,
        'batch': batch_size,
        'steps': steps,
        'rounds': rounds,
        'timing': f'wall over {rounds} supervised epochs under traffic; '
                  'warm epoch excluded from every field',
    })
    return 0


def bench_e2e_alexnet() -> int:
    """END-TO-END AlexNet throughput: the real CLI training-loop path —
    imgbin pages -> native/PIL JPEG decode -> augment (crop+mirror) ->
    threadbuffer -> trainer.update (H2D *included*) — on synthetic data
    packed with the in-tree im2bin.  This is the number to read next to
    the device-only ``alexnet`` mode; the JSON carries both plus the
    measured host-link bandwidth so the gap is attributable.
    """
    import tempfile

    import jax

    from cxxnet_tpu.io.data import create_iterator
    from cxxnet_tpu.models import alexnet_conf
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string

    batch_size = _bench_batch(256)
    n_images = int(os.environ.get('CXXNET_E2E_IMAGES', '1024'))

    with tempfile.TemporaryDirectory() as tmp:
        lst, binpath = _pack_synthetic_imgbin(tmp, n_images)

        conf = alexnet_conf() + f"""
batch_size = {batch_size}
eta = 0.01
momentum = 0.9
metric = error
eval_train = 0
random_type = xavier
compute_type = bfloat16
""" + _extra_conf()
        trainer = NetTrainer(parse_config_string(conf))
        trainer.init_model()
        # default: uint8 on the wire + device-side normalize (half the
        # H2D bytes, no per-batch host ml_dtypes cast); set
        # CXXNET_E2E_DEVNORM=0 to A/B the host-normalized f32/bf16 path
        dev_norm = os.environ.get('CXXNET_E2E_DEVNORM', '1') == '1'
        it = create_iterator(_imgbinx_chain(lst, binpath, batch_size,
                                            device_normalize=dev_norm))
        it.init()

        # round 0: compile + pipeline warmup (untimed)
        for b in it:
            trainer.update(b)
        jax.device_get(trainer.params['16']['bias'])

        # measure the host link once (what a production PCIe host hides);
        # probe matches the wire dtype (uint8 under device_normalize,
        # else pre-cast bf16) so the window is transfer, not host cast
        import ml_dtypes
        wire_dtype = np.uint8 if dev_norm else ml_dtypes.bfloat16
        probe = np.zeros((batch_size, 3, 227, 227), wire_dtype)
        fetch_first = jax.jit(lambda t: t.ravel()[0])

        def _put_synced(x):
            # a 1-element fetch is the only reliable completion barrier
            # over the remote tunnel (block_until_ready acks early there)
            np.asarray(fetch_first(trainer._shard_batch(x)))

        _put_synced(probe)                               # warm both paths
        t0 = time.perf_counter()
        _put_synced(probe)
        link_s = time.perf_counter() - t0
        link_mb = probe.nbytes / 1e6          # wire bytes (uint8 or bf16)

        # production path: one-batch lookahead (stage i+1 before stepping
        # i) so the host link overlaps device compute — same loop shape as
        # main.py:_train_rounds
        n_done, t0, pending = 0, time.perf_counter(), None
        for _round in range(2):
            for b in it:
                staged = trainer.stage_batch(b)
                if pending is not None:
                    trainer.update_staged(pending)
                pending = staged
                n_done += b.batch_size - b.num_batch_padd
        if pending is not None:
            trainer.update_staged(pending)
        jax.device_get(trainer.params['16']['bias'])
        dt = time.perf_counter() - t0

    ips = n_done / dt
    _emit({
        'metric': 'alexnet_e2e_images_per_sec_per_chip',
        'value': round(ips, 1),
        'unit': 'images/sec',
        'vs_baseline': round(ips / BASELINE_IMAGES_PER_SEC, 3),
        'host_link_mb_per_s': round(link_mb / link_s, 1),
        'batch_h2d_mb': round(link_mb, 1),
    })
    return 0


# --- MNIST time-to-accuracy ------------------------------------------------

_MNIST_FILES = ('train-images-idx3-ubyte.gz', 'train-labels-idx1-ubyte.gz',
                't10k-images-idx3-ubyte.gz', 't10k-labels-idx1-ubyte.gz')
_MNIST_URL = 'https://storage.googleapis.com/cvdf-datasets/mnist/'


def _read_idx(path: str) -> np.ndarray:
    with gzip.open(path, 'rb') as f:
        magic, = struct.unpack('>i', f.read(4))
        ndim = magic & 0xff
        dims = struct.unpack('>' + 'i' * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def _locate_mnist() -> str | None:
    """Find (or fetch) REAL MNIST; None -> caller uses the surrogate."""
    ddir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'example', 'MNIST', 'data')
    def complete() -> bool:
        try:
            return all(os.path.exists(os.path.join(ddir, f))
                       for f in _MNIST_FILES) and \
                _read_idx(os.path.join(ddir, _MNIST_FILES[0])).shape[0] >= 60000
        except Exception:
            return False
    if complete():
        return ddir
    os.makedirs(ddir, exist_ok=True)
    try:
        import urllib.request
        for f in _MNIST_FILES:
            dst = os.path.join(ddir, f)
            if not os.path.exists(dst):
                # bounded timeout (silent-drop egress filters would hang
                # forever) + atomic rename (a truncated file would lock
                # every later run into the surrogate path)
                with urllib.request.urlopen(_MNIST_URL + f,
                                            timeout=30) as r, \
                        open(dst + '.part', 'wb') as w:
                    while True:
                        chunk = r.read(1 << 20)
                        if not chunk:
                            break
                        w.write(chunk)
                os.replace(dst + '.part', dst)
        if complete():
            return ddir
    except Exception:
        pass
    return None


_MNIST_CONV_NET = """
netconfig=start
layer[+1:cv1] = conv:cv1
  kernel_size = 5
  pad = 2
  nchannel = 32
layer[+1:ac1] = relu
layer[+1:mp1] = max_pooling
  kernel_size = 2
  stride = 2
layer[+1:cv2] = conv:cv2
  kernel_size = 5
  pad = 2
  nchannel = 64
layer[+1:ac2] = relu
layer[+1:mp2] = max_pooling
  kernel_size = 2
  stride = 2
layer[+1:fl] = flatten
layer[+1:fc1] = fullc:fc1
  nhidden = 256
layer[+1:ac3] = relu
layer[+1:fc2] = fullc:fc2
  nhidden = 10
layer[+0] = softmax
netconfig=end
input_shape = 1,28,28
batch_size = 100
random_type = xavier
eta = 0.05
momentum = 0.9
wd = 0.0
metric = error
eval_train = 0
"""


def bench_mnist_tta() -> int:
    """Wall-clock (incl. compile) to 2% test error on REAL MNIST with a
    LeNet-style conv net, through the framework's own data+trainer path.
    Falls back to the quadrant-blob surrogate (MNIST shapes, MLP) when the
    real data is absent and cannot be fetched; the JSON says which ran."""
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string

    ddir = _locate_mnist()
    if ddir is None:
        return _mnist_tta_surrogate()

    imgs = _read_idx(os.path.join(ddir, _MNIST_FILES[0]))
    labels = _read_idx(os.path.join(ddir, _MNIST_FILES[1]))
    timgs = _read_idx(os.path.join(ddir, _MNIST_FILES[2]))
    tlabels = _read_idx(os.path.join(ddir, _MNIST_FILES[3]))

    # normalize once, outside the timed loop; rounds only reshuffle indices
    imgs_f = (imgs.astype(np.float32) / 255.0)[:, None]
    labels_f = labels.astype(np.float32).reshape(-1, 1)
    timgs_f = (timgs.astype(np.float32) / 255.0)[:, None]
    tlabels_f = tlabels.astype(np.float32).reshape(-1, 1)

    def batches(x, y, bs, rng=None):
        idx = np.arange(len(x))
        if rng is not None:
            rng.shuffle(idx)
        return [DataBatch(x[idx[i:i + bs]], y[idx[i:i + bs]])
                for i in range(0, len(idx) - bs + 1, bs)]

    trainer = NetTrainer(parse_config_string(_MNIST_CONV_NET))
    trainer.init_model()
    rng = np.random.RandomState(0)
    test = batches(timgs_f, tlabels_f, 100)

    t0 = time.perf_counter()
    err, rounds = 1.0, 0
    first_update_sec = first_eval_sec = None
    while err > 0.02 and rounds < 15:
        trainer.start_round(rounds)
        for b in batches(imgs_f, labels_f, 100, rng):
            tu0 = time.perf_counter()
            trainer.update(b)
            if first_update_sec is None:
                # jit tracing+compile happens synchronously inside the
                # first call: this split separates one-time compile from
                # training in the wall number (the reference's ~30s CPU
                # baseline had no compile component)
                first_update_sec = time.perf_counter() - tu0
        te0 = time.perf_counter()
        res = trainer.evaluate(iter(test), 'test')
        if first_eval_sec is None:
            first_eval_sec = time.perf_counter() - te0
        err = float(res.split(':')[-1])
        rounds += 1
    dt = time.perf_counter() - t0
    _emit({
        'metric': 'mnist_time_to_2pct_error',
        'value': round(dt, 2),
        'unit': 'sec',
        'vs_baseline': round(BASELINE_MNIST_TTA_SEC / dt, 3),
        'data': 'mnist',
        'rounds': rounds,
        'final_error': round(err, 4),
        'compile_split_sec': {'first_update': round(first_update_sec, 2),
                              'first_eval': round(first_eval_sec, 2)},
    })
    return 0 if err <= 0.02 else 1


def _mnist_tta_surrogate() -> int:
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.models import mlp_conf
    from cxxnet_tpu.utils.config import parse_config_string

    conf = mlp_conf() + """
batch_size = 100
eta = 0.1
momentum = 0.9
metric = error
eval_train = 0
"""
    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()
    rng = np.random.RandomState(0)

    def blobs(n):
        y = rng.randint(0, 10, n)
        x = np.zeros((n, 784), np.float32)
        for i, c in enumerate(y):
            x[i, c * 78:(c + 1) * 78] = rng.rand(78)
        return x.reshape(n, 1, 1, 784), y.astype(np.float32).reshape(-1, 1)

    train = [DataBatch(*blobs(100)) for _ in range(60)]
    test = [DataBatch(*blobs(100)) for _ in range(10)]
    t0 = time.perf_counter()
    err, rounds = 1.0, 0
    first_update_sec = first_eval_sec = None
    while err > 0.02 and rounds < 15:
        trainer.start_round(rounds)
        for b in train:
            tu0 = time.perf_counter()
            trainer.update(b)
            if first_update_sec is None:
                first_update_sec = time.perf_counter() - tu0
        te0 = time.perf_counter()
        res = trainer.evaluate(iter(test), 'test')
        if first_eval_sec is None:
            first_eval_sec = time.perf_counter() - te0
        err = float(res.split(':')[-1])
        rounds += 1
    dt = time.perf_counter() - t0
    _emit({
        'metric': 'mnist_time_to_2pct_error',
        'value': round(dt, 2),
        'unit': 'sec',
        'vs_baseline': round(BASELINE_MNIST_TTA_SEC / dt, 3),
        'data': 'surrogate',
        'rounds': rounds,
        'final_error': round(err, 4),
        'compile_split_sec': {'first_update': round(first_update_sec, 2),
                              'first_eval': round(first_eval_sec, 2)},
    })
    return 0 if err <= 0.02 else 1


_CNN_FUSED_CONF = """
netconfig = start
layer[0->1] = conv:c1
  kernel_size = 3
  pad = 1
  nchannel = 8
layer[1->1] = relu
layer[1->2] = max_pooling
  kernel_size = 2
  stride = 2
layer[2->3] = conv:c2
  kernel_size = 3
  pad = 1
  nchannel = 16
layer[3->3] = relu
layer[3->4] = flatten
layer[4->5] = fullc:fc1
  nhidden = 10
layer[5->6] = softmax
netconfig = end

input_shape = 3,12,12
eta = 0.01
momentum = 0.9
metric = error
eval_train = 0
random_type = xavier
"""

# the fold leg's topology: conv+BN stacks, the shape serve.fold_bn
# rewrites (doc/kernels.md "Inference conv+BN folding")
_CNN_FOLD_CONF = """
netconfig = start
layer[0->1] = conv:c1
  kernel_size = 3
  pad = 1
  nchannel = 8
layer[1->2] = batch_norm:bn1
layer[2->3] = relu
layer[3->4] = conv:c2
  kernel_size = 3
  pad = 1
  stride = 2
  nchannel = 16
layer[4->5] = batch_norm:bn2
layer[5->6] = relu
layer[6->7] = flatten
layer[7->8] = fullc:fc1
  nhidden = 10
layer[8->9] = softmax
netconfig = end

input_shape = 3,12,12
random_type = xavier
"""


def bench_cnn_fused() -> int:
    """graftfuse A/B (doc/kernels.md), three legs in ONE receipt:

    * **train** — fused Pallas conv+bias+relu blocks (``fuse=1``) vs the
      unfused XLA composition (``fuse=0``), steps/sec by the K-vs-1 scan
      quotient; final params after identical update streams are
      twin-asserted within the fused block's pinned tolerance
      (``ops/pallas_cnn``) IN the bench — a speedup over diverging math
      is not a speedup;
    * **inference** — a real ``PredictEngine`` with ``fold_bn=1``
      (conv+BN folded at build time, nnet/fold.py) vs the unfolded
      engine, rows/sec; scores twin-asserted within the fold pass's
      pinned tolerance, ``fold_view`` stamped;
    * **micro_batch sweep** — μ-cuDNN-style conv microbatching at every
      declared split: steps/sec AND the ``train.step`` program's
      ledger ``peak_bytes`` (compiler truth, obs/programs.py) per
      split, with final params bitwise-asserted against the unsplit
      trainer — the split bounds peak HBM, it never changes the math.

    On a cpu host the fused leg runs the Pallas block in interpret mode
    — the twins are real correctness proofs, the speedups are not chip
    numbers (the receipt's ``platform`` stamp + self-heal handle that).
    """
    import jax

    from cxxnet_tpu.nnet.fold import FOLD_ATOL, FOLD_RTOL
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.obs.programs import get_ledger
    from cxxnet_tpu.ops.pallas_cnn import _FUSED_ATOL, _FUSED_RTOL
    from cxxnet_tpu.serve.engine import PredictEngine
    from cxxnet_tpu.utils.config import parse_config_string

    plat = jax.devices()[0].platform
    led = get_ledger()
    batch = _bench_batch(8)
    steps = _bench_steps(6)
    rng = np.random.RandomState(0)
    data = rng.randn(batch, 3, 12, 12).astype(np.float32)
    label = rng.randint(0, 10, (batch, 1)).astype(np.float32)

    def make(extra: str) -> NetTrainer:
        tr = NetTrainer(parse_config_string(
            _CNN_FUSED_CONF + f'batch_size = {batch}\n'
            + extra + _extra_conf()))
        tr.init_model()
        return tr

    def train_steps(tr: NetTrainer, n: int) -> None:
        d = tr._shard_batch(data)
        lb = tr._shard_batch(label, cast=False)
        for _ in range(n):
            tr.update_on_device(d, lb)

    def param_maxerr(a: NetTrainer, b: NetTrainer) -> float:
        err = 0.0
        for lk, fields in a.params.items():
            for f in fields:
                err = max(err, float(np.max(np.abs(
                    np.asarray(a.params[lk][f], np.float32)
                    - np.asarray(b.params[lk][f], np.float32)))))
        return err

    def steps_per_sec(tr: NetTrainer) -> float:
        dstack = tr.shard_batch_stack(np.stack([data, data]))
        lstack = tr.shard_batch_stack(np.stack([label, label]),
                                      cast=False)
        m1 = tr.compile_multi_step(1)
        mk = tr.compile_multi_step(steps)

        def run(fn, n):
            return float(np.asarray(
                tr.update_n_on_device(fn, dstack, lstack, n)))

        per_step, _ = _quotient_per_step(
            lambda: run(m1, 1), lambda: run(mk, steps), steps)
        return 1.0 / per_step

    # ---- leg 1: fused vs unfused training --------------------------------
    tr_on, tr_off = make('fuse = 1\n'), make('fuse = 0\n')
    if not tr_on.net._convact_pairs:
        raise AssertionError('fuse=1 conf paired no conv+relu blocks — '
                             'the A/B would measure nothing')
    train_steps(tr_on, 4)
    train_steps(tr_off, 4)
    train_err = param_maxerr(tr_on, tr_off)
    train_twin = bool(np.allclose(0.0, train_err,
                                  rtol=_FUSED_RTOL, atol=_FUSED_ATOL))
    if not train_twin:
        raise AssertionError(
            f'fused training diverged from unfused: param maxerr '
            f'{train_err} > pinned {_FUSED_ATOL}')
    rate_on, rate_off = steps_per_sec(tr_on), steps_per_sec(tr_off)
    train_speedup = rate_on / rate_off

    # ---- leg 2: conv+BN folded vs plain inference ------------------------
    calib = rng.randn(batch, 3, 12, 12).astype(np.float32)
    srv = NetTrainer(parse_config_string(
        _CNN_FOLD_CONF + f'batch_size = {batch}\n' + _extra_conf()))
    srv.init_model()
    eng_plain = PredictEngine(srv, (batch,))
    eng_fold = PredictEngine(srv, (batch,), fold_bn=1, fold_batch=calib)
    fold_view = eng_fold.fold_view()
    if not fold_view or not fold_view.get('pairs'):
        raise AssertionError('fold_bn=1 planned no conv+BN pairs')
    # the twin is the fold pass's pinned contract: equality ON the
    # calibration batch (BN here uses incoming-batch statistics even at
    # eval — the reference quirk — so the frozen-stats fold is exact
    # only where its statistics came from; doc/kernels.md)
    q = calib
    s_plain = eng_plain.predict_scores(q)
    s_fold = eng_fold.predict_scores(q)
    infer_err = float(np.max(np.abs(s_fold - s_plain)))
    infer_twin = bool(np.allclose(s_fold, s_plain,
                                  rtol=FOLD_RTOL, atol=FOLD_ATOL))
    if not infer_twin:
        raise AssertionError(
            f'folded engine diverged from unfolded: score maxerr '
            f'{infer_err}')

    def rows_per_sec(eng) -> float:
        reps = max(4, steps)
        eng.predict_scores(q)            # compile + warm
        walls = []
        for _ in range(4):
            t0 = time.perf_counter()
            for _ in range(reps):
                eng.predict_scores(q)
            walls.append(time.perf_counter() - t0)
        return batch * reps / min(walls)

    rows_fold = rows_per_sec(eng_fold)
    rows_plain = rows_per_sec(eng_plain)
    infer_speedup = rows_fold / rows_plain

    # ---- leg 3: micro_batch sweep ----------------------------------------
    splits = [s for s in (1, 2, 4, 8) if batch % s == 0]
    sweep, base_snap = [], None

    def snap(tr: NetTrainer) -> dict:
        # a host copy taken BEFORE the timing loop advances the trainer
        return {lk: {f: np.asarray(v, np.float32)
                     for f, v in fields.items()}
                for lk, fields in tr.params.items()}

    for split in splits:
        tr = make(f'fuse = 0\nmicro_batch = {split}\n')
        train_steps(tr, 3)
        if split == splits[0]:
            base_snap, mb_err = snap(tr), 0.0
        else:
            mb_err = max(float(np.max(np.abs(
                np.asarray(tr.params[lk][f], np.float32)
                - base_snap[lk][f])))
                for lk in base_snap for f in base_snap[lk])
            if mb_err != 0.0:
                raise AssertionError(
                    f'micro_batch={split} step diverged from unsplit: '
                    f'param maxerr {mb_err}')
        # compiler truth: THIS trainer's train.step entry (full #N name
        # — base-name matching would conflate the sweep's instances)
        entries = led.entries_for(tr._prog_step.name)
        peak = max((int(e.peak_bytes) for e in entries), default=0)
        sweep.append({'micro_batch': split,
                      'steps_per_sec': round(steps_per_sec(tr), 2),
                      'peak_bytes': peak,
                      'bitwise_equal_to_unsplit': True})
    peaks = [r['peak_bytes'] for r in sweep]

    payload = {
        'metric': 'cnn_fused_speedup',
        # the headline is the BEST leg: the claim is "at least one
        # fusion wins", each leg's own number rides next to its twin
        'value': round(max(train_speedup, infer_speedup), 4),
        'unit': 'x',
        'platform': plat,
        'vs_baseline': None,
        'train': {
            'speedup': round(train_speedup, 4),
            'fused_steps_per_sec': round(rate_on, 2),
            'unfused_steps_per_sec': round(rate_off, 2),
            'fused_pairs': len(tr_on.net._convact_pairs),
            'twin_ok': train_twin,
            'param_max_abs_err': train_err,
            'rtol': _FUSED_RTOL, 'atol': _FUSED_ATOL,
        },
        'inference': {
            'speedup': round(infer_speedup, 4),
            'folded_rows_per_sec': round(rows_fold, 2),
            'plain_rows_per_sec': round(rows_plain, 2),
            'fold_view': fold_view,
            'twin_ok': infer_twin,
            'score_max_abs_err': infer_err,
            'rtol': FOLD_RTOL, 'atol': FOLD_ATOL,
        },
        'micro_batch': {
            'sweep': sweep,
            'peak_bytes_monotone': bool(
                all(a >= b for a, b in zip(peaks, peaks[1:]))),
        },
        'batch': batch,
        'programs': _program_summary(),
        'receipt_file': 'BENCH_CNN_r01.json',
        'timing': 'train legs scan-in-jit K-vs-1 quotient; inference '
                  'legs best-of-4 wall; every A/B twin-asserted in-bench',
    }
    _write_receipt_file(payload)
    _emit(payload)
    return 0


def bench_autotune() -> int:
    """grafttune A/B (doc/autotune.md): run the two-stage search on TWO
    bench modes — the supervised train scan and serve decode — then
    re-measure the tuned config against the hand-tuned default with
    fresh state, so the headline speedup is an independent measurement,
    not the search's own probe replayed.  The receipt stamps the full
    search story (declared budget vs wall, stage-1 ledger pruning
    counts, every probe) plus an in-receipt recompile-storm-guard
    drill: an online TuneController driven through a verdict sequence
    that would thrash a bucket ladder, against a ledger program with a
    tight ``obs.recompile`` bound — green means zero
    ``RecompileStormError`` records and total compiles under both the
    program's bound and the space's declared compile budget."""
    import jax

    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.models import transformer as TT
    from cxxnet_tpu.nnet import execution
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.obs.programs import get_ledger
    from cxxnet_tpu.runtime import faults
    from cxxnet_tpu.serve.decode import DecodeService
    from cxxnet_tpu.tune import (LedgerGate, TuneController, TuneSearch,
                                 TuneSpace)
    from cxxnet_tpu.utils.config import parse_config_string

    plat = jax.devices()[0].platform
    led = get_ledger()

    # ---- leg 1: train scan (steps_per_dispatch) --------------------------
    batch_size = _bench_batch(32)
    n_probe = int(os.environ.get('CXXNET_TUNE_PROBE_STEPS', '32'))
    conf = _SCAN_MLP + f'batch_size = {batch_size}\n' + _extra_conf()
    rng = np.random.RandomState(0)
    centers = rng.randn(16, 256).astype(np.float32) * 2
    batches = []
    for _ in range(n_probe):
        y = rng.randint(0, 16, batch_size)
        x = centers[y] + 0.3 * rng.randn(batch_size, 256).astype(np.float32)
        batches.append(DataBatch(x.reshape(batch_size, 1, 1, 256),
                                 y[:, None].astype(np.float32)))

    search_trainer = NetTrainer(parse_config_string(conf))
    search_trainer.init_model()
    # warm-up at the baseline K fills the ledger — stage 1 prices every
    # candidate from THIS program's compiler truth
    execution.measured_probe(search_trainer, 1, batches, repeats=1)
    led.ensure_analyzed_batch()
    base_bytes = max(
        (e.peak_bytes or (e.argument_bytes + e.output_bytes
                          + e.temp_bytes))
        for e in led.entries())
    # the declared ceiling comes FROM the measured baseline footprint:
    # ~5x headroom-adjusted means the k=8 rung (pricing 8x) cannot fit
    # and must be pruned by the ledger, never measured
    scan_mem_mb = base_bytes * 5.0 / (1 << 20)
    scan_spec = (f'knobs=steps_per_dispatch:1..8;mode=train;budget=60;'
                 f'seed=0;probe_steps={n_probe};probe_repeats=3;'
                 f'mem_mb={scan_mem_mb:.3f}')
    scan_space = TuneSpace.parse(scan_spec)
    scan_base = {'steps_per_dispatch': 1}
    scan_gate = LedgerGate(
        base_bytes=float(base_bytes),
        ceiling_bytes=scan_space.mem_mb * (1 << 20)
        * (1.0 - scan_space.headroom),
        baseline=scan_base, mem_knobs=scan_space.mem_knobs())
    scan_res = TuneSearch(
        scan_space,
        lambda c: execution.measured_probe(
            search_trainer, c['steps_per_dispatch'], batches,
            repeats=scan_space.probe_repeats),
        gate=scan_gate, baseline=scan_base).run('train')
    k_tuned = scan_res.best['steps_per_dispatch']

    # independent A/B: fresh trainers, the tuned K vs the default K —
    # and the bitwise-twin contract: both legs dispatch the same batches
    # the same number of times, so final params must be IDENTICAL (a
    # tuned config may move knobs, never the math)
    def scan_leg(k):
        tr = NetTrainer(parse_config_string(conf))
        tr.init_model()
        rate = execution.measured_probe(tr, k, batches, repeats=4)
        return rate, tr

    rate_default, t_def = scan_leg(1)
    rate_tuned, t_tuned = scan_leg(k_tuned)
    scan_best = dict(scan_res.best)
    scan_fallback = False
    if k_tuned == 1:
        # the search kept the hand-set default: identical configs are
        # 1.0x by definition — the re-measure only adds noise
        rate_tuned = rate_default
    elif rate_tuned < rate_default:
        # validation gate: a tuned config the independent re-measure
        # cannot confirm is never shipped — fall back to the default
        # (the same >=baseline contract the search itself keeps)
        scan_best = dict(scan_base)
        rate_tuned = rate_default
        scan_fallback = True
    scan_bitwise = all(
        np.array_equal(np.asarray(t_def.params[lk][fk]),
                       np.asarray(t_tuned.params[lk][fk]))
        for lk, fields in t_def.params.items() for fk in fields)
    if not scan_bitwise:
        raise AssertionError(
            'tuned scan leg diverged bitwise from the per-step leg — '
            'the autotuner may move knobs, never the math')
    scan_speedup = rate_tuned / rate_default

    # ---- leg 2: serve decode (slots x pages) -----------------------------
    cfg = TT.TransformerConfig(vocab_size=64, d_model=32, num_heads=2,
                               d_ff=64, num_stages=1, seq_len=128,
                               attn='local')
    params = TT.init_params(np.random.RandomState(0), cfg)
    max_prompt, max_new = 12, 16
    n_req = int(os.environ.get('CXXNET_TUNE_REQUESTS', '16'))
    dec_base = {'slots': 2, 'pages': 16}

    def build_svc(cand):
        return DecodeService(
            params, cfg, slots=cand['slots'], pages=cand['pages'],
            page_size=8, max_prompt=max_prompt, max_new_bound=max_new,
            eos_id=None, max_queue=64, max_wait=0.002, deadline=60.0)

    def dec_prompts(seed):
        prng = np.random.RandomState(seed)
        return [prng.randint(0, cfg.vocab_size,
                             (1, int(prng.randint(1, max_prompt))))
                .astype(np.int32) for _ in range(n_req)]

    def dec_rate(svc, reps):
        prompts = dec_prompts(0)

        def one_pass():
            t0 = time.perf_counter()
            reqs = [svc.submit_async(p, max_new, 0.0, None)
                    for p in prompts]
            toks = sum(len(svc.batcher.wait(r)) for r in reqs)
            return toks / max(1e-9, time.perf_counter() - t0)

        one_pass()                       # compile off the clock
        return max(one_pass() for _ in range(reps))

    # baseline engine warm-up: its resident footprint is the stage-1
    # base price for every candidate's slots/pages scaling
    svc0 = build_svc(dec_base)
    try:
        dec_base_bytes = float(svc0.engine.resident_bytes())
    finally:
        svc0.close(30.0)
    dec_mem_mb = dec_base_bytes * 5.0 / (1 << 20)
    dec_spec = (f'knobs=slots:1..8,pages:8..32;mode=decode;budget=120;'
                f'seed=0;probe_steps={n_req};probe_repeats=2;'
                f'max_probes=6;mem_mb={dec_mem_mb:.3f}')
    dec_space = TuneSpace.parse(dec_spec)
    dec_gate = LedgerGate(
        base_bytes=dec_base_bytes,
        ceiling_bytes=dec_space.mem_mb * (1 << 20)
        * (1.0 - dec_space.headroom),
        baseline=dec_base, mem_knobs=dec_space.mem_knobs(),
        feasible=lambda c: ('fewer KV pages than decode slots'
                            if c['pages'] < c['slots'] else None))

    def dec_probe(cand):
        svc = build_svc(cand)
        try:
            return dec_rate(svc, dec_space.probe_repeats)
        finally:
            svc.close(30.0)

    dec_res = TuneSearch(dec_space, dec_probe, gate=dec_gate,
                         baseline=dec_base).run('decode')

    # independent A/B re-measure + the stream-twin contract on the
    # tuned engine: every served stream equals its offline generate
    def dec_leg(cand, twin):
        svc = build_svc(cand)
        try:
            rate = dec_rate(svc, 4)
            twin_ok = True
            if twin:
                for p in dec_prompts(0)[:2]:
                    got = svc.batcher.wait(
                        svc.submit_async(p, max_new, 0.0, None))
                    off = np.asarray(TT.generate(
                        svc.engine.oracle_params(), p, max_new,
                        svc.engine.cfg, temperature=0.0,
                        rng=None, eos_id=None))[0]
                    twin_ok = twin_ok and \
                        (np.asarray(got) == off[:len(got)]).all()
            return rate, twin_ok
        finally:
            svc.close(30.0)

    dec_rate_default, _ = dec_leg(dec_base, twin=False)
    dec_rate_tuned, dec_twin = dec_leg(dec_res.best, twin=True)
    dec_best = dict(dec_res.best)
    dec_fallback = False
    if dec_res.best == dec_base:
        dec_rate_tuned = dec_rate_default
    elif dec_rate_tuned < dec_rate_default:
        dec_best = dict(dec_base)
        dec_rate_tuned = dec_rate_default
        dec_fallback = True
    if not dec_twin:
        raise AssertionError(
            'tuned decode engine broke the stream-twin contract')
    dec_speedup = dec_rate_tuned / dec_rate_default

    # ---- in-receipt recompile-storm guard drill --------------------------
    drill_space = TuneSpace.parse(
        'knobs=slots:1..8;mode=decode;budget=5;compile_budget=4')
    drill_log = faults.FailureLog()
    storm_before = len(faults.global_failure_log().records(
        'RecompileStormError'))
    prog = led.program('tune.storm_drill', bound=2)
    drill_fn = prog.jit(lambda x: x * 2.0,
                        key_fn=lambda a, _k: f's{a[0].shape[0]}')

    ctl = TuneController(
        drill_space, verdicts=lambda: {'v': {'state': 'BREACHED'}},
        gauges=lambda: {'hbm.headroom_frac[d0]': 0.01},
        failure_log=drill_log, hysteresis=1, cooldown=0.0)
    # every re-plan really recompiles: each slot count is a new shape
    # through a bound ledger program — exactly the bucket-ladder thrash
    # the guard exists for
    ctl.bind('slots', lambda v: drill_fn(np.zeros((max(1, v),),
                                                  np.float32)),
             8, program=prog)
    for _ in range(8):                   # a thrashing verdict stream
        ctl.evaluate()
    storm_errors = (len(faults.global_failure_log().records(
        'RecompileStormError')) - storm_before) \
        + len(drill_log.records('RecompileStormError'))
    vetoes = int(ctl.stats.get('recompile_vetoes'))
    drill_ok = (storm_errors == 0 and vetoes >= 1
                and ctl.compiles() <= drill_space.compile_budget
                and prog.compiles <= prog.bound)
    if not drill_ok:
        raise AssertionError(
            f'storm-guard drill failed: storm_errors={storm_errors} '
            f'vetoes={vetoes} compiles={ctl.compiles()} '
            f'program={prog.compiles}/{prog.bound}')

    def search_block(res, space):
        return {'spec': space.describe(), 'budget_s': space.budget,
                'wall_s': round(res.wall_s, 3),
                'budget_honored': res.budget_honored,
                'stage1_candidates': res.stage1_candidates,
                'stage1_pruned': res.stage1_pruned,
                'measured': res.measured, 'failed': res.failed}

    payload = {
        'metric': 'autotune_speedup',
        # the headline is the WORSE of the two modes: the claim is
        # "tuned beats the hand-set default everywhere", not on average
        'value': round(min(scan_speedup, dec_speedup), 4),
        'unit': 'x',
        'platform': plat,
        'vs_baseline': None,
        'modes': {
            'scan': {
                'speedup': round(scan_speedup, 4),
                'default': scan_base, 'tuned': scan_best,
                'fallback_to_default': scan_fallback,
                'default_steps_per_sec': round(rate_default, 2),
                'tuned_steps_per_sec': round(rate_tuned, 2),
                'bitwise_equal': bool(scan_bitwise),
                'search': search_block(scan_res, scan_space),
            },
            'decode': {
                'speedup': round(dec_speedup, 4),
                'default': dec_base, 'tuned': dec_best,
                'fallback_to_default': dec_fallback,
                'default_tokens_per_sec': round(dec_rate_default, 2),
                'tuned_tokens_per_sec': round(dec_rate_tuned, 2),
                'stream_twins': bool(dec_twin),
                'search': search_block(dec_res, dec_space),
            },
        },
        'search': {
            'budget_s': scan_space.budget + dec_space.budget,
            'wall_s': round(scan_res.wall_s + dec_res.wall_s, 3),
            'budget_honored': bool(scan_res.budget_honored
                                   and dec_res.budget_honored),
            'stage1_candidates': (scan_res.stage1_candidates
                                  + dec_res.stage1_candidates),
            'stage1_pruned': (scan_res.stage1_pruned
                              + dec_res.stage1_pruned),
            'measured': scan_res.measured + dec_res.measured,
        },
        'storm_guard': {
            'replans': ctl.status_view()['replans'],
            'vetoes': vetoes,
            'compiles': ctl.compiles(),
            'compile_budget': drill_space.compile_budget,
            'program_compiles': prog.compiles,
            'program_bound': prog.bound,
            'storm_errors': storm_errors,
        },
        'batch': batch_size,
        'requests': n_req,
        'programs': _program_summary(),
        'receipt_file': 'BENCH_TUNE_r01.json',
        'timing': 'speedups are independent re-measures (fresh state, '
                  'best-of-3) of tuned vs default, not the search\'s '
                  'own probes; scan legs bitwise-assert final params',
    }
    _write_receipt_file(payload)
    _emit(payload)
    return 0 if min(scan_speedup, dec_speedup) >= 1.0 else 1


_MODES = {'alexnet': ('alexnet_images_per_sec_per_chip', bench_alexnet),
          'inception_bn': ('inception_bn_images_per_sec_per_chip',
                           bench_inception_bn),
          'googlenet': ('googlenet_images_per_sec_per_chip',
                        bench_googlenet),
          'vgg16': ('vgg16_images_per_sec_per_chip', bench_vgg16),
          'e2e_alexnet': ('alexnet_e2e_images_per_sec_per_chip',
                          bench_e2e_alexnet),
          'eval_alexnet': ('alexnet_eval_images_per_sec_per_chip',
                           bench_eval_alexnet),
          'io': ('host_io_images_per_sec', bench_io),
          'bench_io': ('host_io_images_per_sec', bench_io),  # alias
          'scan': ('supervised_scan_steps_per_sec', bench_scan),
          'online': ('online_steps_per_sec_while_serving', bench_online),
          'obs': ('obs_recorder_overhead', bench_obs),
          'mnist_tta': ('mnist_time_to_2pct_error', bench_mnist_tta),
          'transformer': ('transformer_tokens_per_sec_per_chip',
                          bench_transformer),
          'decode': ('decode_tokens_per_sec_per_chip', bench_decode),
          'cnn_fused': ('cnn_fused_speedup', bench_cnn_fused),
          'autotune': ('autotune_speedup', bench_autotune)}


#: ledger metrics whose ``cpu-fallback`` receipts a real-TPU run can
#: heal, and the (script, mode) that re-measures each — the flash/int8
#: serving legs, whose interpret-mode Pallas numbers prove nothing about
#: on-chip speed (doc/benchmarks.md)
_HEALABLE = {
    'decode_int8_resident_reduction': ('bench_serve.py', 'decode_matrix'),
    'decode_tokens_per_sec': ('bench_serve.py', 'decode'),
    # ROADMAP item 2 tail: BENCH_SERVE_r04's prefix/spec rows are cpu
    # correctness proofs — the speed claims (prefill amortization, the
    # verify window's HBM win) only mean anything on a real chip
    'prefix_share_speedup': ('bench_serve.py', 'prefix_spec'),
    'spec_decode_speedup': ('bench_serve.py', 'spec'),
    # BENCH_KV_r01: the tier ratio is compute-vs-disk-vs-HBM balance,
    # which a cpu host only approximates — re-measure on a real chip
    'kv_tier_speedup': ('bench_serve.py', 'kv_tiers'),
    # BENCH_SHARD_r01: on the virtual CPU mesh every shard shares one
    # host — the tp:N wall-clock ratio is a capacity/batching proxy;
    # real per-chip scaling needs real chips
    'decode_shard_scaling': ('bench_serve.py', 'sharded'),
    # BENCH_TUNE_r01: on cpu the scan win is dispatch-overhead-only and
    # the decode batching curve is host-bound — the tuned-choice story
    # deserves a real chip's cost surface
    'autotune_speedup': ('bench.py', 'autotune'),
    # BENCH_CNN_r01: interpret-mode Pallas proves the fused block's
    # MATH (the twins), never its speed — the fused-vs-XLA and
    # fold-vs-plain ratios only mean anything compiled for a real chip
    'cnn_fused_speedup': ('bench.py', 'cnn_fused'),
}


def heal_candidates(root: str):
    """Newest cpu-measured ledger entry per healable metric: scan the
    committed ``BENCH*.json`` trajectory files (and any prior healed
    receipts) for payloads stamped ``"platform": "cpu-fallback"`` (or
    plain ``"cpu"`` — the direct bench_serve runs) whose metric is in
    ``_HEALABLE``; a later real-platform receipt for the same metric
    supersedes the stale one."""
    import glob
    state: dict = {}
    # receipts/bench_*.json covers both families of healed receipts
    # (bench_serve_<mode>.json and this script's own bench_<mode>.json)
    paths = (glob.glob(os.path.join(root, 'BENCH*.json'))
             + glob.glob(os.path.join(root, 'receipts', 'bench_*.json')))
    # newest file wins by mtime (ties broken by name): a cpu-fallback
    # trajectory entry committed AFTER an old heal receipt must read as
    # stale again, not stay masked by it
    def _stamp(p):
        try:
            return (os.path.getmtime(p), p)
        except OSError:
            return (0.0, p)

    for path in sorted(paths, key=_stamp):
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        metric = payload.get('metric')
        if metric not in _HEALABLE:
            continue
        # a receipt measured on a plain 'cpu' backend (the bench_serve
        # modes run directly under JAX_PLATFORMS=cpu) is just as stale
        # as a tagged fallback: neither says anything about chip speed
        state[metric] = (path,
                         payload.get('platform') in ('cpu',
                                                     'cpu-fallback'))
    return [(path, metric, _HEALABLE[metric])
            for metric, (path, stale) in sorted(state.items()) if stale]


def _run_heal(script: str, mode: str) -> Optional[dict]:
    """Re-measure one healable mode on the (now confirmed up) backend;
    returns its JSON payload or None."""
    env = dict(os.environ)
    env['CXXNET_BENCH_NO_HEAL'] = '1'    # no recursion from the child
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)), script),
         mode],
        env=env, capture_output=True, text=True, timeout=3000)
    for line in reversed((r.stdout or '').strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


def self_heal_receipts(root: Optional[str] = None, runner=None) -> list:
    """The trajectory's self-healing pass (ROADMAP item 4 tail): when a
    bench run finds the real TPU up, any flash/int8 ledger entry still
    stamped ``cpu-fallback`` is re-measured automatically and the healed
    receipt lands in ``receipts/bench_serve_<mode>.json`` (bench.py's
    own healable modes: ``receipts/bench_<mode>.json``) — the
    trajectory repairs itself the first time the tunnel cooperates,
    instead of waiting for someone to remember a manual rerun.  Returns
    the healed (metric, receipt_path) pairs; never raises — a failed
    heal is a note, not a bench failure."""
    if os.environ.get('CXXNET_BENCH_NO_HEAL') == '1':
        return []
    plats = [p.strip() for p in
             os.environ.get('JAX_PLATFORMS', '').split(',') if p.strip()]
    if plats and all(p == 'cpu' for p in plats):
        return []            # explicit CPU-only run: nothing to heal with
    root = root or os.path.dirname(os.path.abspath(__file__))
    runner = runner or _run_heal
    healed = []
    for stale_path, metric, (script, mode) in heal_candidates(root):
        try:
            payload = runner(script, mode)
        except Exception as e:      # healing must not break the
            #                         requested bench mode — but a
            #                         Ctrl-C/SystemExit still aborts
            _emit({'metric': 'receipt_self_heal', 'value': None,
                   'heals': metric, 'error': f'{type(e).__name__}: {e}'})
            continue
        if payload is None or payload.get('value') is None:
            _emit({'metric': 'receipt_self_heal', 'value': None,
                   'heals': metric,
                   'error': 'heal rerun produced no measurement'})
            continue
        if payload.get('platform') in (None, 'cpu', 'cpu-fallback'):
            # the backend went away between the probe and the rerun: a
            # fallback receipt must not overwrite the healing intent
            _emit({'metric': 'receipt_self_heal', 'value': None,
                   'heals': metric,
                   'error': f'rerun landed on platform='
                            f'{payload.get("platform")!r}, not a chip'})
            continue
        payload['heals'] = stale_path
        # the healed receipt's name follows the script that measured it:
        # bench_serve.py modes keep their bench_serve_<mode>.json slot,
        # this script's own modes (autotune, cnn_fused) land in
        # bench_<mode>.json — the same path main() points at when the
        # tunnel is down
        prefix = ('bench_serve' if script == 'bench_serve.py'
                  else 'bench')
        out = os.path.join(root, 'receipts', f'{prefix}_{mode}.json')
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, 'w') as f:
            json.dump(payload, f, indent=1)
        healed.append((metric, out))
        _emit({'metric': 'receipt_self_heal', 'value': payload['value'],
               'heals': metric, 'receipt': out,
               'platform': payload.get('platform')})
    return healed


def _cpu_fallback(mode: str, err: BaseException) -> int:
    """The ledger must ALWAYS record a number: rerun this mode in a child
    process pinned to ``JAX_PLATFORMS=cpu`` and re-emit its receipt
    tagged ``"platform": "cpu-fallback"`` (plus the reason), so a CPU
    number can never masquerade as per-chip throughput.  Problem sizes
    shrink (fewer scan steps, smaller batch) unless explicitly pinned —
    the point is a trend-able data point, not a chip-class one."""
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    # modes that commit MULTIPLE receipt files (obs r01+r02) stamp every
    # one cpu-fallback themselves — the parent only rewrites the last
    env['CXXNET_BENCH_FALLBACK'] = '1'
    env.setdefault('CXXNET_BENCH_STEPS', '4')
    env.setdefault('CXXNET_BENCH_BATCH', '16')
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), mode],
            env=env, capture_output=True, text=True, timeout=3000)
        payload = None
        for line in reversed((r.stdout or '').strip().splitlines()):
            try:
                payload = json.loads(line)
                break
            except ValueError:
                continue
        if payload is None:
            raise RuntimeError(
                f'fallback produced no JSON (rc={r.returncode}): '
                f'{(r.stderr or "").strip().splitlines()[-1:]}')
    except BaseException as fe:  # noqa: BLE001 — one JSON line, always
        _emit({'metric': _MODES[mode][0], 'value': None, 'unit': None,
               'vs_baseline': None,
               'error': f'{type(err).__name__}: {err}',
               'fallback_error': f'{type(fe).__name__}: {fe}'})
        return 1
    payload['platform'] = 'cpu-fallback'
    payload['fallback_reason'] = f'{type(err).__name__}: {err}'
    # a mode that commits a receipt file gets the TAGGED payload in it —
    # the committed receipt must say cpu-fallback, not the child's 'cpu'
    _write_receipt_file(payload)
    _emit(payload)
    return 0 if payload.get('value') is not None else 1


def main() -> int:
    mode = sys.argv[1] if len(sys.argv) > 1 else 'alexnet'
    if mode not in _MODES:
        print(f'unknown bench mode {mode!r}; choose from '
              f'{sorted(_MODES)}', file=sys.stderr)
        return 2
    metric, fn = _MODES[mode]
    try:
        if mode not in ('io', 'bench_io'):   # host-only: no device needed
            try:
                _ensure_backend()
            except BackendUnavailable as e:
                return _cpu_fallback(mode, e)
            # the chip is UP: heal any flash/int8 ledger entry still
            # stamped cpu-fallback before (not instead of) this run
            self_heal_receipts()
        return fn()
    except BaseException as e:           # noqa: BLE001 — one JSON line, always
        payload = {'metric': metric, 'value': None, 'unit': None,
                   'vs_baseline': None,
                   'error': f'{type(e).__name__}: {e}'}
        # the tunnel to the chip goes down for hours at a time; if this
        # run could not reach it, point at the last committed on-chip
        # receipt for the same mode so the measured number is still found
        receipt = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               'receipts', f'bench_{mode}.json')
        if os.path.exists(receipt):
            payload['last_committed_receipt'] = f'receipts/bench_{mode}.json'
        _emit(payload)
        return 1


if __name__ == '__main__':
    sys.exit(main())
