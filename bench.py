#!/usr/bin/env python
"""Benchmark: training throughput (images/sec/chip) on real hardware.

Default (what the driver runs) — AlexNet batch 256, prints ONE JSON line:
  {"metric": "alexnet_images_per_sec_per_chip", "value": N,
   "unit": "images/sec", "vs_baseline": N}

Extra modes for the BASELINE.md ledger (same JSON shape):
  python bench.py inception_bn     # Inception-BN batch 128 throughput
  python bench.py mnist_tta        # MNIST MLP time-to-2%-test-error (sec)

Baseline: the reference repo publishes no numbers (BASELINE.md).  We use
500 images/sec as the stand-in for cxxnet-CUDA AlexNet on a 2015-era
high-end GPU (Titan X class, cuDNN-era full fwd+bwd+update; see BASELINE.md
ledger) until a measured reference figure exists.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_IMAGES_PER_SEC = 500.0          # AlexNet stand-in (see docstring)
BASELINE_INCEPTION_IMAGES_PER_SEC = 130.0  # Inception-BN stand-in, same era
BASELINE_GOOGLENET_IMAGES_PER_SEC = 150.0  # GoogLeNet v1 stand-in, same era
BASELINE_MNIST_TTA_SEC = 30.0            # reference MNIST.conf CPU run


def _throughput(conf: str, batch_size: int, shape, metric: str,
                baseline: float, last_key: str) -> int:
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    import jax

    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()

    # raw uint8 pixels pre-staged on device: measures the full training
    # step (device-side cast/normalize + fwd + bwd + optimizer) per chip.
    # The dev-harness host link (a ~26MB/s tunnel to the remote chip) is
    # excluded — in production the input pipeline double-buffers H2D behind
    # compute (utils/thread_buffer + update_on_device).
    rng = np.random.RandomState(0)
    dev_batches = []
    for i in range(4):
        b = DataBatch(
            rng.randint(0, 256, (batch_size,) + shape, dtype=np.uint8),
            rng.randint(0, 1000, (batch_size, 1)).astype(np.float32))
        dev_batches.append((trainer._shard_batch(b.data),
                            trainer._shard_batch(b.label, cast=False)))

    # warmup: compile + 3 steps
    for i in range(3):
        trainer.update_on_device(*dev_batches[i % 4])
    jax.device_get(trainer.params[last_key]['bias'])

    steps = 30
    t0 = time.perf_counter()
    for i in range(steps):
        trainer.update_on_device(*dev_batches[i % 4])
    # force full sync: read back a small param slice
    jax.device_get(trainer.params[last_key]['bias'])
    dt = time.perf_counter() - t0

    ips = steps * batch_size / dt
    print(json.dumps({
        'metric': metric,
        'value': round(ips, 1),
        'unit': 'images/sec',
        'vs_baseline': round(ips / baseline, 3),
    }))
    return 0


def bench_alexnet() -> int:
    from cxxnet_tpu.models import alexnet_conf
    batch_size = 256
    conf = alexnet_conf() + f"""
batch_size = {batch_size}
eta = 0.01
momentum = 0.9
wmat:wd = 0.0005
bias:wd = 0.0
metric = error
eval_train = 0
random_type = xavier
compute_type = bfloat16
"""
    return _throughput(conf, batch_size, (3, 227, 227),
                       'alexnet_images_per_sec_per_chip',
                       BASELINE_IMAGES_PER_SEC, last_key='16')


def bench_inception_bn() -> int:
    from cxxnet_tpu.models import inception_bn_conf
    from cxxnet_tpu.nnet.net_config import NetConfig
    from cxxnet_tpu.utils.config import parse_config_string
    batch_size = 128
    conf = inception_bn_conf() + f"""
batch_size = {batch_size}
eta = 0.01
momentum = 0.9
metric = error
eval_train = 0
random_type = xavier
compute_type = bfloat16
"""
    # find the final fullc layer index for the sync read-back
    cfg = NetConfig()
    cfg.configure(parse_config_string(conf))
    last = max(i for i, e in enumerate(cfg.layers)
               if e.type == 1)  # kFullConnect
    return _throughput(conf, batch_size, (3, 224, 224),
                       'inception_bn_images_per_sec_per_chip',
                       BASELINE_INCEPTION_IMAGES_PER_SEC, last_key=str(last))


def bench_googlenet() -> int:
    from cxxnet_tpu.models import googlenet_conf
    from cxxnet_tpu.nnet.net_config import NetConfig
    from cxxnet_tpu.utils.config import parse_config_string
    batch_size = 128
    conf = googlenet_conf() + f"""
batch_size = {batch_size}
eta = 0.01
momentum = 0.9
metric = error
eval_train = 0
random_type = xavier
compute_type = bfloat16
"""
    cfg = NetConfig()
    cfg.configure(parse_config_string(conf))
    name_to_idx = {e.name: i for i, e in enumerate(cfg.layers) if e.name}
    return _throughput(conf, batch_size, (3, 224, 224),
                       'googlenet_images_per_sec_per_chip',
                       BASELINE_GOOGLENET_IMAGES_PER_SEC,
                       last_key=str(name_to_idx['loss3_fc']))


def bench_mnist_tta() -> int:
    """Time to 2% test error on synthetic-free real MNIST shapes is not
    possible offline; use the standard quadrant-blob surrogate (same
    tensor shapes/batch as MNIST.conf) and report wall-clock to 2% eval
    error including compile."""
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.models import mlp_conf
    from cxxnet_tpu.utils.config import parse_config_string

    conf = mlp_conf() + """
batch_size = 100
eta = 0.1
momentum = 0.9
metric = error
eval_train = 0
"""
    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()
    rng = np.random.RandomState(0)

    def blobs(n):
        y = rng.randint(0, 10, n)
        x = np.zeros((n, 784), np.float32)
        for i, c in enumerate(y):
            x[i, c * 78:(c + 1) * 78] = rng.rand(78)
        return x.reshape(n, 1, 1, 784), y.astype(np.float32).reshape(-1, 1)

    train = [DataBatch(*blobs(100)) for _ in range(60)]
    test = [DataBatch(*blobs(100)) for _ in range(10)]
    t0 = time.perf_counter()
    err, rounds = 1.0, 0
    while err > 0.02 and rounds < 15:
        trainer.start_round(rounds)
        for b in train:
            trainer.update(b)
        res = trainer.evaluate(iter(test), 'test')
        err = float(res.split(':')[-1])
        rounds += 1
    dt = time.perf_counter() - t0
    print(json.dumps({
        'metric': 'mnist_mlp_time_to_2pct_error',
        'value': round(dt, 2),
        'unit': 'sec',
        'vs_baseline': round(BASELINE_MNIST_TTA_SEC / dt, 3),
    }))
    return 0 if err <= 0.02 else 1


def main() -> int:
    modes = {'alexnet': bench_alexnet,
             'inception_bn': bench_inception_bn,
             'googlenet': bench_googlenet,
             'mnist_tta': bench_mnist_tta}
    mode = sys.argv[1] if len(sys.argv) > 1 else 'alexnet'
    if mode not in modes:
        print(f'unknown bench mode {mode!r}; choose from '
              f'{sorted(modes)}', file=sys.stderr)
        return 2
    return modes[mode]()


if __name__ == '__main__':
    sys.exit(main())
