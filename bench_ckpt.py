#!/usr/bin/env python
"""Benchmark: per-step checkpoint save stall, sync vs async, at
``save_every=1`` (the aggressive cadence the preemptible-fleet story
wants).  CPU platform — the stall under measure is host/storage work, so
no accelerator is needed and the ledger is reproducible anywhere.

Prints ONE JSON line (the BENCH_CKPT_rNN.json ledger shape):

  {"metric": "ckpt_save_stall_ms_per_step", "value": <async ms>,
   "sync_ms_per_step": S, "async_ms_per_step": A, "stall_ratio": S/A, ...}

*stall* is the wall time the STEP LOOP is blocked by the save boundary:
the full serialize+fsync+commit for the synchronous path
(``trainer.save_training_state``), versus snapshot+submit (plus any
double-buffer backpressure) for ``runtime.async_ckpt.AsyncCheckpointer``.
Every save leg gets one untimed warmup save (orbax/pool setup is one-time
cost, not per-step stall), and the bench restores both legs' final
checkpoints and asserts they are BITWISE equal before emitting — a ledger
entry can never describe an async path that drifted from sync bytes.

CLI overrides (``k=v``): ``steps=``, ``batch=``, ``nhidden=``,
``workers=`` (parsed with ``utils.config.cfg_get_int``).
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')

import numpy as np

MLP_CONF = """
netconfig=start
layer[+1] = fullc:fc1
  nhidden = {nhidden}
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = {nhidden}
layer[+1] = relu
layer[+1] = fullc:fc3
  nhidden = 10
layer[+0] = softmax
netconfig=end
input_shape = 1,1,784
batch_size = {batch}
dev = cpu
eta = 0.01
momentum = 0.9
metric = error
eval_train = 0
random_type = xavier
"""


def _fresh_trainer(batch: int, nhidden: int):
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    tr = NetTrainer(parse_config_string(
        MLP_CONF.format(batch=batch, nhidden=nhidden)))
    tr.init_model()
    return tr


def _batches(n: int, batch: int):
    from cxxnet_tpu.io.data import DataBatch
    rng = np.random.RandomState(0)
    return [DataBatch(rng.randn(batch, 1, 1, 784).astype(np.float32),
                      rng.randint(0, 10, (batch, 1)).astype(np.float32))
            for _ in range(n)]


def _state_bytes(tr) -> int:
    import jax
    tree = {'params': tr.params, 'opt_state': tr.opt_state,
            'grad_acc': tr.grad_acc}
    return sum(np.asarray(x).nbytes for x in jax.tree.leaves(tree))


def _params_host(tr):
    import jax
    return [np.asarray(x) for x in jax.tree.leaves(tr.params)]


def main() -> int:
    from cxxnet_tpu.utils.config import apply_cli_overrides, cfg_get_int
    cfg = apply_cli_overrides([], sys.argv[1:])
    steps = cfg_get_int(cfg, 'steps', 12)
    batch = cfg_get_int(cfg, 'batch', 200)
    nhidden = cfg_get_int(cfg, 'nhidden', 512)
    workers = cfg_get_int(cfg, 'workers', 8)

    import tempfile

    import jax

    from cxxnet_tpu.runtime.async_ckpt import AsyncCheckpointer

    batches = _batches(steps + 2, batch)   # 2 warmup + `steps` timed

    with tempfile.TemporaryDirectory() as tmp:
        # --- baseline step time (no saves), warmup/compile included up
        # front so neither leg pays tracing inside its timed region
        tr_sync = _fresh_trainer(batch, nhidden)
        tr_async = _fresh_trainer(batch, nhidden)
        tr_sync.update(batches[0])
        tr_async.update(batches[0])
        t0 = time.perf_counter()
        tr_sync.update(batches[1])
        step_ms = (time.perf_counter() - t0) * 1e3
        tr_async.update(batches[1])

        # --- sync leg: save_training_state at EVERY step --------------
        sdir = os.path.join(tmp, 'sync')
        tr_sync.save_training_state(sdir, 0)        # warmup (orbax setup)
        stall_sync = []
        for i, b in enumerate(batches[2:2 + steps]):
            tr_sync.update(b)
            t0 = time.perf_counter()
            tr_sync.save_training_state(sdir, tr_sync.sample_counter)
            stall_sync.append(time.perf_counter() - t0)

        # --- async leg: snapshot+submit at EVERY step -----------------
        adir = os.path.join(tmp, 'async')
        ck = AsyncCheckpointer(workers=workers)
        ck.save_sharded_async(adir, 0, tr_async.snapshot_training_state())
        ck.wait()                                   # warmup (pool spinup)
        stall_async = []
        for i, b in enumerate(batches[2:2 + steps]):
            tr_async.update(b)
            t0 = time.perf_counter()
            ck.save_sharded_async(adir, tr_async.sample_counter,
                                  tr_async.snapshot_training_state())
            stall_async.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        ck.wait()                                   # final save barriers
        final_barrier_ms = (time.perf_counter() - t0) * 1e3

        # --- the trust gate: async bytes must restore bitwise-equal ---
        last = tr_sync.sample_counter
        probe_s = _fresh_trainer(batch, nhidden)
        probe_a = _fresh_trainer(batch, nhidden)
        probe_s.load_training_state(sdir, step=last, restore_params=True)
        probe_a.load_training_state(adir, step=last, restore_params=True)
        bitwise = all((x == y).all() for x, y in
                      zip(_params_host(probe_s), _params_host(probe_a)))
        if not bitwise:
            raise AssertionError(
                'async-written checkpoint restored different bytes than '
                'its sync twin — ledger not emitted')
        state_mb = _state_bytes(tr_sync) / 1e6
        ck.close()

    sync_ms = 1e3 * sum(stall_sync) / len(stall_sync)
    async_ms = 1e3 * sum(stall_async) / len(stall_async)
    print(json.dumps({
        'metric': 'ckpt_save_stall_ms_per_step',
        'value': round(async_ms, 3),
        'unit': 'ms/step',
        'sync_ms_per_step': round(sync_ms, 3),
        'async_ms_per_step': round(async_ms, 3),
        'stall_ratio': round(sync_ms / async_ms, 2),
        'step_ms_nosave': round(step_ms, 3),
        'save_every': 1,
        'steps': steps,
        'state_mb': round(state_mb, 2),
        'workers': workers,
        'bitwise_restore_equal': True,
        'platform': jax.devices()[0].platform,
        'timing': 'mean stall over timed steps, one untimed warmup save '
                  'per leg; stall = wall time the step loop is blocked '
                  'at the save boundary',
    }))
    return 0


if __name__ == '__main__':
    sys.exit(main())
