#!/usr/bin/env python
"""Benchmark: online serving latency/throughput (doc/serving.md).

Prints ONE JSON line per run so future PRs get a serving perf trajectory
next to the training BENCH_*.json ledger.  Two modes:

``predict`` (default) — the PR 2 fixed-shape path::

  {"metric": "serve_p99_latency_ms", "value": P99, "unit": "ms",
   "p50_ms": P50, "mean_ms": M, "requests_per_sec": R,
   "rows_per_sec": RW, "compile_count": C, "buckets": [...],
   "clients": N, "duration_sec": D}

``decode`` — the continuous-batching decode engine (serve/decode.py)::

  {"metric": "decode_tokens_per_sec", "value": TPS, "unit": "tokens/sec",
   "token_p50_ms": P50, "token_p99_ms": P99, "streams": N,
   "shed": {"expired": E, "pages": P, "rejected": R},
   "gen_cache": {"hit": H, "miss": M}, "slots": S, "pages": PG, ...}

``decode_matrix`` — the serve.flash_decode x serve.dtype A/B grid over
ONE fixed seeded workload (doc/serving.md "Flash paged decode" /
"Quantized inference").  Every leg's streams are twin-asserted in-bench
against offline ``generate`` over that leg's own stored tree (the
BENCH_SCAN_r01 discipline: a receipt is only emitted for outputs proven
correct)::

  {"metric": "decode_int8_resident_reduction", "value": X, "unit": "x",
   "legs": [{"attention": "gather|flash", "dtype": "f32|bf16|int8",
             "tokens_per_sec": T, "token_p50_ms": P50,
             "token_p99_ms": P99, "resident_bytes": B,
             "twin_checked": N}, ...], "model": {...}}

``prefix`` — prefix-share ON vs OFF at 90% shared-prefix traffic
(doc/serving.md "Prefix sharing"): prefill-amortized tokens/sec (wall
includes every prefill) + time-to-first-token per leg, every stream
twin-asserted.  ``spec`` — greedy speculative decoding legs (draft off /
cold small draft / self-speculation twin): tokens/sec + acceptance rate,
every stream twin-asserted token-equal.  ``prefix_spec`` — both in one
receipt (the BENCH_SERVE_r04 shape)::

  {"metric": "prefix_share_speedup", "value": X, "unit": "x",
   "prefix": {"on": {...}, "off": {...}}, "spec": {"legs": [...]}}

``kv_tiers`` — graftcache (doc/serving.md "Tiered KV cache"): a prefix
working set larger than the HBM page pool served via host/disk tiers vs
cold prefill over identical round-robin traffic, every stream in both
legs twin-asserted (the BENCH_KV_r01 shape)::

  {"metric": "kv_tier_speedup", "value": X, "unit": "x",
   "warm": {"tokens_per_sec": T, "streams": N, "twin_checked": N,
            "kv_promoted_pages": P, "kv": {"hits": H, "spills": S,
            "disk_promote_pages": D, ...}},
   "cold": {"tokens_per_sec": T, "streams": N, "twin_checked": N},
   "cache_pages": CP, "hbm_pages": HP}   # guard re-checks CP > HP

``sharded`` — graftshard (doc/serving.md "Sharded serving"): decode
tokens/sec at ``tp:1/2/4`` under a fixed per-device page budget (the
mesh scales pool capacity, so the slot count riding it scales too) +
the prefill-disaggregation A/B (``prefill_workers=0`` vs ``2`` with a
long prompt at the head of the queue; the metric is the short crowd's
time-to-first-token p99 — what the knob buys is admission past the
head-of-line blocker), every leg's streams twin-asserted against a
HOST copy of the leg's tree (the BENCH_SHARD_r01 shape)::

  {"metric": "decode_shard_scaling", "value": X, "unit": "x",
   "legs": [{"tp": N, "tokens_per_sec": T, "streams": S,
             "twin_checked": S, "resident_bytes_per_device": [...]},
            ...],
   "disagg": {"off": {...}, "on": {...}, "short_ttft_improvement": I},
   "twin_violations": 0}

Method: a tiny model (random init — serving cost is shape-bound, not
value-bound) behind the real engine + DynamicBatcher stack;
``--clients`` in-process threads submit mixed-size requests (seeded)
back-to-back for ``--duration`` seconds after a warmup.  Decode clients
send mixed prompt lengths with staggered arrivals; per-token latency is
the gap between consecutive emissions of one stream.

Fallback policy (PR 5): when the accelerator backend cannot be reached
within ``CXXNET_BENCH_BACKEND_WAIT`` seconds the run re-executes pinned
to ``JAX_PLATFORMS=cpu`` and the receipt is tagged
``"platform": "cpu-fallback"`` — the ledger always records a number.
Env: CXXNET_SERVE_BENCH_* override the defaults below.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

NET_CFG = """
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 64
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 16
layer[+0] = softmax
netconfig=end
input_shape = 1,1,32
batch_size = 32
eta = 0.1
"""


def _backend_ok(budget: float) -> bool:
    """True when jax can reach a non-CPU backend (or CPU was asked for
    explicitly); bounded subprocess probe, same policy as bench.py."""
    plats = [p.strip() for p in
             os.environ.get('JAX_PLATFORMS', '').split(',') if p.strip()]
    if plats and all(p == 'cpu' for p in plats):
        return True                       # explicit CPU run: no probe
    try:
        r = subprocess.run(
            [sys.executable, '-c',
             'import jax; print(jax.devices()[0].platform)'],
            capture_output=True, text=True,
            timeout=max(20.0, min(180.0, budget)))
        return r.returncode == 0 and \
            (r.stdout or '').strip().splitlines()[-1:] != ['cpu']
    except subprocess.TimeoutExpired:
        return False


def _cpu_fallback(argv, reason: str) -> int:
    """Re-run this bench pinned to CPU and re-tag its receipt."""
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    r = subprocess.run([sys.executable, os.path.abspath(__file__)]
                      + list(argv or sys.argv[1:]),
                      env=env, capture_output=True, text=True,
                      timeout=3000)
    payload = None
    for line in reversed((r.stdout or '').strip().splitlines()):
        try:
            payload = json.loads(line)
            break
        except ValueError:
            continue
    if payload is None:
        print(json.dumps({'metric': 'serve_bench', 'value': None,
                          'error': f'cpu fallback produced no JSON '
                                   f'(rc={r.returncode})',
                          'fallback_reason': reason}))
        return 1
    payload['platform'] = 'cpu-fallback'
    payload['fallback_reason'] = reason
    print(json.dumps(payload))
    return 0 if payload.get('value') is not None else 1


def bench_predict(args) -> dict:
    from cxxnet_tpu import wrapper
    from cxxnet_tpu.serve import DynamicBatcher, PredictEngine
    from cxxnet_tpu.utils.bucketing import parse_buckets

    net = wrapper.Net(dev='', cfg=NET_CFG)
    net.set_param('inference_only', '1')
    net.init_model()
    buckets = parse_buckets(args.buckets)
    engine = PredictEngine(net._trainer, buckets)
    engine.warm()
    batcher = DynamicBatcher(engine, max_queue=4 * args.clients,
                             max_wait=args.max_wait, deadline=30.0)

    lat_ms = []
    rows_done = [0]
    lock = threading.Lock()
    stop = threading.Event()

    def client(cid: int) -> None:
        rng = np.random.RandomState(cid)
        while not stop.is_set():
            n = int(rng.randint(1, max(2, buckets[-1] // 2)))
            d = rng.randn(n, 1, 1, 32).astype(np.float32)
            t0 = time.monotonic()
            batcher.submit(d)
            dt = (time.monotonic() - t0) * 1e3
            with lock:
                lat_ms.append(dt)
                rows_done[0] += n

    threads = [threading.Thread(target=client, args=(cid,), daemon=True)
               for cid in range(args.clients)]
    warmup = min(0.5, args.duration / 4)
    for t in threads:
        t.start()
    time.sleep(warmup)
    with lock:          # measure steady state only
        lat_ms.clear()
        rows_done[0] = 0
    t_start = time.monotonic()
    time.sleep(args.duration)
    elapsed = time.monotonic() - t_start
    stop.set()
    for t in threads:
        t.join(10)
    batcher.close(timeout=10)

    arr = np.asarray(lat_ms)
    return {
        'metric': 'serve_p99_latency_ms',
        'value': round(float(np.quantile(arr, 0.99)), 4),
        'unit': 'ms',
        'p50_ms': round(float(np.quantile(arr, 0.5)), 4),
        'mean_ms': round(float(arr.mean()), 4),
        'requests_per_sec': round(arr.size / elapsed, 2),
        'rows_per_sec': round(rows_done[0] / elapsed, 2),
        'compile_count': engine.compile_count,
        'buckets': list(buckets),
        'clients': args.clients,
        'duration_sec': round(elapsed, 3),
        'platform': __import__('jax').default_backend(),
    }


def bench_decode(args) -> dict:
    """Continuous-batching decode: mixed prompt lengths, staggered
    arrivals, tokens/sec + per-token p50/p99 + shed counts."""
    from cxxnet_tpu.models import transformer as T
    from cxxnet_tpu.serve import ServeError
    from cxxnet_tpu.serve.decode import DecodeService

    cfg = T.TransformerConfig(vocab_size=256, d_model=64, num_heads=4,
                              d_ff=128, num_stages=2, seq_len=64,
                              attn='local')
    params = T.init_params(np.random.RandomState(0), cfg)
    svc = DecodeService(params, cfg, slots=args.slots, pages=args.pages,
                        page_size=args.page_size, max_prompt=32,
                        max_new_bound=args.max_new,
                        max_queue=4 * args.clients, deadline=60.0)
    stats = svc.engine.stats
    T.gen_cache_stats(reset=True)

    tok_gaps = []
    streams = [0]
    toks_done = [0]
    lock = threading.Lock()
    stop = threading.Event()

    def client(cid: int) -> None:
        rng = np.random.RandomState(1000 + cid)
        while not stop.is_set():
            s0 = int(rng.randint(1, 32))
            prompt = rng.randint(0, cfg.vocab_size, (1, s0)).astype(np.int32)
            try:
                req = svc.submit_async(prompt, args.max_new)
                svc.batcher.wait(req)
            except ServeError:
                continue           # shed: counted by the engine stats
            with lock:
                streams[0] += 1
                toks_done[0] += len(req.tokens)
                tt = req.token_times
                tok_gaps.extend((b - a) * 1e3 for a, b in zip(tt, tt[1:]))
            time.sleep(rng.uniform(0, 0.01))   # staggered arrivals

    threads = [threading.Thread(target=client, args=(cid,), daemon=True)
               for cid in range(args.clients)]
    for t in threads:
        t.start()
    time.sleep(min(1.0, args.duration / 3))    # warmup: compile + fill
    with lock:
        tok_gaps.clear()
        streams[0] = toks_done[0] = 0
    t_start = time.monotonic()
    time.sleep(args.duration)
    elapsed = time.monotonic() - t_start
    stop.set()
    for t in threads:
        t.join(30)
    svc.close(30)

    gaps = np.asarray(tok_gaps) if tok_gaps else np.asarray([float('nan')])
    gs = T.gen_cache_stats()
    return {
        'metric': 'decode_tokens_per_sec',
        'value': round(toks_done[0] / elapsed, 2),
        'unit': 'tokens/sec',
        'token_p50_ms': round(float(np.quantile(gaps, 0.5)), 4),
        'token_p99_ms': round(float(np.quantile(gaps, 0.99)), 4),
        'streams': streams[0],
        'streams_per_sec': round(streams[0] / elapsed, 2),
        'shed': {'expired': int(stats.get('expired')),
                 'pages': int(stats.get('shed_pages')),
                 'rejected': int(stats.get('rejected'))},
        'step_occupancy_p50': round(
            float(stats.quantile('step_occupancy', 0.5)), 3),
        # retrace visibility: the engine's own compiled programs (the
        # decode path never consults generate()'s cache; gen_cache is
        # here for surfaces that do — e.g. the CLI drive's twin check)
        'prefill_programs': int(stats.get('prefill_programs')),
        'gen_cache': {'hit': gs['hit'], 'miss': gs['miss']},
        'slots': args.slots, 'pages': args.pages,
        'page_size': args.page_size, 'max_new': args.max_new,
        'clients': args.clients,
        'duration_sec': round(elapsed, 3),
        'platform': __import__('jax').default_backend(),
    }


def bench_decode_matrix(args) -> dict:
    """A/B grid: gather-vs-flash attention x f32/bf16/int8 serving tier,
    ONE fixed seeded workload per leg so tokens/sec, per-token quantiles
    and resident_bytes compare like for like.  Twin-asserted in-bench."""
    import jax
    from cxxnet_tpu.models import transformer as T
    from cxxnet_tpu.serve.decode import DecodeService

    # params-heavy model (vocab dominates): the int8 tier's >=3x
    # resident claim is about real serving models, not toy trees whose
    # KV pool drowns the weights
    cfg = T.TransformerConfig(vocab_size=8192, d_model=256, num_heads=8,
                              d_ff=512, num_stages=2, seq_len=64,
                              attn='local')
    params = T.init_params(np.random.RandomState(0), cfg)
    rng = np.random.RandomState(args.seed)
    n_req = args.requests
    prompts = [rng.randint(0, cfg.vocab_size,
                           (1, int(rng.randint(1, args.max_prompt))))
               .astype(np.int32) for _ in range(n_req)]

    def run_leg(attention: str, dtype: str) -> dict:
        svc = DecodeService(
            params, cfg, slots=args.slots, pages=args.pages,
            page_size=args.page_size, max_prompt=args.max_prompt,
            max_new_bound=args.max_new, max_queue=2 * n_req,
            deadline=600.0, dtype=dtype,
            flash_decode=1 if attention == 'flash' else 0)
        try:
            warm = svc.submit_async(prompts[0], args.max_new)
            svc.batcher.wait(warm)            # compile outside the clock
            t0 = time.monotonic()
            reqs = [svc.submit_async(p, args.max_new) for p in prompts]
            toks, gaps = 0, []
            for r in reqs:
                svc.batcher.wait(r)
                toks += len(r.tokens)
                tt = r.token_times
                gaps.extend((b - a) * 1e3 for a, b in zip(tt, tt[1:]))
            wall = time.monotonic() - t0
            # twin gate (BENCH_SCAN_r01 discipline): every tier's oracle
            # is generate() over the ENGINE's stored tree + compute cfg
            checked = 0
            for i in range(min(args.twin_checks, n_req)):
                off = np.asarray(T.generate(
                    svc.engine.params, prompts[i], args.max_new,
                    svc.engine.cfg))[0]
                got = np.asarray(reqs[i].result)
                assert (got == off[:len(got)]).all(), (
                    f'{attention}/{dtype} stream {i} diverged from its '
                    f'offline twin')
                checked += 1
            def q(p):
                # null, not NaN, when a leg produced no inter-token gaps
                # (e.g. --max-new 1): the receipt is strict JSON
                if not gaps:
                    return None
                return round(float(np.quantile(np.asarray(gaps), p)), 4)

            return {
                'attention': attention, 'dtype': dtype,
                'tokens_per_sec': round(toks / wall, 2),
                'token_p50_ms': q(0.5),
                'token_p99_ms': q(0.99),
                'resident_bytes': int(svc.engine.resident_bytes()),
                'streams': n_req, 'twin_checked': checked,
                'wall_sec': round(wall, 3),
            }
        finally:
            svc.close(60)

    legs = [run_leg(attention, dtype)
            for attention in ('gather', 'flash')
            for dtype in ('f32', 'bf16', 'int8')]
    by = {(l['attention'], l['dtype']): l for l in legs}
    reduction = (by[('gather', 'f32')]['resident_bytes']
                 / by[('gather', 'int8')]['resident_bytes'])
    return {
        'metric': 'decode_int8_resident_reduction',
        'value': round(reduction, 2),
        'unit': 'x',
        'legs': legs,
        'model': {'vocab': cfg.vocab_size, 'd_model': cfg.d_model,
                  'heads': cfg.num_heads, 'd_ff': cfg.d_ff,
                  'stages': cfg.num_stages},
        'slots': args.slots, 'pages': args.pages,
        'page_size': args.page_size, 'max_new': args.max_new,
        'requests': n_req,
        'platform': jax.default_backend(),
    }


def _decode_model():
    """The shared decode-bench model (random init — serving cost is
    shape-bound, not value-bound)."""
    from cxxnet_tpu.models import transformer as T
    cfg = T.TransformerConfig(vocab_size=256, d_model=64, num_heads=4,
                              d_ff=128, num_stages=2, seq_len=64,
                              attn='local')
    return T.init_params(np.random.RandomState(0), cfg), cfg


def _drive_leg(svc, prompts, max_new, twin_all=True):
    """Submit every prompt, wait, twin-assert EVERY stream against its
    offline generate (BENCH_SCAN_r01 discipline: a receipt is only
    emitted for outputs proven correct).  Returns (tokens, wall_sec,
    ttft_ms list)."""
    from cxxnet_tpu.models import transformer as T
    t0 = time.monotonic()
    reqs = [svc.submit_async(p, max_new) for p in prompts]
    toks, ttft = 0, []
    for r in reqs:
        svc.batcher.wait(r)
        toks += len(r.tokens)
        ttft.append((r.token_times[0] - r.t_submit) * 1e3)
    wall = time.monotonic() - t0
    checked = 0
    # sharded engines oracle against a HOST copy of the params — the
    # offline reference must never itself compile SPMD
    oracle = getattr(svc.engine, 'oracle_params',
                     lambda: svc.engine.params)()
    for p, r in zip(prompts, reqs):
        off = np.asarray(T.generate(oracle, p, max_new,
                                    svc.engine.cfg))[0]
        got = np.asarray(r.result)
        assert (got == off[:len(got)]).all(), (
            f'stream {checked} diverged from its offline twin')
        checked += 1
        if not twin_all and checked >= 3:
            break
    return toks, wall, ttft, checked


def bench_prefix(args) -> dict:
    """Prefix-share ON vs OFF over identical 90%-shared traffic:
    prefill-amortized tokens/sec (the wall clock includes every
    prefill) and time-to-first-token, every stream twin-asserted.

    The workload is the shape the amortization thesis targets: a long
    PAGE-ALIGNED system prefix (31 of 32 pages) + a one-page unique
    tail per request, short generations — sharing requires the same
    prompt bucket and pad width (doc/serving.md "Prefix sharing"), so
    90% of requests splice 31 pages and prefill one."""
    import jax
    from cxxnet_tpu.models import transformer as T
    from cxxnet_tpu.serve.decode import DecodeService

    cfg = T.TransformerConfig(vocab_size=512, d_model=128, num_heads=8,
                              d_ff=512, num_stages=2, seq_len=512,
                              attn='local')
    params = T.init_params(np.random.RandomState(0), cfg)
    ps = args.page_size
    plen = 31 * ps
    total = plen + ps
    max_new = int(os.environ.get('CXXNET_SERVE_BENCH_PREFIX_MAX_NEW', 2))
    pages = max(args.pages, 384)
    rng = np.random.RandomState(args.seed)
    prefix = rng.randint(0, cfg.vocab_size, (1, plen)).astype(np.int32)
    prompts = []
    for i in range(args.requests):
        if i % 10 == 9:                            # the 10% cold minority
            prompts.append(rng.randint(0, cfg.vocab_size,
                                       (1, total)).astype(np.int32))
        else:
            tail = rng.randint(0, cfg.vocab_size, (1, ps)).astype(np.int32)
            prompts.append(np.concatenate([prefix, tail], axis=1))

    def leg(share: bool) -> dict:
        svc = DecodeService(
            params, cfg, slots=args.slots, pages=pages,
            page_size=ps, max_prompt=total,
            max_new_bound=max_new, max_queue=2 * args.requests,
            deadline=600.0, prefix_share=pages // 2 if share else 0)
        try:
            # warmup outside the clock: compiles prefill + tail-prefill
            # + the step (and, with sharing on, publishes the prefix —
            # the pay-once half of the amortization thesis)
            for p in prompts[:2]:
                svc.batcher.wait(svc.submit_async(p, max_new))
            toks, wall, ttft, checked = _drive_leg(svc, prompts, max_new)
            st = svc.engine.stats
            return {
                'prefix_share': bool(share),
                'tokens_per_sec': round(toks / wall, 2),
                'ttft_p50_ms': round(float(np.quantile(ttft, 0.5)), 3),
                'ttft_p99_ms': round(float(np.quantile(ttft, 0.99)), 3),
                'wall_sec': round(wall, 3),
                'streams': len(prompts), 'twin_checked': checked,
                'prefix_hits': int(st.get('prefix_hits')),
                'prefix_misses': int(st.get('prefix_misses')),
                'cow_copies': int(st.get('cow_copies')),
                'shared_page_splices': int(st.get('prefix_hit_pages')),
                'free_pages_min': int(svc.engine._free_min),
            }
        finally:
            svc.close(60)

    on, off = leg(True), leg(False)
    return {
        'metric': 'prefix_share_speedup',
        'value': round(on['tokens_per_sec'] / off['tokens_per_sec'], 2),
        'unit': 'x',
        'on': on, 'off': off,
        'shared_fraction': 0.9, 'prefix_pages': 31,
        'prompt_tokens': total,
        'model': {'vocab': cfg.vocab_size, 'd_model': cfg.d_model,
                  'heads': cfg.num_heads, 'd_ff': cfg.d_ff,
                  'stages': cfg.num_stages},
        'requests': args.requests, 'max_new': max_new,
        'page_size': ps, 'slots': args.slots,
        'platform': jax.default_backend(),
    }


def bench_spec(args) -> dict:
    """Greedy speculative decoding: draft-off baseline vs a cold small
    draft vs the self-speculation twin draft (acceptance upper bound),
    one seeded workload, every stream twin-asserted token-equal."""
    import jax
    from cxxnet_tpu.models import transformer as T
    from cxxnet_tpu.serve.decode import DecodeService

    params, cfg = _decode_model()
    dcfg = T.TransformerConfig(vocab_size=cfg.vocab_size, d_model=16,
                               num_heads=2, d_ff=32, num_stages=1,
                               seq_len=cfg.seq_len, attn='local')
    dparams = T.init_params(np.random.RandomState(1), dcfg)
    rng = np.random.RandomState(args.seed)
    prompts = [rng.randint(0, cfg.vocab_size,
                           (1, int(rng.randint(2, args.max_prompt))))
               .astype(np.int32) for _ in range(args.requests)]

    def leg(name: str, draft, spec_k: int) -> dict:
        svc = DecodeService(
            params, cfg, slots=args.slots, pages=args.pages,
            page_size=args.page_size, max_prompt=args.max_prompt,
            max_new_bound=args.max_new, max_queue=2 * args.requests,
            deadline=600.0, spec_k=spec_k, draft=draft)
        try:
            svc.batcher.wait(svc.submit_async(prompts[0], args.max_new))
            toks, wall, _, checked = _drive_leg(svc, prompts,
                                                args.max_new)
            st = svc.engine.stats
            proposed = st.get('spec_proposed')
            return {
                'draft': name,
                'tokens_per_sec': round(toks / wall, 2),
                'wall_sec': round(wall, 3),
                'streams': len(prompts), 'twin_checked': checked,
                'spec_k': spec_k,
                'spec_proposed': int(proposed),
                'spec_accepted': int(st.get('spec_accepted')),
                'acceptance_rate': round(
                    st.get('spec_accepted') / proposed, 3)
                if proposed else None,
                'decode_steps': int(st.get('decode_steps')),
            }
        finally:
            svc.close(60)

    legs = [leg('off', None, 0),
            leg('small', (dparams, dcfg), args.spec_k),
            leg('twin', (params, cfg), args.spec_k)]
    base = legs[0]['tokens_per_sec']
    best = max(legs[1:], key=lambda leg_: leg_['tokens_per_sec'])
    out = {
        'metric': 'spec_decode_speedup',
        'value': round(best['tokens_per_sec'] / base, 2),
        'unit': 'x',
        'best_draft': best['draft'],
        'legs': legs,
        'requests': args.requests, 'max_new': args.max_new,
        'spec_k': args.spec_k, 'slots': args.slots,
        'platform': jax.default_backend(),
    }
    if out['platform'] == 'cpu':
        # random-init models make any CHEAPER draft disagree with the
        # target (acceptance ~0), and on compute-bound CPU the verify
        # window saves no arithmetic — the same receipt-reading rule as
        # BENCH_SERVE_r03's flash rows: cpu legs prove token-equality
        # and report acceptance; the speed claim is the on-chip one
        # (one K-window pass costs ~one step of HBM weight traffic)
        out['note'] = ('cpu legs prove correctness + acceptance '
                       'accounting, not speed; see doc/benchmarks.md')
    return out


def bench_prefix_spec(args) -> dict:
    """The BENCH_SERVE_r04 receipt: both multipliers over one config —
    the prefix-share A/B (headline) plus the spec-decode legs."""
    prefix = bench_prefix(args)
    spec = bench_spec(args)
    return {
        'metric': 'prefix_share_speedup',
        'value': prefix['value'],
        'unit': 'x',
        'prefix': prefix,
        'spec': spec,
        'platform': prefix['platform'],
    }


def bench_kv_tiers(args) -> dict:
    """graftcache: a prefix working set LARGER than the HBM page pool
    served through the host/disk tiers vs cold prefill (doc/serving.md
    "Tiered KV cache").

    The workload is N distinct long page-aligned prefixes (each 31
    pages) + one-page unique tails, all prompts exactly one 512-token
    size class (sharing requires the same prompt bucket and pad
    width).  The pool
    is capped TIGHT — the full prefix working set cannot fit in HBM —
    and the index cap holds barely one prefix, so round-robin traffic
    forces the demote -> spill -> prefetch -> promote cycle on nearly
    every arrival instead of riding tier-0 index hits.  The COLD leg
    serves the identical scored traffic with no cache at all (pure
    prefill — the re-prefill cost a promote avoids).  Every stream in
    BOTH legs is twin-asserted in-bench against offline ``generate``
    (the BENCH_SCAN_r01 discipline), and the receipt carries the
    cache-vs-HBM page accounting the guard re-checks."""
    import shutil
    import tempfile

    import jax
    from cxxnet_tpu.models import transformer as T
    from cxxnet_tpu.serve.decode import DecodeService

    # a fat MLP (d_ff 16x d_model): prefill FLOPs per token dwarf the
    # promote path's per-token record bytes, which is exactly the regime
    # the tier thesis targets — repaying cached K/V beats recomputing it
    cfg = T.TransformerConfig(vocab_size=512, d_model=128, num_heads=8,
                              d_ff=2048, num_stages=2, seq_len=1024,
                              attn='local')
    params = T.init_params(np.random.RandomState(0), cfg)
    ps = args.page_size
    prefix_pages = 31
    plen = prefix_pages * ps
    total = plen + ps        # 512 — exactly one prompt size class (w=0)
    max_new = int(os.environ.get('CXXNET_SERVE_BENCH_KV_MAX_NEW', 2))
    n_prefixes = int(os.environ.get('CXXNET_SERVE_BENCH_KV_PREFIXES', 6))
    # tight HBM: barely one stream + one indexed prefix; the cached
    # working set (n_prefixes * prefix_pages pages) cannot fit
    pages = 48
    slots = 2
    # publish covers prefix AND tail page (total // ps pages), so the
    # cap needs one page of slack past that to accept a whole prompt
    share_cap = prefix_pages + 2
    rng = np.random.RandomState(args.seed)
    prefixes = [rng.randint(0, cfg.vocab_size, (1, plen)).astype(np.int32)
                for _ in range(n_prefixes)]

    def tailed(pfx):
        tail = rng.randint(0, cfg.vocab_size, (1, ps)).astype(np.int32)
        return np.concatenate([pfx, tail], axis=1)

    prime = [tailed(p) for p in prefixes]
    # scored: four visits per prefix, round-robin — consecutive
    # arrivals never share a prefix, so the one-prefix index cap forces
    # a promote (not a tier-0 hit) on nearly every request; enough
    # streams that per-arrival scheduling noise averages out of the
    # ratio
    scored = [tailed(prefixes[i % n_prefixes])
              for i in range(4 * n_prefixes)]

    def drive_serial(svc, prompts, reps=3):
        """Pipelined submit, in-order wait: the admit thread drains the
        queue FIFO (round-robin prefix order — the tier churn — is
        preserved), but the next admission overlaps the previous
        stream's decode instead of paying a submit->admit handoff per
        request.  The pass repeats ``reps`` times and the BEST wall
        scores (the tier state is cyclic — every pass promotes the same
        chains — so min-of-N removes scheduler noise, not work).  Every
        stream twin-asserted."""
        walls = []
        for _ in range(reps):
            t0 = time.monotonic()
            reqs = [svc.submit_async(p, max_new) for p in prompts]
            for r in reqs:
                svc.batcher.wait(r)
            walls.append(time.monotonic() - t0)
        toks = sum(len(r.tokens) for r in reqs)
        wall = min(walls)
        checked = 0
        for p, r in zip(prompts, reqs):
            off = np.asarray(T.generate(svc.engine.params, p, max_new,
                                        svc.engine.cfg))[0]
            got = np.asarray(r.result)
            assert (got == off[:len(got)]).all(), (
                f'stream {checked} diverged from its offline twin')
            checked += 1
        return toks, wall, checked

    kv_root = tempfile.mkdtemp(prefix='cxxnet-bench-kv-')
    try:
        warm_svc = DecodeService(
            params, cfg, slots=slots, pages=pages, page_size=ps,
            max_prompt=total, max_new_bound=max_new,
            max_queue=4 * len(scored), deadline=600.0,
            prefix_share=share_cap, kv_host_mb=4, kv_disk_mb=64,
            kv_dir=os.path.join(kv_root, 'records'))
        try:
            eng = warm_svc.engine
            # priming pass: prefill each prefix once; the one-prefix
            # index cap demotes every earlier prefix down-tier (host
            # overflows to disk records)
            for p in prime:
                warm_svc.batcher.wait(warm_svc.submit_async(p, max_new))
            assert eng._kv.flush(60.0), 'spill queue never drained'
            # warmup outside the clock: TWO concurrent promote-shaped
            # arrivals compile the tail prefill, the batched upload
            # scatter AND the occupancy-2 step program (prime arrivals
            # were serial full-prefill misses, so all of those are
            # still cold — a first compile inside the clock would be
            # the artifact, not the tiers).  prefixes[0]/[1] — the
            # COLDEST prefixes, disk-only by now — so the warmup walks
            # the full disk -> host -> HBM promote path, not a tier-0
            # index hit that would leave those programs uncompiled
            wreqs = [warm_svc.submit_async(tailed(prefixes[i]), max_new)
                     for i in range(2)]
            for r in wreqs:
                warm_svc.batcher.wait(r)
            toks, wall, checked = drive_serial(warm_svc, scored)
            eng.kv_occupancy()               # fold tier gauges
            ks = eng.kv_stats
            cache_bytes = int(ks.get('host_bytes') + ks.get('disk_bytes'))
            pool_bytes = int(eng._kpool.nbytes + eng._vpool.nbytes)
            page_bytes = pool_bytes // eng.n_pages   # K+V, all stages
            cache_pages = cache_bytes // page_bytes
            warm = {
                'tokens_per_sec': round(toks / wall, 2),
                'wall_sec': round(wall, 3),
                'streams': len(scored), 'twin_checked': checked,
                'kv_promoted_pages': int(
                    eng.stats.get('kv_promoted_pages')),
                'kv_uploads': int(eng.stats.get('kv_uploads')),
                'prefix_hits': int(eng.stats.get('prefix_hits')),
                'kv': {k: int(ks.get(k)) for k in
                       ('hits', 'misses', 'demote_pages',
                        'promote_pages', 'disk_promote_pages', 'spills',
                        'host_bytes', 'disk_bytes',
                        'corrupt_quarantined')},
                'promote_ms_p50': round(ks.quantile('promote_ms', 0.5),
                                        3),
                'promote_ms_p99': round(ks.quantile('promote_ms', 0.99),
                                        3),
            }
        finally:
            warm_svc.close(60)

        cold_svc = DecodeService(
            params, cfg, slots=slots, pages=pages, page_size=ps,
            max_prompt=total, max_new_bound=max_new,
            max_queue=4 * len(scored), deadline=600.0, prefix_share=0)
        try:
            # warmup compiles only (two concurrent throwaway streams —
            # the occupancy-2 step program must be warm here too)
            creqs = [cold_svc.submit_async(prime[i], max_new)
                     for i in range(2)]
            for r in creqs:
                cold_svc.batcher.wait(r)
            ctoks, cwall, cchecked = drive_serial(cold_svc, scored)
            cold = {
                'tokens_per_sec': round(ctoks / cwall, 2),
                'wall_sec': round(cwall, 3),
                'streams': len(scored), 'twin_checked': cchecked,
            }
        finally:
            cold_svc.close(60)
    finally:
        shutil.rmtree(kv_root, ignore_errors=True)

    hbm_pages = pages - 1                    # page 0 is scratch
    assert cache_pages > hbm_pages, (
        f'the tiered cache holds {cache_pages} pages — not larger than '
        f'the {hbm_pages}-page HBM pool; the bench proves nothing')
    assert warm['kv_promoted_pages'] > 0 and \
        warm['kv']['disk_promote_pages'] > 0, (
        'warm leg never promoted through the tiers')
    return {
        'metric': 'kv_tier_speedup',
        'value': round(warm['tokens_per_sec'] / cold['tokens_per_sec'],
                       2),
        'unit': 'x',
        'warm': warm, 'cold': cold,
        'cache_pages': int(cache_pages), 'hbm_pages': int(hbm_pages),
        'cache_bytes': cache_bytes, 'pool_bytes': pool_bytes,
        'prefixes': n_prefixes, 'prefix_pages': prefix_pages,
        'prompt_tokens': total, 'page_size': ps, 'slots': slots,
        'reps': 3, 'kv_host_mb': 4, 'kv_disk_mb': 64,
        'max_new': max_new,
        'model': {'vocab': cfg.vocab_size, 'd_model': cfg.d_model,
                  'heads': cfg.num_heads, 'd_ff': cfg.d_ff,
                  'stages': cfg.num_stages},
        'platform': jax.default_backend(),
    }


def bench_scenarios(args) -> dict:
    """graftstorm: adversarial traffic scenarios scored static vs
    autoscale-on (doc/serving.md "Scenarios and autoscaling").

    ONE physical engine serves every leg (the compiled step, params and
    page pool are identical); the STATIC leg pins the live admission
    caps at a tight baseline, the AUTOSCALE leg starts at the same
    baseline and lets the SLO-driven autoscaler grow toward the
    physical ceiling under queue-pressure verdicts.  Same seeded storm
    both legs, so the delta is the autoscaler and nothing else.  Every
    leg's served streams are twin-asserted against offline ``generate``
    (the BENCH_SCAN_r01 discipline), and the ledger must reconcile
    exactly against the service counters — a shed percentage here
    cannot be a silently-dropped request.  The last leg composes a
    ``slow_step@every`` FaultPlan with a flash crowd in one run: zero
    twin violations, typed sheds only."""
    import jax
    from cxxnet_tpu.models import transformer as T
    from cxxnet_tpu.runtime import faults
    from cxxnet_tpu.serve.autoscale import AutoscalePolicy, Autoscaler
    from cxxnet_tpu.serve.decode import DecodeService
    from cxxnet_tpu.serve.scenario import ScenarioLedger, ScenarioSpec, drive

    params, cfg = _decode_model()
    svc = DecodeService(params, cfg, slots=args.slots, pages=args.pages,
                        page_size=8, max_prompt=24, max_new_bound=8,
                        eos_id=None, max_queue=32,
                        max_wait=args.max_wait, deadline=8.0)
    eng = svc.engine
    tight = {'max_slots': 1, 'max_pages': 6}
    # hysteresis=3 + cooldown=0.05 damp trough-shrinking under periodic
    # (diurnal) load — with faster shrink the knobs sag in every trough
    # and the next peak lands on shrunk capacity
    policy = AutoscalePolicy.parse(
        'min_slots=1;min_pages=2;min_queue=4;'
        'cooldown=0.05;hysteresis=3;step=2')

    def verdicts():
        # queue-pressure verdict, the SLO engine's stand-in: the bench
        # must stay deterministic-ish and self-contained, and the hub
        # path is proven by pytest -m scenario.  BREACHED means the
        # queue is about to overflow (28 of 32) — classing a drainable
        # burst as BREACHED trips the degrade rung and mass-sheds
        depth = svc.batcher.depth()
        cv = eng.capacity_view()
        if depth >= 28:
            state = 'BREACHED'
        elif depth >= 2 or cv['occupied'] >= cv['live_slot_cap']:
            state = 'AT_RISK'
        else:
            state = 'OK'
        return {'queue': {'state': state}}

    scenarios = [
        ('steady', 'shape=steady;seed=101;requests=60;qps=400;'
                   'max_prompt=16;max_new=8'),
        ('flash', 'shape=flash;seed=102;requests=64;qps=300;burst=16;'
                  'max_prompt=16;max_new=8'),
        ('heavy_tail', 'shape=heavy_tail;seed=103;requests=60;qps=400;'
                       'tail=1.1;max_prompt=24;max_new=8'),
        ('diurnal_abandon', 'shape=diurnal;seed=104;requests=60;qps=400;'
                            'abandon=0.35;patience=0.04;'
                            'max_prompt=16;max_new=8'),
    ]

    def twin_check(spec, led):
        sched = spec.schedule()
        for idx, stream in led.streams.items():
            prompt = spec.prompt_for(idx, sched[idx].prompt_len,
                                     cfg.vocab_size)
            off = np.asarray(T.generate(eng.params, prompt,
                                        sched[idx].max_new, eng.cfg))[0]
            got = np.asarray(stream)
            assert (got == off[:len(got)]).all(), \
                f'stream {idx} diverged from its offline twin'
        return len(led.streams)

    def run_leg(spec, autoscale):
        eng.set_live_limits(**tight)
        svc.batcher.set_max_queue(32)
        scaler, on_tick = None, None
        if autoscale:
            scaler = Autoscaler(policy, verdicts=verdicts,
                                gauges=lambda: {})
            scaler.bind_engine(eng)      # tight caps ARE the baseline
            scaler.bind_batcher(svc.batcher)
            on_tick = lambda _t: scaler.evaluate()
        base = ScenarioLedger.stat_snapshot(eng.stats)
        t0 = time.monotonic()
        led = drive(svc, spec, vocab=cfg.vocab_size, on_tick=on_tick)
        wall = time.monotonic() - t0
        led.reconcile(eng.stats, base=base)
        checked = twin_check(spec, led)
        s = led.summary()
        row = {
            'served': s['served'], 'shed': led.shed(),
            'abandoned': s['abandoned'],
            'loss': led.shed() + s['abandoned'],
            'p50_ms': None if s['p50_s'] is None else s['p50_s'] * 1e3,
            'p99_ms': None if s['p99_s'] is None else s['p99_s'] * 1e3,
            'wall_sec': wall, 'twin_checked': checked,
        }
        if scaler is not None:
            hist = scaler.history()
            row['actions'] = len(hist)
            row['degraded'] = scaler.degraded
            # sustained OK drifts knobs back to baseline, so final caps
            # alone hide the storm response — record the peak too
            row['peak_slots'] = max(
                [a['to'] for a in hist if a['knob'] == 'slots'],
                default=tight['max_slots'])
            row['peak_pages'] = max(
                [a['to'] for a in hist if a['knob'] == 'pages'],
                default=tight['max_pages'])
            row['final_caps'] = list(eng.live_limits())
            scaler.close()
        return row

    def warm(spec):
        # an unscored throwaway drive at physical caps: pre-pays the
        # per-prompt-length XLA compiles AND first-use batcher-path
        # state so the FIRST scored leg isn't charged costs the second
        # leg then gets for free (A/B fairness — serial ``generate``
        # warmup demonstrably does not cover the submit_async path)
        eng.set_live_limits(max_slots=args.slots,
                            max_pages=args.pages - 1)
        drive(svc, spec, vocab=cfg.vocab_size)

    rows, wins = [], 0
    try:
        for name, spec_text in scenarios:
            spec = ScenarioSpec.parse(spec_text)
            warm(spec)
            static = run_leg(spec, autoscale=False)
            scaled = run_leg(spec, autoscale=True)
            # the autoscaler wins a scenario by losing strictly fewer
            # requests (typed sheds + client abandons), or losing the
            # same with p99 no worse than 110% of static
            if scaled['loss'] < static['loss']:
                win = True
            elif scaled['loss'] == static['loss']:
                sp, tp = scaled['p99_ms'], static['p99_ms']
                win = sp is not None and tp is not None and sp <= tp * 1.1
            else:
                win = False
            wins += bool(win)
            rows.append({'name': name, 'spec': spec.describe(),
                         'static': static, 'autoscale': scaled,
                         'win': bool(win)})

        # the composed chaos drill: slow_step@every faults + flash crowd
        # + autoscaler in ONE run — zero twin violations, typed-only sheds
        plan = faults.FaultPlan.parse('seed=1;slow_step@every=4:0.004')
        chaos_spec = ScenarioSpec.parse(
            'shape=flash;seed=105;requests=32;qps=120;burst=8;'
            'max_prompt=16;max_new=6')
        warm(chaos_spec)
        eng.set_live_limits(**tight)
        scaler = Autoscaler(policy, verdicts=verdicts, gauges=lambda: {})
        scaler.bind_engine(eng)
        scaler.bind_batcher(svc.batcher)
        base = ScenarioLedger.stat_snapshot(eng.stats)
        prev = faults.install_plan(plan)
        try:
            led = drive(svc, chaos_spec, vocab=cfg.vocab_size,
                        on_tick=lambda _t: scaler.evaluate())
        finally:
            faults.install_plan(prev)
        led.reconcile(eng.stats, base=base)
        checked = twin_check(chaos_spec, led)
        fired = [t for t in plan.fired() if t.startswith('slow_step')]
        assert fired, 'the chaos plan never fired'
        # typed-only: engine_errors is the one bucket that could hide an
        # untyped failure; reconcile already proved nothing fell outside
        assert led.counts['engine_errors'] == 0, led.summary()
        s = led.summary()
        chaos = {'spec': chaos_spec.describe(),
                 'fault_plan': plan.describe(),
                 'slow_steps_fired': len(fired),
                 'twin_checked': checked, 'twin_violations': 0,
                 'untyped_sheds': 0, **s}
        for k in ('p50_s', 'p99_s'):
            v = chaos.pop(k)
            chaos[k.replace('_s', '_ms')] = None if v is None else v * 1e3
        scaler.close()
    finally:
        svc.close(30.0)

    return {
        'metric': 'scenario_autoscale_wins', 'value': wins,
        'unit': 'scenarios', 'total_scenarios': len(rows),
        'policy': policy.describe(),
        'tight_caps': tight, 'scenarios': rows, 'chaos': chaos,
        'engine': {'slots': args.slots, 'pages': args.pages,
                   'vocab': cfg.vocab_size, 'd_model': cfg.d_model},
        'platform': jax.default_backend(),
    }


def bench_sharded(args) -> dict:
    """graftshard ledger (doc/serving.md "Sharded serving"): decode
    tokens/sec at tp:1/2/4 under a FIXED PER-DEVICE page budget — the
    mesh is a capacity lever: the pool (and the slot count feeding it)
    scales with the shard width while each device's slice stays one
    chip's share, so at tp:1 a crowd round-robining over shared prompt
    stems thrashes the prefix index (full stem prefill per stream)
    while the tp:4 pool keeps every stem resident (page splices) —
    plus the prefill-disaggregation A/B (``prefill_workers=0`` vs
    ``2``) reading the short crowd's TTFT p99 past a long head-of-line
    prompt.  Every leg's streams twin-asserted in-bench against
    offline ``generate`` over a host copy of the leg's own tree."""
    import jax
    from cxxnet_tpu.serve.decode import DecodeService

    ndev = len(jax.devices())
    widths = [tp for tp in (1, 2, 4) if tp <= ndev]
    from cxxnet_tpu.models import transformer as T
    # a wider body than the shared decode-bench model: the quantity
    # under test is AVOIDED stem-prefill compute, so the stem prefill
    # must dwarf per-call dispatch overhead or the ledger reads noise
    cfg = T.TransformerConfig(vocab_size=256, d_model=256, num_heads=4,
                              d_ff=1024, num_stages=2, seq_len=64,
                              attn='local')
    params = T.init_params(np.random.RandomState(1), cfg)
    ps = args.page_size
    max_new = int(os.environ.get('CXXNET_SERVE_BENCH_SHARD_MAX_NEW', 8))
    rng = np.random.RandomState(args.seed)
    # Residency workload: the crowd round-robins over a few long shared
    # prompt stems.  The per-device page budget is ONE stream's worth,
    # so the tp:1 pool cannot keep a stem's prefix pages resident past
    # the next stem's admission (reclaim evicts them) and every stream
    # pays the full stem prefill again; the tp:4 pool holds every stem
    # and streams splice cached pages instead — HBM capacity scaling
    # the mesh buys, read out as aggregate tokens/sec.
    stem_len = 60 * ps                     # prefills the 1024 bucket
    n_stems = int(os.environ.get('CXXNET_SERVE_BENCH_SHARD_STEMS', 3))
    reps = 8
    stems = [rng.randint(0, cfg.vocab_size,
                         (1, stem_len)).astype(np.int32)
             for _ in range(n_stems)]
    prompts = [stems[i % n_stems] for i in range(n_stems * reps)]
    s0b = T._size_class(stem_len, floor=8)
    # exactly one stream's pages per device: prompt pages + decode tail
    pages_per_dev = int(os.environ.get(
        'CXXNET_SERVE_BENCH_SHARD_PAGES',
        (s0b + max_new - 2) // ps + 1))

    legs = []
    violations = 0
    for tp in widths:
        svc = DecodeService(
            params, cfg, slots=2 * tp, pages=1 + pages_per_dev * tp,
            page_size=ps, max_prompt=stem_len, max_new_bound=max_new,
            max_queue=4 * len(prompts), deadline=600.0,
            prefix_share=n_stems * (s0b // ps),
            shard='' if tp == 1 else f'tp:{tp}')
        try:
            for p in stems:        # warmup: compile + publish off-clock
                svc.batcher.wait(svc.submit_async(p, max_new))
            toks, wall, _, checked = _drive_leg(svc, prompts, max_new)
            hits = svc.engine.stats.get('prefix_hits')
            misses = svc.engine.stats.get('prefix_misses')
            hitp = svc.engine.stats.get('prefix_hit_pages')
            legs.append({
                'tp': tp, 'slots': 2 * tp,
                'pages': 1 + pages_per_dev * tp,
                'tokens_per_sec': round(toks / wall, 2),
                'wall_sec': round(wall, 3),
                'prefix_hits': int(hits), 'prefix_misses': int(misses),
                'prefix_hit_pages': int(hitp),
                'streams': len(prompts), 'twin_checked': checked,
                'resident_bytes_per_device':
                    [int(b) for b in svc.engine.resident_bytes_per_device()],
            })
        except AssertionError:
            violations += 1
            raise
        finally:
            svc.close(60)

    # --- prefill disaggregation A/B: a LONG prompt at the head of the
    # admission queue must not block the short streams behind it.  With
    # workers=0, admission runs serially on the batcher worker, so
    # every short waits out the long prefill; with workers=2, one
    # worker chews the long prompt while the other drains the shorts —
    # their time-to-first-token is the head-of-line claim.
    long_len = 60 * ps                     # the same 1024-bucket weight
    d_max_new = 24                         # longs: slot-holding streams
    n_short = 12
    longs = [rng.randint(0, cfg.vocab_size,
                         (1, long_len)).astype(np.int32)
             for _ in range(3)]
    shorts = [rng.randint(0, cfg.vocab_size,
                          (1, int(rng.randint(1, 8)))).astype(np.int32)
              for _ in range(n_short)]
    # longs INTERLEAVED with the short crowd: with workers=0 every
    # mid-queue long prefill blocks all shorts behind it (serial
    # admission), with workers=2 the second worker keeps draining
    # shorts through it — the short crowd's TTFT p99 is the claim
    order = ([(longs[0], False)]
             + [(s, True) for s in shorts[:n_short // 2]]
             + [(longs[1], False)]
             + [(s, True) for s in shorts[n_short // 2:]]
             + [(longs[2], False)])
    dcfg_prompts = [p for p, _ in order]
    short_idx = {i for i, (_, sh) in enumerate(order) if sh}
    short_new = 4                          # shorts: TTFT-bound streams

    def disagg_leg(workers: int) -> dict:
        svc = DecodeService(
            params, cfg, slots=6, pages=256, page_size=ps,
            max_prompt=long_len, max_new_bound=d_max_new,
            max_queue=64, deadline=600.0, prefill_workers=workers)
        try:
            # warmup compiles BOTH prompt buckets off the clock
            svc.batcher.wait(svc.submit_async(longs[0], 2))
            svc.batcher.wait(svc.submit_async(shorts[0], 2))
            t0 = time.monotonic()
            reqs = [svc.submit_async(
                p, short_new if i in short_idx else d_max_new)
                for i, p in enumerate(dcfg_prompts)]
            ttft = []
            for i, r in enumerate(reqs):
                svc.batcher.wait(r)
                if i in short_idx:
                    ttft.append((r.token_times[0] - r.t_submit) * 1e3)
            wall = time.monotonic() - t0
            toks = sum(len(r.tokens) for r in reqs)
            from cxxnet_tpu.models import transformer as T
            checked = 0
            for i, (p, r) in enumerate(zip(dcfg_prompts, reqs)):
                mn = short_new if i in short_idx else d_max_new
                off = np.asarray(T.generate(params, p, mn, cfg))[0]
                got = np.asarray(r.result)
                assert (got == off[:len(got)]).all(), \
                    'disagg stream diverged from its offline twin'
                checked += 1
            tt = np.asarray(ttft)
            return {
                'prefill_workers': workers,
                'tokens_per_sec': round(toks / wall, 2),
                'short_ttft_p50_ms': round(float(np.quantile(tt, 0.5)), 3),
                'short_ttft_p99_ms': round(float(np.quantile(tt, 0.99)), 3),
                'streams': len(dcfg_prompts), 'twin_checked': checked,
            }
        finally:
            svc.close(60)

    d_off, d_on = disagg_leg(0), disagg_leg(2)
    tp1 = legs[0]['tokens_per_sec']
    tpN = legs[-1]['tokens_per_sec']
    return {
        'metric': 'decode_shard_scaling',
        'value': round(tpN / tp1, 2),
        'unit': 'x',
        'legs': legs,
        'pages_per_device': pages_per_dev,
        'disagg': {
            'off': d_off, 'on': d_on,
            'short_ttft_improvement': round(
                d_off['short_ttft_p99_ms']
                / max(d_on['short_ttft_p99_ms'], 1e-9), 2),
        },
        'twin_violations': violations,
        'max_new': max_new, 'page_size': ps,
        'devices': ndev,
        'model': {'vocab': cfg.vocab_size, 'd_model': cfg.d_model,
                  'heads': cfg.num_heads, 'd_ff': cfg.d_ff,
                  'stages': cfg.num_stages},
        'platform': jax.default_backend(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('mode', nargs='?', default='predict',
                    choices=('predict', 'decode', 'decode_matrix',
                             'prefix', 'spec', 'prefix_spec',
                             'scenarios', 'kv_tiers', 'sharded'))
    ap.add_argument('--clients', type=int, default=int(
        os.environ.get('CXXNET_SERVE_BENCH_CLIENTS', 8)))
    ap.add_argument('--duration', type=float, default=float(
        os.environ.get('CXXNET_SERVE_BENCH_DURATION', 3.0)))
    ap.add_argument('--buckets', default=os.environ.get(
        'CXXNET_SERVE_BENCH_BUCKETS', '1,8,32'))
    ap.add_argument('--max-wait', type=float, default=0.001)
    ap.add_argument('--slots', type=int, default=int(
        os.environ.get('CXXNET_SERVE_BENCH_SLOTS', 8)))
    ap.add_argument('--pages', type=int, default=int(
        os.environ.get('CXXNET_SERVE_BENCH_PAGES', 96)))
    ap.add_argument('--page-size', type=int, default=16)
    ap.add_argument('--max-new', type=int, default=int(
        os.environ.get('CXXNET_SERVE_BENCH_MAX_NEW', 32)))
    ap.add_argument('--max-prompt', type=int, default=int(
        os.environ.get('CXXNET_SERVE_BENCH_MAX_PROMPT', 24)))
    ap.add_argument('--requests', type=int, default=int(
        os.environ.get('CXXNET_SERVE_BENCH_REQUESTS', 12)))
    ap.add_argument('--twin-checks', type=int, default=2)
    ap.add_argument('--spec-k', type=int, default=int(
        os.environ.get('CXXNET_SERVE_BENCH_SPEC_K', 4)))
    ap.add_argument('--seed', type=int, default=7)
    args = ap.parse_args(argv)

    if args.mode == 'sharded':
        # the sharded legs need a mesh: on CPU, widen the virtual
        # device set BEFORE jax initializes (the conftest pattern)
        plats = os.environ.get('JAX_PLATFORMS', '')
        flags = os.environ.get('XLA_FLAGS', '')
        if (not plats or plats == 'cpu') and \
                'xla_force_host_platform_device_count' not in flags:
            os.environ['XLA_FLAGS'] = (
                flags + ' --xla_force_host_platform_device_count=8'
            ).strip()

    budget = float(os.environ.get('CXXNET_BENCH_BACKEND_WAIT', '60'))
    if not _backend_ok(budget):
        return _cpu_fallback(argv, f'TPU backend unavailable within '
                                   f'{budget:.0f}s')
    modes = {'predict': bench_predict, 'decode': bench_decode,
             'decode_matrix': bench_decode_matrix,
             'prefix': bench_prefix, 'spec': bench_spec,
             'prefix_spec': bench_prefix_spec,
             'scenarios': bench_scenarios,
             'kv_tiers': bench_kv_tiers,
             'sharded': bench_sharded}
    metrics = {'predict': 'serve_p99_latency_ms',
               'decode': 'decode_tokens_per_sec',
               'decode_matrix': 'decode_int8_resident_reduction',
               'prefix': 'prefix_share_speedup',
               'spec': 'spec_decode_speedup',
               'prefix_spec': 'prefix_share_speedup',
               'scenarios': 'scenario_autoscale_wins',
               'kv_tiers': 'kv_tier_speedup',
               'sharded': 'decode_shard_scaling'}
    try:
        out = modes[args.mode](args)
    except Exception as e:  # structured failure, never a bare traceback
        out = {'metric': metrics[args.mode],
               'value': None, 'unit': None, 'error': repr(e)}
    print(json.dumps(out))
    return 0 if 'error' not in out else 1


if __name__ == '__main__':
    sys.exit(main())
