#!/usr/bin/env python
"""Benchmark: online serving latency/throughput (doc/serving.md).

Prints ONE JSON line so future PRs get a serving perf trajectory next to
the training BENCH_*.json ledger:

  {"metric": "serve_p99_latency_ms", "value": P99, "unit": "ms",
   "p50_ms": P50, "mean_ms": M, "requests_per_sec": R,
   "rows_per_sec": RW, "compile_count": C, "buckets": [...],
   "clients": N, "duration_sec": D}

Method: a tiny MLP (random init — serving cost is shape-bound, not
value-bound) behind the real PredictEngine + DynamicBatcher stack;
``--clients`` in-process threads submit mixed-size requests (1..max/2
rows, seeded) back-to-back for ``--duration`` seconds after a warmup.
The engine pre-compiles every bucket, so measured latency is pure
serving-path overhead: queue + coalesce window + pad + forward + split.

Env: honors JAX_PLATFORMS (run with =cpu for a hardware-independent
number); CXXNET_SERVE_BENCH_* override the defaults below.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

NET_CFG = """
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 64
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 16
layer[+0] = softmax
netconfig=end
input_shape = 1,1,32
batch_size = 32
eta = 0.1
"""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--clients', type=int, default=int(
        os.environ.get('CXXNET_SERVE_BENCH_CLIENTS', 8)))
    ap.add_argument('--duration', type=float, default=float(
        os.environ.get('CXXNET_SERVE_BENCH_DURATION', 3.0)))
    ap.add_argument('--buckets', default=os.environ.get(
        'CXXNET_SERVE_BENCH_BUCKETS', '1,8,32'))
    ap.add_argument('--max-wait', type=float, default=0.001)
    args = ap.parse_args(argv)

    try:
        from cxxnet_tpu import wrapper
        from cxxnet_tpu.serve import DynamicBatcher, PredictEngine
        from cxxnet_tpu.utils.bucketing import parse_buckets

        net = wrapper.Net(dev='', cfg=NET_CFG)
        net.set_param('inference_only', '1')
        net.init_model()
        buckets = parse_buckets(args.buckets)
        engine = PredictEngine(net._trainer, buckets)
        engine.warm()
        batcher = DynamicBatcher(engine, max_queue=4 * args.clients,
                                 max_wait=args.max_wait, deadline=30.0)

        lat_ms = []
        rows_done = [0]
        lock = threading.Lock()
        stop = threading.Event()

        def client(cid: int) -> None:
            rng = np.random.RandomState(cid)
            while not stop.is_set():
                n = int(rng.randint(1, max(2, buckets[-1] // 2)))
                d = rng.randn(n, 1, 1, 32).astype(np.float32)
                t0 = time.monotonic()
                batcher.submit(d)
                dt = (time.monotonic() - t0) * 1e3
                with lock:
                    lat_ms.append(dt)
                    rows_done[0] += n

        threads = [threading.Thread(target=client, args=(cid,), daemon=True)
                   for cid in range(args.clients)]
        warmup = min(0.5, args.duration / 4)
        for t in threads:
            t.start()
        time.sleep(warmup)
        with lock:          # measure steady state only
            lat_ms.clear()
            rows_done[0] = 0
        t_start = time.monotonic()
        time.sleep(args.duration)
        elapsed = time.monotonic() - t_start
        stop.set()
        for t in threads:
            t.join(10)
        batcher.close(timeout=10)

        arr = np.asarray(lat_ms)
        out = {
            'metric': 'serve_p99_latency_ms',
            'value': round(float(np.quantile(arr, 0.99)), 4),
            'unit': 'ms',
            'p50_ms': round(float(np.quantile(arr, 0.5)), 4),
            'mean_ms': round(float(arr.mean()), 4),
            'requests_per_sec': round(arr.size / elapsed, 2),
            'rows_per_sec': round(rows_done[0] / elapsed, 2),
            'compile_count': engine.compile_count,
            'buckets': list(buckets),
            'clients': args.clients,
            'duration_sec': round(elapsed, 3),
            'platform': __import__('jax').default_backend(),
        }
    except Exception as e:  # structured failure, never a bare traceback
        out = {'metric': 'serve_p99_latency_ms', 'value': None,
               'unit': 'ms', 'error': repr(e)}
    print(json.dumps(out))
    return 0 if 'error' not in out else 1


if __name__ == '__main__':
    sys.exit(main())
