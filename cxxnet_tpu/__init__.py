"""cxxnet_tpu — a TPU-native, config-driven CNN training framework.

A ground-up JAX/XLA re-architecture with the capabilities of the reference
cxxnet (see SURVEY.md): the ``.conf`` network language, train/pred/extract/
finetune tasks, the full layer zoo, SGD/NAG/Adam updaters with schedules and
tag-scoped hyperparameters, a chained-iterator data pipeline, checkpointing,
and data-parallel scaling over a ``jax.sharding.Mesh``.
"""

__version__ = '0.1.0'
