"""cxxnet_tpu — a TPU-native, config-driven CNN training framework.

A ground-up JAX/XLA re-architecture with the capabilities of the reference
cxxnet (see SURVEY.md): the ``.conf`` network language, train/pred/extract/
finetune tasks, the full layer zoo, SGD/NAG/Adam updaters with schedules and
tag-scoped hyperparameters, a chained-iterator data pipeline, checkpointing,
and data-parallel scaling over a ``jax.sharding.Mesh``.
"""

__version__ = '0.1.0'


def _honor_platform_env() -> None:
    """Make ``JAX_PLATFORMS`` authoritative.

    Some deployment images force-register an out-of-process TPU PJRT
    plugin from ``sitecustomize`` in every interpreter, which can override
    the env var's backend selection (and hang backend discovery when the
    device link is unreachable).  Re-asserting the env choice through the
    live config keeps ``JAX_PLATFORMS=cpu`` runs (tests, embedded C-ABI
    hosts, data tooling) off the device path entirely.
    """
    import os
    want = os.environ.get('JAX_PLATFORMS')
    if not want:
        return
    try:
        import jax
        jax.config.update('jax_platforms', want)
    except Exception:  # lint: allow(fault-taxonomy): jax absent/too old — backend selection is moot, nothing to route
        pass


_honor_platform_env()
