"""graftlint — project-native static analysis (doc/static_analysis.md).

Eight PRs accumulated invariants that were enforced only by runtime
tests and reviewer memory: bitwise-twin determinism, the typed fault
taxonomy, lock-guarded shared state across the threaded subsystems, a
host-sync-free scanned hot loop, and config keys that must not drift
from their doc tables.  This package encodes each as a stdlib-``ast``
checker so a regression fails tier-1 (``pytest -m lint``) before any
chip time is spent, not after a fleet run goes wrong.

The five checkers (one module each; ``core`` holds the shared
machinery):

* ``lock_discipline`` — shared attributes of thread-spawning classes
  are accessed under their declared lock (``# guarded-by:``), and lock
  acquisition order is globally consistent (rules ``lock-discipline``,
  ``lock-order``),
* ``tracer_hygiene``  — no implicit device→host syncs or
  nondeterminism inside jitted/scanned code (rule ``tracer-hygiene``),
* ``fault_taxonomy``  — ``raise`` sites in runtime/serve/online use the
  typed ``faults.*`` taxonomy; broad ``except Exception`` routes to the
  FailureLog or carries an explicit allow (rule ``fault-taxonomy``),
* ``config_keys``     — every config key the CLI/wrapper parse is
  documented in the doc tables (rule ``config-key-drift``); also home
  of the shared doc-table extractor other tests consume,
* ``monotonic_clock`` — durations/deadlines use ``time.monotonic()``,
  never ``time.time()`` (rule ``monotonic-clock``).

Triaged legacy findings live in the committed ``lint_baseline.json``
(shrink-only: entries may be removed as findings are fixed, never
added); new findings always fail.  Drive it with ``python
tools/lint.py`` (exit 0 clean/baselined, 1 new findings or stale
baseline, 2 internal error).
"""

from __future__ import annotations

from .core import (ALL_RULES, Finding, Repo, diff_against_baseline,
                   load_baseline, run_all)

__all__ = ['ALL_RULES', 'Finding', 'Repo', 'diff_against_baseline',
           'load_baseline', 'run_all']
