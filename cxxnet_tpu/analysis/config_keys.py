"""Config-key drift: code vs. doc tables (rule ``config-key-drift``).

``main.py``/``wrapper.py`` parse their config keys through two idioms —
the ``simple`` string-key dispatch table inside ``set_param`` and
``name == '<key>'`` section-marker comparisons.  Both are extracted
statically here and cross-checked against the key tables in
``doc/tasks.md`` / ``doc/io.md`` / ``doc/trainer.md``: a key the CLI
parses but no doc table mentions is drift and fails the lint.  This
generalizes PR 7's one-off fallback-matrix drift test; the markdown
table helpers below are the shared extractor that test (and any future
doc-drift consumer) uses — one extractor, many consumers.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding, Module, Repo

RULES = ('config-key-drift',)

#: config-parsing sources and the doc files whose tables document them
KEY_SOURCES = ('cxxnet_tpu/main.py', 'cxxnet_tpu/wrapper.py')
DOC_FILES = ('doc/tasks.md', 'doc/io.md', 'doc/trainer.md')

_KEY_RE = re.compile(r'^[a-z_][a-z0-9_]*(\.[a-z_][a-z0-9_]*)*$')

#: backtick span opening with a config-key-shaped token, optionally
#: followed by `= value` (the doc tables write both `key` and `key = v`)
_DOC_KEY_RE = re.compile(r'`([a-zA-Z_][a-zA-Z0-9_.]*)\s*(?:=[^`]*)?`')


# --- code side --------------------------------------------------------------

def parsed_keys(mod: Module) -> Dict[str, int]:
    """Config keys the module parses -> first line seen.

    Sources: (a) string keys of dict literals inside any ``set_param``
    function (the CLI's ``simple`` dispatch table), (b) constants
    compared against a variable named ``name`` anywhere in the module
    (the section-marker idiom ``if name == 'data':``)."""
    keys: Dict[str, int] = {}

    def note(key: str, line: int) -> None:
        if _KEY_RE.match(key):
            keys.setdefault(key, line)

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == 'set_param':
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    const = [k for k in sub.keys
                             if isinstance(k, ast.Constant)
                             and isinstance(k.value, str)]
                    if len(const) == len(sub.keys) and const:
                        for k in const:
                            note(k.value, k.lineno)
        if isinstance(node, ast.Compare):
            left = node.left
            if isinstance(left, ast.Name) and left.id == 'name':
                for op, comp in zip(node.ops, node.comparators):
                    if not isinstance(op, (ast.Eq, ast.In)):
                        continue
                    if isinstance(comp, ast.Constant) \
                            and isinstance(comp.value, str):
                        note(comp.value, comp.lineno)
                    elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                        for el in comp.elts:
                            if isinstance(el, ast.Constant) \
                                    and isinstance(el.value, str):
                                note(el.value, el.lineno)
    return keys


# --- doc side (the shared extractor) ----------------------------------------

def doc_keys(text: str) -> set:
    """Every config-key-shaped backtick token in a markdown file —
    table cells and inline prose both count as documentation."""
    return {m.group(1) for m in _DOC_KEY_RE.finditer(text)}


def doc_table_rows(text: str, after: Optional[str] = None
                   ) -> List[Tuple[str, ...]]:
    """Markdown table rows as tuples of stripped cell strings,
    excluding header-separator rows (``|---|---|``).  ``after`` (a
    heading substring) restricts parsing to everything past its first
    occurrence — the "last table in the section" idiom the demotion-
    matrix drift test relies on."""
    if after is not None:
        _, _, text = text.partition(after)
    rows: List[Tuple[str, ...]] = []
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith('|') and line.endswith('|')):
            continue
        cells = tuple(c.strip() for c in line[1:-1].split('|'))
        if all(set(c) <= set('-: ') for c in cells):
            continue
        rows.append(cells)
    return rows


def backtick_key(cell: str) -> Optional[str]:
    """The leading backticked key of a table cell — accepts both the
    bare ``key`` and ``key = v`` spellings; None for prose/header
    cells."""
    m = _DOC_KEY_RE.match(cell.strip())
    return m.group(1) if m else None


def documented_keys(repo: Repo,
                    doc_files: Sequence[str] = DOC_FILES) -> set:
    out: set = set()
    for rel in doc_files:
        if repo.has(rel):
            out |= doc_keys(repo.read_text(rel))
    return out


# --- the checker ------------------------------------------------------------

def check_module(mod: Module, documented: set,
                 doc_files: Sequence[str] = DOC_FILES) -> List[Finding]:
    findings: List[Finding] = []
    docs = ', '.join(os.path.basename(d) for d in doc_files)
    for key, line in sorted(parsed_keys(mod).items()):
        if key in documented:
            continue
        findings.append(Finding(
            'config-key-drift', mod.rel, line,
            f'config key {key!r} is parsed here but documented in none '
            f'of the key tables ({docs}) — add a doc row or drop the '
            f'key'))
    return findings


def run(repo: Repo) -> List[Finding]:
    documented = documented_keys(repo)
    findings: List[Finding] = []
    for rel in KEY_SOURCES:
        if repo.has(rel):
            findings.extend(check_module(repo.module(rel), documented))
    return findings
