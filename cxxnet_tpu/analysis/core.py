"""Shared machinery for the graftlint checkers.

A checker module exposes ``RULES`` (the rule ids it can emit) and
``run(repo) -> List[Finding]``.  :func:`run_all` drives every checker
over a :class:`Repo`, applies inline suppressions, and returns the
surviving findings; :func:`diff_against_baseline` splits them against
the committed ``lint_baseline.json``.

Conventions (the full grammar lives in doc/static_analysis.md):

* ``# lint: allow(<rule>): <reason>`` — suppress ``<rule>`` findings on
  this line or the line directly below (``*`` = any rule).  The reason
  is mandatory: an allow without one does not suppress.
* Baseline entries match findings by ``(rule, path, message)`` — never
  by line number, so unrelated edits cannot silently re-baseline a
  finding.  Messages therefore name symbols, not positions.
"""

from __future__ import annotations

import ast
import collections
import json
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: repo-relative directory the checkers scan (the shipped package; tests,
#: tools and benches are driven code, not the 24/7 product surface)
PACKAGE_DIR = 'cxxnet_tpu'

ALLOW_RE = re.compile(r'#\s*lint:\s*allow\(([\w*.-]+)\)\s*:\s*(\S.*)')


@dataclass(frozen=True)
class Finding:
    """One typed lint finding.  ``message`` is position-independent (it
    names symbols); ``line`` is presentation only."""

    rule: str
    path: str          # repo-relative, posix separators
    line: int
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def format(self) -> str:
        return f'{self.path}:{self.line}: [{self.rule}] {self.message}'


def _scan_allows(lines: Sequence[str]) -> Dict[int, Set[str]]:
    """lineno -> {rule or '*'} for every well-formed (reason-carrying)
    ``# lint: allow(rule): reason`` comment."""
    allows: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, 1):
        m = ALLOW_RE.search(line)
        if m:
            allows.setdefault(i, set()).add(m.group(1))
    return allows


class Module:
    """One parsed source file: AST + raw lines + inline allows."""

    def __init__(self, root: str, rel: str):
        self.rel = rel
        self.path = os.path.join(root, rel)
        with tokenize.open(self.path) as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.tree = ast.parse(self.src, filename=rel)
        self.allows = _scan_allows(self.lines)

    def allowed(self, rule: str, line: int) -> bool:
        for at in (line, line - 1):
            rules = self.allows.get(at)
            if rules and (rule in rules or '*' in rules):
                return True
        return False


class Repo:
    """Lazy, cached view of the repository for cross-file checkers."""

    def __init__(self, root: Optional[str] = None):
        self.root = os.path.abspath(root) if root else default_root()
        self._cache: Dict[str, Module] = {}

    def module(self, rel: str) -> Module:
        rel = rel.replace(os.sep, '/')
        mod = self._cache.get(rel)
        if mod is None:
            mod = self._cache[rel] = Module(self.root, rel)
        return mod

    def has(self, rel: str) -> bool:
        return os.path.exists(os.path.join(self.root, rel))

    def package_files(self) -> List[str]:
        """Repo-relative paths of every ``.py`` in the shipped package."""
        out: List[str] = []
        base = os.path.join(self.root, PACKAGE_DIR)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d != '__pycache__')
            for name in sorted(filenames):
                if name.endswith('.py'):
                    rel = os.path.relpath(os.path.join(dirpath, name),
                                          self.root)
                    out.append(rel.replace(os.sep, '/'))
        return out

    def read_text(self, rel: str) -> str:
        with open(os.path.join(self.root, rel), encoding='utf-8') as f:
            return f.read()


def default_root() -> str:
    """The repository root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _checkers():
    from . import (config_keys, fault_taxonomy, jit_ledger,
                   lock_discipline, monotonic_clock, span_hygiene,
                   tracer_hygiene)
    return (lock_discipline, tracer_hygiene, fault_taxonomy, config_keys,
            monotonic_clock, span_hygiene, jit_ledger)


ALL_RULES: Tuple[str, ...] = ('lock-discipline', 'lock-order',
                              'tracer-hygiene', 'fault-taxonomy',
                              'config-key-drift', 'monotonic-clock',
                              'span-hygiene', 'jit-ledger')


def run_all(root: Optional[str] = None,
            rules: Optional[Sequence[str]] = None,
            repo: Optional[Repo] = None) -> List[Finding]:
    """Run every checker (or the ``rules`` subset) and return findings
    that survive inline suppression, sorted by (path, line, rule)."""
    repo = repo if repo is not None else Repo(root)
    wanted = set(rules) if rules else set(ALL_RULES)
    unknown = wanted - set(ALL_RULES)
    if unknown:
        raise ValueError(f'unknown lint rule(s): {sorted(unknown)}; '
                         f'known: {list(ALL_RULES)}')
    findings: List[Finding] = []
    for checker in _checkers():
        if not wanted.intersection(checker.RULES):
            continue
        findings.extend(f for f in checker.run(repo) if f.rule in wanted)
    out = [f for f in findings
           if not repo.module(f.path).allowed(f.rule, f.line)]
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


def apply_suppressions(findings: Iterable[Finding],
                       mod: 'Module') -> List[Finding]:
    """Filter one module's findings through its inline allows (the
    fixture tests' entry point; :func:`run_all` does this repo-wide)."""
    return [f for f in findings if not mod.allowed(f.rule, f.line)]


# --- baseline (shrink-only ratchet) ----------------------------------------

def baseline_path(root: Optional[str] = None) -> str:
    return os.path.join(root or default_root(), 'lint_baseline.json')


def load_baseline(path: Optional[str] = None) -> List[dict]:
    """Entries of ``lint_baseline.json`` (``[]`` when absent).  Each is
    ``{rule, path, message, reason}``; a missing/empty reason is a
    malformed baseline and raises."""
    path = path or baseline_path()
    if not os.path.exists(path):
        return []
    with open(path, encoding='utf-8') as f:
        data = json.load(f)
    entries = data.get('entries', [])
    for e in entries:
        for field in ('rule', 'path', 'message', 'reason'):
            if not str(e.get(field, '')).strip():
                raise ValueError(
                    f'baseline entry missing {field!r}: {e!r} — every '
                    'triaged finding must carry a reason')
    return entries


def diff_against_baseline(findings: Iterable[Finding],
                          entries: Iterable[dict]
                          ) -> Tuple[List[Finding], List[dict], int]:
    """Multiset match on ``(rule, path, message)``.  Returns ``(new
    findings, stale baseline entries, matched count)``: new findings
    fail the lint; stale entries fail the shrink-only ratchet (fixing a
    finding must also delete its baseline entry)."""
    budget = collections.Counter(
        (e['rule'], e['path'], e['message']) for e in entries)
    new: List[Finding] = []
    matched = 0
    for f in findings:
        if budget.get(f.key(), 0) > 0:
            budget[f.key()] -= 1
            matched += 1
        else:
            new.append(f)
    stale = []
    for e in entries:
        k = (e['rule'], e['path'], e['message'])
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            stale.append(e)
    return new, stale, matched


# --- small AST helpers shared by checkers ----------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def parse_snippet(src: str, rel: str = '<fixture>') -> Module:
    """Build a Module from an in-memory snippet (checker unit tests)."""
    mod = Module.__new__(Module)
    mod.rel = rel
    mod.path = rel
    mod.src = src
    mod.lines = src.splitlines()
    mod.tree = ast.parse(src, filename=rel)
    mod.allows = _scan_allows(mod.lines)
    return mod
