"""Typed fault taxonomy enforcement (rule ``fault-taxonomy``).

PR 1 introduced ``runtime/faults.py`` precisely so that every failure
mode in the long-running subsystems is a TYPED error a supervisor,
batcher, or client can route on.  A raw ``RuntimeError`` in
``runtime/``, ``serve/`` or ``online/`` silently falls outside every
retry/recovery/shedding policy, so this checker pins the contract:

* every ``raise`` of a *newly constructed* exception must be a
  ``faults.*`` class (resolved statically from the class defs in
  ``runtime/faults.py``, however it was imported) or a plain
  ``ValueError``/``TypeError`` on argument validation;
* re-raises (``raise``, ``raise err``, ``raise req.error``) are always
  fine — the type was chosen where the error was born;
* a broad ``except Exception``/bare ``except`` must either route the
  error to the FailureLog (a ``.record(...)`` call in its body — the
  watcher-must-outlive-bad-cycles idiom) or carry an explicit
  ``# lint: allow(fault-taxonomy): <reason>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding, Module, Repo, dotted_name

RULES = ('fault-taxonomy',)

#: directories whose raises must use the taxonomy (repo-relative).
#: parallel/ joined with the elastic multi-host runtime: a raw error in
#: the coordinator/client/supervisor stack would fall outside the
#: RECOVERABLE set and turn a drillable host loss into a dead run.
TARGET_DIRS = ('cxxnet_tpu/runtime/', 'cxxnet_tpu/serve/',
               'cxxnet_tpu/online/', 'cxxnet_tpu/parallel/',
               'cxxnet_tpu/tune/')

FAULTS_MODULE = 'cxxnet_tpu/runtime/faults.py'

#: builtins allowed for argument/usage validation at API boundaries
VALIDATION_OK = {'ValueError', 'TypeError', 'NotImplementedError',
                 'StopIteration', 'GeneratorExit', 'KeyboardInterrupt',
                 'AssertionError'}


def fault_class_names(repo: Repo) -> Set[str]:
    """Every exception class defined in ``runtime/faults.py``: classes
    whose base chain (within the module) reaches a builtin exception."""
    if not repo.has(FAULTS_MODULE):
        return set()        # scratch trees (CLI tests) have no taxonomy
    mod = repo.module(FAULTS_MODULE)
    bases = {}
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef):
            bases[node.name] = [dotted_name(b) or '' for b in node.bases]
    roots = {'Exception', 'BaseException', 'RuntimeError', 'OSError',
             'IOError', 'ValueError', 'TypeError', 'ArithmeticError'}
    out: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, bs in bases.items():
            if name in out:
                continue
            for b in bs:
                leaf = b.split('.')[-1]
                if leaf in roots or leaf in out:
                    out.add(name)
                    changed = True
                    break
    return out


def _raise_findings(mod: Module, allowed: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    parents: dict = {}
    for node in ast.walk(mod.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def context(node: ast.AST) -> str:
        n = node
        while n in parents:
            n = parents[n]
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                return n.name
        return '<module>'

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Raise) or node.exc is None:
            continue
        exc = node.exc
        if not isinstance(exc, ast.Call):
            continue        # re-raise of a stored/caught exception
        name = dotted_name(exc.func)
        if name is None:
            continue        # dynamic construction — out of static reach
        leaf = name.split('.')[-1]
        if leaf in allowed or leaf in VALIDATION_OK:
            continue
        findings.append(Finding(
            'fault-taxonomy', mod.rel, node.lineno,
            f'raise {leaf} in {context(node)} is not a typed faults.* '
            f'error (or ValueError/TypeError argument validation) — '
            f'untyped errors fall outside every retry/recovery/shedding '
            f'policy'))
    return findings


def _routes_to_log(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ''
            if name.split('.')[-1] == 'record':
                return True
            if 'failure_log' in name:
                return True
    return False


def _except_findings(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    def is_broad(t: Optional[ast.AST]) -> bool:
        if t is None:
            return True                 # bare except
        if isinstance(t, ast.Name):
            # BaseException stays out of scope: the package's
            # `except BaseException` sites are deliberate
            # propagate-to-consumer patterns (thread_buffer, pool)
            return t.id == 'Exception'
        if isinstance(t, ast.Tuple):
            # `except (Exception, X):` swallows everything Exception does
            return any(is_broad(el) for el in t.elts)
        return False

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not is_broad(node.type):
            continue
        if _routes_to_log(node):
            continue
        findings.append(Finding(
            'fault-taxonomy', mod.rel, node.lineno,
            'broad "except Exception" neither routes to the FailureLog '
            '(.record(...)) nor carries an explicit allow — swallowed '
            'errors are invisible at fleet scale'))
    return findings


def check_module(mod: Module, allowed: Optional[Set[str]] = None,
                 raises: bool = True) -> List[Finding]:
    allowed = allowed if allowed is not None else set()
    out = _raise_findings(mod, allowed) if raises else []
    return out + _except_findings(mod)


def run(repo: Repo) -> List[Finding]:
    allowed = fault_class_names(repo)
    findings: List[Finding] = []
    for rel in repo.package_files():
        # the raise-typing contract binds the fault-routed subsystems;
        # swallowing-broad-except visibility binds the whole package
        in_target = any(rel.startswith(d) for d in TARGET_DIRS)
        findings.extend(check_module(repo.module(rel), allowed,
                                     raises=in_target))
    return findings
