"""Program-ledger discipline (rule ``jit-ledger``).

graftprof's :class:`~cxxnet_tpu.obs.programs.ProgramLedger` is only the
compiler's truth while every load-bearing executable actually routes
through it: one direct ``jax.jit`` call site in the trainer or the
serving stack and ``/programs`` silently under-reports flops, memory,
and — worse — the recompile sentinel goes blind to exactly the storm
it exists to catch.  So the rule is blunt: inside ``nnet/`` and
``serve/``, no direct ``jax.jit(...)`` (any spelling — call,
decorator, ``partial(jax.jit, ...)``) outside the ledger wrap.  The
sanctioned spelling is ``get_ledger().program(name).jit(fn, ...)``
(obs/programs.py), which never mentions ``jax.jit`` at the site.  A
genuinely trivial program (a device-side restage, a two-op scatter)
states itself with ``# lint: allow(jit-ledger): <reason>``.

``models/`` and ``ops/`` stay out of scope deliberately: the
transformer ``generate`` cache and the Pallas kernels are library
surfaces with their own bounded caches, registered at the ENGINE call
sites the ledger already rows.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Module, Repo, dotted_name

RULES = ('jit-ledger',)

#: directories whose jit sites must be ledger-routed (or allowed)
TARGET_DIRS = ('cxxnet_tpu/nnet/', 'cxxnet_tpu/serve/')


def _jit_names(mod: Module) -> set:
    """Every dotted spelling resolving to ``jax.jit`` in this module:
    ``jax.jit``, ``import jax as j`` → ``j.jit``, and
    ``from jax import jit [as jjit]``."""
    out = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == 'jax':
                    out.add(f'{a.asname or "jax"}.jit')
        elif isinstance(node, ast.ImportFrom) and node.module == 'jax':
            for a in node.names:
                if a.name == 'jit':
                    out.add(a.asname or 'jit')
    return out


def check_module(mod: Module) -> List[Finding]:
    names = _jit_names(mod)
    if not names:
        return []
    findings: List[Finding] = []

    def hit(expr, lineno: int, how: str) -> None:
        findings.append(Finding(
            'jit-ledger', mod.rel, lineno,
            f'direct jax.jit {how} — route through the ProgramLedger '
            '(obs/programs.py: get_ledger().program(name).jit(fn, ...)) '
            'so /programs, MFU and the recompile sentinel see this '
            'executable, or carry an allow with a reason'))

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # the bare decorator spelling: @jax.jit / @jjit with no
            # call — an ast.Attribute/Name in decorator_list, never a
            # Call (decorator factories like @partial(jax.jit, ...)
            # fall through to the Call arm below)
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call) \
                        and dotted_name(dec) in names:
                    hit(dec, dec.lineno, 'bare decorator')
            continue
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name in names:
            hit(node, node.lineno, 'call site')
            continue
        # partial(jax.jit, ...) — the decorator-factory spelling
        if name is not None and name.split('.')[-1] == 'partial' \
                and node.args:
            first = dotted_name(node.args[0])
            if first in names:
                hit(node, node.lineno, 'via functools.partial')
    return findings


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for rel in repo.package_files():
        if not rel.startswith(TARGET_DIRS):
            continue
        findings.extend(check_module(repo.module(rel)))
    return findings
