"""Lock discipline for the threaded subsystems (rules ``lock-discipline``,
``lock-order``).

Every class that spawns a worker thread (``threading.Thread`` targeting
its own code, or closures handed to a ``ThreadPoolExecutor`` it owns)
shares instance state between that worker and its public methods.  The
repo's convention makes the guard explicit::

    self._runs = []        # guarded-by: _lock
    ...
    def _free_slot(self, sid):   # requires-lock: _cond
        ...

* ``# guarded-by: <lockname>`` on an attribute assignment declares that
  every access outside ``__init__`` must happen inside a ``with
  self.<lockname>:`` block (a ``threading.Lock``/``RLock``/``Condition``
  attribute) or inside a method annotated ``# requires-lock:
  <lockname>`` (caller holds it — decode.py's ``_free_slot`` idiom).
* Undeclared attributes are *inferred* shared when the worker call
  graph writes them AND a non-worker method touches them; if any such
  access is unguarded, ONE finding per (class, attribute) asks for a
  declaration or an explicit ``# lint: allow(lock-discipline): reason``.
* Code lexically inside a nested ``def``/``lambda`` does not inherit
  the enclosing ``with`` — closures run later, usually on another
  thread (exactly the bug class this checker exists for).

``lock-order``: nested ``with self.<lock>`` acquisitions build a global
directed graph over the package; any cycle (two code paths acquiring
the same pair of locks in opposite orders) is a potential deadlock and
fails — the fleet-scale lesson of PAPERS.md's distributed-training
line: concurrency order bugs, not kernels, are what break at scale.

Both rules run over the whole shipped package, which includes the
elastic multi-host runtime (``parallel/elastic.py``): its coordinator
connection/monitor threads and client heartbeat thread declare their
shared state ``# guarded-by: _cond``/``_lock`` like every other
threaded subsystem — membership races are exactly the bug class the
chaos drills cannot afford.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Module, Repo, dotted_name

RULES = ('lock-discipline', 'lock-order')

GUARDED_RE = re.compile(r'#\s*guarded-by:\s*(\w+)')
REQUIRES_RE = re.compile(r'#\s*requires-lock:\s*(\w+)')

_LOCK_TYPES = ('Lock', 'RLock', 'Condition', 'Semaphore',
               'BoundedSemaphore')


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func) or ''
    return name.split('.')[-1] in _LOCK_TYPES


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` for ``self.X`` nodes, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == 'self'):
        return node.attr
    return None


class _ClassInfo:
    """Everything the per-class analysis needs, gathered in one pass."""

    def __init__(self, mod: Module, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.locks: Set[str] = set()          # lock-typed attributes
        self.guarded: Dict[str, str] = {}     # attr -> lockname
        self.requires: Dict[str, str] = {}    # method -> held lockname
        self.spawns = False                   # creates a Thread/Executor
        # worker FUNCTION NODES (a method, or a closure handed to
        # Thread(target=)/executor.submit) — node identity, not method
        # name: a closure's enclosing method is NOT worker code
        self.workers: Set[ast.AST] = set()
        self.worker_names: Set[str] = set()   # for messages
        self._collect()

    # -- declaration collection --------------------------------------------
    def _line(self, no: int) -> str:
        return self.mod.lines[no - 1] if no - 1 < len(self.mod.lines) else ''

    def _collect(self) -> None:
        for meth in self.methods.values():
            m = REQUIRES_RE.search(self._line(meth.lineno))
            if m:
                self.requires[meth.name] = m.group(1)
            for sub in ast.walk(meth):
                # lock-typed attributes + guarded-by declarations ride
                # `self.X = ...` statements (idiomatically in __init__)
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    value = sub.value
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        if value is not None and _is_lock_ctor(value):
                            self.locks.add(attr)
                        # the annotation may trail any physical line of
                        # a multi-line assignment
                        for no in range(sub.lineno,
                                        (sub.end_lineno or sub.lineno) + 1):
                            g = GUARDED_RE.search(self._line(no))
                            if g:
                                self.guarded[attr] = g.group(1)
                                break
                if isinstance(sub, ast.Call):
                    callee = dotted_name(sub.func) or ''
                    tail = callee.split('.')[-1]
                    if tail in ('Thread', 'Timer'):
                        self.spawns = True
                        for kw in sub.keywords:
                            if kw.arg == 'target':
                                self._note_worker(kw.value, meth)
                    if tail == 'ThreadPoolExecutor':
                        self.spawns = True
        # executor-submitted closures only count once we know the class
        # owns an executor (self.spawns), hence the second pass
        if self.spawns:
            for meth in self.methods.values():
                for sub in ast.walk(meth):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == 'submit' and sub.args):
                        self._note_worker(sub.args[0], meth)
        self._close_workers()

    def _note_worker(self, target: ast.AST, meth: ast.FunctionDef) -> None:
        attr = _self_attr(target)
        if attr is not None and attr in self.methods:
            self.workers.add(self.methods[attr])
            self.worker_names.add(attr)
            return
        if isinstance(target, ast.Name):
            # a local def inside `meth` (OrderedWorkerPool's worker /
            # AsyncCheckpointer's task closure): ONLY that def's body
            # runs on the worker thread, not the rest of `meth`
            for sub in ast.walk(meth):
                if (isinstance(sub, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))
                        and sub.name == target.id):
                    self.workers.add(sub)
                    self.worker_names.add(f'{meth.name}.{sub.name}')
                    return

    def _close_workers(self) -> None:
        """Transitive closure: ``self.m()`` calls from worker code pull
        ``m`` into the worker set (the watcher→poll_once idiom)."""
        changed = True
        while changed:
            changed = False
            for wnode in list(self.workers):
                for sub in ast.walk(wnode):
                    if isinstance(sub, ast.Call):
                        attr = _self_attr(sub.func)
                        meth = self.methods.get(attr or '')
                        if meth is not None and meth not in self.workers:
                            # pulled into the worker set for analysis,
                            # but not named in messages: entry points
                            # (Thread targets / submitted closures) are
                            # what a reader greps for
                            self.workers.add(meth)
                            changed = True

    # -- access analysis ----------------------------------------------------
    class _Access:
        __slots__ = ('attr', 'line', 'is_write', 'held', 'in_worker',
                     'in_init', 'where')

        def __init__(self, attr, line, is_write, held, in_worker,
                     in_init, where):
            self.attr = attr
            self.line = line
            self.is_write = is_write
            self.held = held            # frozenset of held lock names
            self.in_worker = in_worker  # runs on a worker thread
            self.in_init = in_init      # __init__/__del__ direct code
            self.where = where          # innermost function label

    def accesses(self):
        """Every ``self.X`` touch in the class, attributed to its
        innermost function.  ``held`` is lexical ``with self.<lock>:``
        scope; a nested function body starts a FRESH scope (closures
        run later, usually on another thread), seeded only by its own
        ``# requires-lock:`` annotation."""
        out = []

        def visit(node, held, in_worker, in_init, where):
            if isinstance(node, ast.With):
                newly = []
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    # `with self._lock:` — the lock expr itself is not
                    # an "access" of guarded state
                    if attr is not None and attr in self.locks:
                        newly.append(attr)
                    else:
                        visit(item.context_expr, held, in_worker,
                              in_init, where)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, held, in_worker,
                              in_init, where)
                inner = held | set(newly)
                for stmt in node.body:
                    visit(stmt, inner, in_worker, in_init, where)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fresh = set()
                r = REQUIRES_RE.search(self._line(node.lineno))
                if r:
                    fresh.add(r.group(1))
                sub_worker = in_worker or node in self.workers
                for stmt in node.body:
                    visit(stmt, fresh, sub_worker, False, node.name)
                return
            if isinstance(node, ast.Lambda):
                visit(node.body, set(), in_worker, False, where)
                return
            attr = _self_attr(node)
            if attr is not None and attr not in self.locks:
                is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                out.append(self._Access(attr, node.lineno, is_write,
                                        frozenset(held), in_worker,
                                        in_init, where))
            for child in ast.iter_child_nodes(node):
                visit(child, held, in_worker, in_init, where)

        for meth in self.methods.values():
            held = set()
            req = self.requires.get(meth.name)
            if req:
                held.add(req)
            init = meth.name in ('__init__', '__del__')
            for stmt in meth.body:
                visit(stmt, held, meth in self.workers, init, meth.name)
        return out


def _check_class(mod: Module, info: _ClassInfo) -> List[Finding]:
    findings: List[Finding] = []
    if not info.spawns and not info.guarded:
        return findings

    all_acc = [a for a in info.accesses() if not a.in_init]
    per_attr: Dict[str, list] = {}
    for a in all_acc:
        per_attr.setdefault(a.attr, []).append(a)

    def holds(a, lock=None):
        # declared attrs demand THEIR lock; inferred sharing is
        # satisfied by any held lock (the class picked one)
        return (lock in a.held) if lock is not None else bool(a.held)

    # 1) declared attributes: every access site must hold the lock
    for attr, lock in sorted(info.guarded.items()):
        if lock not in info.locks:
            findings.append(Finding(
                'lock-discipline', mod.rel, info.node.lineno,
                f'{info.name}.{attr} declares guarded-by {lock}, but '
                f'{info.name} has no lock attribute self.{lock}'))
            continue
        for a in per_attr.get(attr, []):
            if not holds(a, lock):
                kind = 'written' if a.is_write else 'read'
                findings.append(Finding(
                    'lock-discipline', mod.rel, a.line,
                    f'{info.name}.{attr} is guarded-by {lock} but '
                    f'{kind} in {a.where} without holding self.{lock}'))

    # 2) inferred shared attributes (thread-spawning classes only):
    #    written on a worker thread AND touched off it — the unguarded
    #    counter / torn-publish regression class.  One finding per
    #    (class, attr), at the first unguarded site.
    if info.spawns and info.workers:
        for attr, sites in sorted(per_attr.items()):
            if attr in info.guarded:
                continue
            if not any(a.in_worker and a.is_write for a in sites):
                continue
            if not any(not a.in_worker for a in sites):
                continue
            bad = [a for a in sites if not holds(a)]
            if not bad:
                continue        # every touch is already lock-scoped
            where = ', '.join(sorted({a.where for a in bad}))
            workers = '/'.join(sorted(info.worker_names))
            findings.append(Finding(
                'lock-discipline', mod.rel, min(a.line for a in bad),
                f'{info.name}.{attr} is written by worker-thread code '
                f'({workers}) and touched without a lock in {where} — '
                f'declare "# guarded-by: <lock>" on its __init__ '
                f'assignment or allow with a reason'))
    return findings


# --- lock acquisition order -------------------------------------------------

def _order_edges(mod: Module) -> List[Tuple[str, str, int]]:
    """``(held, acquired, line)`` for every nested/multi-item ``with``
    over lock-like attributes.  Lock identity is ``Class.attr`` for
    ``self`` locks and the dotted expression otherwise."""
    edges: List[Tuple[str, str, int]] = []

    def lock_id(expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        name = dotted_name(expr)
        if name is None or '(' in name:
            return None
        if name.startswith('self.') and cls:
            return f'{cls}.{name[5:]}'
        return name

    def looks_locky(expr: ast.AST) -> bool:
        name = dotted_name(expr) or ''
        leaf = name.split('.')[-1]
        return ('lock' in leaf.lower() or 'cond' in leaf.lower()
                or 'sem' in leaf.lower())

    def visit(node: ast.AST, held: List[str], cls: Optional[str]) -> None:
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                visit(child, held, node.name)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                visit(child, [], cls)      # fresh stack: runs later
            return
        if isinstance(node, ast.With):
            inner = list(held)
            for item in node.items:
                if looks_locky(item.context_expr):
                    lid = lock_id(item.context_expr, cls)
                    if lid is not None:
                        for h in inner:
                            edges.append((h, lid, node.lineno))
                        inner.append(lid)
            for stmt in node.body:
                visit(stmt, inner, cls)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held, cls)

    for stmt in mod.tree.body:
        visit(stmt, [], None)
    return edges


def _find_cycle(graph: Dict[str, Set[str]]) -> Optional[List[str]]:
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GREY
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m, WHITE) == GREY:
                return stack[stack.index(m):] + [m]
            if color.get(m, WHITE) == WHITE:
                cyc = dfs(m)
                if cyc:
                    return cyc
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


def order_findings(modules: List[Module]) -> List[Finding]:
    """Cycle detection over the lock-acquisition graph of a set of
    modules (live run and fixture tests share this entry point)."""
    graph: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for mod in modules:
        for held, acquired, line in _order_edges(mod):
            graph.setdefault(held, set()).add(acquired)
            graph.setdefault(acquired, set())
            sites.setdefault((held, acquired), (mod.rel, line))
    cycle = _find_cycle(graph)
    if not cycle:
        return []
    rel, line = sites[(cycle[0], cycle[1])]
    chain = ' -> '.join(cycle)
    return [Finding(
        'lock-order', rel, line,
        f'inconsistent lock acquisition order (potential deadlock): '
        f'{chain}')]


# --- entry points -----------------------------------------------------------

def check_module(mod: Module) -> List[Finding]:
    """All lock-discipline findings for one parsed module (fixture and
    live paths share this)."""
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_check_class(mod, _ClassInfo(mod, node)))
    return findings


def run(repo: Repo) -> List[Finding]:
    files = repo.package_files()
    findings: List[Finding] = []
    for rel in files:
        findings.extend(check_module(repo.module(rel)))
    findings.extend(order_findings([repo.module(rel) for rel in files]))
    return findings
