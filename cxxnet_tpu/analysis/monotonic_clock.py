"""Monotonic-clock discipline (rule ``monotonic-clock``).

Every duration, deadline, and watchdog in this codebase (ThreadBuffer
deadlines, batcher coalescing windows, freshness SLO, retry backoff)
is arithmetic over timestamps.  ``time.time()`` is wall-clock: NTP
slews and steps it, so a deadline computed from it can fire early,
late, or never — the classic stalled-watchdog-during-clock-step bug.
``time.monotonic()`` (or ``perf_counter`` for fine measurement) is the
only correct base for elapsed time, so the rule is blunt: no
``time.time()`` in the package at all.  A genuine wall-clock need
(stamping a receipt with calendar time) states itself with
``# lint: allow(monotonic-clock): <reason>``.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Module, Repo, dotted_name

RULES = ('monotonic-clock',)


def check_module(mod: Module) -> List[Finding]:
    # resolve every spelling: `import time [as t]` module aliases and
    # `from time import time [as wall]` bound names — an aliased
    # wall-clock deadline is just as wrong as a spelled-out one
    module_names = set()
    bound_names = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == 'time':
                    module_names.add(a.asname or 'time')
        elif isinstance(node, ast.ImportFrom) and node.module == 'time':
            for a in node.names:
                if a.name == 'time':
                    bound_names.add(a.asname or 'time')
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        hit = (name is not None
               and (any(name == f'{m}.time' for m in module_names)
                    or (name in bound_names and not node.args)))
        if hit:
            findings.append(Finding(
                'monotonic-clock', mod.rel, node.lineno,
                'time.time() is wall-clock — durations and deadlines '
                'must use time.monotonic() (allow with a reason for '
                'genuine calendar timestamps)'))
    return findings


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for rel in repo.package_files():
        findings.extend(check_module(repo.module(rel)))
    return findings
