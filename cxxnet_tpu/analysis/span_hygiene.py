"""Span hygiene for the telemetry hub (rule ``span-hygiene``).

Two invariants keep graftscope spans (``obs/hub.py``) from corrupting
the paths they observe:

* **no spans inside jitted/scanned scopes** — a span body runs
  ``time.monotonic_ns()`` and a Python deque append: inside a traced
  function that is at best a trace-time constant and at worst a forced
  device→host sync per dispatch, exactly the regression the
  ``tracer-hygiene`` rule exists to prevent.  Spans bracket
  *dispatches* from the host side; they never ride into a trace.
  Traced scope is resolved with the same machinery as
  ``tracer_hygiene`` (decorators, ``jax.jit(fn)`` wrapping, ``lax``
  combinators, ``pallas_call`` operands, lexical nesting),
* **context-manager form only** — ``with span(...):`` (or the
  decorator form).  A manually-entered span (``s = span(...);
  s.__enter__()``) leaks its slot on any exception between begin and
  end, and the recorded duration silently covers the wrong region.

The rule applies to every module that imports from the ``obs`` package
(plus the fixtures); the ``obs`` package itself is exempt from the
form check — it *constructs* spans.  The existing ``monotonic-clock``
rule already covers ``obs/`` (it scans the whole package), so the
hub's clocks are checked for free.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from . import tracer_hygiene
from .core import Finding, Module, Repo, dotted_name

RULES = ('span-hygiene',)

#: the span-construction package — exempt from the with-form check
#: (it returns spans; everyone else must ``with`` them)
OBS_PACKAGE_PREFIX = 'cxxnet_tpu/obs/'


def _uses_obs(mod: Module) -> bool:
    """Does this module import the telemetry surface at all?  Keys the
    rule to relevant modules so an unrelated local ``span()`` helper in
    some future module is not misflagged."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom):
            parts = (node.module or '').split('.')
            if 'obs' in parts:
                return True
        elif isinstance(node, ast.Import):
            if any('obs' in a.name.split('.') for a in node.names):
                return True
    return False


def _span_calls(mod: Module) -> List[ast.Call]:
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ''
            if name.split('.')[-1] == 'span':
                out.append(node)
    return out


def _allowed_call_ids(mod: Module) -> Set[int]:
    """ids of Call nodes in sanctioned positions: a ``with`` item's
    context expression or a decorator."""
    ok: Set[int] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    ok.add(id(item.context_expr))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    ok.add(id(dec))
    return ok


def _parent_map(tree: ast.AST) -> dict:
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _enclosing_fn(node: ast.AST, parents: dict) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return cur
        cur = parents.get(cur)
    return None


def check_module(mod: Module) -> List[Finding]:
    calls = _span_calls(mod)
    if not calls:
        return []
    scope = tracer_hygiene._Scope(mod)
    allowed = _allowed_call_ids(mod)
    parents = _parent_map(mod.tree)
    in_obs = mod.rel.startswith(OBS_PACKAGE_PREFIX)
    findings: List[Finding] = []
    for call in calls:
        fn = _enclosing_fn(call, parents)
        label = getattr(fn, 'name', '<module>') if fn is not None \
            else '<module>'
        if fn is not None and fn in scope.traced:
            findings.append(Finding(
                'span-hygiene', mod.rel, call.lineno,
                f'span() inside jitted/scanned scope {label} — a span '
                'body is host code (monotonic_ns + ring append) and '
                'would sync or constant-fold inside the trace; bracket '
                'the dispatch from outside instead'))
        elif id(call) not in allowed and not in_obs:
            findings.append(Finding(
                'span-hygiene', mod.rel, call.lineno,
                f'span() in {label} must use the context-manager form '
                '(`with span(...):`) or the decorator form — a manual '
                'begin leaks the span on any exception before the end'))
    findings.sort(key=lambda f: f.line)
    return findings


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for rel in repo.package_files():
        mod = repo.module(rel)
        if rel.startswith(OBS_PACKAGE_PREFIX) or _uses_obs(mod):
            findings.extend(check_module(mod))
    return findings
