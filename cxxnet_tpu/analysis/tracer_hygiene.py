"""Tracer hygiene inside jitted/scanned code (rule ``tracer-hygiene``).

The K-dispatch win (PR 5/7) holds only while the scanned window stays
on device: ONE implicit device→host sync inside the traced region —
``float(loss)``, ``loss.item()``, ``np.asarray(x)``, a ``print`` of a
traced value — re-serializes every dispatch on the host link and
silently erases the speedup (or worse, retraces per step).  Host-side
nondeterminism (``time.time``, ``random.*``, argless ``datetime.now``)
inside a traced function bakes a trace-time constant into the compiled
program, breaking the bitwise-twin contract between runs.

Traced scope is resolved statically per module:

* functions decorated with ``jax.jit`` / ``partial(jax.jit, ...)`` /
  ``jax.pmap``,
* functions wrapped by ``jax.jit(fn)`` calls (names resolve to local
  defs and ``self.<method>`` of the enclosing class; inline lambdas
  count),
* functions handed to ``lax.scan`` / ``lax.cond`` / ``lax.while_loop``
  / ``lax.fori_loop`` / ``lax.map`` / ``jax.vmap`` / ``shard_map``,
* **Pallas kernel bodies** — the function operand of ``pl.pallas_call``
  (a direct name, or a local ``kernel = functools.partial(fn, ...)``
  assignment, which the kernel modules' idiom uses).  A kernel body is
  the most traced scope there is: a host sync inside one doesn't just
  slow a dispatch, it breaks compilation on real hardware while
  silently "working" under ``interpret=True`` on CPU.  A kernel that
  reaches ``pallas_call`` through a helper's *parameter*
  (``_lrn_call(kernel, ...)`` where the helper forwards ``kernel`` into
  the call position) IS resolved, one call level deep: the helper's
  forwarding parameters are computed from its body, and the caller's
  matching argument (positional or keyword, directly or through
  ``partial``) is marked traced.  Remaining soundness limit: two or
  more levels of parameter indirection,
* anything lexically nested inside a traced function.

Only the hot-loop modules are scanned (``TARGET_FILES``): the contract
is about the trainer/decode dispatch path — and the Pallas kernel tier
— not utility code that lawfully mixes host and device work.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding, Module, Repo, dotted_name

RULES = ('tracer-hygiene',)

#: the dispatch-path modules whose traced regions carry the bitwise /
#: no-host-sync contract (doc/static_analysis.md)
TARGET_FILES = ('cxxnet_tpu/nnet/trainer.py',
                'cxxnet_tpu/nnet/execution.py',
                'cxxnet_tpu/serve/decode.py',
                'cxxnet_tpu/ops/pallas_kernels.py',
                'cxxnet_tpu/ops/pallas_cnn.py')

#: function-argument positions per wrapper.  lax combinators demand a
#: `lax` qualifier (``jax.tree.map`` is NOT ``lax.map``); jit/pmap/vmap
#: accept a `jax` qualifier or a bare name (``from jax import jit``).
_LAX_HOF = {'scan': (0,), 'cond': (1, 2), 'while_loop': (0, 1),
            'fori_loop': (2,), 'map': (0,), 'switch': None}
_JAX_WRAP = {'jit': (0,), 'pmap': (0,), 'vmap': (0,), 'shard_map': (0,)}


def _hof_positions(fname: str):
    parts = fname.split('.')
    leaf = parts[-1]
    if leaf in _LAX_HOF and 'lax' in parts[:-1]:
        return True, _LAX_HOF[leaf]
    if leaf in _JAX_WRAP and (len(parts) == 1 or parts[0] == 'jax'
                              or leaf == 'shard_map'):
        return True, _JAX_WRAP[leaf]
    # pl.pallas_call(kernel, ...) — the kernel operand runs fully traced
    # (Mosaic on TPU, the pallas interpreter on CPU)
    if leaf == 'pallas_call':
        return True, (0,)
    return False, None

_SYNC_BUILTINS = {'float', 'bool', 'int'}
_SYNC_ATTRS = {'item', 'tolist'}
_NP_SYNCS = {'np.asarray', 'np.array', 'numpy.asarray', 'numpy.array'}
_NONDET = {'time.time', 'time.monotonic', 'time.perf_counter',
           'time.time_ns', 'os.urandom', 'uuid.uuid4'}


def _is_jit_decorator(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name and name.split('.')[-1] in ('jit', 'pmap'):
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func) or ''
        if fname.split('.')[-1] in ('jit', 'pmap'):
            return True
        if fname.split('.')[-1] == 'partial' and dec.args:
            first = dotted_name(dec.args[0]) or ''
            if first.split('.')[-1] in ('jit', 'pmap'):
                return True
    return False


class _Scope:
    """Resolves which function defs in a module are traced."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.traced: Set[ast.AST] = set()          # FunctionDef / Lambda
        self._local_defs: dict = {}                # (parent, name) -> def
        self._methods: dict = {}                   # (class, name) -> def
        self._assigns: dict = {}            # (parent, name) -> value expr
        self._fwd_cache: dict = {}   # helper def -> ((pos, name), ...)
        self._index(mod.tree, None, None)
        self._mark(mod.tree)

    def _index(self, node: ast.AST, parent: Optional[ast.AST],
               cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._local_defs[(parent, child.name)] = child
                self._index(child, child, cls)
            elif isinstance(child, ast.ClassDef):
                for sub in child.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self._methods[(child.name, sub.name)] = sub
                self._index(child, parent, child.name)
            else:
                if isinstance(child, ast.Assign) \
                        and len(child.targets) == 1 \
                        and isinstance(child.targets[0], ast.Name):
                    # kernel = functools.partial(_fn, ...) — the kernel
                    # modules' pallas_call idiom; last assignment wins
                    self._assigns[(parent, child.targets[0].id)] = \
                        child.value
                self._index(child, parent, cls)

    def _resolve(self, arg: ast.AST, fn_parent: Optional[ast.AST],
                 cls: Optional[str], _depth: int = 0) -> Optional[ast.AST]:
        if _depth > 8:                   # assignment-chain cycle guard
            return None
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Call):
            # functools.partial(fn, ...): the wrapped fn is the operand
            fname = dotted_name(arg.func) or ''
            if fname.split('.')[-1] == 'partial' and arg.args:
                return self._resolve(arg.args[0], fn_parent, cls,
                                     _depth + 1)
            return None
        if isinstance(arg, ast.Name):
            # walk outward through enclosing function scopes
            parent = fn_parent
            while True:
                d = self._local_defs.get((parent, arg.id))
                if d is not None:
                    return d
                a = self._assigns.get((parent, arg.id))
                if a is not None:
                    return self._resolve(a, parent, cls, _depth + 1)
                if parent is None:
                    return None
                parent = next((p for (p, n), v in self._local_defs.items()
                               if v is parent), None)
        name = dotted_name(arg)
        if name and name.startswith('self.') and cls is not None:
            return self._methods.get((cls, name[5:]))
        return None

    def _forwarded_params(self, helper: ast.AST):
        """Parameters of ``helper`` that flow into a traced HOF position
        inside its own body — the ``_lrn_call(kernel, ...)`` indirection:
        a helper taking ``kernel`` and forwarding it into
        ``pl.pallas_call(kernel, ...)`` (directly or via ``partial``)
        makes the CALLER's matching argument a traced function.  One
        level only: a helper forwarding into another helper is the
        documented remaining limit.  Returns ``((position, name), ...)``.
        """
        cached = self._fwd_cache.get(helper)
        if cached is not None:
            return cached
        names: Set[str] = set()
        for node in ast.walk(helper):
            if not isinstance(node, ast.Call):
                continue
            is_hof, idxs = _hof_positions(dotted_name(node.func) or '')
            if not is_hof:
                continue
            args = range(len(node.args)) if idxs is None else idxs
            for i in args:
                if i >= len(node.args):
                    continue
                a = node.args[i]
                if isinstance(a, ast.Call):
                    # partial(kernel, ...) in the HOF position
                    fname = dotted_name(a.func) or ''
                    if fname.split('.')[-1] == 'partial' and a.args:
                        a = a.args[0]
                if isinstance(a, ast.Name):
                    names.add(a.id)
        pos = helper.args.posonlyargs + helper.args.args
        out = tuple((j, a.arg) for j, a in enumerate(pos)
                    if a.arg in names)
        self._fwd_cache[helper] = out
        return out

    def _mark(self, tree: ast.AST) -> None:
        # decorators
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_jit_decorator(d) for d in node.decorator_list):
                    self.traced.add(node)
        # wrapper calls: jax.jit(fn), lax.scan(body, ...), jax.vmap(f)...
        def walk(node, fn_parent, cls):
            for child in ast.iter_child_nodes(node):
                nparent, ncls = fn_parent, cls
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    nparent = child
                elif isinstance(child, ast.ClassDef):
                    ncls = child.name
                if isinstance(child, ast.Call):
                    fname = dotted_name(child.func) or ''
                    is_hof, idxs = _hof_positions(fname)
                    if is_hof:
                        args = (range(len(child.args)) if idxs is None
                                else idxs)
                        for i in args:
                            if i < len(child.args):
                                t = self._resolve(child.args[i],
                                                  fn_parent, cls)
                                if t is not None:
                                    self.traced.add(t)
                    else:
                        # helper indirection: _lrn_call(kernel, ...)
                        # where the helper forwards a parameter into a
                        # HOF position — the caller's argument is traced
                        helper = self._resolve(child.func, fn_parent, cls)
                        if isinstance(helper, (ast.FunctionDef,
                                               ast.AsyncFunctionDef)):
                            for j, pname in self._forwarded_params(helper):
                                a = child.args[j] \
                                    if j < len(child.args) else next(
                                        (kw.value for kw in child.keywords
                                         if kw.arg == pname), None)
                                if a is None:
                                    continue
                                t = self._resolve(a, fn_parent, cls)
                                if t is not None:
                                    self.traced.add(t)
                walk(child, nparent, ncls)
        walk(tree, None, None)
        # closure: nested defs/lambdas inside traced fns are traced
        changed = True
        while changed:
            changed = False
            for t in list(self.traced):
                body = t.body if isinstance(t.body, list) else [t.body]
                for stmt in body:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef,
                                            ast.Lambda)):
                            if sub not in self.traced:
                                self.traced.add(sub)
                                changed = True


def _iter_own_nodes(fn: ast.AST):
    """Walk a function body but stop at nested def/lambda boundaries —
    nested functions of a traced fn are traced themselves and get their
    own visit, so every violation is reported exactly once, at the
    innermost function that contains it."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _check_traced_body(mod: Module, fn: ast.AST,
                       out: List[Finding]) -> None:
    label = getattr(fn, 'name', '<lambda>')
    for node in _iter_own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ''
            leaf = fname.split('.')[-1]
            msg = None
            if isinstance(node.func, ast.Name) and \
                    node.func.id in _SYNC_BUILTINS:
                msg = (f'{node.func.id}() on a traced value forces a '
                       f'device->host sync inside {label}')
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_ATTRS and not node.args:
                msg = (f'.{node.func.attr}() forces a device->host sync '
                       f'inside traced {label}')
            elif fname in _NP_SYNCS:
                msg = (f'{fname}() materializes a traced value on host '
                       f'inside {label}')
            elif isinstance(node.func, ast.Name) and \
                    node.func.id == 'print':
                msg = (f'print() of traced values syncs and retraces '
                       f'inside {label} (use jax.debug.print)')
            elif fname in _NONDET:
                msg = (f'{fname}() inside traced {label} bakes a '
                       f'trace-time constant into the compiled program')
            elif fname.startswith('random.') or \
                    fname.startswith('np.random.') or \
                    fname.startswith('numpy.random.'):
                msg = (f'{fname}() inside traced {label} is host '
                       f'nondeterminism — derive a jax.random key')
            elif fname.endswith('datetime.now') or fname == 'datetime.now':
                if not node.args and not node.keywords:
                    msg = (f'argless datetime.now() inside traced '
                           f'{label} is a trace-time constant')
            if msg is not None:
                out.append(Finding('tracer-hygiene', mod.rel,
                                   node.lineno, msg))


def check_module(mod: Module) -> List[Finding]:
    scope = _Scope(mod)
    findings: List[Finding] = []
    for fn in sorted(scope.traced, key=lambda f: f.lineno):
        _check_traced_body(mod, fn, findings)
    findings.sort(key=lambda f: f.line)
    return findings


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for rel in TARGET_FILES:
        if repo.has(rel):
            findings.extend(check_module(repo.module(rel)))
    return findings
