"""Python glue behind the native C ABI (runtime/cxxnet_wrapper.cc).

The reference exposed its C++ trainer through a C ABI
(``wrapper/cxxnet_wrapper.h:29-225``) so other languages could bind it.
Here the dependency points the other way — the trainer lives in
Python/JAX — so the native ``libcxxnetwrapper.so`` embeds CPython and
calls the flat functions in this module.  Each function takes only
C-friendly types (memoryviews, tuples, strings) and returns either a
contiguous float32 ``np.ndarray``, a ``str``, or ``None`` so the C layer
needs no per-call marshalling logic.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .wrapper import DataIter, Net


def _from_buffer(mv, shape: Tuple[int, ...]) -> np.ndarray:
    arr = np.frombuffer(mv, np.float32, count=int(np.prod(shape)))
    return arr.reshape(shape).copy()


def _as_f32(arr: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(arr, np.float32)


def _as_4d(arr: np.ndarray) -> np.ndarray:
    """Shape to 4-d (batch, c, y, x) the way reference nodes are laid out
    (matrices become (batch, 1, 1, len), layer/layer.h:44-55)."""
    arr = _as_f32(arr)
    if arr.ndim == 4:
        return arr
    if arr.ndim == 2:
        return arr.reshape(arr.shape[0], 1, 1, arr.shape[1])
    if arr.ndim == 1:
        return arr.reshape(arr.shape[0], 1, 1, 1)
    raise ValueError(f'cannot view shape {arr.shape} as 4-d node')


# ---- iterator surface (CXNIO*) ------------------------------------------

def io_create(cfg: str) -> DataIter:
    return DataIter(cfg)


def io_next(it: DataIter) -> int:
    return 1 if it.next() else 0


def io_before_first(it: DataIter) -> None:
    it.before_first()


def io_get_data(it: DataIter) -> np.ndarray:
    return _as_4d(it.get_data())


def io_get_label(it: DataIter) -> np.ndarray:
    lab = _as_f32(it.get_label())
    return lab if lab.ndim == 2 else lab.reshape(lab.shape[0], -1)


# ---- net surface (CXNNet*) ----------------------------------------------

def net_create(device: str, cfg: str) -> Net:
    return Net(dev=device or '', cfg=cfg)


def net_set_param(net: Net, name: str, val: str) -> None:
    net.set_param(name, val)


def net_init_model(net: Net) -> None:
    net.init_model()


def net_save_model(net: Net, fname: str) -> None:
    net.save_model(fname)


def net_load_model(net: Net, fname: str) -> None:
    net.load_model(fname)


def net_start_round(net: Net, rnd: int) -> None:
    net.start_round(rnd)


def net_set_weight(net: Net, mv, size: int, layer_name: str,
                   tag: str) -> None:
    cur = net.get_weight(layer_name, tag)
    if cur is None:
        raise KeyError(f'layer {layer_name} has no weight {tag}')
    if int(size) != cur.size:
        raise ValueError(f'set_weight: size {size} != {cur.size}')
    net.set_weight(_from_buffer(mv, cur.shape), layer_name, tag)


def net_get_weight(net: Net, layer_name: str,
                   tag: str) -> Optional[np.ndarray]:
    w = net.get_weight(layer_name, tag)
    return None if w is None else _as_f32(w)


def net_update_iter(net: Net, it: DataIter) -> None:
    net.update(it)


def net_update_batch(net: Net, data_mv, dshape, label_mv, lshape) -> None:
    net.update(_from_buffer(data_mv, tuple(dshape)),
               _from_buffer(label_mv, tuple(lshape)))


def net_predict_batch(net: Net, data_mv, dshape) -> np.ndarray:
    return _as_f32(net.predict(_from_buffer(data_mv, tuple(dshape))))


def net_predict_iter(net: Net, it: DataIter) -> np.ndarray:
    # Whole-iterator predict (CXNNetPredictIter).  The underlying path is
    # the pipelined predict_stream generator — per-batch host chunks with
    # pad rows already trimmed — so peak host memory beyond the returned
    # array is O(batch); the single concatenation happens only here, at
    # the ABI boundary (the C side needs one contiguous buffer).
    chunks = list(net.predict_stream(it))
    if not chunks:
        return np.empty((0,), np.float32)
    return _as_f32(np.concatenate(chunks, axis=0))


def net_extract_batch(net: Net, data_mv, dshape, node: str) -> np.ndarray:
    return _as_4d(net.extract(_from_buffer(data_mv, tuple(dshape)), node))


def net_extract_iter(net: Net, it: DataIter, node: str) -> np.ndarray:
    # Whole-iterator extract: same streaming path as net_predict_iter —
    # concatenate trimmed per-batch activations once, at the boundary.
    chunks = list(net.extract_stream(it, node))
    if not chunks:
        return np.empty((0, 1, 1, 1), np.float32)
    return _as_4d(np.concatenate(chunks, axis=0))


def net_evaluate(net: Net, it: DataIter, name: str) -> str:
    return net.evaluate(it, name)


# ---- serving surface (CXNNetServe*) --------------------------------------

def net_serve_start(net: Net, cfg: str) -> None:
    """Stand up the serving stack.  ``cfg`` is a compact ``k=v[;k=v...]``
    list (utils.config.parse_kv_list): ``buckets`` (``:``-separated, e.g.
    ``1:8:32``), ``max_queue``, ``max_wait`` (seconds), ``deadline``
    (seconds), ``warm`` (0/1), ``models`` (``|``-separated ``id:dir``
    fleet siblings), ``mem_budget`` (bytes), ``dtype`` (``f32``/
    ``bf16``/``int8`` quantized-inference tier), ``replicas`` (>=2 =
    data-parallel per-device engine replicas behind the one batcher).
    Empty string = all defaults."""
    from .utils.config import parse_kv_list
    kw = {}
    for key, val in parse_kv_list(cfg or ''):
        if key == 'buckets':
            kw['buckets'] = val.replace(':', ',')
        elif key == 'max_queue':
            kw['max_queue'] = int(val)
        elif key == 'max_wait':
            kw['max_wait'] = float(val)
        elif key == 'deadline':
            kw['deadline'] = float(val)
        elif key == 'warm':
            kw['warm'] = bool(int(val))
        elif key == 'models':
            kw['models'] = dict(seg.split(':', 1)
                                for seg in val.split('|') if seg)
        elif key == 'mem_budget':
            kw['mem_budget'] = int(val)
        elif key == 'dtype':
            kw['dtype'] = val
        elif key == 'replicas':
            kw['replicas'] = int(val)
        else:
            raise ValueError(f'unknown serve option: {key!r}')
    net.serve_start(**kw)


def net_serve_predict(net: Net, data_mv, dshape) -> np.ndarray:
    """One request through the micro-batcher: class id per row.  Typed
    serving errors (queue full, deadline) propagate as Python exceptions
    for the C layer's error surface."""
    return _as_f32(net.serve_predict(_from_buffer(data_mv, tuple(dshape))))


def net_serve_reload(net: Net, fname: str) -> None:
    net.serve_reload(fname)


def net_serve_stats(net: Net) -> str:
    return net.serve_stats()


def net_serve_stop(net: Net) -> None:
    net.serve_stop()


def net_obs_stats(net: Net) -> str:
    """The process-wide telemetry hub's ``/statusz`` JSON as one string
    (doc/observability.md) — the C embedder's machine-readable window
    into a live trainer/server without binding an HTTP port."""
    return net.obs_stats()


def net_obs_slos(net: Net) -> str:
    """The ``/slos`` JSON as one string: every attached SLO engine's
    typed verdicts (doc/observability.md "SLOs and burn rates") — the
    portless health surface for C embedders and the future autoscaler."""
    return net.obs_slos()


def net_obs_programs(net: Net) -> str:
    """The ``/programs`` JSON as one string: the compiler-truth program
    ledger — per-executable compile wall-ms, HLO cost and memory rows
    plus the recompile-sentinel totals (doc/observability.md "Programs,
    memory, and MFU")."""
    return net.obs_programs()


def net_autotune(net: Net, spec: str, probe_fn, task: str = 'train') -> str:
    """Run the grafttune search over ``spec`` with the embedding's
    measured probe (``probe_fn(candidate_dict) -> score``, higher
    better) and return the JSON receipt; ``best`` holds the tuned knobs
    (doc/autotune.md)."""
    return net.autotune(spec, probe_fn, task=task)


# ---- train-while-serve surface (CXNNetOnline*) ----------------------------

def net_online_start(net: Net, it: DataIter, cfg: str) -> None:
    """Start the train-while-serve loop (doc/online.md): training runs on
    a background thread over ``it`` while the colocated serving stack
    answers ``net_online_predict``.  ``cfg`` is a compact ``k=v[;k=v...]``
    list: ``model_dir`` (required), ``rounds``, ``save_every``,
    ``freshness_slo``/``freshness_strict``, ``reload``, ``buckets``
    (``:``-separated), ``max_queue``, ``max_wait``, ``deadline``,
    ``steps_per_dispatch``, ``watchdog_deadline``, ``dtype`` (the
    serving engine's quantized tier, ``f32``/``bf16``/``int8``)."""
    from .utils.config import parse_kv_list
    kw = {}
    ints = ('rounds', 'save_every', 'max_queue', 'steps_per_dispatch')
    floats = ('freshness_slo', 'reload', 'max_wait', 'deadline',
              'watchdog_deadline')
    for key, val in parse_kv_list(cfg or ''):
        if key == 'model_dir':
            kw['model_dir'] = val
        elif key == 'buckets':
            kw['buckets'] = val.replace(':', ',')
        elif key == 'dtype':
            kw['dtype'] = val
        elif key == 'freshness_strict':
            kw['freshness_strict'] = bool(int(val))
        elif key in ints:
            kw[key] = int(val)
        elif key in floats:
            kw[key] = float(val)
        else:
            raise ValueError(f'unknown online option: {key!r}')
    if 'model_dir' not in kw:
        raise ValueError('online cfg must set model_dir=')
    net.online_start(it, **kw)


def net_online_predict(net: Net, data_mv, dshape) -> np.ndarray:
    """One request through the live online stack: class id per row.
    Typed serving errors propagate as Python exceptions."""
    return _as_f32(net.online_predict(_from_buffer(data_mv, tuple(dshape))))


def net_online_stats(net: Net) -> str:
    return net.online_stats()


def net_online_wait(net: Net) -> str:
    """Block until the background training run finishes; returns its
    summary as one JSON line (freshness p50/p99, swaps, served,
    dropped, ...)."""
    import json
    return json.dumps(net.online_wait(), sort_keys=True)


def net_online_stop(net: Net) -> None:
    net.online_stop()


# ---- continuous decode surface (CXNLMServe*) ------------------------------

def lm_serve_start(cfg: str):
    """Stand up the continuous-batching decode stack (doc/serving.md
    "Continuous decode") for a transformer LM.  ``cfg`` is the compact
    ``k=v[;k=v...]`` spec :class:`wrapper.LMServe` parses: model spec
    ``vocab``/``d_model``/``heads``/``d_ff``/``stages``/``experts``,
    params from ``model_in`` (a ``%04d.lm`` tree) or ``seed`` init,
    engine shape ``slots``/``pages``/``page_size``/``max_prompt``/
    ``max_new``/``eos``, batcher knobs ``max_queue``/``max_wait``/
    ``deadline``, serving tier ``dtype`` (``f32``/``bf16``/``int8``),
    attention leg ``flash_decode`` (``auto``/``0``/``1``), prefix
    sharing ``prefix_share`` (index page cap, 0 = off), greedy
    speculative decoding ``spec_k`` + ``draft.*`` draft-model keys, and
    the graftcache KV tiers ``kv_host_mb``/``kv_disk_mb``/``kv_dir``/
    ``kv_share_dir`` (doc/serving.md "Tiered KV cache"), plus
    graftshard's ``shard=tp:N`` tensor-parallel decode and
    ``prefill_workers=N`` disaggregated prefill (doc/serving.md
    "Sharded serving").
    Returns the service handle the other ``lm_serve_*`` calls take."""
    from .wrapper import LMServe
    return LMServe.from_spec(cfg)


def lm_serve_generate(svc, prompt_mv, n: int, max_new: int,
                      temperature: float = 0.0, seed: int = 0) -> np.ndarray:
    """One decode request through the admission-controlled stack: blocks
    for the full stream, returns contiguous int32 token ids (the stream
    ends at the engine's EOS when configured).  Typed serving errors
    propagate as Python exceptions for the C error surface."""
    prompt = np.frombuffer(prompt_mv, np.int32, count=int(n))[None]
    rng = None
    if temperature > 0:
        import jax
        rng = jax.random.PRNGKey(int(seed))
    toks = svc.generate(prompt, int(max_new), float(temperature), rng)
    return np.ascontiguousarray(toks, np.int32)


def lm_serve_stats(svc) -> str:
    return svc.report()


def lm_serve_scenario(svc, spec: str, time_scale: float = 1.0) -> str:
    """Drive a seeded adversarial traffic scenario (``serve.scenario=``
    grammar — doc/serving.md "Scenarios and autoscaling") against the
    service and return the reconciled ledger summary as a JSON string
    (submitted / per-bucket terminal counts / p50 / p99 seconds).
    Deterministic: the same spec replays the same storm bit for bit."""
    import json
    return json.dumps(svc.run_scenario(spec, time_scale=float(time_scale)),
                      sort_keys=True)


def lm_serve_autoscale(svc, policy: str):
    """Attach an SLO-driven autoscaler (``serve.autoscale=`` grammar)
    over the service's live admission caps; returns the scaler handle
    (its ``close()`` detaches — call before ``lm_serve_stop``)."""
    return svc.autoscale(policy)


def lm_serve_stop(svc) -> None:
    svc.close()
