"""Data pipeline: chained iterators feeding NCHW host batches."""

from .data import (DataBatch, DataInst, IIterator, ThreadBufferIterator,
                   create_iterator)
