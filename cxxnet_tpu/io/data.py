"""Data pipeline core: DataBatch, iterator interface, and the chain factory.

Chained-iterator architecture preserved from the reference
(``src/io/data.h:19-181``, factory ``src/io/data.cpp:23-74``): sources
(``mnist`` | ``imgbin`` | ``img``) are wrapped by augment+batch stages and
optional ``threadbuffer`` / ``membuffer`` prefetch/cache stages, all
assembled from the ordered config pairs of one ``data = .. iter = .. end``
section.  Batches carry NCHW numpy arrays (the host-side layout contract);
the net transposes to NHWC on device.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..utils.thread_buffer import ThreadBuffer

ConfigEntry = Tuple[str, str]


class NormSpec:
    """Deferred input normalization: what the augment stage would have done
    on host ((x - mean) * scale, ``iter_augment_proc-inl.hpp:199-231``),
    carried alongside a raw uint8 batch so the jitted step applies it on
    device instead.  TPU-side redesign: the reference always ships float32
    to the device; shipping the decoded uint8 halves H2D bytes and skips
    the per-batch host cast (see ``device_normalize`` in iter_augment)."""

    __slots__ = ('mean_img', 'mean_vals', 'scale')

    def __init__(self, mean_img=None, mean_vals=None, scale=1.0):
        self.mean_img = mean_img            # (c, y, x) float32 or None
        self.mean_vals = mean_vals          # (c,) float32 or None
        self.scale = float(scale)

    def resolved_mean(self) -> np.ndarray:
        """The mean actually subtracted, with the host augment path's
        priority (per-channel ``mean_value`` outranks a mean image),
        broadcastable against (..., c, y, x).  Single source of truth for
        host ``apply`` and the trainer's device constants."""
        if self.mean_vals is not None:
            return np.asarray(self.mean_vals, np.float32)[:, None, None]
        if self.mean_img is not None:
            return np.asarray(self.mean_img, np.float32)
        return np.zeros((1, 1, 1), np.float32)

    def apply(self, data: np.ndarray) -> np.ndarray:
        """Host-side application of the deferred normalization — the same
        (x - mean) * scale the jitted step runs (trainer._apply_input_norm).
        Used where raw batches leave the device path, e.g. the C-ABI
        ``CXNIOGetData`` contract, which hands out post-augment float
        data."""
        out = np.asarray(data, np.float32)
        return (out - self.resolved_mean()) * self.scale


class DataBatch:
    """One minibatch (``src/io/data.h:83-181``)."""

    __slots__ = ('data', 'label', 'inst_index', 'num_batch_padd',
                 'pad_synthetic', 'extra_data', 'norm_spec')

    def __init__(self, data: np.ndarray, label: np.ndarray,
                 inst_index: Optional[np.ndarray] = None,
                 num_batch_padd: int = 0,
                 extra_data: Optional[List[np.ndarray]] = None,
                 pad_synthetic: bool = False,
                 norm_spec: Optional[NormSpec] = None):
        self.data = data                    # (b, c, y, x) float32, or uint8
        self.label = label                  # (b, label_width) float32
        self.inst_index = inst_index        # (b,) uint32 or None
        self.num_batch_padd = num_batch_padd
        # True when the padd rows are filler (round_batch=0 short tail) and
        # must be masked out of gradients; False when they are real wrapped
        # instances (round_batch=1) that the reference trains on
        self.pad_synthetic = pad_synthetic
        self.extra_data = extra_data or []
        # set when data is raw uint8 and the trainer must normalize on
        # device (device_normalize=1)
        self.norm_spec = norm_spec

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]


class DataInst:
    """One instance (``src/io/data.h:41-57``)."""

    __slots__ = ('index', 'data', 'label', 'extra_data')

    def __init__(self, index: int, data: np.ndarray, label: np.ndarray,
                 extra_data: Optional[List[np.ndarray]] = None):
        self.index = index
        self.data = data                    # (c, y, x)
        self.label = label                  # (label_width,)
        self.extra_data = extra_data or []


class IIterator:
    """Reference iterator protocol: SetParam*, Init, then per-epoch
    BeforeFirst/Next/Value — exposed pythonically as ``__iter__``."""

    def set_param(self, name: str, val: str) -> None:
        pass

    def init(self) -> None:
        pass

    def get_norm_spec(self) -> Optional[NormSpec]:
        """The deferred-normalization spec of the augment stage in this
        chain, or None.  Wrappers delegate to their wrapped iterator."""
        base = getattr(self, 'base', None)
        return base.get_norm_spec() if base is not None else None

    def pipeline_stats(self):
        """The chain's ``utils.metric.StatSet`` of per-stage pipeline
        counters (decode/augment/collate ms, pool occupancy, buffer
        stalls), or None when no stage is instrumented (stats turn on
        with ``nworker``, doc/io.md).  Wrappers delegate."""
        base = getattr(self, 'base', None)
        return base.pipeline_stats() if base is not None else None

    def iter_thunks(self):
        """One epoch pass as zero-arg callables, each materializing the
        next ``DataInst`` — the submission stream of the parallel
        decode/augment pool (``utils/parallel_pool.py``).  Sources whose
        per-instance work is heavy (JPEG decode, ``iter_imbin``)
        override this to DEFER that work into the thunk so pool workers
        carry it; the default wraps ``__iter__`` (work already done on
        the calling thread, the pool still parallelizes augmentation).
        Thunk order must equal ``__iter__`` order — the pool's
        bitwise-identity contract hangs on it."""
        for inst in self:
            yield (lambda inst=inst: inst)

    def is_replay_stable(self) -> bool:
        """True when every ``__iter__`` replays the SAME item sequence —
        the contract supervised fault recovery relies on to re-wind to
        batch k (doc/fault_tolerance.md).  Iterators that reshuffle per
        epoch pass (imgbin/imgbinx with ``shuffle=1``) return False:
        recovery still restores exact params, but the replayed pass sees
        a fresh permutation.  Wrappers delegate to their wrapped
        iterator."""
        base = getattr(self, 'base', None)
        return base.is_replay_stable() if base is not None else True

    def __iter__(self) -> Iterator:
        raise NotImplementedError


class ThreadBufferIterator(IIterator):
    """Batch-level prefetch (``iter_batch_proc-inl.hpp:136-224``).

    ``buffer_deadline = <seconds>`` (config) arms a per-batch watchdog: a
    producer that misses the deadline raises
    ``runtime.faults.PipelineStallError`` instead of blocking the trainer
    forever (0 disables).  The buffer is batch-scoped for deterministic
    stall injection (doc/fault_tolerance.md).

    ``nworker = N`` (config) is accepted here — the natural place to
    size the pipeline — and cascades down the chain to the augment
    stage, which fans per-instance decode+augment across N pool threads
    (``utils/parallel_pool.py``); output stays bitwise identical for
    any N.  When the chain is instrumented (nworker set), this stage's
    producer/consumer stalls land on the same StatSet."""

    def __init__(self, base: IIterator, buffer_size: int = 2):
        self.base = base
        self._buffer_size = buffer_size
        self._deadline = None
        self._first_deadline = None
        self._buf = self._make_buf()

    def _make_buf(self) -> ThreadBuffer:
        # the FIRST batch of an epoch also pays epoch setup (page
        # permutation, cold decode/augment paths), so it gets a grace
        # multiple of the steady-state deadline unless the conf pins one
        first = self._first_deadline
        if first is None and self._deadline is not None:
            first = self._deadline * 5
        return ThreadBuffer(lambda: iter(self.base), self._buffer_size,
                            deadline=self._deadline, first_deadline=first,
                            fault_scope='batch')

    def set_param(self, name, val):
        if name in ('buffer_deadline', 'buffer_first_deadline'):
            if name == 'buffer_deadline':
                self._deadline = float(val) if float(val) > 0 else None
            else:
                self._first_deadline = \
                    float(val) if float(val) > 0 else None
            # join the old buffer's producers before replacing it — a
            # dropped-but-live producer would keep draining the shared
            # base iterator underneath the new buffer
            self._buf.close(timeout=1.0)
            self._buf = self._make_buf()
        self.base.set_param(name, val)

    def init(self):
        self.base.init()

    def close(self, timeout=None):
        """Join any live prefetch producers (see ThreadBuffer.close)."""
        return self._buf.close(timeout)

    def __iter__(self):
        # late-bound: the chain's StatSet exists only after set_param
        # cascaded an ``nworker`` key to the augment stage
        stats = self.base.pipeline_stats()
        self._buf.stats = stats
        if stats is not None and self._deadline is not None \
                and self._first_deadline is None:
            # pooled chains (nworker): the first batch also fills the
            # pool's in-flight window (nworker*4 instances), so the
            # default epoch-setup grace doubles — same rule as the
            # supervisor's watchdog (doc/fault_tolerance.md)
            self._buf._first_deadline = self._deadline * 10
        return iter(self._buf)


class DenseBufferIterator(IIterator):
    """Cache the first ``max_nbatch`` batches in RAM and loop over them
    (``iter_mem_buffer-inl.hpp:16-75``)."""

    def __init__(self, base: IIterator):
        self.base = base
        self.max_nbatch = 0
        self._cache: Optional[List[DataBatch]] = None

    def set_param(self, name, val):
        if name == 'max_nbatch':
            self.max_nbatch = int(val)
        self.base.set_param(name, val)

    def init(self):
        self.base.init()

    def __iter__(self):
        if self._cache is None:
            cache = []
            for batch in self.base:
                cache.append(batch)
                if self.max_nbatch and len(cache) >= self.max_nbatch:
                    break
            self._cache = cache
        return iter(self._cache)


def create_iterator(cfg: List[ConfigEntry]) -> IIterator:
    """Assemble an iterator chain from one config section
    (``src/io/data.cpp:23-74``)."""
    from .iter_batch import BatchAdaptIterator
    from .iter_mnist import MNISTIterator

    it: Optional[IIterator] = None
    for name, val in cfg:
        if name == 'iter':
            if val == 'mnist':
                assert it is None, 'mnist cannot chain over another iterator'
                it = MNISTIterator()
            elif val in ('imgbin', 'imgbinx', 'imgbin_stream', 'img'):
                assert it is None, f'{val} cannot chain over another iterator'
                from .iter_augment import AugmentIterator
                if val == 'img':
                    from .iter_img import ImageIterator
                    src = ImageIterator()
                elif val == 'imgbinx':
                    from .iter_imbin import ImageBinXIterator
                    src = ImageBinXIterator()
                elif val == 'imgbin_stream':
                    from .iter_stream import ImageBinStreamIterator
                    src = ImageBinStreamIterator()
                else:
                    from .iter_imbin import ImageBinIterator
                    src = ImageBinIterator()
                it = BatchAdaptIterator(AugmentIterator(src))
            elif val == 'threadbuffer':
                assert it is not None, 'must specify input of threadbuffer'
                it = ThreadBufferIterator(it)
            elif val == 'membuffer':
                assert it is not None, 'must specify input of membuffer'
                it = DenseBufferIterator(it)
            elif val == 'attachtxt':
                from .iter_attach import AttachTxtIterator
                assert it is not None, 'must specify input of attachtxt'
                it = AttachTxtIterator(it)
            elif val == 'end':
                break
            else:
                raise ValueError(f'unknown iterator type {val}')
        elif it is not None:
            it.set_param(name, val)
    assert it is not None, 'must specify iterator by iter=itername'
    return it
