"""Attach per-instance side features from a text file
(``src/io/iter_attach_txt-inl.hpp:15-99``): joins rows of
``filename`` (one vector per line, instances keyed by ``inst_index``) into
``batch.extra_data``.
"""

from __future__ import annotations

import numpy as np

from .data import IIterator


class AttachTxtIterator(IIterator):
    def __init__(self, base: IIterator):
        self.base = base
        self.filename = ''
        self.num_extra = 1
        self._table = None

    def set_param(self, name, val):
        self.base.set_param(name, val)
        if name == 'attach_file':
            self.filename = val
        if name == 'extra_data_num':
            self.num_extra = int(val)

    def init(self):
        self.base.init()
        assert self.filename, 'attachtxt: must set attach_file'
        self._table = np.loadtxt(self.filename, dtype=np.float32, ndmin=2)

    def __iter__(self):
        for batch in self.base:
            if batch.inst_index is None:
                raise ValueError('attachtxt requires instance indices')
            rows = self._table[batch.inst_index.astype(np.int64)]
            batch.extra_data = [
                rows.reshape(rows.shape[0], 1, 1, -1)
                for _ in range(self.num_extra)]
            yield batch
