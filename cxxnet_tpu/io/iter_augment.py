"""Per-instance augmentation (``src/io/iter_augment_proc-inl.hpp:21-246`` +
the affine pipeline of ``src/io/image_augmenter-inl.hpp:13-204``).

Stages, in reference order:

1. optional affine warp (rotation from ``max_rotate_angle``/``rotate``/
   ``rotate_list``, shear, scale, aspect ratio) — only active when those
   params are set (``NeedProcess``); scipy affine_transform replaces
   cv::warpAffine, constant fill ``fill_value`` (default 255),
2. crop to ``input_shape`` — random (``rand_crop``) or center, with
   deterministic overrides ``crop_y_start``/``crop_x_start``,
3. mirror — random (``rand_mirror``) or forced (``mirror=1``),
4. mean subtraction — per-channel ``mean_value`` or a mean *image* file
   (``image_mean``), built over one pass of the dataset and cached to disk
   on first use exactly like the reference,
5. random contrast/illumination, then ``scale``/``divideby``.

For flat inputs (``input_shape`` c==1,y==1) only scaling applies.
"""

from __future__ import annotations

import os
import struct
import time

import numpy as np

from .data import DataInst, IIterator


class ImageAugmenter:
    """Affine warp stage (rotation/shear/scale/aspect)."""

    def __init__(self):
        self.max_rotate_angle = 0.0
        self.max_aspect_ratio = 0.0
        self.max_shear_ratio = 0.0
        self.min_crop_size = -1
        self.max_crop_size = -1
        self.rotate = -1.0
        self.rotate_list = []
        self.max_random_scale = 1.0
        self.min_random_scale = 1.0
        self.min_img_size = 0.0
        self.max_img_size = 1e10
        self.fill_value = 255

    def set_param(self, name, val):
        if name == 'max_rotate_angle':
            self.max_rotate_angle = float(val)
        if name == 'max_shear_ratio':
            self.max_shear_ratio = float(val)
        if name == 'max_aspect_ratio':
            self.max_aspect_ratio = float(val)
        if name == 'min_crop_size':
            self.min_crop_size = int(val)
        if name == 'max_crop_size':
            self.max_crop_size = int(val)
        if name == 'min_random_scale':
            self.min_random_scale = float(val)
        if name == 'max_random_scale':
            self.max_random_scale = float(val)
        if name == 'min_img_size':
            self.min_img_size = float(val)
        if name == 'max_img_size':
            self.max_img_size = float(val)
        if name == 'fill_value':
            self.fill_value = int(val)
        if name == 'rotate':
            self.rotate = float(val)
        if name == 'rotate_list':
            self.rotate_list = [int(t) for t in val.split(',') if t]

    def need_process(self) -> bool:
        if (self.max_rotate_angle > 0 or self.max_shear_ratio > 0
                or self.rotate > 0 or self.rotate_list):
            return True
        if self.min_crop_size > 0 and self.max_crop_size > 0:
            return True
        return False

    def process(self, data: np.ndarray, rng: np.random.RandomState,
                out_y: int, out_x: int) -> np.ndarray:
        """data: (c, h, w) → warped image, still larger than (out_y, out_x)
        when possible (the caller crops)."""
        if not self.need_process():
            return data
        from scipy import ndimage
        c, rows, cols = data.shape
        s = rng.rand() * self.max_shear_ratio * 2 - self.max_shear_ratio
        if self.max_rotate_angle > 0:
            angle = rng.randint(0, int(self.max_rotate_angle * 2) + 1) \
                - self.max_rotate_angle
        else:
            angle = 0
        if self.rotate > 0:
            angle = self.rotate
        if self.rotate_list:
            angle = self.rotate_list[rng.randint(0, len(self.rotate_list))]
        a = np.cos(angle / 180.0 * np.pi)
        b = np.sin(angle / 180.0 * np.pi)
        scale = rng.rand() * (self.max_random_scale
                              - self.min_random_scale) + self.min_random_scale
        ratio = rng.rand() * self.max_aspect_ratio * 2 \
            - self.max_aspect_ratio + 1
        hs = 2 * scale / (1 + ratio)
        ws = ratio * hs
        new_w = int(max(self.min_img_size,
                        min(self.max_img_size, scale * cols)))
        new_h = int(max(self.min_img_size,
                        min(self.max_img_size, scale * rows)))
        # forward matrix (reference image_augmenter:97-104), mapping
        # (x=col, y=row) source → destination
        M = np.array([[hs * a - s * b * ws, hs * b + s * a * ws],
                      [-b * ws, a * ws]], dtype=np.float64)
        tx = (new_w - (M[0, 0] * cols + M[0, 1] * rows)) / 2
        ty = (new_h - (M[1, 0] * cols + M[1, 1] * rows)) / 2
        # scipy works on (row, col) with inverse mapping
        Mrc = np.array([[M[1, 1], M[1, 0]], [M[0, 1], M[0, 0]]])
        inv = np.linalg.inv(Mrc)
        offset = -inv @ np.array([ty, tx])
        # warp in float32: affine_transform returns the INPUT dtype when no
        # output= is given, so uint8 sources would quantize interpolated
        # pixels and wrap cubic-spline overshoot (e.g. -3 -> 253)
        data = np.asarray(data, np.float32)
        out = np.empty((c, new_h, new_w), np.float32)
        for ch in range(c):
            out[ch] = ndimage.affine_transform(
                data[ch], inv, offset=offset, output_shape=(new_h, new_w),
                order=3, mode='constant', cval=self.fill_value)
        return out


class AugmentIterator(IIterator):
    """Serial by default (one sequential RNG, reference-exact stream).

    ``nworker = N`` switches to the pooled path: per-instance decode
    (``base.iter_thunks``) + augmentation fan across an order-preserving
    worker pool (``utils/parallel_pool.py``).  Per-instance RNG is then
    seeded from the **epoch-absolute instance index** — NOT drawn from a
    shared sequential stream — so the output is bitwise identical for
    any worker count (including N=1), replay-stability is preserved,
    and a pooled run is reproducible against another pooled run of any
    width.  (The pooled stream therefore differs from the legacy serial
    stream: pick one mode per experiment.)  Per-stage timings land on
    ``pipeline_stats()``.

    ``elastic_hosts = H`` / ``elastic_rank = h`` promote the same
    invariant from threads to hosts (doc/fault_tolerance.md "Multi-host
    recovery"): this stage keeps the GLOBAL epoch-absolute enumeration
    of the source's thunk stream but materializes only instances with
    ``index % H == h`` — skipped thunks never decode (the work is
    deferred into the thunk), and the per-instance RNG still keys on
    the global index.  Interleaving the H hosts' streams round-robin
    therefore reconstructs the 1-host stream bitwise, at any host
    count.  Requires the pooled path (``nworker >= 1``): the serial
    path's shared sequential RNG cannot shard."""

    def __init__(self, base: IIterator):
        self.base = base
        self.nworker = 0            # 0 = legacy serial path
        self.elastic_hosts = 1      # per-host stream sharding (elastic)
        self.elastic_rank = 0
        self._stats = None
        self.shape = (0, 0, 0)      # (c, y, x)
        self.rand_crop = 0
        self.rand_mirror = 0
        self.mirror = 0
        self.crop_y_start = -1
        self.crop_x_start = -1
        self.scale = 1.0
        self.silent = 0
        self.name_meanimg = ''
        self.mean_vals = None       # per-channel values (ch order 0,1,2)
        self.max_random_contrast = 0.0
        self.max_random_illumination = 0.0
        self.seed_data = 0
        self.device_normalize = 0
        self.aug = ImageAugmenter()
        self._meanimg = None
        self._warned_dev_norm = False

    def set_param(self, name, val):
        self.base.set_param(name, val)
        self.aug.set_param(name, val)
        if name == 'nworker':
            self.nworker = max(0, int(val))
            if self.nworker and self._stats is None:
                from ..utils.metric import StatSet
                self._stats = StatSet()
        if name == 'elastic_hosts':
            self.elastic_hosts = max(1, int(val))
        if name == 'elastic_rank':
            self.elastic_rank = int(val)
        if name == 'input_shape':
            self.shape = tuple(int(t) for t in val.split(','))
        if name == 'seed_data':
            self.seed_data = int(val)
        if name == 'rand_crop':
            self.rand_crop = int(val)
        if name == 'silent':
            self.silent = int(val)
        if name == 'divideby':
            self.scale = 1.0 / float(val)
        if name == 'scale':
            self.scale = float(val)
        if name == 'image_mean':
            self.name_meanimg = val
        if name == 'crop_y_start':
            self.crop_y_start = int(val)
        if name == 'crop_x_start':
            self.crop_x_start = int(val)
        if name == 'rand_mirror':
            self.rand_mirror = int(val)
        if name == 'mirror':
            self.mirror = int(val)
        if name == 'max_random_contrast':
            self.max_random_contrast = float(val)
        if name == 'max_random_illumination':
            self.max_random_illumination = float(val)
        if name == 'mean_value':
            self.mean_vals = np.asarray(
                [float(t) for t in val.split(',')], np.float32)
        if name == 'device_normalize':
            self.device_normalize = int(val)

    def init(self):
        self.base.init()
        if self.name_meanimg:
            if os.path.exists(self.name_meanimg):
                if self.silent == 0:
                    print(f'loading mean image from {self.name_meanimg}')
                self._meanimg = _load_mean(self.name_meanimg)
            else:
                self._create_mean_img()

    def _process_raw(self, data, rng):
        """Affine + crop + mirror for ONE instance array — the stage-1
        body of ``_raw_iter``, factored out so the pooled path can run
        it per-worker with a per-instance RNG.  Draw order is exactly
        the serial path's (process → crop randints → mirror rand)."""
        c, ty, tx = self.shape
        data = self.aug.process(data, rng, ty, tx)
        if ty == 1 and c == 1:
            return data                   # flat input: no crop
        _, h, w = data.shape
        assert h >= ty and w >= tx, \
            'Data size must be bigger than the input size to net.'
        yy, xx = h - ty, w - tx
        if self.rand_crop != 0 and (yy != 0 or xx != 0):
            yy = rng.randint(0, yy + 1)
            xx = rng.randint(0, xx + 1)
        else:
            yy //= 2
            xx //= 2
        if h != ty and self.crop_y_start != -1:
            yy = self.crop_y_start
        if w != tx and self.crop_x_start != -1:
            xx = self.crop_x_start
        crop = data[:, yy:yy + ty, xx:xx + tx]
        if (self.rand_mirror != 0 and rng.rand() < 0.5) or self.mirror == 1:
            crop = crop[:, :, ::-1]
        return crop

    def _raw_iter(self):
        """Instances after affine + crop + mirror, before mean/scale —
        used for mean-image computation."""
        rng = np.random.RandomState(self.seed_data)
        for inst in self.base:
            yield inst, self._process_raw(inst.data, rng)

    def _device_norm_active(self) -> bool:
        """uint8-through mode: crop/mirror on host, (x-mean)*scale deferred
        to the device step (NormSpec).  Random contrast/illumination are
        per-instance host-RNG draws baked into the pixels, so they force
        the host path."""
        if not self.device_normalize:
            return False
        c, ty, tx = self.shape
        if ty == 1 and c == 1:
            return False                    # flat input: host scale only
        if self.max_random_contrast > 0 or self.max_random_illumination > 0:
            if not self._warned_dev_norm and self.silent == 0:
                print('device_normalize=1 ignored: random contrast/'
                      'illumination require the host normalize path')
                self._warned_dev_norm = True
            return False
        return True

    def get_norm_spec(self):
        if not self._device_norm_active():
            return None
        from .data import NormSpec
        # host-path quirk preserved: a mean image whose shape mismatches
        # the input is silently skipped (see __iter__), so the deferred
        # spec must drop it too rather than crash the jitted broadcast
        mean_img = self._meanimg
        if mean_img is not None and tuple(mean_img.shape) != self.shape:
            mean_img = None
        return NormSpec(mean_img=mean_img, mean_vals=self.mean_vals,
                        scale=self.scale)

    def _finish_host(self, inst, crop, rng):
        """Host-normalize ONE cropped instance (contrast/illumination/
        mean/scale) — the stage-2 body of the serial ``__iter__``, same
        draw order (contrast rand, then illumination rand)."""
        c, ty, tx = self.shape
        if ty == 1 and c == 1:
            return DataInst(inst.index,
                            np.asarray(crop, np.float32) * self.scale,
                            inst.label, inst.extra_data)
        contrast = 1.0
        illum = 0.0
        if self.max_random_contrast > 0:
            contrast = rng.rand() * self.max_random_contrast * 2 \
                - self.max_random_contrast + 1
        if self.max_random_illumination > 0:
            illum = rng.rand() * self.max_random_illumination * 2 \
                - self.max_random_illumination
        out = crop.astype(np.float32)
        if self.mean_vals is not None:
            out = out - self.mean_vals[:, None, None]
        elif self._meanimg is not None:
            if self._meanimg.shape == out.shape:
                out = out - self._meanimg
        out = (out * contrast + illum) * self.scale
        return DataInst(inst.index, out, inst.label, inst.extra_data)

    def pipeline_stats(self):
        return self._stats

    def _inst_rng(self, i: int, salt: int) -> np.random.RandomState:
        """Pooled-path RNG for epoch-absolute instance ``i``: a fresh
        MT19937 seeded from (seed_data, salt, i) only, so any worker can
        compute instance i's draws with no shared stream — the bitwise-
        identical-for-any-worker-count property.  ``salt`` separates the
        affine/crop/mirror stream (0) from contrast/illumination (91),
        mirroring the serial path's two seeds."""
        return np.random.RandomState(
            (self.seed_data + salt + (i + 1) * 2654435761) % (2 ** 31))

    def _sharded_thunks(self):
        """The pooled submission stream: ``(global_index, thunk)`` pairs,
        elastic-sharded to this host.  The enumeration stays GLOBAL so
        the per-instance RNG — and hence the emitted bytes — for
        instance i are identical no matter how many hosts split the
        stream; a skipped thunk costs nothing (decode rides inside)."""
        hosts, rank = self.elastic_hosts, self.elastic_rank
        if hosts <= 1:
            yield from enumerate(self.base.iter_thunks())
            return
        for i, thunk in enumerate(self.base.iter_thunks()):
            if i % hosts == rank:
                yield i, thunk

    def _iter_pooled(self):
        """nworker path: decode thunks from the source fan across an
        order-preserving pool together with this stage's augmentation;
        per-stage wall times flow to ``pipeline_stats()``."""
        from ..utils.parallel_pool import OrderedWorkerPool
        dev_norm = self._device_norm_active()
        stats = self._stats
        pool = OrderedWorkerPool(self.nworker, stats=stats, name='pool')

        def job(task):
            i, thunk = task
            t0 = time.perf_counter()
            inst = thunk()                      # source decode (deferred)
            t1 = time.perf_counter()
            crop = self._process_raw(inst.data, self._inst_rng(i, 0))
            if dev_norm:
                out = DataInst(inst.index, np.ascontiguousarray(crop),
                               inst.label, inst.extra_data)
            else:
                out = self._finish_host(inst, crop, self._inst_rng(i, 91))
            if stats is not None:
                t2 = time.perf_counter()
                stats.observe('decode_ms', (t1 - t0) * 1e3)
                stats.observe('augment_ms', (t2 - t1) * 1e3)
            return out

        yield from pool.imap(job, self._sharded_thunks())

    def __iter__(self):
        if self.nworker:
            yield from self._iter_pooled()
            return
        if self.elastic_hosts > 1:
            raise ValueError(
                'elastic_hosts > 1 requires the pooled path (nworker >= '
                '1): the serial stream draws from one shared sequential '
                'RNG, which cannot shard per host and stay bitwise '
                'reconstructable')
        if self._device_norm_active():
            # raw crops go to the device untouched; normalization happens
            # inside the jitted step (trainer._apply_input_norm)
            for inst, crop in self._raw_iter():
                yield DataInst(inst.index, np.ascontiguousarray(crop),
                               inst.label, inst.extra_data)
            return
        rng = np.random.RandomState(self.seed_data + 91)
        for inst, crop in self._raw_iter():
            yield self._finish_host(inst, crop, rng)

    def _create_mean_img(self):
        if self.silent == 0:
            print(f'cannot find {self.name_meanimg}: create mean image, '
                  f'this will take some time...')
        start = time.monotonic()
        mean = None
        cnt = 0
        for _, crop in self._raw_iter():
            mean = crop.astype(np.float64) if mean is None else mean + crop
            cnt += 1
            if cnt % 1000 == 0 and self.silent == 0:
                print(f'[{cnt:8d}] images processed, '
                      f'{int(time.monotonic() - start)} sec elapsed')
        assert cnt > 0, 'input iterator failed.'
        self._meanimg = (mean / cnt).astype(np.float32)
        _save_mean(self.name_meanimg, self._meanimg)
        if self.silent == 0:
            print(f'save mean image to {self.name_meanimg}..')


def _save_mean(path: str, img: np.ndarray) -> None:
    """(ndim, shape, float32 data) — mshadow SaveBinary convention."""
    with open(path, 'wb') as f:
        f.write(struct.pack('<I', img.ndim))
        f.write(struct.pack(f'<{img.ndim}I', *img.shape))
        f.write(np.ascontiguousarray(img, np.float32).tobytes())


def _load_mean(path: str) -> np.ndarray:
    with open(path, 'rb') as f:
        (ndim,) = struct.unpack('<I', f.read(4))
        shape = struct.unpack(f'<{ndim}I', f.read(4 * ndim))
        data = np.frombuffer(f.read(), np.float32)
    return data[:int(np.prod(shape))].reshape(shape).copy()
