"""Instance→batch adapter (``src/io/iter_batch_proc-inl.hpp:16-133``).

Collects ``DataInst`` from an instance iterator into fixed-size batches.
``round_batch=1``: when the epoch ends mid-batch, wrap around to the first
instances of the *next* epoch pass and report ``num_batch_padd`` (the count
of wrapped/padding instances) so evaluation can exclude them — same contract
as the reference.  ``test_skipread=1`` re-serves one cached batch to bound
maximum pipeline throughput (used by the ``test_io`` harness).
"""

from __future__ import annotations

import time

import numpy as np

from .data import DataBatch, IIterator


class BatchAdaptIterator(IIterator):
    def __init__(self, base):
        self.base = base               # instance iterator
        self.batch_size = 0
        self.round_batch = 0
        self.test_skipread = 0
        self.label_width = 1
        self._cached: DataBatch | None = None
        self._norm_spec = None
        self._stats = None

    def set_param(self, name, val):
        if name == 'batch_size':
            self.batch_size = int(val)
        if name == 'round_batch':
            self.round_batch = int(val)
        if name == 'test_skipread':
            self.test_skipread = int(val)
        if name == 'label_width':
            self.label_width = int(val)
        self.base.set_param(name, val)

    def init(self):
        self.base.init()
        self._norm_spec = self.base.get_norm_spec()
        self._stats = self.base.pipeline_stats()

    def _make_batch(self, insts):
        if self._stats is not None:
            t0 = time.perf_counter()
            out = self._collate(insts)
            self._stats.observe('collate_ms',
                                (time.perf_counter() - t0) * 1e3)
            return out
        return self._collate(insts)

    def _collate(self, insts):
        data = np.stack([i.data for i in insts])
        if not (data.dtype == np.uint8 and self._norm_spec is not None):
            # reference host contract: float32 batches
            # (device_normalize keeps the decoded uint8 on the wire)
            data = data.astype(np.float32)
        label = np.stack([np.atleast_1d(i.label) for i in insts]).astype(np.float32)
        index = np.asarray([i.index for i in insts], dtype=np.uint32)
        return data, label, index

    def __iter__(self):
        assert self.batch_size > 0, 'batch: batch_size must be set'
        if self.test_skipread and self._cached is not None:
            while True:   # bounded by consumer; used only by test_io harness
                yield self._cached
        bs = self.batch_size
        buf = []
        for inst in self.base:
            buf.append(inst)
            if len(buf) == bs:
                data, label, index = self._make_batch(buf)
                batch = DataBatch(data, label, index,
                                  norm_spec=self._norm_spec)
                if self.test_skipread and self._cached is None:
                    self._cached = batch
                yield batch
                buf = []
        if buf and self.round_batch:
            # wrap with the first instances of a fresh epoch pass, like the
            # reference's BeforeFirst-and-continue (iter_batch_proc:84-101)
            npadd = bs - len(buf)
            wrap = []
            while len(wrap) < npadd:
                took = False
                for inst in self.base:
                    wrap.append(inst)
                    took = True
                    if len(wrap) == npadd:
                        break
                if not took:
                    raise RuntimeError('round_batch: source is empty')
            data, label, index = self._make_batch(buf + wrap)
            yield DataBatch(data, label, index, num_batch_padd=npadd,
                            norm_spec=self._norm_spec)
        elif buf:
            # round_batch=0: emit the short final batch padded to full size
            # with num_batch_padd = batch_size - top
            # (iter_batch_proc-inl.hpp:101-103; the reference pads with stale
            # rows of its reused buffer — here the last real instance is
            # repeated, equally ignored downstream).  Consumers mask the pad
            # rows out of grads/metrics/predictions; full-size batches keep
            # jit shapes static on TPU.
            npadd = bs - len(buf)
            data, label, index = self._make_batch(buf + [buf[-1]] * npadd)
            yield DataBatch(data, label, index, num_batch_padd=npadd,
                            pad_synthetic=True, norm_spec=self._norm_spec)
