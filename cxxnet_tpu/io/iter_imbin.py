"""Binary-page image sources (``imgbin`` and ``imgbinx``).

Reads the reference's packed image format: a ``.bin`` stream of 64MB
``BinaryPage``s whose objects are encoded (JPEG/PNG) image blobs, paired
record-for-record with a ``.lst`` file carrying ``index \\t labels...``.

``imgbin`` (``src/io/iter_thread_imbin-inl.hpp:16-283``):
* multi-part datasets via ``image_conf_prefix`` printf-style pattern +
  ``image_conf_ids = a-b`` (iter_thread_imbin:225-278),
* distributed worker sharding: parts (or pages, for a single file) are
  round-robin split across workers by ``dist_num_worker`` /
  ``dist_worker_rank`` (``PS_RANK`` env respected, :189-220),
* ``shuffle=1`` randomizes page order — pages are fixed 64MB records, so a
  single ``.bin`` is random-access by page index (beyond the reference,
  whose plain imgbin reads strictly sequentially and has no shuffle).

``imgbinx`` (``src/io/iter_thread_imbin_x-inl.hpp:18-397``): the two-stage
pipeline — a page-loading stage behind a ThreadBuffer (page-order shuffle
reseeded each epoch) feeding a decode stage behind a second, deeper
ThreadBuffer that also randomizes instance order *within* each page; decode
therefore overlaps page IO instead of serializing behind it.

Decode uses native libjpeg when built, PIL otherwise.
"""

from __future__ import annotations

import collections
import io
import os

import numpy as np

from ..utils.io_stream import BinaryPage
from ..utils.thread_buffer import ThreadBuffer
from .data import DataInst, IIterator
from .iter_img import parse_lst_line


def scan_page_table(bin_path: str, start_page: int = 0):
    """Per-page object counts of a ``.bin`` file, read from the page
    headers only (4 bytes at each 64MB boundary) — no payload IO.
    ``start_page`` skips already-scanned pages: re-scanning a GROWN file
    reads only the appended pages' headers (the file size is read fresh
    on every call, never cached across calls — an appendable file's size
    is only valid for the scan that observed it).  Only COMPLETE pages
    are reported; a partially-appended tail page is invisible until the
    writer finishes it."""
    counts = []
    size = os.path.getsize(bin_path)
    with open(bin_path, 'rb') as f:
        for off in range(start_page * BinaryPage.N_BYTES,
                         size - BinaryPage.N_BYTES + 1, BinaryPage.N_BYTES):
            f.seek(off)
            counts.append(int.from_bytes(f.read(4), 'little'))
    return counts


class ImageBinIterator(IIterator):
    def __init__(self):
        self.path_imglist = ''
        self.path_imgbin = ''
        self.label_width = 1
        self.silent = 0
        self.shuffle = 0
        self.seed_data = 0
        self.conf_prefix = ''
        self.conf_ids = ''
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self._lists = []
        self._bins = []

    def set_param(self, name, val):
        if name in ('image_list', 'path_imglist'):
            self.path_imglist = val
        if name in ('image_bin', 'path_imgbin'):
            self.path_imgbin = val
        if name == 'label_width':
            self.label_width = int(val)
        if name == 'silent':
            self.silent = int(val)
        if name == 'shuffle':
            self.shuffle = int(val)
        if name == 'seed_data':
            self.seed_data = int(val)
        if name == 'image_conf_prefix':
            self.conf_prefix = val
        if name == 'image_conf_ids':
            self.conf_ids = val
        if name == 'dist_num_worker':
            self.dist_num_worker = int(val)
        if name == 'dist_worker_rank':
            self.dist_worker_rank = int(val)

    def init(self):
        rank = int(os.environ.get('PS_RANK', self.dist_worker_rank))
        nworker = self.dist_num_worker
        if self.conf_prefix:
            a, _, b = self.conf_ids.partition('-')
            ids = list(range(int(a), int(b or a) + 1))
            # shard whole parts across workers (iter_thread_imbin:196-213)
            ids = ids[rank::nworker] if nworker > 1 else ids
            self._lists = [self.conf_prefix % i + '.lst' for i in ids]
            self._bins = [self.conf_prefix % i + '.bin' for i in ids]
        else:
            assert self.path_imglist and self.path_imgbin, \
                'imgbin: must set image_list and image_bin'
            self._lists = [self.path_imglist]
            self._bins = [self.path_imgbin]
        self._single_shard = (nworker > 1 and not self.conf_prefix,
                              rank, nworker)
        self._epoch = 0
        self._tables: dict = {}
        if self.silent == 0:
            print(f'{type(self).__name__}: {len(self._bins)} part(s), '
                  f'worker {rank}/{nworker}')

    def _iter_pages(self, bin_path):
        """Prefer the native C++ page reader (background prefetch thread +
        libjpeg); fall back to the Python BinaryPage parser."""
        from ..runtime.native import NativePageReader, native_available
        if native_available():
            reader = NativePageReader(bin_path)
            try:
                yield from reader.iter_pages()
            finally:
                reader.close()
            return
        with open(bin_path, 'rb') as f:
            while True:
                page = BinaryPage()
                if not page.load(f):
                    return
                yield list(page)

    def _decode(self, blob):
        from ..runtime.native import decode_jpeg
        arr = decode_jpeg(blob)          # fast path: native libjpeg
        if arr is None:                  # non-JPEG (png, ...) or no native
            from PIL import Image
            with Image.open(io.BytesIO(blob)) as im:
                arr = np.asarray(im.convert('RGB'), np.uint8)
        # keep the decoded uint8: the augment stage owns the float32
        # conversion (host path) or defers it to device (device_normalize)
        return np.transpose(arr, (2, 0, 1))

    def _load_lines(self, part):
        with open(self._lists[part]) as f:
            return [parse_lst_line(l) for l in f if l.strip()]

    def _page_starts(self, part):
        """(counts, starts): per-page object counts and the cumulative
        .lst line offset of each page of this part.  Cached per part;
        :meth:`_refresh_page_table` extends the cache when the file has
        grown."""
        if part not in self._tables:
            counts = scan_page_table(self._bins[part])
            starts = [0]
            for c in counts:
                starts.append(starts[-1] + c)
            self._tables[part] = (counts, starts)
        return self._tables[part]

    def _refresh_page_table(self, part):
        """Extend the cached page table with any pages appended since it
        was last scanned, reading ONLY the new pages' headers — a
        re-opened/grown file yields its new tail without re-reading (or
        re-decoding) the pages already indexed.  The incremental scan
        the streaming source (``imgbin_stream``) polls on."""
        if part not in self._tables:
            return self._page_starts(part)
        counts, starts = self._tables[part]
        for c in scan_page_table(self._bins[part], start_page=len(counts)):
            counts.append(c)
            starts.append(starts[-1] + c)
        return counts, starts

    def _page_stream(self, part, page_order=None):
        """Yield (page_idx, blobs); ``page_order=None`` streams the file
        sequentially, else reads page-by-page in the given order — pages
        are fixed 64MB records, hence random-access.  Both paths prefer
        the native C++ prefetching reader."""
        if page_order is None:
            yield from enumerate(self._iter_pages(self._bins[part]))
            return
        page_order = list(page_order)
        from ..runtime.native import NativePageReader, native_order_available
        if native_order_available():
            reader = NativePageReader(self._bins[part], order=page_order)
            try:
                for pidx, page in zip(page_order, reader.iter_pages()):
                    yield pidx, page
            finally:
                reader.close()
            return
        with open(self._bins[part], 'rb') as f:
            for pidx in page_order:
                f.seek(pidx * BinaryPage.N_BYTES)
                page = BinaryPage()
                if not page.load(f):
                    raise RuntimeError('imgbin: truncated page '
                                       f'{pidx} in {self._bins[part]}')
                yield pidx, list(page)

    def _make_inst(self, blob, line):
        index, labels, _ = line
        return DataInst(index, self._decode(blob),
                        labels[:self.label_width]
                        if self.label_width else labels)

    def is_replay_stable(self) -> bool:
        # shuffle=1 draws a fresh permutation per __iter__ (_epoch_rngs
        # bumps the epoch ordinal), so a replayed pass is a different
        # sequence; sequential reads are bit-stable
        return not self.shuffle

    def _epoch_rngs(self):
        """Fresh deterministic RNGs for one epoch pass, seeded from
        (seed_data, epoch ordinal) on the consumer thread — so producer
        prefetch depth or an abandoned pass (round_batch wrap) cannot
        desync later epochs, yet every epoch gets a new permutation.
        Distinct page/instance streams mirror the reference imgbinx's
        kRandMagic=121/111 samplers."""
        e = self._epoch
        self._epoch += 1
        return (np.random.RandomState((self.seed_data + 121 + e * 7919)
                                      % (2 ** 31)),
                np.random.RandomState((self.seed_data + 111 + e * 104729)
                                      % (2 ** 31)))

    def _epoch_pages(self, rng_page):
        """One epoch pass at page granularity: yields ``(blobs,
        lines_slice)`` applying part-order shuffle, page-order shuffle
        within each part (single-file datasets included — the fix for
        ``shuffle=1`` being a no-op there), worker sharding, and .lst
        pairing in one place.  Sharded shuffled passes filter the page
        permutation *before* any IO, so each worker reads only its own
        1/N of the pages."""
        sharded, rank, nworker = self._single_shard
        order = list(range(len(self._bins)))
        if self.shuffle:
            rng_page.shuffle(order)
        for part in order:
            lines = self._load_lines(part)
            if self.shuffle:
                counts, starts = self._page_starts(part)
                if starts[-1] > len(lines):
                    raise RuntimeError('imgbin: .lst shorter than .bin '
                                       'contents')
                page_order = [p for p in rng_page.permutation(len(counts))
                              if not sharded or p % nworker == rank]
                for pidx, blobs in self._page_stream(part, page_order):
                    base = starts[pidx]
                    yield blobs, lines[base:base + len(blobs)]
            elif sharded:
                # unshuffled but sharded: seek past non-owned pages instead
                # of reading and discarding them (1/N of the IO per worker)
                counts, starts = self._page_starts(part)
                if starts[-1] > len(lines):
                    raise RuntimeError('imgbin: .lst shorter than .bin '
                                       'contents')
                owned = [p for p in range(len(counts))
                         if p % nworker == rank]
                for pidx, blobs in self._page_stream(part, owned):
                    yield blobs, lines[starts[pidx]:
                                       starts[pidx] + len(blobs)]
            else:
                base = 0
                for pidx, blobs in self._page_stream(part):
                    if base + len(blobs) > len(lines):
                        raise RuntimeError('imgbin: .lst shorter than .bin '
                                           'contents')
                    yield blobs, lines[base:base + len(blobs)]
                    base += len(blobs)

    def __iter__(self):
        # defined over iter_thunks so the serial and pooled paths can
        # never disagree on instance order (the pool's bitwise-identity
        # contract, io/data.py)
        for thunk in self.iter_thunks():
            yield thunk()

    def iter_thunks(self):
        """Parallel-pool submission stream (``io/data.py``) — and the
        single definition of this source's instance order (``__iter__``
        derives from it).  Each thunk carries the ENCODED blob and
        defers the JPEG decode onto whichever thread runs it — the
        stage the reference pinned to one thread
        (``iter_thread_imbin-inl.hpp``)."""
        rng_page, _ = self._epoch_rngs()
        for blobs, lines in self._epoch_pages(rng_page):
            for blob, line in zip(blobs, lines):
                yield (lambda b=blob, li=line: self._make_inst(b, li))


class ImageBinXIterator(ImageBinIterator):
    """Two-stage imgbinx pipeline (``iter_thread_imbin_x-inl.hpp:18-397``):
    the page stage (``_epoch_pages``) runs behind a ThreadBuffer feeding a
    decode stage behind a second, deeper ThreadBuffer.  ``shuffle=1``
    randomizes part order, page order within each part, and instance order
    *within* each page — the reference's SGD-quality shuffle for datasets
    too big to permute globally — while decode overlaps page IO instead of
    serializing behind it (buffer depths 2 pages / 256 instances,
    reference :22-23).

    Beyond the reference's single decode thread: the decode stage is a
    bounded, ORDER-PRESERVING thread pool (``decode_threads``, default
    min(8, cores); env ``CXXNET_DECODE_THREADS`` overrides).  JPEG decode
    releases the GIL in both the native libjpeg path and PIL, so the pool
    scales the supply side on many-core TPU hosts — one 2015-era decode
    thread feeds a 2015 GPU (~500 img/s) but starves a chip consuming
    ~15k img/s (measured: ``bench.py io``).  Results are yielded strictly
    in submission order, so epoch instance order is bitwise identical to
    the serial path for any thread count."""

    PAGE_BUFFER = 2
    INST_BUFFER = 256

    def __init__(self):
        super().__init__()
        raw = os.environ.get('CXXNET_DECODE_THREADS', '').strip()
        auto = min(8, os.cpu_count() or 1)
        if raw:
            try:
                self.decode_threads = max(1, int(raw))   # 0 -> serial
            except ValueError:
                self.decode_threads = auto               # junk -> auto
        else:
            self.decode_threads = auto

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'decode_threads':
            self.decode_threads = max(1, int(val))

    def __iter__(self):
        rng_page, rng_inst = self._epoch_rngs()

        def insts():
            from concurrent.futures import ThreadPoolExecutor
            window = self.decode_threads * 4
            with ThreadPoolExecutor(self.decode_threads) as pool:
                pending = collections.deque()
                for blobs, lines in ThreadBuffer(
                        lambda: self._epoch_pages(rng_page),
                        self.PAGE_BUFFER):
                    inst_order = (rng_inst.permutation(len(blobs))
                                  if self.shuffle else range(len(blobs)))
                    for k in inst_order:
                        pending.append(pool.submit(
                            self._make_inst, blobs[k], lines[k]))
                        while len(pending) > window:
                            yield pending.popleft().result()
                while pending:
                    yield pending.popleft().result()

        return iter(ThreadBuffer(insts, self.INST_BUFFER))

    def iter_thunks(self):
        """imgbinx submission stream: page reads stay behind their own
        ThreadBuffer (IO overlaps the pool) and ``shuffle=1`` keeps the
        within-page instance shuffle; the decode itself rides the thunk
        — the chain-level ``nworker`` pool replaces this class's private
        decode pool, never stacks on it."""
        rng_page, rng_inst = self._epoch_rngs()
        for blobs, lines in ThreadBuffer(
                lambda: self._epoch_pages(rng_page), self.PAGE_BUFFER):
            inst_order = (rng_inst.permutation(len(blobs))
                          if self.shuffle else range(len(blobs)))
            for k in inst_order:
                yield (lambda b=blobs[k], li=lines[k]:
                       self._make_inst(b, li))
