"""Binary-page image source (``src/io/iter_thread_imbin-inl.hpp:16-283``).

Reads the reference's packed image format: a ``.bin`` stream of 64MB
``BinaryPage``s whose objects are encoded (JPEG/PNG) image blobs, paired
record-for-record with a ``.lst`` file carrying ``index \\t labels...``.
Features preserved:

* multi-part datasets via ``image_conf_prefix`` printf-style pattern +
  ``image_conf_ids = a-b`` (iter_thread_imbin:225-278),
* distributed worker sharding: parts (or pages, for a single file) are
  round-robin split across workers by ``dist_num_worker`` /
  ``dist_worker_rank`` (``PS_RANK`` env respected, :189-220),
* page-level shuffle (``shuffle=1``).

Decode uses PIL; the page read-ahead runs behind a ThreadBuffer when the
config wraps this source in ``iter = threadbuffer``.
"""

from __future__ import annotations

import io
import os

import numpy as np

from ..utils.io_stream import BinaryPage
from .data import DataInst, IIterator
from .iter_img import parse_lst_line


class ImageBinIterator(IIterator):
    def __init__(self):
        self.path_imglist = ''
        self.path_imgbin = ''
        self.label_width = 1
        self.silent = 0
        self.shuffle = 0
        self.seed_data = 0
        self.conf_prefix = ''
        self.conf_ids = ''
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self._lists = []
        self._bins = []

    def set_param(self, name, val):
        if name in ('image_list', 'path_imglist'):
            self.path_imglist = val
        if name in ('image_bin', 'path_imgbin'):
            self.path_imgbin = val
        if name == 'label_width':
            self.label_width = int(val)
        if name == 'silent':
            self.silent = int(val)
        if name == 'shuffle':
            self.shuffle = int(val)
        if name == 'seed_data':
            self.seed_data = int(val)
        if name == 'image_conf_prefix':
            self.conf_prefix = val
        if name == 'image_conf_ids':
            self.conf_ids = val
        if name == 'dist_num_worker':
            self.dist_num_worker = int(val)
        if name == 'dist_worker_rank':
            self.dist_worker_rank = int(val)

    def init(self):
        rank = int(os.environ.get('PS_RANK', self.dist_worker_rank))
        nworker = self.dist_num_worker
        if self.conf_prefix:
            a, _, b = self.conf_ids.partition('-')
            ids = list(range(int(a), int(b or a) + 1))
            # shard whole parts across workers (iter_thread_imbin:196-213)
            ids = ids[rank::nworker] if nworker > 1 else ids
            self._lists = [self.conf_prefix % i + '.lst' for i in ids]
            self._bins = [self.conf_prefix % i + '.bin' for i in ids]
        else:
            assert self.path_imglist and self.path_imgbin, \
                'imgbin: must set image_list and image_bin'
            self._lists = [self.path_imglist]
            self._bins = [self.path_imgbin]
        self._single_shard = (nworker > 1 and not self.conf_prefix,
                              rank, nworker)
        if self.silent == 0:
            print(f'ImageBinIterator: {len(self._bins)} part(s), '
                  f'worker {rank}/{nworker}')

    def _iter_pages(self, bin_path):
        """Prefer the native C++ page reader (background prefetch thread +
        libjpeg); fall back to the Python BinaryPage parser."""
        from ..runtime.native import NativePageReader, native_available
        if native_available():
            reader = NativePageReader(bin_path)
            try:
                yield from reader.iter_pages()
            finally:
                reader.close()
            return
        with open(bin_path, 'rb') as f:
            while True:
                page = BinaryPage()
                if not page.load(f):
                    return
                yield list(page)

    def _decode(self, blob):
        from ..runtime.native import decode_jpeg
        arr = decode_jpeg(blob)          # fast path: native libjpeg
        if arr is None:                  # non-JPEG (png, ...) or no native
            from PIL import Image
            with Image.open(io.BytesIO(blob)) as im:
                arr = np.asarray(im.convert('RGB'), np.uint8)
        return np.transpose(arr.astype(np.float32), (2, 0, 1))

    def __iter__(self):
        sharded, rank, nworker = self._single_shard
        order = list(range(len(self._bins)))
        rng = np.random.RandomState(self.seed_data) if self.shuffle else None
        if rng is not None:
            rng.shuffle(order)
        for part in order:
            with open(self._lists[part]) as f:
                lines = (parse_lst_line(l) for l in f if l.strip())
                lines = iter(list(lines))
            for page_idx, page in enumerate(self._iter_pages(self._bins[part])):
                take = (not sharded) or (page_idx % nworker == rank)
                for blob in page:
                    try:
                        index, labels, _ = next(lines)
                    except StopIteration:
                        raise RuntimeError(
                            'imgbin: .lst shorter than .bin contents')
                    if not take:
                        continue
                    yield DataInst(index, self._decode(blob),
                                   labels[:self.label_width]
                                   if self.label_width else labels)
