"""Image-list source iterator (``src/io/iter_img-inl.hpp:16-135``).

Reads a ``.lst`` file (``index \\t label[ \\t label...] \\t filename``) and
decodes one image per instance (PIL replaces OpenCV), yielding ``(3, h, w)``
uint8 pixel data in 0-255 range (the augment stage owns the float32
conversion — host normalize path — or defers it to the device under
``device_normalize=1``), channels in the tensor order the
reference produces, with labels of ``label_width`` columns.
"""

from __future__ import annotations

import os

import numpy as np

from .data import DataInst, IIterator


def load_image_chw(path: str) -> np.ndarray:
    from PIL import Image
    with Image.open(path) as im:
        # uint8 through: the augment stage owns the float32 conversion
        arr = np.asarray(im.convert('RGB'), dtype=np.uint8)
    return np.transpose(arr, (2, 0, 1))          # (3, h, w)


def parse_lst_line(line: str):
    toks = line.strip().split('\t')
    if len(toks) < 3:
        toks = line.strip().split()
    index = int(float(toks[0]))
    labels = np.asarray([float(t) for t in toks[1:-1]], dtype=np.float32)
    fname = toks[-1]
    return index, labels, fname


class ImageIterator(IIterator):
    def __init__(self):
        self.path_imglist = ''
        self.image_root = ''
        self.label_width = 1
        self.silent = 0
        self._lines = []

    def set_param(self, name, val):
        if name in ('image_list', 'path_imglist'):
            self.path_imglist = val
        if name in ('image_root', 'path_imgdir'):
            self.image_root = val
        if name == 'label_width':
            self.label_width = int(val)
        if name == 'silent':
            self.silent = int(val)

    def init(self):
        assert self.path_imglist, 'img iterator: must set image_list'
        with open(self.path_imglist) as f:
            self._lines = [parse_lst_line(l) for l in f if l.strip()]
        if self.silent == 0:
            print(f'ImageIterator: {len(self._lines)} images in '
                  f'{self.path_imglist}')

    def __iter__(self):
        for index, labels, fname in self._lines:
            path = os.path.join(self.image_root, fname) \
                if self.image_root else fname
            yield DataInst(index, load_image_chw(path),
                           labels[:self.label_width]
                           if self.label_width else labels)
