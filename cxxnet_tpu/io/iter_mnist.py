"""MNIST idx-format source iterator (``src/io/iter_mnist-inl.hpp:14-156``).

Reads (optionally gzipped) idx image/label files fully into memory,
normalizes pixels by 1/256, optionally shuffles **once at init** (the
reference reshuffles only at Init, not per round — preserved), and yields
full batches, dropping the tail remainder exactly like the reference's
``Next`` (loc + batch_size <= n).
``input_flat=1`` (default) yields ``(b,1,1,784)``; ``0`` yields
``(b,1,28,28)``.
"""

from __future__ import annotations

import struct

import numpy as np

from ..utils.io_stream import open_maybe_gz
from .data import DataBatch, IIterator


class MNISTIterator(IIterator):
    def __init__(self):
        self.silent = 0
        self.batch_size = 0
        self.input_flat = 1
        self.shuffle = 0
        self.inst_offset = 0
        self.path_img = ''
        self.path_label = ''
        self.seed_data = 0
        self._ready = False

    def set_param(self, name, val):
        if name == 'silent':
            self.silent = int(val)
        if name == 'batch_size':
            self.batch_size = int(val)
        if name == 'input_flat':
            self.input_flat = int(val)
        if name == 'shuffle':
            self.shuffle = int(val)
        if name == 'index_offset':
            self.inst_offset = int(val)
        if name == 'path_img':
            self.path_img = val
        if name == 'path_label':
            self.path_label = val
        if name == 'seed_data':
            self.seed_data = int(val)

    def init(self):
        if self._ready:
            return
        with open_maybe_gz(self.path_img) as f:
            _, n, rows, cols = struct.unpack('>iiii', f.read(16))
            img = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        img = img.reshape(n, rows, cols).astype(np.float32) * (1.0 / 256.0)
        with open_maybe_gz(self.path_label) as f:
            _, nl = struct.unpack('>ii', f.read(8))
            labels = np.frombuffer(f.read(nl), dtype=np.uint8).astype(np.float32)
        assert n == nl, 'MNIST: image/label count mismatch'
        inst = np.arange(n, dtype=np.uint32) + self.inst_offset
        if self.shuffle:
            rng = np.random.RandomState(self.seed_data)
            perm = rng.permutation(n)
            img, labels, inst = img[perm], labels[perm], inst[perm]
        self._img, self._labels, self._inst = img, labels, inst
        self._ready = True
        if self.silent == 0:
            shp = ((self.batch_size, 1, 1, rows * cols) if self.input_flat
                   else (self.batch_size, 1, rows, cols))
            print(f'MNISTIterator: load {n} images, shuffle={self.shuffle}, '
                  f'shape={",".join(map(str, shp))}')

    def __iter__(self):
        assert self.batch_size > 0, 'MNIST: batch_size must be set'
        n = self._img.shape[0]
        bs = self.batch_size
        for loc in range(0, n - bs + 1, bs):
            block = self._img[loc:loc + bs]
            if self.input_flat:
                data = block.reshape(bs, 1, 1, -1)
            else:
                data = block.reshape(bs, 1, block.shape[1], block.shape[2])
            yield DataBatch(data, self._labels[loc:loc + bs, None],
                            self._inst[loc:loc + bs])
