"""Streaming imgbin source (``iter = imgbin_stream``): tail an
APPENDABLE ``.bin``/``.lst`` pair instead of snapshotting it.

The train-while-serve pipeline (doc/online.md) ingests data that keeps
arriving: a producer appends complete ``BinaryPage`` records to the
``.bin`` and their ``index \\t labels \\t name`` lines to the ``.lst``
(:func:`append_records` is the writer-side helper with the required
commit order — lines first, then the page).  This source reads the file
front-to-back like plain ``imgbin``, and when it catches up it polls for
growth (``stream_poll`` seconds between checks) and continues into the
new tail; a pass ends after ``stream_idle`` seconds with no growth
(``stream_idle = 0`` = snapshot pass: read what's there, stop at EOF).

Determinism contract (tested in ``tests/test_online.py``):

* **bitwise twin** — over the same final bytes, the stream yields
  exactly the instance sequence a static ``imgbin`` pass yields, no
  matter how the file grew while it was being read (append-only order
  IS arrival order; ``shuffle=1`` is rejected — a tail reader cannot
  permute pages it hasn't seen),
* **incremental tail** — catching up after growth re-reads ONLY the new
  pages (header scan via ``ImageBinIterator._refresh_page_table``),
  never re-decoding pages already consumed,
* **epoch-absolute indexing preserved** — ``iter_thunks`` (the
  ``nworker`` pool's submission stream) derives from the same page walk
  as ``__iter__``, so per-instance augmentation RNG (seeded from the
  epoch-absolute instance index, doc/io.md) is bitwise identical to the
  static source and to any worker count,
* **replay-stable** — an append-only file replays the same prefix, so
  supervised fault recovery may re-wind the stream to batch k
  (``is_replay_stable`` is True; the whole chaos contract of
  doc/online.md hangs on it).
"""

from __future__ import annotations

import os
import time

from ..utils.io_stream import BinaryPage
from .iter_img import parse_lst_line
from .iter_imbin import ImageBinIterator


def append_records(bin_path: str, lst_path: str, records) -> int:
    """Writer-side helper: append ``records`` — an iterable of
    ``(index, label_or_labels, blob)`` — as one or more complete
    ``BinaryPage``s.  Commit order is the stream reader's contract:
    ``.lst`` lines first (flushed + fsynced), then the page bytes — a
    reader that sees a page always finds its lines.  Returns the number
    of records appended."""
    records = list(records)
    if not records:
        return 0
    with open(lst_path, 'a') as fl:
        for index, labels, _blob in records:
            try:
                lab = '\t'.join(f'{float(v):g}' for v in labels)
            except TypeError:
                lab = f'{float(labels):g}'
            fl.write(f'{index}\t{lab}\tstream\n')
        fl.flush()
        os.fsync(fl.fileno())
    page = BinaryPage()
    with open(bin_path, 'ab') as fb:
        for _index, _labels, blob in records:
            if not page.push(blob):
                page.save(fb)
                page.clear()
                if not page.push(blob):
                    raise ValueError('append_records: blob larger than '
                                     'a page')
        if page.size:
            page.save(fb)
        fb.flush()
        os.fsync(fb.fileno())
    return len(records)


class ImageBinStreamIterator(ImageBinIterator):
    """Tail one appendable imgbin file (see module docstring).

    Config keys beyond plain ``imgbin`` (``image_list``/``image_bin``):

    * ``stream_poll``  — seconds between growth checks once caught up
      (default 0.05),
    * ``stream_idle``  — end the pass after this many seconds with no
      growth; 0 (default) reads the current snapshot and stops at EOF,
    * ``stream_fence`` — end the pass after EXACTLY this many instances,
      waiting out growth as needed (0 = off).  Time-based pass endings
      (`stream_idle`) are per-observer: two hosts tailing the same
      growing file can disagree about where a pass ends.  The fence
      pins the pass length to a number every host shares, which is what
      elastic multi-host training requires of its global sample stream
      (doc/fault_tolerance.md "Multi-host recovery").
    """

    def __init__(self):
        super().__init__()
        self.stream_poll = 0.05
        self.stream_idle = 0.0
        self.stream_fence = 0

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'stream_poll':
            self.stream_poll = float(val)
        if name == 'stream_idle':
            self.stream_idle = float(val)
        if name == 'stream_fence':
            self.stream_fence = int(val)

    def init(self):
        if self.conf_prefix:
            raise ValueError('imgbin_stream tails ONE appendable file; '
                             'multi-part image_conf_prefix datasets are '
                             'a static-imgbin feature')
        if self.shuffle:
            raise ValueError(
                'imgbin_stream cannot shuffle: a tail reader cannot '
                'permute pages it has not seen yet — arrival order IS '
                'the stream order (and the bitwise-twin/replay contract '
                'depends on it)')
        if self.dist_num_worker > 1:
            raise ValueError('imgbin_stream does not shard across '
                             'workers yet (single-tail contract)')
        super().init()
        # incremental .lst tail state (the .lst twin of the page-table
        # refresh): parsed lines + the byte offset they came from
        self._lines_buf = []
        self._lst_offset = 0

    def is_replay_stable(self) -> bool:
        # append-only: every pass replays the same prefix in the same
        # order — supervised recovery may re-wind this stream
        return True

    def _load_lines(self, part):
        """Incremental tail read — the ``.lst`` twin of
        :meth:`_refresh_page_table`: only bytes appended since the last
        read are parsed (a long-lived stream must not re-parse the whole
        file per page), and a trailing line not yet terminated by
        ``\\n`` stays unconsumed until the writer finishes it.  The
        file is append-only by contract, so the accumulated parse is
        the file's parse."""
        try:
            with open(self._lists[part], 'rb') as f:
                f.seek(self._lst_offset)
                chunk = f.read()
        except FileNotFoundError:
            return self._lines_buf
        if chunk:
            cut = chunk.rfind(b'\n')
            if cut >= 0:
                text = chunk[:cut + 1].decode()
                self._lst_offset += cut + 1
                self._lines_buf.extend(
                    parse_lst_line(l) for l in text.split('\n')
                    if l.strip())
        return self._lines_buf

    def _await_lines(self, part, need: int):
        """The ``.lst`` lines covering the first ``need`` instances.
        A page committed before its lines are visible gets a short grace
        (the writer contract is lines-first, so this only waits out a
        racing writer), then fails like the static reader."""
        lines = self._load_lines(part)
        if len(lines) >= need:
            return lines
        budget = max(self.stream_idle, 10 * self.stream_poll)
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            time.sleep(self.stream_poll)
            lines = self._load_lines(part)
            if len(lines) >= need:
                return lines
        raise RuntimeError('imgbin_stream: .lst shorter than .bin '
                           f'contents ({len(lines)} lines < {need} '
                           'instances) — append lines before pages')

    def _epoch_pages(self, rng_page):
        """One streaming pass at page granularity: drain every complete
        page on disk, then poll for growth until ``stream_idle`` elapses
        with none — or, with ``stream_fence``, until exactly that many
        instances have been yielded (the last page truncates at the
        fence).  Only the APPENDED pages are header-scanned on growth
        (:meth:`_refresh_page_table`); consumed pages are never re-read."""
        part = 0
        pidx = 0
        yielded = 0
        fence = self.stream_fence
        idle_since = None
        while True:
            try:
                counts, starts = self._refresh_page_table(part)
            except FileNotFoundError:
                # the writer hasn't created the file yet: an empty
                # stream, not an error — poll like any caught-up tail
                counts, starts = [], [0]
            if pidx < len(counts):
                idle_since = None
                order = list(range(pidx, len(counts)))
                pidx = len(counts)
                for p, blobs in self._page_stream(part, order):
                    if len(blobs) != counts[p]:
                        raise RuntimeError(
                            f'imgbin_stream: page {p} holds {len(blobs)} '
                            f'objects but its header said {counts[p]}')
                    if fence:
                        blobs = blobs[:fence - yielded]
                        if not blobs:
                            return
                    lines = self._await_lines(part, starts[p] + len(blobs))
                    yield blobs, lines[starts[p]:starts[p] + len(blobs)]
                    yielded += len(blobs)
                    if fence and yielded >= fence:
                        return
                continue
            if fence:
                # fenced pass: the remaining instances are owed; wait
                # out the writer rather than ending early
                time.sleep(self.stream_poll)
                continue
            if self.stream_idle <= 0:
                return
            now = time.monotonic()
            if idle_since is None:
                idle_since = now
            elif now - idle_since >= self.stream_idle:
                return
            time.sleep(self.stream_poll)
