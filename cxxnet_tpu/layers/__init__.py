"""Layer zoo.  Importing this package populates the layer registry."""

from . import common, conv, loss, norm, pairtest, pooling  # noqa: F401
from .base import (ForwardContext, Layer, LayerParam, NodeSpec, Params,
                   as_mat, create_layer, get_layer_type, layer_type_name,
                   kPairTestGap, kSharedLayer)
from .loss import LossLayerBase
