"""Layer framework core: hyperparameters, node specs, registry, base class.

TPU-native redesign of the reference layer system
(``src/layer/layer.h:31-373``, ``src/layer/param.h:15-138``):

* Layers are **pure functions** over JAX arrays — `forward(params, inputs,
  ctx)` returns outputs with no in-place node mutation.  Backward passes come
  from `jax.grad` through the whole net (verified layer-by-layer against
  NumPy references in the pairtest harness, see ``layers/pairtest.py``), so
  everything stays inside one jitted, XLA-fusable train step.
* Activations use NHWC layout (TPU-friendly); the reference's NCHW
  ``(batch, channel, y, x)`` shapes appear only at the config/checkpoint
  boundary.  Matrices are plain ``(batch, len)``.
* The integer layer-type ids are the reference's stable on-disk ids
  (``src/layer/layer.h:284-314``) and are preserved for checkpoint interop.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# stable layer-type ids (on-disk format) — src/layer/layer.h:284-314
kSharedLayer = 0
kFullConnect = 1
kSoftmax = 2
kRectifiedLinear = 3
kSigmoid = 4
kTanh = 5
kSoftplus = 6
kFlatten = 7
kDropout = 8
kConv = 10
kMaxPooling = 11
kSumPooling = 12
kAvgPooling = 13
kLRN = 15
kBias = 17
kConcat = 18
kXelu = 19
kCaffe = 20
kReluMaxPooling = 21
kMaxout = 22
kSplit = 23
kInsanity = 24
kInsanityPooling = 25
kL2Loss = 26
kMultiLogistic = 27
kChConcat = 28
kPRelu = 29
kBatchNorm = 30
kFixConnect = 31
kPairTestGap = 1024

_NAME2TYPE = {
    'fullc': kFullConnect, 'fixconn': kFixConnect, 'bias': kBias,
    'softmax': kSoftmax, 'relu': kRectifiedLinear, 'sigmoid': kSigmoid,
    'tanh': kTanh, 'softplus': kSoftplus, 'flatten': kFlatten,
    'dropout': kDropout, 'conv': kConv, 'relu_max_pooling': kReluMaxPooling,
    'max_pooling': kMaxPooling, 'sum_pooling': kSumPooling,
    'avg_pooling': kAvgPooling, 'lrn': kLRN, 'concat': kConcat,
    'xelu': kXelu, 'maxout': kMaxout, 'split': kSplit,
    'insanity': kInsanity, 'insanity_max_pooling': kInsanityPooling,
    'l2_loss': kL2Loss, 'multi_logistic': kMultiLogistic,
    'ch_concat': kChConcat, 'prelu': kPRelu, 'batch_norm': kBatchNorm,
}
_TYPE2NAME = {v: k for k, v in _NAME2TYPE.items()}
_TYPE2NAME[kMaxPooling] = 'max_pooling'  # keep canonical names on collision


def get_layer_type(type_str: str) -> int:
    """String → stable integer type id (``GetLayerType``, layer.h:322-361)."""
    if type_str.startswith('share'):
        return kSharedLayer
    if type_str.startswith('pairtest-'):
        rest = type_str[len('pairtest-'):]
        master, _, slave = rest.partition('-')
        slave = slave.split(':')[0]
        return kPairTestGap * get_layer_type(master) + get_layer_type(slave)
    if type_str in _NAME2TYPE:
        return _NAME2TYPE[type_str]
    if type_str == 'caffe':
        # reference plugin enum 20 (plugin/caffe_adapter-inl.hpp): wraps
        # live caffe::Layer objects — rejected scope on a TPU stack (see
        # PARITY.md), reported distinctly from a typo'd layer name
        raise ValueError(
            "layer type 'caffe' (reference plugin enum 20) is an "
            'unsupported plugin: it adapts in-process caffe::Layer objects '
            'and has no TPU equivalent')
    raise ValueError(f'unknown layer type: "{type_str}"')


def layer_type_name(type_id: int) -> str:
    if type_id >= kPairTestGap:
        return (f'pairtest-{layer_type_name(type_id // kPairTestGap)}'
                f'-{layer_type_name(type_id % kPairTestGap)}')
    if type_id == kSharedLayer:
        return 'share'
    return _TYPE2NAME.get(type_id, f'<type{type_id}>')


@dataclasses.dataclass
class LayerParam:
    """Shared layer hyperparameters (``src/layer/param.h:15-110``)."""

    num_hidden: int = 0
    init_sigma: float = 0.01
    init_sparse: int = 10
    init_uniform: float = -1.0
    init_bias: float = 0.0
    num_channel: int = 0
    random_type: int = 0          # 0 gaussian, 1 xavier/uniform, 2 kaiming
    num_group: int = 1
    kernel_height: int = 0
    kernel_width: int = 0
    stride: int = 1
    pad_y: int = 0
    pad_x: int = 0
    no_bias: int = 0
    temp_col_max: int = 64 << 18
    silent: int = 0
    num_input_channel: int = 0
    num_input_node: int = 0
    # conv MXU-lowering experiment knob (beyond reference):
    # auto | native (lax.conv) | im2col (patches GEMM, shallow inputs) |
    # split (per-group convs instead of feature_group_count)
    conv_lowering: str = 'auto'
    # μ-cuDNN-style conv microbatching (beyond reference): split the
    # conv's batch axis into this many sequential slices to bound the
    # layer's live workspace; bitwise-equal to unsplit by construction
    # (ops/pallas_cnn.microbatched_conv) and priced by grafttune's
    # LedgerGate as a mem_inv knob
    micro_batch: int = 1

    def set_param(self, name: str, val: str) -> None:
        if name == 'init_sigma':
            self.init_sigma = float(val)
        if name == 'init_uniform':
            self.init_uniform = float(val)
        if name == 'init_bias':
            self.init_bias = float(val)
        if name == 'init_sparse':
            self.init_sparse = int(val)
        if name == 'random_type':
            table = {'gaussian': 0, 'uniform': 1, 'xavier': 1, 'kaiming': 2}
            if val not in table:
                raise ValueError(f'invalid random_type {val}')
            self.random_type = table[val]
        if name == 'nhidden':
            self.num_hidden = int(val)
        if name == 'nchannel':
            self.num_channel = int(val)
        if name == 'ngroup':
            self.num_group = int(val)
        if name == 'kernel_size':
            self.kernel_height = self.kernel_width = int(val)
        if name == 'kernel_height':
            self.kernel_height = int(val)
        if name == 'kernel_width':
            self.kernel_width = int(val)
        if name == 'stride':
            self.stride = int(val)
        if name == 'pad':
            self.pad_y = self.pad_x = int(val)
        if name == 'pad_y':
            self.pad_y = int(val)
        if name == 'pad_x':
            self.pad_x = int(val)
        if name == 'no_bias':
            self.no_bias = int(val)
        if name == 'silent':
            self.silent = int(val)
        if name == 'temp_col_max':
            self.temp_col_max = int(val) << 18
        if name == 'conv_lowering':
            if val not in ('auto', 'native', 'im2col', 'split', 's2d'):
                raise ValueError(f'conv_lowering: unknown mode {val}')
            self.conv_lowering = val
        if name == 'micro_batch':
            if int(val) < 1:
                raise ValueError(f'micro_batch: must be >= 1, got {val}')
            self.micro_batch = int(val)

    def rand_init_weight(self, rng: jax.Array, shape: Tuple[int, ...],
                         in_num: int, out_num: int,
                         dtype=jnp.float32) -> jax.Array:
        """Weight init matching ``RandInitWeight`` (param.h:113-138):
        gaussian(0, init_sigma) / xavier-uniform sqrt(3/(in+out)) /
        kaiming gaussian sqrt(2/fan)."""
        if self.random_type == 0:
            return self.init_sigma * jax.random.normal(rng, shape, dtype)
        if self.random_type == 1:
            a = math.sqrt(3.0 / (in_num + out_num))
            if self.init_uniform > 0:
                a = self.init_uniform
            return jax.random.uniform(rng, shape, dtype, minval=-a, maxval=a)
        if self.random_type == 2:
            if self.num_hidden > 0:
                sigma = math.sqrt(2.0 / self.num_hidden)
            else:
                sigma = math.sqrt(
                    2.0 / (self.num_channel * self.kernel_width * self.kernel_height))
            return sigma * jax.random.normal(rng, shape, dtype)
        raise ValueError(f'unsupported random_type {self.random_type}')


class NodeSpec:
    """Logical per-instance shape of a node: ``(c, y, x)``.

    Mirrors the reference node shape contract (``layer/layer.h:31-71``):
    matrices are ``(1, 1, len)`` and stored as 2-D ``(batch, len)`` arrays;
    images are stored NHWC as ``(batch, y, x, c)``.
    """

    __slots__ = ('c', 'y', 'x')

    def __init__(self, c: int, y: int, x: int):
        self.c, self.y, self.x = int(c), int(y), int(x)

    @property
    def is_mat(self) -> bool:
        return self.c == 1 and self.y == 1

    @property
    def flat_size(self) -> int:
        return self.c * self.y * self.x

    def batch_shape(self, batch: int) -> Tuple[int, ...]:
        if self.is_mat:
            return (batch, self.x)
        return (batch, self.y, self.x, self.c)

    def __repr__(self):
        return f'NodeSpec(c={self.c}, y={self.y}, x={self.x})'

    def __eq__(self, other):
        return (self.c, self.y, self.x) == (other.c, other.y, other.x)


def as_mat(x: jax.Array) -> jax.Array:
    """FlatTo2D view: collapse all non-batch dims (``layer.h:63-66``).

    4-D nodes flatten in the reference's NCHW element order so downstream
    fully-connected weights keep the same column meaning.
    """
    if x.ndim == 2:
        return x
    if x.ndim == 4:
        b = x.shape[0]
        return jnp.transpose(x, (0, 3, 1, 2)).reshape(b, -1)
    return x.reshape(x.shape[0], -1)


@dataclasses.dataclass
class ForwardContext:
    """Per-apply context threaded through layer forwards."""

    is_train: bool
    rng: Optional[jax.Array] = None          # base key; fold per layer index
    layer_index: int = -1
    round: int = 0                           # training round (insanity anneal)
    max_round: int = 1
    # activation dtype for the MXU path (bfloat16 for mixed precision);
    # params and loss stay float32, matmuls accumulate in float32
    compute_dtype: object = jnp.float32
    # device count of the mesh this trace runs under: auto-enabled Pallas
    # paths stand down when > 1 (an opaque pallas_call has no GSPMD
    # sharding rule, so the partitioner would gather the full sharded
    # activation around it)
    spmd_devices: int = 1

    def layer_rng(self) -> jax.Array:
        if self.rng is None:
            raise ValueError('layer requires rng but none was provided')
        return jax.random.fold_in(self.rng, self.layer_index)


Params = Dict[str, jax.Array]


class Layer:
    """Base class for all layers.

    Unlike the reference's stateful ``ILayer`` (mutating nodes in place,
    visitor-based weight access), layers here are parameter *descriptions*:
    ``init_params`` produces a dict pytree and ``forward`` is pure.  Field
    names ('wmat', 'bias', ...) match the reference visitor field names so
    tag-scoped hyperparameters (``wmat:lr``) and checkpoint blobs line up.
    """

    type_name: str = ''
    type_id: int = -1
    # fields that participate in weight decay / tag-scoped lr ('wmat'/'bias')
    param_fields: Sequence[str] = ()

    def __init__(self, name: str = ''):
        self.name = name
        self.param = LayerParam()

    # --- configuration ----------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        self.param.set_param(name, val)

    # --- shape inference --------------------------------------------------
    def infer_shapes(self, in_specs: List[NodeSpec]) -> List[NodeSpec]:
        """Compute output specs; also records input geometry hyperparams
        (num_input_node / num_input_channel) like ``InitConnection``."""
        raise NotImplementedError

    # --- parameters -------------------------------------------------------
    def init_params(self, rng: jax.Array, in_specs: List[NodeSpec],
                    dtype=jnp.float32) -> Params:
        return {}

    # --- compute ----------------------------------------------------------
    def forward(self, params: Params, inputs: List[jax.Array],
                ctx: ForwardContext) -> List[jax.Array]:
        raise NotImplementedError

    # loss layers override; returns per-batch summed loss (pre-scaling)
    def loss(self, params: Params, inputs: List[jax.Array],
             labels: jax.Array, ctx: ForwardContext) -> jax.Array:
        raise NotImplementedError(f'{self.type_name} is not a loss layer')

    @property
    def is_loss(self) -> bool:
        return False

    def allow_sharing(self) -> bool:
        """Whether this layer can be referenced by ``share[tag]``."""
        return bool(self.param_fields)

    def __repr__(self):
        return f'{type(self).__name__}(name={self.name!r})'


LAYER_REGISTRY: Dict[int, type] = {}


def register_layer(cls):
    """Class decorator: register under its stable type id."""
    LAYER_REGISTRY[cls.type_id] = cls
    return cls


def create_layer(type_id: int, name: str = '') -> Layer:
    """Factory (``CreateLayer_``, layer_impl-inl.hpp:36-76)."""
    if type_id >= kPairTestGap:
        from .pairtest import PairTestLayer
        return PairTestLayer(type_id // kPairTestGap, type_id % kPairTestGap,
                             name=name)
    cls = LAYER_REGISTRY.get(type_id)
    if cls is None:
        raise ValueError(
            f'CreateLayer: unknown/unsupported layer type {type_id} '
            f'({layer_type_name(type_id)})')
    return cls(name=name)
