"""Dense / elementwise / structural layers.

Functional JAX redesigns of the reference layers (citations per class).
Backward passes are derived by ``jax.grad`` through these forwards; the
pairtest harness checks them against hand-written NumPy gradients.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from .base import (Layer, NodeSpec, Params, as_mat,
                   kBias, kChConcat, kConcat, kDropout, kFixConnect, kFlatten,
                   kFullConnect, kInsanity, kMaxout, kPRelu,
                   kRectifiedLinear, kSigmoid, kSoftplus, kSplit, kTanh,
                   kXelu, register_layer)


@register_layer
class FullConnectLayer(Layer):
    """Dense layer (``src/layer/fullc_layer-inl.hpp:101-130``).

    ``out = in @ W + bias``.  Weight is stored ``(nin, nhidden)`` so the
    forward matmul hits the MXU without a transpose; the reference's
    ``(nhidden, nin)`` layout is restored only when writing checkpoints.
    """

    type_name = 'fullc'
    type_id = kFullConnect
    param_fields = ('wmat', 'bias')

    def __init__(self, name: str = ''):
        super().__init__(name=name)
        # Reference knob (fullc_layer-inl.hpp:17,22,120-122): push
        # activations + output-grads to the parameter server and compute dW
        # after the gather, saving bandwidth for big FC layers.  Under XLA
        # the gradient all-reduce strategy is chosen by the SPMD
        # partitioner, so the flag is accepted for config compatibility but
        # the comm optimization itself is delegated to the compiler.
        self.fullc_gather = 0

    def set_param(self, name: str, val: str) -> None:
        if name == 'fullc_gather':
            self.fullc_gather = int(val)
        super().set_param(name, val)

    def infer_shapes(self, in_specs: List[NodeSpec]) -> List[NodeSpec]:
        assert len(in_specs) == 1, 'fullc: only supports 1-1 connection'
        if self.param.num_hidden <= 0:
            raise ValueError('fullc: must set nhidden correctly')
        self.param.num_input_node = in_specs[0].flat_size
        return [NodeSpec(1, 1, self.param.num_hidden)]

    def init_params(self, rng, in_specs, dtype=jnp.float32) -> Params:
        nin = in_specs[0].flat_size
        nh = self.param.num_hidden
        p = {'wmat': self.param.rand_init_weight(rng, (nin, nh), nin, nh, dtype)}
        if self.param.no_bias == 0:
            p['bias'] = jnp.full((nh,), self.param.init_bias, dtype)
        return p

    def forward(self, params, inputs, ctx):
        x = as_mat(inputs[0])
        w = params['wmat'].astype(x.dtype)
        from ..ops.pallas_kernels import fullc_use_pallas, pallas_matmul
        if fullc_use_pallas(x.shape[0], w.shape[0], w.shape[1],
                            is_train=ctx.is_train,
                            spmd_devices=ctx.spmd_devices):
            out = pallas_matmul(x, w)
        else:
            out = jnp.dot(x, w)
        if self.param.no_bias == 0:
            out = out + params['bias'].astype(x.dtype)
        return [out.astype(x.dtype)]


class _ActivationLayer(Layer):
    """Elementwise activation (``src/layer/activation_layer-inl.hpp:22-39``)."""

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1
        return [in_specs[0]]

    def forward(self, params, inputs, ctx):
        return [self._act(inputs[0])]

    def _act(self, x):
        raise NotImplementedError


@register_layer
class ReluLayer(_ActivationLayer):
    type_name = 'relu'
    type_id = kRectifiedLinear

    def _act(self, x):
        return jnp.maximum(x, 0.0)


@register_layer
class SigmoidLayer(_ActivationLayer):
    type_name = 'sigmoid'
    type_id = kSigmoid

    def _act(self, x):
        return jax.nn.sigmoid(x)


@register_layer
class TanhLayer(_ActivationLayer):
    type_name = 'tanh'
    type_id = kTanh

    def _act(self, x):
        return jnp.tanh(x)


@register_layer
class SoftplusLayer(_ActivationLayer):
    """softplus has a type id in the reference (layer.h:290) but no factory
    case — configuring it there aborts.  We support it."""

    type_name = 'softplus'
    type_id = kSoftplus

    def _act(self, x):
        return jax.nn.softplus(x)


@register_layer
class FlattenLayer(Layer):
    """Reshape to ``(batch, c*y*x)`` (``src/layer/flatten_layer-inl.hpp``).

    Flattening follows the reference's NCHW element order (see ``as_mat``)
    so fullc weights and extracted features keep reference column meaning.
    """

    type_name = 'flatten'
    type_id = kFlatten

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1
        return [NodeSpec(1, 1, in_specs[0].flat_size)]

    def forward(self, params, inputs, ctx):
        return [as_mat(inputs[0])]


@register_layer
class DropoutLayer(Layer):
    """Inverted dropout, self-loop (``src/layer/dropout_layer-inl.hpp``):
    train-time mask ``Bernoulli(1-p)/(1-p)``; eval is identity."""

    type_name = 'dropout'
    type_id = kDropout

    def __init__(self, name=''):
        super().__init__(name)
        self.threshold = 0.0

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'threshold':
            self.threshold = float(val)

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1
        if not (0.0 <= self.threshold < 1.0):
            raise ValueError('DropoutLayer: invalid dropout threshold')
        return [in_specs[0]]

    def forward(self, params, inputs, ctx):
        x = inputs[0]
        if not ctx.is_train or self.threshold == 0.0:
            return [x]
        pkeep = 1.0 - self.threshold
        mask = jax.random.uniform(ctx.layer_rng(), x.shape, x.dtype) < pkeep
        return [x * mask.astype(x.dtype) / pkeep]


@register_layer
class BiasLayer(Layer):
    """Self-loop learnable bias on a matrix node
    (``src/layer/bias_layer-inl.hpp``)."""

    type_name = 'bias'
    type_id = kBias
    param_fields = ('bias',)

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1
        if not in_specs[0].is_mat:
            raise ValueError('BiasLayer only works for flattened nodes')
        self.param.num_input_node = in_specs[0].x
        return [in_specs[0]]

    def init_params(self, rng, in_specs, dtype=jnp.float32):
        return {'bias': jnp.full((in_specs[0].x,), self.param.init_bias, dtype)}

    def forward(self, params, inputs, ctx):
        return [inputs[0] + params['bias']]


@register_layer
class XeluLayer(Layer):
    """Leaky relu variant ``x > 0 ? x : x / b`` (``src/layer/xelu_layer-inl.hpp``,
    op at ``src/layer/op.h``: divide, not multiply)."""

    type_name = 'xelu'
    type_id = kXelu

    def __init__(self, name=''):
        super().__init__(name)
        self.b = 5.0

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'b':
            self.b = float(val)

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1
        return [in_specs[0]]

    def forward(self, params, inputs, ctx):
        x = inputs[0]
        return [jnp.where(x > 0, x, x / self.b)]


@register_layer
class InsanityLayer(Layer):
    """Randomized leaky relu (RReLU) with slope annealing
    (``src/layer/insanity_layer-inl.hpp``): train slope denominator
    ~ U[lb, ub]; eval uses the midpoint.  The reference's per-call
    ``calm_start/calm_end`` annealing mutates bounds each forward; here the
    anneal step is derived from ``ctx.round`` so the jitted step stays pure.
    """

    type_name = 'insanity'
    type_id = kInsanity

    def __init__(self, name=''):
        super().__init__(name)
        self.lb = 5.0
        self.ub = 10.0
        self.calm_start = 0
        self.calm_end = 0

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'lb':
            self.lb = float(val)
        if name == 'ub':
            self.ub = float(val)
        if name == 'calm_start':
            self.calm_start = int(val)
        if name == 'calm_end':
            self.calm_end = int(val)

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1
        return [in_specs[0]]

    def _bounds(self, step):
        """Anneal bounds toward the midpoint; ``step`` may be a traced
        jit value, so use jnp ops."""
        lb, ub = jnp.asarray(self.lb), jnp.asarray(self.ub)
        if self.calm_end > self.calm_start:
            delta = (self.ub - (self.ub + self.lb) / 2.0) \
                / (self.calm_end - self.calm_start)
            s = jnp.clip(jnp.asarray(step) - self.calm_start, 0,
                         self.calm_end - self.calm_start)
            ub = ub - delta * s
            lb = lb + delta * s
        return lb, ub

    def forward(self, params, inputs, ctx):
        x = inputs[0]
        lb, ub = self._bounds(ctx.round)
        if ctx.is_train:
            u = jax.random.uniform(ctx.layer_rng(), x.shape, x.dtype)
            mask = u * (ub - lb) + lb
            return [jnp.where(x > 0, x, x / mask)]
        mid = (lb + ub) / 2.0
        return [jnp.where(x > 0, x, x / mid)]


@register_layer
class PReluLayer(Layer):
    """Learnable per-channel slope with optional train-time noise
    (``src/layer/prelu_layer-inl.hpp``).  Slope mask is clipped to [0,1];
    negative side multiplies by slope (mxelu)."""

    type_name = 'prelu'
    type_id = kPRelu
    param_fields = ('bias',)   # reference visits the slope under tag 'bias'

    def __init__(self, name=''):
        super().__init__(name)
        self.init_slope = 0.25
        self.init_random = 0
        self.random = 0.0

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'init_slope':
            self.init_slope = float(val)
        if name == 'random_slope':
            self.init_random = int(val)
        if name == 'random':
            self.random = float(val)

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1
        s = in_specs[0]
        self._channels = s.x if s.is_mat else s.c
        return [s]

    def init_params(self, rng, in_specs, dtype=jnp.float32):
        if self.init_random == 0:
            slope = jnp.full((self._channels,), self.init_slope, dtype)
        else:
            slope = jax.random.uniform(rng, (self._channels,), dtype) * self.init_slope
        return {'bias': slope}

    def forward(self, params, inputs, ctx):
        x = inputs[0]
        slope = params['bias']  # broadcasts over trailing channel axis in
        # both layouts: (b, len) matrices and (b, y, x, c) NHWC images
        mask = jnp.broadcast_to(slope, x.shape)
        if ctx.is_train and self.random > 0:
            u = jax.random.uniform(ctx.layer_rng(), x.shape, x.dtype)
            mask = mask * (1 + u * self.random * 2.0 - self.random)
        mask = jnp.clip(mask, 0.0, 1.0)
        return [jnp.where(x > 0, x, x * mask)]


@register_layer
class SplitLayer(Layer):
    """1→n fan-out copy (``src/layer/split_layer-inl.hpp``); gradients sum."""

    type_name = 'split'
    type_id = kSplit

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1
        self._n_out = getattr(self, '_n_out', 2)
        return [in_specs[0] for _ in range(self._n_out)]

    def set_num_outputs(self, n: int):
        self._n_out = n

    def forward(self, params, inputs, ctx):
        return [inputs[0] for _ in range(self._n_out)]


class _ConcatBase(Layer):
    """2-4 input concat (``src/layer/concat_layer-inl.hpp``)."""

    def infer_shapes(self, in_specs):
        if not 2 <= len(in_specs) <= 4:
            raise ValueError(f'{self.type_name}: supports 2-4 inputs')
        c, y, x = in_specs[0].c, in_specs[0].y, in_specs[0].x
        if self.type_id == kConcat:       # concat along x (reference dim 3)
            for s in in_specs[1:]:
                if (s.c, s.y) != (c, y):
                    raise ValueError('concat: non-x dims must match')
            x = sum(s.x for s in in_specs)
        else:                             # ch_concat along channel (dim 1)
            for s in in_specs[1:]:
                if (s.y, s.x) != (y, x):
                    raise ValueError('ch_concat: non-channel dims must match')
            c = sum(s.c for s in in_specs)
        return [NodeSpec(c, y, x)]

    def forward(self, params, inputs, ctx):
        if self.type_id == kConcat:
            axis = 1 if inputs[0].ndim == 2 else 2   # x axis in NHWC
        else:
            axis = 3                                  # channel axis in NHWC
        return [jnp.concatenate(inputs, axis=axis)]


@register_layer
class ConcatLayer(_ConcatBase):
    type_name = 'concat'
    type_id = kConcat


@register_layer
class ChConcatLayer(_ConcatBase):
    type_name = 'ch_concat'
    type_id = kChConcat


@register_layer
class MaxoutLayer(Layer):
    """Maxout over channel groups.  The reference declares ``kMaxout``
    (layer.h:304) but has no factory case, so any config selecting it died;
    we implement the standard formulation: channels are reduced by a factor
    of ``ngroup`` via max over consecutive groups."""

    type_name = 'maxout'
    type_id = kMaxout

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1
        s = in_specs[0]
        k = self.param.num_group
        if k <= 1:
            raise ValueError('maxout: set ngroup > 1')
        if s.is_mat:
            if s.x % k:
                raise ValueError('maxout: input width must divide ngroup')
            return [NodeSpec(1, 1, s.x // k)]
        if s.c % k:
            raise ValueError('maxout: channels must divide ngroup')
        return [NodeSpec(s.c // k, s.y, s.x)]

    def forward(self, params, inputs, ctx):
        x = inputs[0]
        k = self.param.num_group
        shape = x.shape[:-1] + (x.shape[-1] // k, k)
        return [jnp.max(x.reshape(shape), axis=-1)]


@register_layer
class FixConnectLayer(Layer):
    """Fixed (non-learned) sparse projection loaded from a text file
    (``src/layer/fixconn_layer-inl.hpp:42-57``).  File format:
    ``nrow ncol nnz`` then ``row col value`` triples; weight is
    ``(nhidden, nin)`` applied as ``out = in @ W.T``.  The matrix is a
    constant baked into the jitted graph, not a trainable parameter."""

    type_name = 'fixconn'
    type_id = kFixConnect

    def __init__(self, name=''):
        super().__init__(name)
        self.fname_weight = 'NULL'
        self._wmat = None

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'fixconn_weight':
            self.fname_weight = val

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1
        if not in_specs[0].is_mat:
            raise ValueError('FixConnLayer: input must be a matrix')
        if self.param.num_hidden <= 0:
            raise ValueError('FixConnLayer: must set nhidden correctly')
        if self.fname_weight == 'NULL':
            raise ValueError('FixConnLayer: must specify fixconn_weight')
        import numpy as np
        nin = in_specs[0].x
        w = np.zeros((self.param.num_hidden, nin), dtype=np.float32)
        with open(self.fname_weight) as f:
            toks = f.read().split()
        nrow, ncol, nnz = int(toks[0]), int(toks[1]), int(toks[2])
        if (nrow, ncol) != w.shape:
            raise ValueError('FixConnLayer: weight shape mismatch')
        for i in range(nnz):
            r, c, v = toks[3 + 3 * i:6 + 3 * i]
            w[int(r), int(c)] = float(v)
        self._wmat = jnp.asarray(w)
        return [NodeSpec(1, 1, self.param.num_hidden)]

    def forward(self, params, inputs, ctx):
        return [as_mat(inputs[0]) @ self._wmat.T]
