"""Convolution layer.

TPU-native replacement for the reference's im2col-GEMM convolution
(``src/layer/convolution_layer-inl.hpp:70-155``) and its cuDNN override
(``cudnn_convolution_layer-inl.hpp``): forward and both backward passes
lower to ``lax.conv_general_dilated`` in NHWC/HWIO layout, which XLA tiles
directly onto the MXU — no explicit column buffer, so the reference's
``temp_col_max`` chunking knob is accepted but has no effect on memory.

Grouped convolution (``ngroup``) maps to ``feature_group_count``.
Output spatial size matches the reference exactly:
``(in + 2*pad - k) / stride + 1`` (floor).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
from jax import lax

from .base import Layer, NodeSpec, kConv, register_layer


@register_layer
class ConvolutionLayer(Layer):
    type_name = 'conv'
    type_id = kConv
    param_fields = ('wmat', 'bias')

    def infer_shapes(self, in_specs: List[NodeSpec]) -> List[NodeSpec]:
        assert len(in_specs) == 1, 'conv: only supports 1-1 connection'
        p = self.param
        s = in_specs[0]
        if p.num_channel <= 0:
            raise ValueError('conv: must set nchannel correctly')
        if p.kernel_height <= 0 or p.kernel_width <= 0:
            raise ValueError('conv: must set kernel_size correctly')
        if s.c % p.num_group or p.num_channel % p.num_group:
            raise ValueError('conv: channels must be divisible by ngroup')
        p.num_input_channel = s.c
        oy = (s.y + 2 * p.pad_y - p.kernel_height) // p.stride + 1
        ox = (s.x + 2 * p.pad_x - p.kernel_width) // p.stride + 1
        if oy <= 0 or ox <= 0:
            raise ValueError('conv: kernel larger than padded input')
        return [NodeSpec(p.num_channel, oy, ox)]

    def init_params(self, rng, in_specs, dtype=jnp.float32):
        p = self.param
        cin_g = in_specs[0].c // p.num_group
        # HWIO layout for lax.conv; fan numbers match the reference's
        # (ngroup, nch/g, nin/g*kh*kw) weight: in = nin/g*kh*kw, out = nch/g
        shape = (p.kernel_height, p.kernel_width, cin_g, p.num_channel)
        in_num = cin_g * p.kernel_height * p.kernel_width
        out_num = p.num_channel // p.num_group
        out = {'wmat': p.rand_init_weight(rng, shape, in_num, out_num, dtype)}
        if p.no_bias == 0:
            out['bias'] = jnp.full((p.num_channel,), p.init_bias, dtype)
        return out

    def forward(self, params, inputs, ctx):
        p = self.param
        x = inputs[0]  # (b, y, x, c)
        # operands share the activation dtype; the MXU accumulates in f32
        # internally for bf16 inputs, so no preferred_element_type needed
        # (which also trips the conv transpose rule on mixed cotangents)
        out = lax.conv_general_dilated(
            x, params['wmat'].astype(x.dtype),
            window_strides=(p.stride, p.stride),
            padding=((p.pad_y, p.pad_y), (p.pad_x, p.pad_x)),
            dimension_numbers=('NHWC', 'HWIO', 'NHWC'),
            feature_group_count=p.num_group)
        if p.no_bias == 0:
            out = out + params['bias'].astype(x.dtype)
        return [out.astype(x.dtype)]
