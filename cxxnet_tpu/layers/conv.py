"""Convolution layer.

TPU-native replacement for the reference's im2col-GEMM convolution
(``src/layer/convolution_layer-inl.hpp:70-155``) and its cuDNN override
(``cudnn_convolution_layer-inl.hpp``): forward and both backward passes
lower to ``lax.conv_general_dilated`` in NHWC/HWIO layout, which XLA tiles
directly onto the MXU — no explicit column buffer, so the reference's
``temp_col_max`` chunking knob is accepted but has no effect on memory.

Grouped convolution (``ngroup``) maps to ``feature_group_count``.
Output spatial size matches the reference exactly:
``(in + 2*pad - k) / stride + 1`` (floor).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
from jax import lax

from .base import Layer, NodeSpec, kConv, register_layer

_DN = ('NHWC', 'HWIO', 'NHWC')


def conv_native(x, w, strides, pad, groups=1):
    """Plain lax.conv lowering; grouped via feature_group_count."""
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad,
        dimension_numbers=_DN, feature_group_count=groups)


def conv_im2col(x, w, strides, pad):
    """Explicit patches->GEMM lowering: a shallow input (e.g. AlexNet
    conv1's c=3) gives the native conv only a c-deep contraction per MXU
    pass; the patch GEMM contracts kh*kw*c deep (363) at the cost of
    materializing the column tensor — the reference's im2col
    (``convolution_layer-inl.hpp:70-106``) reborn as an XLA-level
    lowering choice.  Backward comes from AD: dW is a GEMM, dx flows
    through the patch-extraction transpose (col2im)."""
    kh, kw, _, cout = w.shape
    pat = lax.conv_general_dilated_patches(
        x, filter_shape=(kh, kw), window_strides=strides,
        padding=pad, dimension_numbers=_DN)
    b, oy, ox, k = pat.shape
    # patches feature order is (c, kh, kw)
    w2 = jnp.transpose(w, (2, 0, 1, 3)).reshape(k, cout)
    return (pat.reshape(b * oy * ox, k) @ w2).reshape(b, oy, ox, cout)


def conv_s2d(x, w, strides, pad):
    """Space-to-depth lowering for strided convs on shallow inputs
    (AlexNet conv1: 11x11 s4 on c=3).  Rearranging each stride-sized
    pixel block into channels turns the stride-s conv into a stride-1
    conv whose contraction is ``s*s*c`` deep (conv1: 48, and the
    ceil(k/s)=3-tap kernel contracts 3*3*48=432 per output) — the MXU
    fill of im2col WITHOUT materializing the patch tensor (the s2d
    input is the same bytes as the input; the kernel rearrangement is
    weight-sized).  The MLPerf-era TPU ResNet entry-conv trick, applied
    as a general lowering.  Math: with the kernel zero-padded to
    ``K = ceil(k/s)*s``, ``y[o] = sum_u x[o*s+u] w[u]`` regroups by
    ``u = a*s + r`` into a stride-1 conv over block index ``a`` with
    ``(r, c)`` as channels — exact, so backward comes from AD through
    the reshapes.  Handles ARBITRARY padding (the pad folds into
    explicit zeros before blocking, so no alignment is required for
    correctness); the ``_lowering`` gate nonetheless only routes
    stride-aligned pads here — a conservative POLICY bound, keeping s2d
    on the shape class the on-chip receipts actually measured, not a
    correctness requirement."""
    sy, sx = strides
    (py_lo, py_hi), (px_lo, px_hi) = pad
    b, _, _, c = x.shape
    kh, kw, cin, cout = w.shape
    x = jnp.pad(x, ((0, 0), (py_lo, py_hi), (px_lo, px_hi), (0, 0)))
    h2, w2 = x.shape[1], x.shape[2]
    out_h = (h2 - kh) // sy + 1
    out_w = (w2 - kw) // sx + 1
    bkh, bkw = -(-kh // sy), -(-kw // sx)       # kernel taps in blocks
    wp = jnp.pad(w, ((0, bkh * sy - kh), (0, bkw * sx - kw),
                     (0, 0), (0, 0)))
    # input must cover block (out-1)+bk-1 on each axis
    hp = max(-(-h2 // sy), out_h - 1 + bkh) * sy
    wpx = max(-(-w2 // sx), out_w - 1 + bkw) * sx
    x = jnp.pad(x, ((0, 0), (0, hp - h2), (0, wpx - w2), (0, 0)))
    xb = x.reshape(b, hp // sy, sy, wpx // sx, sx, c)
    xb = xb.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, hp // sy, wpx // sx, sy * sx * c)
    wb = wp.reshape(bkh, sy, bkw, sx, cin, cout)
    wb = wb.transpose(0, 2, 1, 3, 4, 5).reshape(
        bkh, bkw, sy * sx * cin, cout)
    out = lax.conv_general_dilated(xb, wb, (1, 1), ((0, 0), (0, 0)),
                                   dimension_numbers=_DN)
    return out[:, :out_h, :out_w, :]


def _conv_native_mb(x, w, strides, pad, groups):
    """Module-level adapter handed to ``microbatched_conv`` (a stable,
    hashable nondiff arg — closures would retrace per call)."""
    return conv_native(x, w, strides, pad, groups)


def _conv_im2col_mb(x, w, strides, pad, groups):
    """im2col adapter for microbatching; the routing gate guarantees
    ``groups == 1`` (im2col targets ungrouped convs)."""
    return conv_im2col(x, w, strides, pad)


def conv_split(x, w, strides, pad, groups):
    """Per-group convs + concat instead of feature_group_count: lets XLA
    pick each group's layout independently (grouped convs halve the
    contraction depth per pass under fgc)."""
    cin_g = x.shape[-1] // groups
    cout_g = w.shape[-1] // groups
    return jnp.concatenate([
        lax.conv_general_dilated(
            x[..., i * cin_g:(i + 1) * cin_g],
            w[..., i * cout_g:(i + 1) * cout_g],
            window_strides=strides, padding=pad, dimension_numbers=_DN)
        for i in range(groups)], axis=-1)


@register_layer
class ConvolutionLayer(Layer):
    type_name = 'conv'
    type_id = kConv
    param_fields = ('wmat', 'bias')

    def infer_shapes(self, in_specs: List[NodeSpec]) -> List[NodeSpec]:
        assert len(in_specs) == 1, 'conv: only supports 1-1 connection'
        p = self.param
        s = in_specs[0]
        if p.num_channel <= 0:
            raise ValueError('conv: must set nchannel correctly')
        if p.kernel_height <= 0 or p.kernel_width <= 0:
            raise ValueError('conv: must set kernel_size correctly')
        if s.c % p.num_group or p.num_channel % p.num_group:
            raise ValueError('conv: channels must be divisible by ngroup')
        p.num_input_channel = s.c
        oy = (s.y + 2 * p.pad_y - p.kernel_height) // p.stride + 1
        ox = (s.x + 2 * p.pad_x - p.kernel_width) // p.stride + 1
        if oy <= 0 or ox <= 0:
            raise ValueError('conv: kernel larger than padded input')
        return [NodeSpec(p.num_channel, oy, ox)]

    def init_params(self, rng, in_specs, dtype=jnp.float32):
        p = self.param
        cin_g = in_specs[0].c // p.num_group
        # HWIO layout for lax.conv; fan numbers match the reference's
        # (ngroup, nch/g, nin/g*kh*kw) weight: in = nin/g*kh*kw, out = nch/g
        shape = (p.kernel_height, p.kernel_width, cin_g, p.num_channel)
        in_num = cin_g * p.kernel_height * p.kernel_width
        out_num = p.num_channel // p.num_group
        out = {'wmat': p.rand_init_weight(rng, shape, in_num, out_num, dtype)}
        if p.no_bias == 0:
            out['bias'] = jnp.full((p.num_channel,), p.init_bias, dtype)
        return out

    def _lowering(self) -> str:
        """Resolve the conv_lowering knob.  'auto' currently means native
        for every shape — the im2col and split variants exist as measured
        experiments (tools/conv_lowering_bench.py times THESE module
        functions); auto flips per shape class only when an on-chip
        receipt shows a win (same policy as
        ops.pallas_kernels.lrn_auto_mode)."""
        mode = self.param.conv_lowering
        if mode == 'auto':
            return 'native'
        # each variant degrades to native on the shapes it does not
        # target, so the knob is usable as a netconfig GLOBAL (replayed
        # into every layer): im2col targets ungrouped convs, split
        # grouped ones, s2d ungrouped strided convs.  The s2d
        # stride-aligned-padding clause is a conservative POLICY bound,
        # not correctness (conv_s2d handles arbitrary pads — it folds
        # them into explicit zeros first): it pins the lowering to the
        # entry-conv shape class the receipts measured wins on
        if mode == 'split' and self.param.num_group == 1:
            return 'native'
        if mode == 'im2col' and self.param.num_group != 1:
            return 'native'
        if mode == 's2d' and (self.param.num_group != 1
                              or self.param.stride <= 1
                              or self.param.pad_y % self.param.stride
                              or self.param.pad_x % self.param.stride):
            return 'native'
        return mode

    def _micro_split(self, mode: str, batch: int) -> int:
        """Resolve the ``micro_batch`` knob for this dispatch: engage
        only on the per-example-independent lowerings (native/im2col —
        s2d/split reshape the batch themselves) when the split divides
        the batch evenly; anything else falls through to unsplit, which
        is bitwise-identical anyway."""
        split = self.param.micro_batch
        if split <= 1 or mode not in ('native', 'im2col'):
            return 1
        if batch % split:
            return 1
        return split

    def forward(self, params, inputs, ctx):
        p = self.param
        x = inputs[0]  # (b, y, x, c)
        # operands share the activation dtype; the MXU accumulates in f32
        # internally for bf16 inputs, so no preferred_element_type needed
        # (which also trips the conv transpose rule on mixed cotangents)
        w = params['wmat'].astype(x.dtype)
        strides = (p.stride, p.stride)
        pad = ((p.pad_y, p.pad_y), (p.pad_x, p.pad_x))
        mode = self._lowering()
        split = self._micro_split(mode, x.shape[0])
        if split > 1:
            from ..ops.pallas_cnn import microbatched_conv
            fn = _conv_im2col_mb if mode == 'im2col' else _conv_native_mb
            out = microbatched_conv(x, w, strides, pad, p.num_group,
                                    split, fn)
        elif mode == 'im2col':
            out = conv_im2col(x, w, strides, pad)
        elif mode == 's2d':
            out = conv_s2d(x, w, strides, pad)
        elif mode == 'split':
            out = conv_split(x, w, strides, pad, p.num_group)
        else:
            out = conv_native(x, w, strides, pad, p.num_group)
        if p.no_bias == 0:
            out = out + params['bias'].astype(x.dtype)
        return [out.astype(x.dtype)]
