"""Loss layers: softmax, l2_loss, multi_logistic.

The reference loss layers are self-loop layers that overwrite the node with
the forward transform, then overwrite it again with the hand-set gradient on
a CPU roundtrip (``src/layer/loss/loss_layer_base-inl.hpp:87-96``).  Here the
forward transform stays for metrics/prediction, and each layer contributes a
scalar loss whose ``jax.grad`` equals the reference's hand-set gradient —
entirely on device, no D2H:

* softmax  (``loss/softmax_layer-inl.hpp``): grad p - onehot(y)  ⇔ CE loss
* l2_loss  (``loss/l2_loss_layer-inl.hpp``): grad pred - label  ⇔ 0.5*SSE
* multi_logistic (``loss/multi_logistic_layer-inl.hpp``): grad p - y ⇔ BCE

All are scaled by ``grad_scale / (batch_size * update_period)``
(loss_layer_base:61-63) — note ``batch_size`` is the *global* batch size.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import (Layer, as_mat, kL2Loss, kMultiLogistic,
                   kSoftmax, register_layer)


class LossLayerBase(Layer):
    """Self-loop loss layer (``loss_layer_base-inl.hpp:14-63``)."""

    def __init__(self, name=''):
        super().__init__(name)
        self.target = 'label'
        self.grad_scale = 1.0
        self.batch_size = 1
        self.update_period = 1

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'target':
            self.target = val
        if name == 'grad_scale':
            self.grad_scale = float(val)
        if name == 'batch_size':
            self.batch_size = int(val)
        if name == 'update_period':
            self.update_period = int(val)

    @property
    def is_loss(self):
        return True

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1, 'LossLayer: only supports 1-1 connection'
        return [in_specs[0]]

    @property
    def scale(self) -> float:
        return self.grad_scale / (self.batch_size * self.update_period)

    def loss(self, params, inputs, labels, ctx, mask=None):
        """Scalar loss.  labels: (batch, label_width) for this layer's
        target field; mask: optional (batch,) 0/1 instance weights for
        padded tail batches."""
        x = as_mat(inputs[0]).astype(jnp.float32)   # losses always in f32
        per_inst = self._per_instance_loss(x, labels)
        if mask is not None:
            per_inst = per_inst * mask
        return jnp.sum(per_inst) * self.scale

    def _per_instance_loss(self, x, labels):
        raise NotImplementedError


@register_layer
class SoftmaxLayer(LossLayerBase):
    type_name = 'softmax'
    type_id = kSoftmax

    def forward(self, params, inputs, ctx):
        return [jax.nn.softmax(as_mat(inputs[0]).astype(jnp.float32),
                               axis=-1)]

    def _per_instance_loss(self, x, labels):
        logp = jax.nn.log_softmax(x, axis=-1)
        idx = labels[:, 0].astype(jnp.int32)
        return -jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]


@register_layer
class L2LossLayer(LossLayerBase):
    type_name = 'l2_loss'
    type_id = kL2Loss

    def forward(self, params, inputs, ctx):
        return [inputs[0]]

    def _per_instance_loss(self, x, labels):
        return 0.5 * jnp.sum((x - labels) ** 2, axis=-1)


@register_layer
class MultiLogisticLayer(LossLayerBase):
    type_name = 'multi_logistic'
    type_id = kMultiLogistic

    def forward(self, params, inputs, ctx):
        return [jax.nn.sigmoid(as_mat(inputs[0]))]

    def _per_instance_loss(self, x, labels):
        # sum of binary cross-entropies with logits x; d/dx = sigmoid(x)-y
        return jnp.sum(jnp.logaddexp(0.0, x) - x * labels, axis=-1)
