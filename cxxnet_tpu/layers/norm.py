"""Normalization layers: LRN and BatchNorm.

LRN (``src/layer/lrn_layer-inl.hpp:46-57``): cross-channel response
normalization, ``out = x * (knorm + alpha/n * sum_{window} x^2)^(-beta)``
with a centered channel window of ``local_size``.

BatchNorm (``src/layer/batch_norm_layer-inl.hpp``): per-channel (conv) or
per-feature (fc).  The reference keeps **no running averages — evaluation
also normalizes with current-minibatch statistics** (doc/layer.md:258); we
reproduce that exactly (a parity quirk worth revisiting).  eps default 1e-10;
learnable slope is visited under the 'wmat' tag, bias under 'bias'.
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import Layer, kBatchNorm, kLRN, register_layer


@register_layer
class LRNLayer(Layer):
    type_name = 'lrn'
    type_id = kLRN

    def __init__(self, name=''):
        super().__init__(name)
        self.knorm = 1.0
        self.nsize = 3
        self.alpha = 0.001
        self.beta = 0.75

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'local_size':
            self.nsize = int(val)
        if name == 'alpha':
            self.alpha = float(val)
        if name == 'beta':
            self.beta = float(val)
        if name == 'knorm':
            self.knorm = float(val)

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1, 'lrn: only supports 1-1 connection'
        return [in_specs[0]]

    def forward(self, params, inputs, ctx):
        x = inputs[0]  # (b, y, x, c)
        from ..ops.pallas_kernels import (lrn_auto_mode, lrn_hybrid,
                                          lrn_pallas)
        mode = lrn_auto_mode(x.shape[-1], ctx.spmd_devices)
        if mode == 'full':
            # Pallas forward AND backward: fwd+bwd measured 2.16x ahead
            # of XLA at 128-lane-aligned channels
            # (receipts/micro_lrn.json; ops/pallas_kernels.py)
            return [lrn_pallas(x, self.nsize, self.alpha, self.beta,
                               self.knorm)]
        if mode == 'hybrid':
            # Pallas forward / XLA backward: the fused fwd wins even at
            # non-MXU-aligned channel counts but the Pallas bwd loses
            return [lrn_hybrid(x, self.nsize, self.alpha, self.beta,
                               self.knorm)]
        x32 = x.astype(jnp.float32)
        n = self.nsize
        half_lo = (n - 1) // 2
        half_hi = n - 1 - half_lo
        sq = x32 * x32
        # cross-channel window sum via cumulative sum along the channel axis
        c = x.shape[-1]
        pad = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half_lo + 1, half_hi)])
        cums = jnp.cumsum(pad, axis=-1)
        window = (cums[..., n:n + c] - cums[..., 0:c])
        norm = window * (self.alpha / n) + self.knorm
        return [(x32 * jnp.power(norm, -self.beta)).astype(x.dtype)]


def fold_scale_shift(gamma, beta, mean, var, eps):
    """The conv+BN fold algebra (nnet/fold.py): with frozen statistics
    ``(mean, var)``, BN is the affine map ``y = z*scale + shift`` with
    ``scale = gamma/sqrt(var+eps)`` and ``shift = beta - mean*scale`` —
    which a preceding conv absorbs as ``w*scale`` (output-channel axis)
    and ``b*scale + shift``.  All f32; the sqrt spelling matches
    ``BatchNormLayer.forward`` exactly so the fold's frozen-stats
    normalization is the same float program as the live one."""
    scale = gamma / jnp.sqrt(var + eps)
    return scale, beta - mean * scale


@register_layer
class BatchNormLayer(Layer):
    type_name = 'batch_norm'
    type_id = kBatchNorm
    param_fields = ('wmat', 'bias')   # slope under 'wmat', bias under 'bias'

    def __init__(self, name=''):
        super().__init__(name)
        self.init_slope = 1.0
        self.init_bias = 0.0
        self.eps = 1e-10

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'init_slope':
            self.init_slope = float(val)
        if name == 'init_bias':
            self.init_bias = float(val)
        if name == 'eps':
            self.eps = float(val)

    def infer_shapes(self, in_specs):
        assert len(in_specs) == 1, 'batch_norm: only supports 1-1 connection'
        s = in_specs[0]
        self._channels = s.x if s.is_mat else s.c
        return [s]

    def init_params(self, rng, in_specs, dtype=jnp.float32):
        return {'wmat': jnp.full((self._channels,), self.init_slope, dtype),
                'bias': jnp.full((self._channels,), self.init_bias, dtype)}

    def forward(self, params, inputs, ctx):
        x = inputs[0]
        x32 = x.astype(jnp.float32)
        axes = tuple(range(x.ndim - 1))   # all but trailing channel/feature
        mean = jnp.mean(x32, axis=axes)
        var = jnp.mean((x32 - mean) ** 2, axis=axes)
        # batch statistics at train AND eval — the reference quirk
        xhat = (x32 - mean) / jnp.sqrt(var + self.eps)
        return [(xhat * params['wmat'] + params['bias']).astype(x.dtype)]
