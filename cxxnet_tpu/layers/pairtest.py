"""PairTest — in-graph differential testing layer.

Rebuilds the reference's ``pairtest-A-B`` harness
(``src/layer/pairtest_layer-inl.hpp:75-199``): a master and a slave
implementation of the same layer type run side by side on identical inputs
and shared weights; outputs are compared with relative tolerance 1e-5 and
mismatches reported (here via ``jax.debug.print`` from inside the jitted
graph).  Per-side overrides use the reference's ``master:``/``slave:``
param prefixes (pairtest:127-136).  The graph output is the master's.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import ForwardContext, Layer, create_layer, layer_type_name


class PairTestLayer(Layer):
    type_name = 'pairtest'

    def __init__(self, master_type: int, slave_type: int, name=''):
        super().__init__(name)
        self.type_id = 1024 * master_type + slave_type
        self.master = create_layer(master_type, name=name)
        self.slave = create_layer(slave_type, name=name)
        self.tol = 1e-5
        self.type_name = (f'pairtest-{layer_type_name(master_type)}'
                          f'-{layer_type_name(slave_type)}')

    @property
    def param_fields(self):
        return self.master.param_fields

    def set_param(self, name, val):
        if name.startswith('master:'):
            self.master.set_param(name[len('master:'):], val)
        elif name.startswith('slave:'):
            self.slave.set_param(name[len('slave:'):], val)
        else:
            self.master.set_param(name, val)
            self.slave.set_param(name, val)
            if name == 'pairtest_tol':
                self.tol = float(val)

    def infer_shapes(self, in_specs):
        out_m = self.master.infer_shapes(in_specs)
        out_s = self.slave.infer_shapes(list(in_specs))
        for a, b in zip(out_m, out_s):
            if a != b:
                raise ValueError(
                    f'{self.type_name}: master/slave output shapes differ: '
                    f'{a} vs {b}')
        return out_m

    def init_params(self, rng, in_specs, dtype=jnp.float32):
        # weights are shared: the slave reuses the master's params
        # (reference syncs them at init, pairtest:137-141)
        return self.master.init_params(rng, in_specs, dtype)

    def forward(self, params, inputs, ctx: ForwardContext):
        out_m = self.master.forward(params, inputs, ctx)
        out_s = self.slave.forward(params, inputs, ctx)
        tol = self.tol
        lname = self.type_name
        for i, (a, b) in enumerate(zip(out_m, out_s)):
            err = jnp.max(jnp.abs(a - b) / (jnp.abs(a) + jnp.abs(b) + 1e-6))
            jax.lax.cond(
                err > tol,
                lambda e: jax.debug.print(
                    'PairTest MISMATCH {l} out[{i}]: rel-err {e}',
                    l=lname, i=i, e=e),
                lambda e: None, err)
        return out_m
