"""Pooling layers (max / sum / avg / relu_max / insanity_max).

TPU-native replacement for ``src/layer/pooling_layer-inl.hpp`` (mshadow
``pool<Reducer>`` expressions) via ``lax.reduce_window``.  Semantics kept
from the reference:

* output size is the "ceil" formula
  ``min(in - k + stride - 1, in - 1) / stride + 1`` (pooling_layer:103-105),
  with edge windows clamped to the input;
* ``avg_pooling`` divides by the *full* window size ``kh*kw`` even for
  clamped edge windows (pooling_layer:47-49);
* ``relu_max_pooling`` fuses a relu before pooling (layer_impl-inl.hpp:55);
* ``insanity_max_pooling`` jitters each source pixel to a random clamped
  neighbor before max pooling at train time, exact pooling at eval
  (``insanity_pooling_layer-inl.hpp:64-99,245-258``).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from .base import (Layer, NodeSpec, kAvgPooling, kInsanityPooling,
                   kMaxPooling, kReluMaxPooling, kSumPooling, register_layer)


def pool_out_dim(in_dim: int, k: int, stride: int) -> int:
    return min(in_dim - k + stride - 1, in_dim - 1) // stride + 1


def _reduce_pool(x, ky, kx, stride, mode):
    """x: (b, y, x, c) → pooled with clamped edge windows."""
    oy = pool_out_dim(x.shape[1], ky, stride)
    ox = pool_out_dim(x.shape[2], kx, stride)
    pad_y = max((oy - 1) * stride + ky - x.shape[1], 0)
    pad_x = max((ox - 1) * stride + kx - x.shape[2], 0)
    if mode == 'max':
        init, op = -jnp.inf, lax.max
    else:
        init, op = 0.0, lax.add
    # init must stay a concrete scalar: a traced constant would stop JAX
    # from recognizing the max/sum special forms, losing the autodiff rule
    out = lax.reduce_window(
        x, init, op,
        window_dimensions=(1, ky, kx, 1),
        window_strides=(1, stride, stride, 1),
        padding=((0, 0), (0, pad_y), (0, pad_x), (0, 0)))
    return out


class _PoolingBase(Layer):
    mode = 'max'
    pre_relu = False

    def infer_shapes(self, in_specs: List[NodeSpec]) -> List[NodeSpec]:
        assert len(in_specs) == 1, 'pooling: only supports 1-1 connection'
        p, s = self.param, in_specs[0]
        if p.kernel_height <= 0 or p.kernel_width <= 0:
            raise ValueError('pooling: must set kernel_size correctly')
        iy, ix = s.y + 2 * p.pad_y, s.x + 2 * p.pad_x
        if p.kernel_width > ix or p.kernel_height > iy:
            raise ValueError('pooling: kernel size exceeds input')
        return [NodeSpec(s.c,
                         pool_out_dim(iy, p.kernel_height, p.stride),
                         pool_out_dim(ix, p.kernel_width, p.stride))]

    def forward(self, params, inputs, ctx):
        p = self.param
        x = inputs[0]
        if self.pre_relu:
            x = jnp.maximum(x, 0.0)
        if p.pad_y or p.pad_x:
            # pad extension (the reference pooling has none): -inf for max
            # so padding never wins; 0 for sum/avg
            fill = -jnp.inf if self.mode == 'max' else 0.0
            x = jnp.pad(x, ((0, 0), (p.pad_y, p.pad_y),
                            (p.pad_x, p.pad_x), (0, 0)),
                        constant_values=fill)
        out = _reduce_pool(x, p.kernel_height, p.kernel_width, p.stride,
                           self.mode)
        if self.mode == 'avg':
            out = out * (1.0 / (p.kernel_height * p.kernel_width))
        return [out]


@register_layer
class MaxPoolingLayer(_PoolingBase):
    type_name = 'max_pooling'
    type_id = kMaxPooling
    mode = 'max'


@register_layer
class SumPoolingLayer(_PoolingBase):
    type_name = 'sum_pooling'
    type_id = kSumPooling
    mode = 'sum'


@register_layer
class AvgPoolingLayer(_PoolingBase):
    type_name = 'avg_pooling'
    type_id = kAvgPooling
    mode = 'avg'


@register_layer
class ReluMaxPoolingLayer(_PoolingBase):
    type_name = 'relu_max_pooling'
    type_id = kReluMaxPooling
    mode = 'max'
    pre_relu = True


@register_layer
class InsanityPoolingLayer(_PoolingBase):
    """Stochastic-jitter max pooling.  Because the reference's jitter target
    depends only on the source coordinate (not the window), jitter-then-pool
    over a pre-gathered image is exactly equivalent to its per-window-read
    formulation — and it vectorizes as five shifted copies + select."""

    type_name = 'insanity_max_pooling'
    type_id = kInsanityPooling
    mode = 'max'

    def __init__(self, name=''):
        super().__init__(name)
        self.p_keep = 1.0

    def set_param(self, name, val):
        super().set_param(name, val)
        if name == 'keep':
            self.p_keep = float(val)

    def forward(self, params, inputs, ctx):
        p = self.param
        x = inputs[0]
        if ctx.is_train and self.p_keep < 1.0:
            u = jax.random.uniform(ctx.layer_rng(), x.shape, x.dtype)
            delta = (1.0 - self.p_keep) / 4.0
            # clamped single-pixel shifts along y then x
            up = jnp.concatenate([x[:, :1], x[:, :-1]], axis=1)
            down = jnp.concatenate([x[:, 1:], x[:, -1:]], axis=1)
            left = jnp.concatenate([x[:, :, :1], x[:, :, :-1]], axis=2)
            right = jnp.concatenate([x[:, :, 1:], x[:, :, -1:]], axis=2)
            x = jnp.select(
                [u < self.p_keep,
                 u < self.p_keep + delta,
                 u < self.p_keep + 2 * delta,
                 u < self.p_keep + 3 * delta],
                [x, up, down, left], default=right)
        out = _reduce_pool(x, p.kernel_height, p.kernel_width, p.stride, 'max')
        return [out]
