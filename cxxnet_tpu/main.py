"""Config-file-driven task CLI.

Equivalent of the reference driver (``src/cxxnet_main.cpp:16-478``)::

    python -m cxxnet_tpu.main config.conf [k=v ...]

Tasks (``task=``): ``train`` (default), ``finetune``, ``pred``,
``pred_raw``, ``extract``.
Counter/checkpoint choreography preserved: model files are
``model_dir/%04d.model`` with an int ``net_type`` prefix; ``continue=1``
scans forward from ``start_counter`` to resume from the newest checkpoint
(``cxxnet_main.cpp:135-157``); eval output goes to **stderr** as
``[round]\\tname-metric:value``; ``test_io=1`` runs the loop without compute.
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional, Tuple

from .io.data import create_iterator
from .nnet import checkpoint as model_io
from .nnet.trainer import NetTrainer
from .utils.config import apply_cli_overrides, parse_config_file
from .utils.profiler import TraceWindow

ConfigEntry = Tuple[str, str]


class LearnTask:
    def __init__(self):
        self.task = 'train'
        self.net_type = 0
        self.reset_net_type = -1
        self.print_step = 100
        self.continue_training = 0
        self.save_period = 1
        self.start_counter = 0
        self.name_model_in = 'NULL'
        self.name_model_dir = 'models'
        self.num_round = 10
        self.max_round = 2147483647
        self.silent = 0
        self.device = 'tpu'
        self.test_io = 0
        self.exact_ckpt = 0
        # fault-tolerant runtime knobs (doc/fault_tolerance.md)
        self.fault_plan = ''           # train.fault_plan grammar
        self.supervise = 0             # train.supervise=1 -> TrainSupervisor
        self.watchdog_deadline = 60.0  # train.watchdog_deadline (s, 0=off)
        self.max_restarts = 3          # train.max_restarts per round
        self.nan_breaker = 3           # train.nan_breaker (consecutive NaNs)
        self.save_every = 0            # train.save_every (steps, 0=per-round)
        self.keep_last = 4             # train.keep_last ckpts kept (0=all)
        self.save_async = 0            # save_async=1 -> background ckpt
                                       # writer (doc/fault_tolerance.md);
                                       # final save always barriers
        self.save_workers = 2          # save_workers per-save write threads
        self._async_ckpt = None        # lazy AsyncCheckpointer
        # scanned hot loop: K staged batches per device dispatch
        # (doc/trainer.md; steps_per_dispatch=1 = per-step reference path)
        self.steps_per_dispatch = 1
        self.scan_strict = 0           # 1 = a demotion raises
                                       # ScanStrictError instead of
                                       # silently falling back per-step
        # graftfuse: μ-cuDNN-style conv microbatching (doc/kernels.md);
        # replayed into every conv layer as a netconfig global — this
        # attr only anchors the autotuner's baseline candidate
        self.micro_batch = 1
        # grafttune: task=autotune searches this declared space
        # (doc/autotune.md); parsed at init so a bad spec fails fast
        self.autotune = ''
        self._tune_space = None
        self._data_itcfg = None        # captured data-section config so
        self._data_defcfg = []         # the tuner can rebuild the train
                                       # iterator at a candidate nworker
        self.extract_node_name = ''
        self.name_pred = 'pred.txt'
        self.output_format = 1
        # online serving knobs (task=serve, doc/serving.md)
        self.serve_buckets = '1,8,32'  # serve.buckets batch-size ladder
        self.serve_max_queue = 64      # serve.max_queue admission bound
        self.serve_max_wait = 0.002    # serve.max_wait coalesce window (s)
        self.serve_deadline = 1.0      # serve.deadline per-request (s)
        self.serve_reload = 0.0        # serve.reload poll period (s, 0=off)
        # continuous decode + multi-model fleet (doc/serving.md)
        self.serve_mode = 'predict'    # serve.mode: predict | decode
        self.serve_slots = 4           # serve.slots decode step width
        self.serve_pages = 64          # serve.pages KV pool (physical pages)
        self.serve_page_size = 16      # serve.page_size tokens per page
        self.serve_max_prompt = 64     # serve.max_prompt longest prompt
        self.serve_max_new = 16        # serve.max_new decode horizon/bound
        self.serve_eos = -1            # serve.eos id (-1 = none)
        self.serve_lm = ''             # serve.lm transformer spec (k=v;...)
        self.serve_lm_seed = 0         # serve.lm_seed init seed (no model_in)
        self.serve_lm_model_in = 'NULL'  # serve.lm_model_in %04d.lm file
        self.serve_requests = 16       # serve.requests decode drive size
        self.serve_temperature = 0.0   # serve.temperature decode sampling
        self.serve_seed = 0            # serve.seed drive prompt/rng seed
        self.serve_models = ''         # serve.models fleet: id=dir;id=dir
        self.serve_mem_budget = 0      # serve.mem_budget bytes (0 = off)
        self.serve_dtype = 'f32'       # serve.dtype: f32 | bf16 | int8
        self.serve_fold_bn = 0         # serve.fold_bn: 1 = fold conv+BN
                                       # at engine build (doc/kernels.md)
        self.serve_flash = 'auto'      # serve.flash_decode: auto | 0 | 1
        self.serve_prefix_share = 0    # serve.prefix_share index pages (0=off)
        # graftcache: tiered KV prefix cache (doc/serving.md "Tiered KV
        # cache"); tiers need serve.prefix_share > 0
        self.serve_kv_host_mb = 0      # serve.kv_host_mb tier-1 RAM (0=off)
        self.serve_kv_disk_mb = 0      # serve.kv_disk_mb tier-2 disk (0=off)
        self.serve_kv_dir = ''         # serve.kv_dir tier-2 record dir
        self.serve_kv_share_dir = ''   # serve.kv_share_dir cross-replica
        self.serve_spec_k = 0          # serve.spec_k window width (0/1=off)
        self.serve_draft = ''          # serve.draft spec (k=v;... like serve.lm)
        # graftshard: mesh-sharded decode + disaggregated prefill +
        # data-parallel predict replicas (doc/serving.md "Sharded serving")
        self.serve_shard = ''          # serve.shard tp:N decode tensor split
        self.serve_prefill_workers = 0  # serve.prefill_workers threads (0=inline)
        self.serve_replicas = 0        # serve.replicas predict DP (0/1=single)
        # graftstorm: adversarial traffic + SLO-driven autoscaling
        self.serve_scenario = ''       # serve.scenario spec (shape=...;seed=...)
        self.serve_autoscale = ''      # serve.autoscale policy (min_slots=...;...)
        # train-while-serve (task=online, doc/online.md); batcher shape
        # comes from the serve.* keys above
        self.online_save_every = 8     # online.save_every steps/checkpoint
        self.online_freshness_slo = 0.0  # online.freshness_slo seconds
        self.online_freshness_strict = 0  # online.freshness_strict 1=raise
        self.online_reload = 0.05      # online.reload registry poll (s)
        self.online_qps = 50.0         # online.qps traffic driver rate
        # elastic multi-host training (doc/fault_tolerance.md
        # "Multi-host recovery"); hosts>0 turns the elastic runtime on
        self.dist_hosts = 0            # dist.hosts worker-host count
        self.dist_rank = -1            # dist.rank (-1 = launcher role)
        self.dist_coordinator = ''     # dist.coordinator host:port
        self.dist_heartbeat = 2.0      # dist.heartbeat seconds
        self.dist_rejoin = 2           # dist.rejoin respawn budget
        self.dist_shards = 0           # dist.shards micro-shards (0=hosts)
        self.dist_sync_timeout = 60.0  # dist.sync_timeout seconds
        self.dist_launch = 0           # dist.launch=1 forces launcher role
        # graftscope telemetry (doc/observability.md)
        self.obs_port = -1             # obs.port: -1 off, 0 ephemeral, >0 fixed
        self.obs_trace_export = ''     # obs.trace_export Chrome-trace path
        self.obs_ring_events = 4096    # obs.ring_events flight-recorder ring
        self.obs_dump_dir = ''         # obs.dump_dir ('' = model_dir/flight)
        # graftwatch: gauge history sampler + declarative SLO engine
        # (doc/observability.md "SLOs and burn rates" / "Fleet view")
        self.obs_sample_every = 0.0    # obs.sample_every s (0 = auto: on
                                       # at 0.25s only when slo.* given)
        self.obs_fleet_port = -1       # obs.fleet_port launcher merged
                                       # endpoint: -1 off, 0 ephemeral
        self.obs_trace_merge = ''      # obs.trace_merge merged Perfetto
                                       # trace path (launcher role)
        # graftprof: compiler-truth ledger + device memory + /profile
        # (doc/observability.md "Programs, memory, and MFU")
        self.obs_recompile = 'warn'    # obs.recompile: warn | raise | off
        self.obs_profile = 1           # obs.profile: /profile?ms=N on
        self.obs_hbm = 1               # obs.hbm: hbm.* device gauges on
        self.slo_specs: List[ConfigEntry] = []   # slo.<name> grammar
        self._obs_server = None
        self._obs_sampler = None
        self._obs_slo = None
        self._train_stats = None       # train-mfu/steps_per_sec gauges
        self.cfg: List[ConfigEntry] = []
        self.net_trainer: Optional[NetTrainer] = None
        self.itr_train = None
        self.itr_evals = []
        self.eval_names = []
        self.itr_pred = None

    def set_param(self, name: str, val: str) -> None:
        if val == 'default':
            return
        simple = {
            'net_type': ('net_type', int), 'reset_net_type': ('reset_net_type', int),
            'print_step': ('print_step', int), 'continue': ('continue_training', int),
            'save_model': ('save_period', int), 'start_counter': ('start_counter', int),
            'model_in': ('name_model_in', str), 'model_dir': ('name_model_dir', str),
            'num_round': ('num_round', int), 'max_round': ('max_round', int),
            'silent': ('silent', int), 'task': ('task', str), 'dev': ('device', str),
            'test_io': ('test_io', int), 'extract_node_name': ('extract_node_name', str),
            'exact_ckpt': ('exact_ckpt', int),
            'train.fault_plan': ('fault_plan', str),
            'train.supervise': ('supervise', int),
            'train.watchdog_deadline': ('watchdog_deadline', float),
            'train.max_restarts': ('max_restarts', int),
            'train.nan_breaker': ('nan_breaker', int),
            'train.save_every': ('save_every', int),
            'train.keep_last': ('keep_last', int),
            'save_async': ('save_async', int),
            'save_workers': ('save_workers', int),
            'steps_per_dispatch': ('steps_per_dispatch', int),
            'train.steps_per_dispatch': ('steps_per_dispatch', int),
            'scan_strict': ('scan_strict', int),
            'train.scan_strict': ('scan_strict', int),
            'micro_batch': ('micro_batch', int),
            'train.micro_batch': ('micro_batch', int),
            'serve.buckets': ('serve_buckets', str),
            'serve.max_queue': ('serve_max_queue', int),
            'serve.max_wait': ('serve_max_wait', float),
            'serve.deadline': ('serve_deadline', float),
            'serve.reload': ('serve_reload', float),
            'serve.mode': ('serve_mode', str),
            'serve.slots': ('serve_slots', int),
            'serve.pages': ('serve_pages', int),
            'serve.page_size': ('serve_page_size', int),
            'serve.max_prompt': ('serve_max_prompt', int),
            'serve.max_new': ('serve_max_new', int),
            'serve.eos': ('serve_eos', int),
            'serve.lm': ('serve_lm', str),
            'serve.lm_seed': ('serve_lm_seed', int),
            'serve.lm_model_in': ('serve_lm_model_in', str),
            'serve.requests': ('serve_requests', int),
            'serve.temperature': ('serve_temperature', float),
            'serve.seed': ('serve_seed', int),
            'serve.models': ('serve_models', str),
            'serve.mem_budget': ('serve_mem_budget', int),
            'serve.dtype': ('serve_dtype', str),
            'serve.fold_bn': ('serve_fold_bn', int),
            'serve.flash_decode': ('serve_flash', str),
            'serve.prefix_share': ('serve_prefix_share', int),
            'serve.kv_host_mb': ('serve_kv_host_mb', int),
            'serve.kv_disk_mb': ('serve_kv_disk_mb', int),
            'serve.kv_dir': ('serve_kv_dir', str),
            'serve.kv_share_dir': ('serve_kv_share_dir', str),
            'serve.spec_k': ('serve_spec_k', int),
            'serve.draft': ('serve_draft', str),
            'serve.shard': ('serve_shard', str),
            'serve.prefill_workers': ('serve_prefill_workers', int),
            'serve.replicas': ('serve_replicas', int),
            'serve.scenario': ('serve_scenario', str),
            'serve.autoscale': ('serve_autoscale', str),
            'dist.hosts': ('dist_hosts', int),
            'dist.rank': ('dist_rank', int),
            'dist.coordinator': ('dist_coordinator', str),
            'dist.heartbeat': ('dist_heartbeat', float),
            'dist.rejoin': ('dist_rejoin', int),
            'dist.shards': ('dist_shards', int),
            'dist.sync_timeout': ('dist_sync_timeout', float),
            'dist.launch': ('dist_launch', int),
            'obs.port': ('obs_port', int),
            'obs.trace_export': ('obs_trace_export', str),
            'obs.ring_events': ('obs_ring_events', int),
            'obs.dump_dir': ('obs_dump_dir', str),
            'obs.sample_every': ('obs_sample_every', float),
            'obs.fleet_port': ('obs_fleet_port', int),
            'obs.trace_merge': ('obs_trace_merge', str),
            'obs.recompile': ('obs_recompile', str),
            'obs.profile': ('obs_profile', int),
            'obs.hbm': ('obs_hbm', int),
            'online.save_every': ('online_save_every', int),
            'online.freshness_slo': ('online_freshness_slo', float),
            'online.freshness_strict': ('online_freshness_strict', int),
            'online.reload': ('online_reload', float),
            'online.qps': ('online_qps', float),
            'autotune': ('autotune', str),
        }
        if name in simple:
            attr, typ = simple[name]
            setattr(self, attr, typ(val))
        if name == 'obs.recompile' and val not in ('warn', 'raise', 'off'):
            # fail at config parse, like a malformed slo.* spec
            raise ValueError(
                f'obs.recompile must be warn|raise|off, got {val!r}')
        if name.startswith('slo.') and len(name) > 4:
            # declarative SLO grammar (doc/observability.md):
            # slo.<name> = <set>.<key><op><threshold>@<window>[:burn];
            # fleet.-scoped specs evaluate at the elastic launcher.
            # Validated here so a bad spec fails at config parse, and
            # @0 rejected outright: per-sample specs are fed through
            # SLOEngine.observe by in-process code (the freshness
            # path) — from the CLI one would never evaluate, a dead
            # objective reading OK forever
            from .obs.slo import SLOSpec
            spec = SLOSpec.parse(name[4:], val)
            if spec.window <= 0:
                raise ValueError(
                    f'{name}: @0 per-sample specs are engine-API-only '
                    f'(SLOEngine.observe); give a window > 0 seconds')
            self.slo_specs.append((name[4:], val))
        if name == 'output_format':
            self.output_format = 1 if val == 'txt' else 0
        self.cfg.append((name, val))

    # --- setup ------------------------------------------------------------
    def _create_net(self) -> NetTrainer:
        if self.reset_net_type != -1:
            self.net_type = self.reset_net_type
        cfg = self.cfg
        if self.task == 'serve':
            # serving never trains: skip optimizer-state allocation
            cfg = cfg + [('inference_only', '1')]
        return NetTrainer(cfg)

    def _model_path(self, counter: int) -> str:
        return os.path.join(self.name_model_dir, f'{counter:04d}.model')

    def _sync_latest_model(self) -> bool:
        """Adopt the newest ``%04d.model`` at or past ``start_counter``.
        Gap-tolerant by design: ``task=online`` publishes checkpoints
        named by STEP on the supervisor's save cadence (0008, 0016, ...),
        so the reference's consecutive-counter walk would stop at the
        first hole and miss every online checkpoint — the newest-file
        scan is the one the serving registry already trusts."""
        from .serve.registry import newest_model_file
        best = newest_model_file(self.name_model_dir)
        if best is None or best[0] < self.start_counter:
            return False
        counter, last = best

        def _read(f):
            self.net_type = int.from_bytes(f.read(4), 'little', signed=True)
            self.net_trainer = self._create_net()
            self.net_trainer.load_model(f)

        model_io.read_model_file(last, _read)
        self.start_counter = counter + 1
        if self.exact_ckpt:
            from .nnet.sharded_ckpt import step_dir
            # ask for EXACTLY the loaded model's step: newer leftover
            # sidecars (e.g. after rolling back by deleting model files)
            # must not block restoring the matching one
            if os.path.isdir(step_dir(self._exact_dir(), counter)):
                self.net_trainer.load_training_state(self._exact_dir(),
                                                     counter)
                if not self.silent:
                    print(f'Init: exact optimizer state restored from '
                          f'{self._exact_dir()} step {counter}', flush=True)
            elif not self.silent:
                print(f'Init: no exact state for step {counter} — resuming '
                      f'with reset momentum (reference behavior)',
                      flush=True)
        return True

    def _load_model(self) -> None:
        base = os.path.basename(self.name_model_in)
        stem = base.split('.')[0]
        if stem.isdigit():
            self.start_counter = int(stem)

        def _read(f):
            self.net_type = int.from_bytes(f.read(4), 'little', signed=True)
            self.net_trainer = self._create_net()
            self.net_trainer.load_model(f)

        model_io.read_model_file(self.name_model_in, _read)
        self.start_counter += 1

    def _copy_model(self) -> None:
        self.net_trainer = self._create_net()

        def _read(f):
            f.read(4)
            self.net_trainer.copy_model_from(f)

        model_io.read_model_file(self.name_model_in, _read)

    def _exact_dir(self) -> str:
        return os.path.join(self.name_model_dir, 'exact_state')

    def _ckpt(self):
        """The CLI's background checkpoint writer (``save_async=1``)."""
        if self._async_ckpt is None:
            from .runtime.async_ckpt import AsyncCheckpointer
            self._async_ckpt = AsyncCheckpointer(workers=self.save_workers)
        return self._async_ckpt

    def _prune_exact(self, counter: int) -> None:
        # only the sidecar matching the newest model file is ever
        # restored: prune older ones (~3x model size each)
        from .nnet.sharded_ckpt import step_dir
        import shutil
        for old in range(counter):
            d = step_dir(self._exact_dir(), old)
            if os.path.isdir(d):
                shutil.rmtree(d, ignore_errors=True)

    def _save_model(self) -> None:
        counter = self.start_counter
        path = self._model_path(counter)
        self.start_counter += 1
        if self.save_period == 0 or self.start_counter % self.save_period != 0:
            return
        os.makedirs(self.name_model_dir, exist_ok=True)
        if self.save_async:
            self._save_model_async(counter, path)
            return

        def _write(f):
            f.write(int(self.net_type).to_bytes(4, 'little', signed=True))
            self.net_trainer.save_model(f)

        # atomic (temp+fsync+rename) + retried: a crash mid-save can never
        # leave a truncated file where continue=1 would load it
        model_io.save_model_file(path, _write)
        # integrity sidecar for hot-reloading servers (serve/registry.py
        # digest-verifies before swapping a checkpoint into live traffic)
        model_io.write_model_digest(path)
        if self.exact_ckpt:
            # beyond reference: sidecar with optimizer state + counters so
            # continue=1 resumes bit-exact mid-momentum (the reference
            # model file drops momentum by design — trainer.save_model)
            self.net_trainer.save_training_state(self._exact_dir(), counter)
            self._prune_exact(counter)

    def _save_model_async(self, counter: int, path: str) -> None:
        """``save_async=1``: the round boundary only snapshots (donation-
        safe device copies + the cheap config header); serialization and
        the atomic+retried+digested writes run on the background writer.
        Same bytes, same crash contract as the sync path — the next round
        starts without waiting on storage.  ``run()`` barriers before
        exit, so the last model file is always durable."""
        from .nnet.trainer import NetTrainer
        from .runtime import async_ckpt
        tr = self.net_trainer
        header = (int(self.net_type).to_bytes(4, 'little', signed=True)
                  + tr.model_header())
        net = tr.net
        # one param snapshot per boundary: the exact-resume tree already
        # carries a params copy, so the model blob serializes from it
        tsnap = tr.snapshot_training_state() if self.exact_ckpt else None
        psnap = (tsnap['params'] if tsnap is not None
                 else async_ckpt.snapshot_tree(tr.params))
        exact_dir = self._exact_dir()
        ck = self._ckpt()

        def job():
            blob = model_io.serialize_blob(net, async_ckpt.host_tree(psnap))
            model_io.save_model_file(
                path, lambda f: NetTrainer.write_model_bytes(f, header,
                                                             blob))
            model_io.write_model_digest(path)
            if tsnap is not None:
                from .nnet import sharded_ckpt
                sharded_ckpt.save_tree_native(exact_dir, counter, tsnap,
                                              pool=ck.io_pool)
                self._prune_exact(counter)

        ck.submit(job, step=counter, label=f'save_model:{counter:04d}')

    def _create_iterators(self) -> None:
        flag = 0
        evname = ''
        itcfg: List[ConfigEntry] = []
        defcfg: List[ConfigEntry] = []
        for name, val in self.cfg:
            if name == 'data':
                flag = 1
                continue
            if name == 'eval':
                evname = val
                flag = 2
                continue
            if name == 'pred':
                flag = 3
                self.name_pred = val
                continue
            if name == 'iter' and val == 'end':
                assert flag != 0, 'wrong configuration file'
                if flag == 1 and self.task not in ('pred', 'pred_raw',
                                                   'serve'):
                    assert self.itr_train is None, 'can only have one data'
                    self.itr_train = create_iterator(itcfg)
                    # grafttune nworker probes rebuild this iterator at
                    # candidate worker counts (doc/autotune.md)
                    self._data_itcfg = list(itcfg)
                if flag == 2 and self.task not in ('pred', 'pred_raw',
                                                   'serve'):
                    self.itr_evals.append(create_iterator(itcfg))
                    self.eval_names.append(evname)
                if flag == 3 and self.task in ('pred', 'pred_raw', 'extract',
                                               'serve', 'online'):
                    assert self.itr_pred is None, 'only one pred section'
                    self.itr_pred = create_iterator(itcfg)
                flag = 0
                itcfg = []
                continue
            if flag == 0:
                defcfg.append((name, val))
            else:
                itcfg.append((name, val))
        self._data_defcfg = list(defcfg)
        for it in ([self.itr_train] if self.itr_train else []) + \
                ([self.itr_pred] if self.itr_pred else []) + self.itr_evals:
            for name, val in defcfg:
                it.set_param(name, val)
            it.init()

    def init(self) -> None:
        if self.task == 'autotune':
            # parse the space NOW so a malformed spec fails at init like
            # a bad slo.*/scenario spec, not mid-search
            from .tune import TuneSpace
            self._tune_space = TuneSpace.parse(self.autotune)
            if self._tune_space.mode == 'decode':
                # decode candidates build their own engines from the
                # serve.lm spec — no netconfig model, like serve decode
                self._create_iterators()
                return
            # mode=train falls through: the probe path needs the real
            # NetTrainer + train iterator
        if self.task == 'serve' and self.serve_mode == 'decode':
            # the decode stack serves a transformer LM tree (serve.lm /
            # serve.lm_model_in), not a netconfig model: no NetTrainer
            self._create_iterators()
            return
        if self.task == 'online' and self.continue_training:
            # resume a train-while-serve run: online model files are
            # named by STEP (the supervisor's save cadence), not round —
            # adopt the newest and re-arm the publish counter so new
            # checkpoints continue strictly past it instead of
            # re-publishing (and re-serving) stale counter names
            if not self._sync_latest_model():
                raise RuntimeError(
                    'Init: cannot find models to continue the online run; '
                    'start fresh or specify model_in')
            self.net_trainer.sample_counter = self.start_counter - 1
            print(f'Init: continue online run from step '
                  f'{self.net_trainer.sample_counter}')
            self._create_iterators()
            return
        if self.task == 'train' and self.continue_training:
            if not self._sync_latest_model():
                raise RuntimeError(
                    'Init: cannot find models to continue training; '
                    'specify model_in instead')
            print(f'Init: Continue training from round {self.start_counter}')
            self._create_iterators()
            return
        self.continue_training = 0
        if self.name_model_in == 'NULL':
            assert self.task in ('train', 'online', 'autotune'), \
                'must specify model_in if not training'
            self.net_trainer = self._create_net()
            self.net_trainer.init_model()
        elif self.task == 'finetune':
            self._copy_model()
        else:
            self._load_model()
        self._create_iterators()

    # --- tasks ------------------------------------------------------------
    def task_train(self) -> None:
        if self.dist_hosts > 0:
            if self.task != 'train':
                # never silently train single-host when the config asked
                # for a fleet (the same contract as maybe_init_distributed)
                raise ValueError(
                    f'dist.hosts={self.dist_hosts} supports task=train '
                    f'only (got task={self.task}); drop the dist.* keys '
                    'or switch the task')
            # elastic multi-host worker (or the in-process single-host
            # twin); the launcher role never reaches here — run()
            # dispatches it before init()
            from .parallel.elastic import elastic_train
            elastic_train(self)
            return
        start = time.monotonic()
        if self.continue_training == 0 and self.name_model_in == 'NULL':
            self._save_model()
        else:
            for it, name in zip(self.itr_evals, self.eval_names):
                sys.stderr.write(self.net_trainer.evaluate(it, name))
            sys.stderr.write('\n')
            sys.stderr.flush()
        if self.itr_train is None:
            return
        if self.test_io:
            print('start I/O test')
        tracer = TraceWindow()
        tracer.configure(self.cfg)
        batch_counter = 0
        try:
            self._train_rounds(tracer, batch_counter, start)
        finally:
            tracer.stop()
            if self._async_ckpt is not None:
                # the FINAL save always barriers: a deferred write error
                # surfaces here (like the sync path's, rounds late), and
                # the newest model file is durable before the CLI returns
                try:
                    self._async_ckpt.wait()
                finally:
                    self._async_ckpt.close(wait=False)
                    self._async_ckpt = None

    def _make_supervisor(self):
        from .io.data import ThreadBufferIterator
        from .runtime import faults
        from .runtime.supervisor import SupervisorConfig, TrainSupervisor
        # the supervisor brings its own watchdog ThreadBuffer, so a
        # conf-level `iter = threadbuffer` stage is unwrapped: batches
        # would otherwise be double-buffered, and two producers would
        # both register the 'batch' fault scope with different index
        # bases — one-shot stall events would land on whichever thread
        # races to the index first
        self._sup_iter = self.itr_train
        if isinstance(self._sup_iter, ThreadBufferIterator):
            self._sup_iter = self._sup_iter.base
        if self._sup_iter is not None \
                and not self._sup_iter.is_replay_stable():
            msg = ('train iterator reshuffles per pass (shuffle=1): '
                   'recovery restores exact params, but the replayed '
                   'pass draws a fresh permutation — the run is NOT '
                   'bitwise-identical to an uninterrupted one')
            faults.global_failure_log().record('replay_unstable', msg)
            if not self.silent:
                print(f'TrainSupervisor: {msg}', flush=True)
        cfg = SupervisorConfig(
            batch_deadline=self.watchdog_deadline or None,
            max_restarts=self.max_restarts,
            nan_breaker=self.nan_breaker,
            save_every=self.save_every,
            keep_last=self.keep_last,
            save_async=self.save_async,
            save_workers=self.save_workers,
            # pooled chains (nworker) report the watchdog's stalls on
            # the chain StatSet and get the doubled first-batch grace
            pipeline_stats=(None if self._sup_iter is None
                            else self._sup_iter.pipeline_stats()))
        return TrainSupervisor(
            self.net_trainer,
            os.path.join(self.name_model_dir, 'supervised_state'), cfg)

    def _supervised_round(self, sup, plan, tracer, batch_counter,
                          start) -> int:
        """One round's batches under the supervisor: watchdog on the
        pipeline, divergence breaker on the loss, restore-and-resume from
        the exact sidecar on recoverable faults.  ``batch_factory(k)``
        re-winds a fresh epoch pass to batch k after a restore — k counts
        DISPATCHED steps (epoch-absolute), so recovery composes with the
        scanned window (a fault mid-window abandons staged batches and
        re-pulls them); bitwise recovery additionally needs a
        replay-stable iterator (``is_replay_stable`` — _make_supervisor
        warns otherwise).  The supervised per-step path dispatches
        immediately (lookahead=0); the scanned path's K-deep staging
        window provides the H2D overlap instead."""
        import itertools
        it = self._sup_iter

        def factory(k):
            return itertools.islice(iter(it), k, None)

        def before_step(i):
            # same progress/trace cadence as the unsupervised loop
            tracer.before_update(batch_counter + i)
            self._progress(i + 1, start)

        return sup.run(
            factory, before_step=before_step,
            make_stepper=lambda: plan.round_stepper(self.net_trainer,
                                                    lookahead=0))

    def _train_rounds(self, tracer, batch_counter, start) -> None:
        from .nnet.execution import ExecutionPlan
        sup = None
        if self.supervise and self.test_io == 0:
            sup = self._make_supervisor()
        # ONE plan per run: everything the old fallback matrix excluded
        # (supervise, update_period>1, eval_train metrics, async saves)
        # now composes with the scan — only profiling and test_io demote
        # statically, extra_data demotes per round (doc/trainer.md)
        plan = ExecutionPlan.resolve(
            requested_k=self.steps_per_dispatch,
            profiling=tracer.enabled, test_io=bool(self.test_io),
            strict=bool(self.scan_strict), silent=bool(self.silent))
        try:
            self._run_rounds(sup, plan, tracer, batch_counter, start)
        finally:
            if sup is not None:
                sup.close()

    def _progress(self, sample_counter: int, start: float) -> None:
        if sample_counter % self.print_step == 0 and not self.silent:
            elapsed = int(time.monotonic() - start)
            print(f'round {self.start_counter - 1:8d}:'
                  f'[{sample_counter:8d}] {elapsed} sec elapsed', flush=True)

    def _round(self, plan, tracer, batch_counter, start):
        """One unsupervised round through the plan's WindowedStepper:
        per-step (K=1) keeps the classic one-batch host->device lookahead
        — batch i+1's transfers are enqueued (stage_batch, async) before
        batch i's step is dispatched, so the host link rides behind
        device compute; scanned (K>1) accumulates K staged batches (the
        lookahead runs K deep) into ONE ``compile_multi_step`` dispatch,
        with the short epoch tail finishing per-step (bitwise-identical,
        so epoch length need not divide K).  An ``attachtxt`` chain
        (extra_data) demotes THIS round only — the next round's stepper
        re-probes."""
        stepper = plan.round_stepper(
            self.net_trainer,
            before_dispatch=lambda u: tracer.before_update(
                batch_counter + u))
        sample_counter = 0
        for batch in self.itr_train:
            if self.test_io == 0:
                stepper.feed(batch)
            sample_counter += 1
            self._progress(sample_counter, start)
        stepper.finish()
        return stepper.updates, sample_counter

    def _run_rounds(self, sup, plan, tracer, batch_counter, start) -> None:
        cc = self.max_round
        while self.start_counter <= self.num_round and cc > 0:
            cc -= 1
            if not self.silent:
                print(f'update round {self.start_counter - 1}', flush=True)
            self.net_trainer.start_round(self.start_counter)
            t_round = time.monotonic()
            if sup is not None:
                n = self._supervised_round(sup, plan, tracer, batch_counter,
                                           start)
                batch_counter += n
            else:
                n, _ = self._round(plan, tracer, batch_counter, start)
                batch_counter += n
            dt_round = time.monotonic() - t_round
            # settle the one-step-deferred divergence gate (no-op unless
            # nan_action=halt / nan_breaker armed the check)
            self.net_trainer.flush_divergence_check()
            if self.test_io == 0:
                sys.stderr.write(f'[{self.start_counter}]')
                if not self.itr_evals:
                    sys.stderr.write(self.net_trainer.evaluate(None, 'train'))
                for it, name in zip(self.itr_evals, self.eval_names):
                    sys.stderr.write(self.net_trainer.evaluate(it, name))
                self._write_io_stats()
                sys.stderr.write('\n')
                self._write_train_speed(n, dt_round)
                sys.stderr.flush()
            self._save_model()
        if not self.silent:
            print(f'\nupdating end, {int(time.monotonic() - start)} sec in all')

    def _write_io_stats(self) -> None:
        """Pipeline observability: when the train chain is instrumented
        (``nworker`` set, doc/io.md) its per-stage stats join the round's
        eval line in the same ``\\tio-key:value`` format, then reset so
        each round reports its own pass.  Render-and-reset is ONE atomic
        drain (``print_and_clear``): the old print()-then-clear() pair
        silently dropped any update a pool/buffer worker recorded
        between the two lock holds."""
        if self.itr_train is None:
            return
        stats = self.itr_train.pipeline_stats()
        if stats is None:
            return
        line = stats.print_and_clear('io')
        if line:
            sys.stderr.write(line)

    def _write_train_speed(self, n: int, dt: float) -> None:
        """The MFU gauge rides the train eval block
        (doc/observability.md "Programs, memory, and MFU"): measured
        steps/sec for the round × ledger flops/step over the
        per-platform peak-FLOPs table.  Deliberately its OWN stderr
        line right under the ``[N]`` eval line: eval lines are a
        bitwise-compared surface (the scan/supervise CLI twins assert
        them equal across runs) and wall-clock numbers may never ride
        one.  ``train-mfu`` only prints when a peak is known (real
        chip or ``CXXNET_PEAK_TFLOPS``) — an unknown denominator
        reports nothing, never a fake 0.  The same gauges serve on
        ``/metrics`` (registered StatSet), so they are SLO-able for
        free."""
        if n <= 0 or dt <= 0:
            return
        from .obs import get_hub
        from .obs.programs import mfu
        if self._train_stats is None:
            from .utils.metric import StatSet
            self._train_stats = StatSet()
            get_hub().register_stats('train', self._train_stats)
        st = self._train_stats
        sps = n / dt
        st.gauge('steps_per_sec', round(sps, 3))
        flops = self.net_trainer.train_step_flops()
        if flops > 0:
            st.gauge('flops_per_step', flops)
        m = mfu(flops, sps)
        if m is not None:
            st.gauge('mfu', round(m, 5))
        sys.stderr.write(st.print('train').lstrip('\t') + '\n')

    # --- telemetry (graftscope, doc/observability.md) ----------------------
    def _obs_start(self) -> None:
        """Arm the telemetry hub for this run: flight-recorder ring +
        fault-triggered dumps + SIGUSR1 are always armed (the recorder
        is the postmortem every chaos drill ships); the live
        ``/metrics`` + ``/statusz`` + ``/healthz`` + ``/slos`` endpoint
        thread comes up only with ``obs.port >= 0`` (0 = ephemeral —
        the bound port prints to stdout, and announces into
        ``CXXNET_OBS_PORT_FILE`` when the elastic launcher set one).
        Any ``slo.<name>=`` spec (or an explicit ``obs.sample_every``)
        additionally starts the gauge-history sampler + SLO engine —
        verdicts serve on ``/slos``/``/metrics``, a breach records the
        typed ``SLOBreachError`` kind (which dumps a postmortem), and
        ``/healthz`` reports ``degraded`` while one is BREACHED."""
        from .obs import get_hub
        hub = get_hub()
        if self.obs_ring_events > 0:
            hub.set_ring(self.obs_ring_events)
        dump_dir = self.obs_dump_dir or os.path.join(self.name_model_dir,
                                                     'flight')
        hub.arm_flight_recorder(dump_dir)
        hub.arm_signal_dump()
        # graftprof: the compiler-truth ledger joins the hub (programs.*
        # gauges + /statusz summary; /programs serves it raw), device
        # memory gauges ride the same sampler/fleet machinery
        from .obs import programs as obs_programs
        ledger = obs_programs.get_ledger()
        ledger.set_recompile(self.obs_recompile)
        ledger.register_into(hub)
        if self.obs_hbm:
            obs_programs.register_hbm(hub)
        # fleet.-scoped specs belong to the launcher's cross-rank view;
        # a worker evaluating one would only ever see "no data"
        local_specs = [(n, v) for n, v in self.slo_specs
                       if not v.startswith('fleet.')]
        fleet_specs = [n for n, v in self.slo_specs
                       if v.startswith('fleet.')]
        if fleet_specs and not os.environ.get('CXXNET_OBS_PORT_FILE') \
                and not self.silent:
            # this process is neither the launcher (that role returned
            # from _maybe_elastic_launch before ever reaching here) nor
            # a worker under one (the launcher sets the port file) —
            # nothing will evaluate these specs, and silence here would
            # be the watching-nothing trap all over again
            print(f"obs: warning — fleet-scoped "
                  f"slo.{{{','.join(sorted(fleet_specs))}}} only "
                  'evaluate at the elastic launcher (dist.hosts > 1); '
                  'nothing watches them in this run', flush=True)
        if local_specs or self.obs_sample_every > 0:
            from .obs.history import GaugeSampler, hub_source
            # <= 0 (including a -1 spelled like obs.port's off) means
            # "auto": the 0.25s default cadence, never a clamped 100 Hz
            self._obs_sampler = GaugeSampler(
                hub_source(hub),
                period=(self.obs_sample_every
                        if self.obs_sample_every > 0 else 0.25))
            if local_specs:
                from .obs.slo import SLOEngine, SLOSpec
                self._obs_slo = SLOEngine(self._obs_sampler.history)
                for spec_name, text in local_specs:
                    self._obs_slo.add(SLOSpec.parse(spec_name, text))
                self._obs_slo.register_into(hub)
                self._obs_sampler.add_listener(self._obs_slo.on_tick)
            self._obs_sampler.start()
        if self.obs_port >= 0:
            from .obs.endpoints import ObsServer
            self._obs_server = ObsServer(
                hub, port=self.obs_port,
                port_file=os.environ.get('CXXNET_OBS_PORT_FILE'),
                profile_dir=(os.path.join(dump_dir, 'profile')
                             if self.obs_profile else None))
            routes = '/metrics /statusz /healthz /slos /programs'
            if self.obs_profile:
                routes += ' /profile'
            print(f'obs: telemetry on http://127.0.0.1:'
                  f'{self._obs_server.port} ({routes}), flight dumps in '
                  f'{dump_dir}', flush=True)

    def _obs_register_iterators(self) -> None:
        """Instrumented io chains join the hub so their per-stage stats
        serve on /metrics alongside the eval line."""
        if self.itr_train is None:
            return
        stats = self.itr_train.pipeline_stats()
        if stats is not None:
            from .obs import get_hub
            get_hub().register_stats('io', stats)

    def _obs_stop(self) -> None:
        from .obs import get_hub
        hub = get_hub()
        if self.obs_trace_export:
            path = hub.export_chrome_trace(self.obs_trace_export)
            if not self.silent:
                print(f'obs: Chrome trace exported to {path} '
                      '(load in Perfetto; doc/observability.md)',
                      flush=True)
        if self._obs_sampler is not None:
            self._obs_sampler.close(timeout=5.0)
            self._obs_sampler = None
        if self._obs_slo is not None:
            if not self.silent:
                from .obs.slo import summary_lines
                for line in summary_lines(self._obs_slo.status_view()):
                    print(f'obs: {line}', flush=True)
            self._obs_slo.close()
            self._obs_slo = None
        if self._obs_server is not None:
            self._obs_server.close(timeout=5.0)
            self._obs_server = None
        hub.disarm()

    def task_predict(self) -> None:
        assert self.itr_pred is not None, 'must specify a pred iterator'
        print('start predicting...')
        with open(self.name_pred, 'w') as fo:
            for pred in self.net_trainer.predict_stream(self.itr_pred):
                for v in pred:
                    fo.write(f'{v:g}\n')
        print(f'finished prediction, write into {self.name_pred}')

    def task_predict_raw(self) -> None:
        """``task=pred_raw``: the final node's raw score vector per
        instance, one space-separated line each — the format
        ``make_submission.py`` consumes.  (The reference gates the pred
        iterator on this task name, ``cxxnet_main.cpp:242``, but its Run()
        never dispatches it — here it works.)"""
        assert self.itr_pred is not None, 'must specify a pred iterator'
        print('start predicting (raw scores)...')
        tr = self.net_trainer
        with open(self.name_pred, 'w') as fo:
            for out in tr.forward_stream(self.itr_pred,
                                         tr.net.node_index('top[-1]')):
                for row in out.reshape(out.shape[0], -1):
                    fo.write(' '.join(f'{v:g}' for v in row) + '\n')
        print(f'finished prediction, write into {self.name_pred}')

    def task_serve(self) -> None:
        """``task=serve``: the online inference stack (doc/serving.md) —
        bucketed engine + dynamic micro-batcher + (optionally) checkpoint
        hot-reload — driven over the ``pred=`` iterator as the request
        source, so the CLI exercises exactly the path a fronting server
        embeds via ``net_serve_*``.  Predictions land in ``pred=``'s file
        (task=pred format); per-bucket latency/queue/throughput stats go
        to stderr at shutdown in eval-line format."""
        assert self.itr_pred is not None, 'must specify a pred iterator'
        import numpy as np

        from .serve import (DynamicBatcher, ModelRegistry, PredictEngine,
                            ReplicatedPredictEngine)
        from .utils.bucketing import parse_buckets

        if self.serve_replicas >= 2:
            # graftshard DP: N per-device replicas behind ONE batcher;
            # coalesced windows round-robin, hot swaps drain the fleet.
            # Completion is engine-owned, so the replicas share the
            # batcher's StatSet (single-owner counting still holds)
            from .utils.metric import StatSet as _SS
            engine = ReplicatedPredictEngine(
                self.net_trainer, parse_buckets(self.serve_buckets),
                dtype=self.serve_dtype, replicas=self.serve_replicas,
                stats=_SS(), fold_bn=self.serve_fold_bn)
        else:
            engine = PredictEngine(self.net_trainer,
                                   parse_buckets(self.serve_buckets),
                                   dtype=self.serve_dtype,
                                   fold_bn=self.serve_fold_bn)
        engine.warm()
        if not self.silent:
            nrep = getattr(engine, 'engines', None)
            print(f'serve: warmed {len(engine.buckets)} bucket programs '
                  f'{engine.buckets} (dtype={self.serve_dtype}, '
                  f'{engine.resident_bytes()} resident bytes'
                  + (f', {len(nrep)} replicas' if nrep else '') + ')',
                  flush=True)
            fv = getattr(engine, 'fold_view', lambda: None)()
            if fv:
                pairs = ','.join(f'{c}+{b}' for c, b in fv['pairs'])
                print(f'serve: folded {len(fv["pairs"])} conv+BN pair(s) '
                      f'[{pairs}] — proof max_abs_err '
                      f'{fv["max_abs_err"]:.3g} on the calibration batch',
                      flush=True)
        batcher = DynamicBatcher(engine, max_queue=self.serve_max_queue,
                                 max_wait=self.serve_max_wait,
                                 deadline=self.serve_deadline,
                                 stats=getattr(engine, 'stats', None))
        registry = None
        if self.serve_reload > 0:
            registry = ModelRegistry(
                engine, self.name_model_dir,
                poll_interval=self.serve_reload,
                current=self.start_counter - 1,
                on_swap=None if self.silent else (
                    lambda c, p: print(f'serve: hot-reloaded checkpoint '
                                       f'{c} from {p}', flush=True)))
            registry.start()
        # live telemetry: the batcher's per-bucket gauges serve on
        # /metrics, the registry state machine on /statusz
        from .obs import get_hub
        from .utils.metric import StatSet
        _hub = get_hub()
        # the refresh folds the LIVE queue depth per render, so an SLO
        # over serve.queue_depth reads admission pressure, not peaks
        batcher.register_into(_hub)
        if registry is not None:
            registry.register_into(_hub)
        fleet = self._serve_fleet(engine)
        if fleet is not None:
            _fleet_stats_set = StatSet()
            _hub.register_stats(
                'fleet', _fleet_stats_set,
                refresh=lambda: fleet.report(stats=_fleet_stats_set))
            for mid in fleet.models():
                try:
                    fleet.get(mid)       # budgeter decides who stays warm
                # lint: allow(fault-taxonomy): a cold sibling must not kill serve; printed, and the budgeter retries on demand
                except Exception as e:
                    print(f'serve: fleet model {mid!r} not loaded: {e}',
                          flush=True)
            if not self.silent:
                print(f'serve: fleet of {len(fleet.models())} models, '
                      f'{len(fleet.loaded())} resident under '
                      f'{self.serve_mem_budget or "unbounded"} bytes',
                      flush=True)
        print('start serving...')
        served = 0
        try:
            with open(self.name_pred, 'w') as fo:
                # windowed async submits: keep up to half the admission
                # queue in flight so the batcher can coalesce, drain in
                # order so the output file matches task=pred row order
                import collections
                pending = collections.deque()
                cap = max(1, self.serve_max_queue // 2)
                # the bulk drive keeps `cap` requests queued by design, so
                # the LIVE-traffic deadline would expire in our own queue
                # on any non-trivial model; bulk requests are throughput-
                # bound, not latency-bound — the bound scales with the
                # queue a request may sit behind (generous per-request
                # allowance; a truly wedged worker still trips it)
                bulk_deadline = max(self.serve_deadline,
                                    60.0 + 30.0 * cap)

                def _drain_one():
                    for v in self.net_trainer._pred_transform(
                            batcher.wait(pending.popleft())):
                        fo.write(f'{v:g}\n')

                for batch in self.itr_pred:
                    n = batch.batch_size - batch.num_batch_padd
                    if not n:
                        continue
                    data = batch.data
                    if batch.norm_spec is not None:
                        # serving wire contract: normalized floats
                        data = batch.norm_spec.apply(data)
                    rows = np.ascontiguousarray(
                        np.asarray(data, np.float32)[:n])
                    pending.append(batcher.submit_async(
                        rows, deadline=bulk_deadline))
                    served += n
                    while len(pending) >= cap:
                        _drain_one()
                while pending:
                    _drain_one()
        finally:
            if registry is not None:
                registry.close(timeout=5.0)
            batcher.close(timeout=30.0)
            if hasattr(engine, 'close'):        # replica worker threads
                engine.close(timeout=10.0)
            sys.stderr.write(f'[serve]{batcher.report("serve")}\n')
            if registry is not None:
                # swap stamps: which step is serving and how stale it is
                # (the serving half of the freshness metric, doc/online.md)
                sys.stderr.write(f'[serve]{registry.report()}\n')
            if fleet is not None:
                sys.stderr.write(f'[serve]{fleet.report()}\n')
                fleet.close(timeout=5.0)
            sys.stderr.flush()
        print(f'finished serving {served} instances, predictions in '
              f'{self.name_pred} (compiled {engine.compile_count} programs '
              f'for {len(engine.buckets)} buckets)')

    def task_online(self) -> None:
        """``task=online``: the train-while-serve loop (doc/online.md) —
        a supervised trainer over the ``data=`` section (idiomatically
        ``iter = imgbin_stream``) publishing a serving checkpoint every
        ``online.save_every`` steps, while the colocated
        engine/batcher/registry stack hot-reloads them under traffic
        replayed from the ``pred=`` section at ``online.qps``.  Each
        round's eval line carries the freshness gauges; the serving
        ledger and a one-line JSON summary print at shutdown."""
        assert self.itr_train is not None, 'task=online needs a data section'
        import json

        import numpy as np

        from .online import OnlineConfig, OnlinePipeline
        from .utils.bucketing import parse_buckets

        request_source = None
        if self.itr_pred is not None:
            # replay the pred section's (normalized) rows cyclically —
            # the CLI's stand-in for a fronting server's live traffic
            rows_pool = []
            for batch in self.itr_pred:
                n = batch.batch_size - batch.num_batch_padd
                if not n:
                    continue
                data = batch.data
                if batch.norm_spec is not None:
                    data = batch.norm_spec.apply(data)
                rows_pool.append(np.ascontiguousarray(
                    np.asarray(data, np.float32)[:n]))
            if rows_pool:
                state = {'i': 0}

                def request_source():
                    r = rows_pool[state['i'] % len(rows_pool)]
                    state['i'] += 1
                    return r
        # online runs default to async publishing (the whole point is a
        # step loop that never waits on storage); an explicit
        # save_async=0 in the conf still wins
        save_async = self.save_async
        if not any(k == 'save_async' for k, _ in self.cfg):
            save_async = 1
        cfg = OnlineConfig(
            model_dir=self.name_model_dir,
            save_every=self.online_save_every,
            save_workers=self.save_workers,
            freshness_slo=self.online_freshness_slo,
            freshness_strict=bool(self.online_freshness_strict),
            reload_poll=self.online_reload,
            buckets=parse_buckets(self.serve_buckets),
            max_queue=self.serve_max_queue,
            max_wait=self.serve_max_wait,
            deadline=self.serve_deadline,
            dtype=self.serve_dtype,
            qps=self.online_qps,
            watchdog_deadline=self.watchdog_deadline or None,
            max_restarts=self.max_restarts,
            nan_breaker=self.nan_breaker,
            keep_last=self.keep_last,
            save_async=save_async,
            steps_per_dispatch=self.steps_per_dispatch,
            net_type=self.net_type,
            silent=bool(self.silent))
        serve_factory = (
            lambda: NetTrainer(self.cfg + [('inference_only', '1')]))
        pipe = OnlinePipeline(self.net_trainer, self.itr_train,
                              serve_factory, cfg,
                              request_source=request_source)
        scaler = None
        if self.serve_autoscale:
            # SLO-driven autoscaling over the online stack: the batcher
            # queue and the train/serve split are the bound knobs; with
            # interval=0 the evaluation rides the before_step hook so
            # the loop stays deterministic
            from .obs import get_hub
            from .serve.autoscale import AutoscalePolicy, Autoscaler
            pol = AutoscalePolicy.parse(self.serve_autoscale)
            scaler = Autoscaler(pol, name='online_scale')
            pipe.start()
            if pipe.batcher is not None:
                scaler.bind_batcher(pipe.batcher)
            scaler.bind_online(pipe)
            scaler.register_into(get_hub())
        print('start online training-while-serving...')
        start = time.monotonic()

        def before_step(i):
            self._progress(i + 1, start)
            if scaler is not None and scaler.policy.interval <= 0:
                scaler.evaluate()

        try:
            summary = pipe.run(
                num_rounds=self.num_round,
                evals=list(zip(self.itr_evals, self.eval_names)),
                before_step=before_step)
            sys.stderr.write(f'[online]{pipe.serve_report()}\n')
            if scaler is not None:
                sys.stderr.write(f'[online]{scaler.report()}\n')
            sys.stderr.flush()
            print(f'online summary: {json.dumps(summary, sort_keys=True)}',
                  flush=True)
        finally:
            if scaler is not None:
                scaler.close()
            pipe.close(timeout=30.0)
        print(f'finished online run, {int(time.monotonic() - start)} sec in all')

    def _parse_lm_spec(self, spec: str, model_in: str = 'NULL',
                       seed: int = 0, default_vocab=None):
        """Build a transformer (params, cfg) from a compact
        ``k=v[;k=v...]`` spec (vocab, d_model, heads, d_ff, stages,
        experts, seq, plus inline ``model_in=``/``seed=`` overrides);
        params come from a ``%04d.lm`` tree or a seeded init.  Shared by
        ``serve.lm`` (the target) and ``serve.draft`` (the speculative-
        decode draft, whose vocab defaults to the target's)."""
        import numpy as np

        from .models import transformer as TT
        from .utils.config import parse_kv_list
        kw = {'attn': 'local'}
        if default_vocab is not None:
            kw['vocab_size'] = int(default_vocab)
        names = {'vocab': ('vocab_size', int), 'd_model': ('d_model', int),
                 'heads': ('num_heads', int), 'd_ff': ('d_ff', int),
                 'stages': ('num_stages', int), 'seq': ('seq_len', int),
                 'experts': ('num_experts', int)}
        for key, val in parse_kv_list(spec or ''):
            if key == 'model_in':
                model_in = val
            elif key == 'seed':
                seed = int(val)
            elif key in names:
                attr, typ = names[key]
                kw[attr] = typ(val)
            else:
                raise ValueError(f'unknown lm spec key: {key!r}')
        cfg = TT.TransformerConfig(**kw)
        if model_in != 'NULL':
            from .serve.decode import load_lm_params
            params = load_lm_params(model_in)
        else:
            params = TT.init_params(np.random.RandomState(seed), cfg)
        return params, cfg

    def _lm_spec(self):
        """The decode target model from ``serve.lm`` /
        ``serve.lm_model_in`` / ``serve.lm_seed``."""
        return self._parse_lm_spec(self.serve_lm,
                                   model_in=self.serve_lm_model_in,
                                   seed=self.serve_lm_seed)

    def task_serve_decode(self) -> None:
        """``task=serve serve.mode=decode``: the continuous-batching
        decode stack (doc/serving.md "Continuous decode") driven over
        seeded synthetic prompts of mixed lengths — the CLI exercises
        exactly the join/leave/page path an embedding server drives via
        ``lm_serve_*``.  Token streams land in ``pred=``'s file (one
        space-separated line per request, arrival order); the first few
        are cross-checked against offline ``transformer.generate`` twins
        and the per-token stats print to stderr at shutdown."""
        import numpy as np

        from .models import transformer as TT
        from .serve.decode import DecodeService

        params, cfg = self._lm_spec()
        draft = None
        if self.serve_draft:
            # the draft rides the same spec grammar; its vocab defaults
            # to the target's (the verify window compares token ids)
            draft = self._parse_lm_spec(self.serve_draft,
                                        default_vocab=cfg.vocab_size)
        svc = DecodeService(
            params, cfg, slots=self.serve_slots, pages=self.serve_pages,
            page_size=self.serve_page_size,
            max_prompt=self.serve_max_prompt,
            max_new_bound=self.serve_max_new,
            eos_id=None if self.serve_eos < 0 else self.serve_eos,
            max_queue=self.serve_max_queue, max_wait=self.serve_max_wait,
            # bulk drive: throughput-bound, not latency-bound (the same
            # reasoning as the predict drive's bulk_deadline)
            deadline=max(self.serve_deadline, 60.0),
            dtype=self.serve_dtype, flash_decode=self.serve_flash,
            prefix_share=self.serve_prefix_share,
            spec_k=self.serve_spec_k, draft=draft,
            kv_host_mb=self.serve_kv_host_mb,
            kv_disk_mb=self.serve_kv_disk_mb,
            kv_dir=self.serve_kv_dir or None,
            kv_share_dir=self.serve_kv_share_dir or None,
            shard=self.serve_shard,
            prefill_workers=self.serve_prefill_workers)
        from .obs import get_hub
        # ONE StatSet backs both the engine and the batcher
        # (DecodeService shares it), so this single registration carries
        # the admission gauges too; refresh folds the pull-style page/
        # gen-cache/acceptance gauges before each /metrics render
        get_hub().register_stats('decode', svc.engine.stats,
                                 refresh=lambda: svc.report('decode'))
        if svc.engine.kv_stats is not None:
            # graftcache tier gauges ride the hub under their own set so
            # slo.kv_hit=kv.hit_rate>=0.5@60-style specs resolve; the
            # refresh folds tier occupancy right before each render
            get_hub().register_stats(
                'kv', svc.engine.kv_stats,
                refresh=svc.engine.kv_occupancy)
        if not self.silent:
            print(f'serve: decode engine up — {self.serve_slots} slots, '
                  f'{self.serve_pages}x{self.serve_page_size}-token KV '
                  f'pages (slot cache {svc.engine.cache_len}, '
                  f'dtype={svc.engine.serve_dtype}, '
                  f'attention={"flash" if svc.engine.use_flash else "gather"}'
                  f', prefix_share={self.serve_prefix_share}'
                  f', spec_k={svc.engine._spec_k}'
                  + (f', shard=tp:{svc.engine._tp} over '
                     f'{svc.engine._tp} devices'
                     if svc.engine._tp > 1 else '')
                  + (f', prefill_workers={self.serve_prefill_workers}'
                     if self.serve_prefill_workers else '')
                  + ')', flush=True)
        if self.serve_scenario:
            self._serve_decode_scenario(svc, cfg)
            return
        print('start serving (decode)...')
        rng = np.random.RandomState(self.serve_seed)
        n_req = max(1, self.serve_requests)
        prompts = [rng.randint(
            0, cfg.vocab_size,
            (1, int(rng.randint(1, max(2, self.serve_max_prompt)))))
            .astype(np.int32) for _ in range(n_req)]
        temp = float(self.serve_temperature)
        keys = [None] * n_req
        if temp > 0:
            import jax
            keys = [jax.random.PRNGKey(self.serve_seed * 100003 + i)
                    for i in range(n_req)]
        reqs = [svc.submit_async(p, self.serve_max_new, temp, k)
                for p, k in zip(prompts, keys)]
        served = 0
        try:
            with open(self.name_pred, 'w') as fo:
                for r in reqs:
                    toks = svc.batcher.wait(r)
                    fo.write(' '.join(str(int(t)) for t in toks) + '\n')
                    served += 1
            # bitwise-twin spot check: the stream each request got must
            # equal its offline generate call (same seed/schedule) —
            # over the ENGINE's stored tree and compute config, so the
            # twin holds on every serve.dtype tier (a quantized model's
            # oracle is generate() over the same quantized tree)
            checked = 0
            for i in range(min(3, n_req)):
                off = np.asarray(TT.generate(
                    svc.engine.oracle_params(), prompts[i],
                    self.serve_max_new, svc.engine.cfg,
                    temperature=temp, rng=keys[i],
                    eos_id=None if self.serve_eos < 0
                    else self.serve_eos))[0]
                got = reqs[i].result
                if not (np.asarray(got) == off[:len(got)]).all():
                    raise AssertionError(
                        f'decode stream {i} diverged from its offline '
                        f'generate twin: {got} vs {off}')
                checked += 1
            if not self.silent:
                print(f'decode twin check: {checked} streams equal their '
                      'offline generate calls', flush=True)
        finally:
            sys.stderr.write(f'[serve]{svc.report("decode")}\n')
            sys.stderr.flush()
            svc.close(30.0)
        print(f'finished serving {served} decode streams, token ids in '
              f'{self.name_pred}')

    def _serve_decode_scenario(self, svc, cfg) -> None:
        """``serve.scenario=``: drive the decode stack through a seeded
        adversarial traffic scenario (doc/serving.md "Scenarios and
        autoscaling") instead of the fixed bulk prompts; with
        ``serve.autoscale=`` an SLO-driven autoscaler retunes the live
        admission caps while the storm runs.  Served streams land in
        ``pred=``'s file (one line per request index); the ledger must
        reconcile exactly against the service counters and the first
        served streams are twin-checked against offline generate."""
        import numpy as np

        from .models import transformer as TT
        from .obs import get_hub
        from .serve.autoscale import AutoscalePolicy, Autoscaler
        from .serve.scenario import ScenarioSpec, drive

        spec = ScenarioSpec.parse(self.serve_scenario)
        scaler = None
        on_tick = None
        if self.serve_autoscale:
            pol = AutoscalePolicy.parse(self.serve_autoscale)
            scaler = Autoscaler(pol)
            scaler.bind_engine(svc.engine)
            scaler.bind_batcher(svc.batcher)
            scaler.register_into(get_hub())
            if pol.interval <= 0:
                on_tick = lambda _t: scaler.evaluate()
        print(f'start serving (decode, scenario {spec.shape})...')
        try:
            led = drive(svc, spec, vocab=cfg.vocab_size, on_tick=on_tick)
            led.reconcile(svc.engine.stats)
            with open(self.name_pred, 'w') as fo:
                for i in sorted(led.streams):
                    fo.write(' '.join(str(int(t))
                                      for t in led.streams[i]) + '\n')
            checked = 0
            for i in sorted(led.streams)[:3]:
                rec = spec.schedule()[i]
                prompt = spec.prompt_for(i, rec.prompt_len,
                                         cfg.vocab_size)
                off = np.asarray(TT.generate(
                    svc.engine.params, prompt, rec.max_new,
                    svc.engine.cfg))[0]
                got = np.asarray(led.streams[i])
                if not (got == off[:len(got)]).all():
                    raise AssertionError(
                        f'scenario stream {i} diverged from its offline '
                        f'generate twin: {got} vs {off}')
                checked += 1
            if not self.silent:
                print(f'scenario twin check: {checked} streams equal '
                      'their offline generate calls', flush=True)
            print(f'scenario summary: {led.summary()}')
            if scaler is not None:
                print(f'autoscale actions: {len(scaler.history())}, '
                      f'degraded={scaler.degraded}')
        finally:
            if scaler is not None:
                sys.stderr.write(f'[serve]{scaler.report()}\n')
                scaler.close()
            sys.stderr.write(f'[serve]{svc.report("decode")}\n')
            sys.stderr.flush()
            svc.close(30.0)
        print(f'finished scenario ({led.counts["served"]} streams '
              f'served), token ids in {self.name_pred}')

    def _serve_fleet(self, engine):
        """``serve.models=id=dir;id=dir``: register sibling checkpoints
        (same architecture as the conf) in a MultiModelRegistry under
        ``serve.mem_budget`` bytes; returns the fleet (or None)."""
        if not self.serve_models:
            return None
        from .serve import MultiModelRegistry, PredictEngine
        from .utils.bucketing import parse_buckets
        from .utils.config import parse_kv_list

        fleet = MultiModelRegistry(mem_budget=self.serve_mem_budget,
                                   poll_interval=self.serve_reload or 1.0)

        def make_factory(mdir):
            def factory():
                from .serve.registry import (load_into_trainer,
                                             newest_model_file)
                best = newest_model_file(mdir)
                if best is None:
                    raise FileNotFoundError(f'no model files in {mdir}')
                tr = load_into_trainer(self._create_net(), best[1])
                return PredictEngine(tr,
                                     parse_buckets(self.serve_buckets),
                                     dtype=self.serve_dtype)
            return factory

        for mid, mdir in parse_kv_list(self.serve_models):
            fleet.add_model(mid, make_factory(mdir), model_dir=mdir)
        if self.serve_reload > 0:
            fleet.start()
        return fleet

    # --- grafttune (doc/autotune.md) --------------------------------------
    def _tune_gate(self, space, baseline, feasible=None):
        """Stage-1 admission from compiler truth: one batched AOT sweep
        fills the ledger, the largest live footprint among analyzed
        programs becomes the base price, and the declared ``mem_mb``
        ceiling (scaled by the required headroom) bounds every
        candidate.  ``mem_mb=0`` disables byte pruning — on a platform
        with no HBM story (CPU) there is nothing truthful to prune
        against."""
        from .obs.programs import get_ledger
        from .tune import LedgerGate
        led = get_ledger()
        led.ensure_analyzed_batch()
        base = 0
        for e in led.entries():
            peak = e.peak_bytes or (e.argument_bytes + e.output_bytes
                                    + e.temp_bytes)
            base = max(base, peak)
        ceiling = 0.0
        if space.mem_mb > 0:
            ceiling = space.mem_mb * (1 << 20) * (1.0 - space.headroom)
        return LedgerGate(base_bytes=float(base), ceiling_bytes=ceiling,
                          baseline=baseline,
                          mem_knobs=space.mem_knobs(),
                          mem_inv_knobs=space.mem_inv_knobs(),
                          feasible=feasible)

    def _tune_baseline(self, space) -> dict:
        """The hand-set config values, clamped into the declared ranges
        — the candidate every measured probe competes against."""
        current = {'steps_per_dispatch': self.steps_per_dispatch,
                   'slots': self.serve_slots, 'pages': self.serve_pages,
                   'page_size': self.serve_page_size,
                   'spec_k': self.serve_spec_k,
                   'max_queue': self.serve_max_queue,
                   'micro_batch': self.micro_batch,
                   'nworker': 1}
        if self._data_itcfg:
            for name, val in self._data_itcfg:
                if name == 'nworker':
                    current['nworker'] = int(val)
        out = {}
        for r in space.knobs:
            out[r.name] = max(r.lo, min(r.hi, int(current[r.name])))
        return out

    def _set_micro_batch(self, value: int) -> None:
        """Apply a candidate ``micro_batch`` to every layer of the LIVE
        trainer and rebuild its step programs: the knob is read at trace
        time (layers/conv.py ``_micro_split``), so an already-compiled
        program would never see the change.  Re-running the convact
        fusion pass keeps its micro_batch>1 exclusion honest."""
        tr = self.net_trainer
        for layer in tr.net.layers:
            layer.param.micro_batch = int(value)
        tr.net._build_convact_fusion()
        tr._compile_steps()

    def _rebuild_train_iterator(self, nworker: int):
        itcfg = [(n, v) for n, v in (self._data_itcfg or [])
                 if n != 'nworker'] + [('nworker', str(int(nworker)))]
        it = create_iterator(itcfg)
        for name, val in self._data_defcfg:
            it.set_param(name, val)
        it.init()
        return it

    def _autotune_train(self, space):
        """mode=train probes: steps/sec of the REAL plan/stepper path
        (``execution.measured_probe``) at each candidate K, over batches
        drawn once from the train iterator — a candidate ``nworker``
        rebuilds the iterator and redraws, so the pool depth it pays for
        is the pool depth it measures."""
        import itertools as _it

        from .nnet import execution
        from .runtime import faults as _faults
        from .tune import TuneSearch
        if self.itr_train is None:
            raise _faults.TuneSpecError(
                'autotune mode=train needs a data section to probe with')
        batches = list(_it.islice(iter(self.itr_train), space.probe_steps))
        if not batches:
            raise _faults.TuneSpecError(
                'autotune: the train iterator yielded no batches')
        baseline = self._tune_baseline(space)
        base_k = baseline.get('steps_per_dispatch', self.steps_per_dispatch)
        # warm-up at the baseline K fills the ledger: stage 1 prices
        # candidates from THIS program's compiler truth
        execution.measured_probe(self.net_trainer, base_k, batches,
                                 repeats=1)
        gate = self._tune_gate(space, baseline)

        base_mb = baseline.get('micro_batch', self.micro_batch)
        applied_mb = [base_mb]

        def probe(cand):
            pb = batches
            if 'nworker' in cand and cand['nworker'] != baseline['nworker']:
                itr = self._rebuild_train_iterator(cand['nworker'])
                pb = list(_it.islice(iter(itr), space.probe_steps))
            mb = int(cand.get('micro_batch', base_mb))
            if mb != applied_mb[0]:
                self._set_micro_batch(mb)
                applied_mb[0] = mb
            k = cand.get('steps_per_dispatch', base_k)
            return execution.measured_probe(
                self.net_trainer, k, pb, repeats=space.probe_repeats)

        try:
            return TuneSearch(space, probe, gate=gate,
                              baseline=baseline).run('train')
        finally:
            # probes mutate the live trainer; leave it at the hand-set
            # split, not whatever the last candidate happened to be
            if applied_mb[0] != base_mb:
                self._set_micro_batch(base_mb)

    def _autotune_decode(self, space):
        """mode=decode probes: tokens/sec of a real DecodeService built
        at each candidate's slots/pages/page_size/spec_k over seeded
        prompts; candidates wanting speculation without a configured
        draft are pruned in stage 1 (feasibility, not bytes)."""
        import numpy as np

        from .serve.decode import DecodeService
        from .tune import TuneSearch
        params, cfg = self._lm_spec()
        draft = None
        if self.serve_draft:
            draft = self._parse_lm_spec(self.serve_draft,
                                        default_vocab=cfg.vocab_size)
        baseline = self._tune_baseline(space)

        def build(cand):
            return DecodeService(
                params, cfg,
                slots=cand.get('slots', self.serve_slots),
                pages=cand.get('pages', self.serve_pages),
                page_size=cand.get('page_size', self.serve_page_size),
                max_prompt=self.serve_max_prompt,
                max_new_bound=self.serve_max_new,
                eos_id=None if self.serve_eos < 0 else self.serve_eos,
                max_queue=cand.get('max_queue', self.serve_max_queue),
                max_wait=self.serve_max_wait,
                deadline=max(self.serve_deadline, 60.0),
                dtype=self.serve_dtype, flash_decode=self.serve_flash,
                prefix_share=self.serve_prefix_share,
                spec_k=cand.get('spec_k', self.serve_spec_k),
                draft=draft)

        def probe(cand):
            svc = build(cand)
            try:
                rng = np.random.RandomState(space.seed)
                n_req = max(1, space.probe_steps)
                prompts = [rng.randint(
                    0, cfg.vocab_size,
                    (1, int(rng.randint(1, max(2, self.serve_max_prompt)))))
                    .astype(np.int32) for _ in range(n_req)]

                def one_pass():
                    t0 = time.perf_counter()
                    reqs = [svc.submit_async(p, self.serve_max_new, 0.0,
                                             None) for p in prompts]
                    toks = sum(len(svc.batcher.wait(r)) for r in reqs)
                    return toks / max(1e-9, time.perf_counter() - t0)

                one_pass()              # warm-up: compile off the clock
                return max(one_pass()
                           for _ in range(max(1, space.probe_repeats)))
            finally:
                svc.close(30.0)

        def feasible(cand):
            if cand.get('spec_k', 0) > 0 and draft is None:
                return 'spec_k needs a serve.draft model'
            if 'pages' in cand and 'slots' in cand \
                    and cand['pages'] < cand['slots']:
                return 'fewer KV pages than decode slots'
            return None

        # baseline engine warm-up fills the ledger for stage-1 pricing
        svc0 = build(baseline)
        try:
            svc0.engine.resident_bytes()
        finally:
            svc0.close(30.0)
        gate = self._tune_gate(space, baseline, feasible=feasible)
        return TuneSearch(space, probe, gate=gate,
                          baseline=baseline).run('decode')

    def task_autotune(self) -> None:
        """``task=autotune``: run the two-stage grafttune search over
        the declared ``autotune=`` space and write the reproducible
        artifact pair — byte-deterministic ``tuned_<mode>.conf`` plus a
        JSON receipt stamping every probe — into ``model_dir``."""
        space = self._tune_space
        os.makedirs(self.name_model_dir, exist_ok=True)
        if space.mode == 'decode':
            result = self._autotune_decode(space)
        else:
            result = self._autotune_train(space)
        conf = result.write_conf(os.path.join(
            self.name_model_dir, f'tuned_{space.mode}.conf'))
        result.write_receipt(os.path.join(
            self.name_model_dir, f'tuned_{space.mode}.json'))
        if not self.silent:
            print(f'autotune: best {result.best} '
                  f'speedup {result.speedup:.3f}x over {result.baseline} '
                  f'({result.stage1_pruned} pruned by ledger, '
                  f'{result.measured} measured, {result.failed} failed, '
                  f'wall {result.wall_s:.1f}s of {space.budget:g}s) '
                  f'-> {conf}', flush=True)

    def task_extract(self) -> None:
        assert self.itr_pred is not None, 'must specify a pred iterator'
        node = self.extract_node_name or 'top[-1]'
        print(f'start extracting feature from {node}...')
        import numpy as np
        tr = self.net_trainer
        feats = list(tr.forward_stream(self.itr_pred,
                                       tr.net.node_index(node)))
        out = np.concatenate(feats, axis=0)
        if self.output_format == 1:
            np.savetxt(self.name_pred, out.reshape(out.shape[0], -1), '%g')
        else:
            out.astype('<f4').tofile(self.name_pred)
        print(f'finished extract, write into {self.name_pred}')

    def run(self, argv: List[str]) -> int:
        if not argv:
            print('Usage: <config> [k=v ...]')
            return 0
        cfg = parse_config_file(argv[0])
        cfg = apply_cli_overrides(cfg, argv[1:])
        for name, val in cfg:
            self.set_param(name, val)
        if self.task == 'train' and self.dist_rank < 0 \
                and (self.dist_hosts > 1
                     or (self.dist_hosts == 1 and self.dist_launch)):
            # elastic launcher role: own the coordinator, spawn one
            # worker per host, respawn preempted ranks.  Dispatched
            # BEFORE init() — the launcher never builds a net or touches
            # a device; workers replay this same argv with their rank
            # appended (doc/fault_tolerance.md "Multi-host recovery")
            from .parallel.elastic import ElasticLauncher
            return ElasticLauncher(
                argv=list(argv), hosts=self.dist_hosts,
                rejoin=self.dist_rejoin, heartbeat=self.dist_heartbeat,
                silent=bool(self.silent),
                # fleet observability: merged rank-labeled /metrics,
                # cross-rank (fleet.*) SLOs, per-host-lane trace merge
                fleet_port=self.obs_fleet_port,
                sample_every=self.obs_sample_every,
                slo_specs=[(n, v) for n, v in self.slo_specs
                           if v.startswith('fleet.')],
                trace_merge=self.obs_trace_merge).run()
        # classic jax.distributed world (param_server=dist / cluster
        # env): one global mesh over every host's devices
        from .parallel.distributed import maybe_init_distributed
        maybe_init_distributed(self.cfg)
        plan = None
        if self.fault_plan:
            # deterministic fault injection (tests/chaos drills): the plan
            # drives the SAME hooks production faults exercise
            from .runtime import faults
            plan = faults.FaultPlan.parse(self.fault_plan)
            faults.install_plan(plan)
            if not self.silent:
                print(f'fault plan armed: {plan.describe()}', flush=True)
        self._obs_start()
        try:
            self.init()
            self._obs_register_iterators()
            if not self.silent:
                print('initializing end, start working')
            if self.task in ('train', 'finetune'):
                self.task_train()
            elif self.task == 'pred':
                self.task_predict()
            elif self.task == 'pred_raw':
                self.task_predict_raw()
            elif self.task == 'extract':
                self.task_extract()
            elif self.task == 'serve':
                if self.serve_mode == 'decode':
                    self.task_serve_decode()
                else:
                    self.task_serve()
            elif self.task == 'online':
                self.task_online()
            elif self.task == 'autotune':
                self.task_autotune()
        finally:
            self._obs_stop()
        if plan is not None and not self.silent:
            # chaos-drill closure: which events actually fired, and what
            # the runtime saw/did about them (doc/fault_tolerance.md)
            from .runtime import faults
            fired = plan.fired()
            print(f"fault plan fired: {'; '.join(fired) or 'nothing'} "
                  f'(failure log: {faults.global_failure_log().summary()})',
                  flush=True)
        return 0


def main(argv: Optional[List[str]] = None) -> int:
    return LearnTask().run(argv if argv is not None else sys.argv[1:])


if __name__ == '__main__':
    sys.exit(main())
