"""Model zoo: programmatic builders for the benchmark topologies.

Each builder returns ``.conf`` netconfig text identical in topology to the
reference acceptance configs (BASELINE.md):

* ``mlp_conf``    — example/MNIST/MNIST.conf 2-layer MLP
* ``lenet_conf``  — example/MNIST/MNIST_CONV.conf conv net
* ``alexnet_conf``— example/ImageNet/ImageNet.conf single-tower AlexNet
  (grouped convs, LRN, dropout)
* ``googlenet_conf`` — original GoogLeNet (inception v1, LRN + two
  grad_scale=0.3 auxiliary softmax heads -> multi-loss training graphs)
* ``inception_bn_conf`` — GoogLeNet-family Inception with BatchNorm (the
  reference has no in-tree conf; built from its conv/ch_concat/batch_norm
  layers following the cxxnet-era model-zoo Inception-BN arrangement)
* ``vgg16_conf`` — VGG-16 configuration D (no in-tree reference conf;
  cxxnet-era model-zoo arrangement)
"""

from .builders import (alexnet_conf, googlenet_conf, inception_bn_conf,
                       lenet_conf, mlp_conf, vgg16_conf)
