"""Netconfig builders for the benchmark model families."""

from __future__ import annotations


def mlp_conf(num_class: int = 10, input_dim: int = 784,
             nhidden: int = 100) -> str:
    """example/MNIST/MNIST.conf topology."""
    return f"""
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = {nhidden}
  init_sigma = 0.01
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = {num_class}
  init_sigma = 0.01
layer[+0] = softmax
netconfig=end
input_shape = 1,1,{input_dim}
"""


def lenet_conf(num_class: int = 10) -> str:
    """example/MNIST/MNIST_CONV.conf topology."""
    return f"""
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  pad = 1
  stride = 2
  nchannel = 32
  random_type = xavier
layer[1->2] = max_pooling
  kernel_size = 3
  stride = 2
layer[2->3] = flatten
layer[3->3] = dropout
  threshold = 0.5
layer[3->4] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[4->5] = sigmoid:se1
layer[5->6] = fullc:fc2
  nhidden = {num_class}
  init_sigma = 0.01
layer[6->6] = softmax
netconfig=end
input_shape = 1,28,28
"""


def alexnet_conf(num_class: int = 1000) -> str:
    """example/ImageNet/ImageNet.conf single-tower AlexNet topology
    (grouped convs 2/4/5, LRN after 1/2, three FCs with dropout)."""
    return f"""
netconfig=start
layer[0->1] = conv:conv1
  kernel_size = 11
  stride = 4
  nchannel = 96
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 3
  stride = 2
layer[3->4] = lrn
  local_size = 5
  alpha = 0.001
  beta = 0.75
  knorm = 1
layer[4->5] = conv:conv2
  ngroup = 2
  nchannel = 256
  kernel_size = 5
  pad = 2
layer[5->6] = relu
layer[6->7] = max_pooling
  kernel_size = 3
  stride = 2
layer[7->8] = lrn
  local_size = 5
  alpha = 0.001
  beta = 0.75
  knorm = 1
layer[8->9] = conv:conv3
  nchannel = 384
  kernel_size = 3
  pad = 1
layer[9->10] = relu
layer[10->11] = conv:conv4
  nchannel = 384
  ngroup = 2
  kernel_size = 3
  pad = 1
layer[11->12] = relu
layer[12->13] = conv:conv5
  nchannel = 256
  ngroup = 2
  kernel_size = 3
  pad = 1
  init_bias = 1.0
layer[13->14] = relu
layer[14->15] = max_pooling
  kernel_size = 3
  stride = 2
layer[15->16] = flatten
layer[16->17] = fullc:fc6
  nhidden = 4096
  init_sigma = 0.005
  init_bias = 1.0
layer[17->18] = relu
layer[18->18] = dropout
  threshold = 0.5
layer[18->19] = fullc:fc7
  nhidden = 4096
  init_sigma = 0.005
  init_bias = 1.0
layer[19->20] = relu
layer[20->20] = dropout
  threshold = 0.5
layer[20->21] = fullc:fc8
  nhidden = {num_class}
layer[21->21] = softmax
netconfig=end
input_shape = 3,227,227
"""


def _conv_bn_relu(lines, src, dst, name, nch, ksize, stride=1, pad=0):
    lines.append(f'layer[{src}->{dst}] = conv:{name}')
    lines.append(f'  nchannel = {nch}')
    lines.append(f'  kernel_size = {ksize}')
    if stride != 1:
        lines.append(f'  stride = {stride}')
    if pad:
        lines.append(f'  pad = {pad}')
    lines.append('  no_bias = 1')
    lines.append(f'layer[{dst}->{dst}] = batch_norm:{name}_bn')
    lines.append(f'layer[{dst}->{dst}] = relu')
    return dst


def _inception(lines, src, prefix, n1, n3r, n3, nd3r, nd3, proj,
               pool='avg_pooling', stride=1):
    """Inception-BN module: 1x1 / 3x3 / double-3x3 / pool-proj branches,
    channel-concatenated."""
    outs = []
    if n1 > 0:
        b = f'{prefix}_1x1'
        _conv_bn_relu(lines, src, b, b, n1, 1)
        outs.append(b)
    b3r = f'{prefix}_3x3r'
    _conv_bn_relu(lines, src, b3r, b3r, n3r, 1)
    b3 = f'{prefix}_3x3'
    _conv_bn_relu(lines, b3r, b3, b3, n3, 3, stride=stride, pad=1)
    outs.append(b3)
    bd3r = f'{prefix}_d3x3r'
    _conv_bn_relu(lines, src, bd3r, bd3r, nd3r, 1)
    bd3a = f'{prefix}_d3x3a'
    _conv_bn_relu(lines, bd3r, bd3a, bd3a, nd3, 3, pad=1)
    bd3 = f'{prefix}_d3x3'
    _conv_bn_relu(lines, bd3a, bd3, bd3, nd3, 3, stride=stride, pad=1)
    outs.append(bd3)
    bp = f'{prefix}_pool'
    lines.append(f'layer[{src}->{bp}] = {pool}')
    lines.append('  kernel_size = 3')
    lines.append(f'  stride = {stride}')
    if stride == 1:
        lines.append('  pad = 1')   # same-size pool branch
    if proj > 0:
        bpp = f'{prefix}_proj'
        _conv_bn_relu(lines, bp, bpp, bpp, proj, 1)
        outs.append(bpp)
    else:
        outs.append(bp)
    dst = f'{prefix}_out'
    lines.append(f'layer[{",".join(outs)}->{dst}] = ch_concat')
    return dst


def inception_bn_conf(num_class: int = 1000) -> str:
    """GoogLeNet-family Inception with BatchNorm (Inception-BN /
    BN-Inception arrangement, cxxnet-era model zoo)."""
    lines = ['netconfig=start']
    top = _conv_bn_relu(lines, '0', 'conv1', 'conv1', 64, 7, stride=2, pad=3)
    _pool(lines, top, 'pool1', 'max_pooling', 3, 2)
    top = _conv_bn_relu(lines, 'pool1', 'conv2r', 'conv2r', 64, 1)
    top = _conv_bn_relu(lines, top, 'conv2', 'conv2', 192, 3, pad=1)
    _pool(lines, top, 'pool2', 'max_pooling', 3, 2)
    top = 'pool2'
    top = _inception(lines, top, 'in3a', 64, 64, 64, 64, 96, 32)
    top = _inception(lines, top, 'in3b', 64, 64, 96, 64, 96, 64)
    top = _inception(lines, top, 'in3c', 0, 128, 160, 64, 96, 0,
                     pool='max_pooling', stride=2)
    top = _inception(lines, top, 'in4a', 224, 64, 96, 96, 128, 128)
    top = _inception(lines, top, 'in4b', 192, 96, 128, 96, 128, 128)
    top = _inception(lines, top, 'in4c', 160, 128, 160, 128, 160, 128)
    top = _inception(lines, top, 'in4d', 96, 128, 192, 160, 192, 128)
    top = _inception(lines, top, 'in4e', 0, 128, 192, 192, 256, 0,
                     pool='max_pooling', stride=2)
    top = _inception(lines, top, 'in5a', 352, 192, 320, 160, 224, 128)
    top = _inception(lines, top, 'in5b', 352, 192, 320, 192, 224, 128,
                     pool='max_pooling')
    lines.append(f'layer[{top}->gpool] = avg_pooling')
    lines.append('  kernel_size = 7')
    lines.append('  stride = 1')
    lines.append('layer[gpool->flat] = flatten')
    lines.append('layer[flat->fc] = fullc:fc1')
    lines.append(f'  nhidden = {num_class}')
    lines.append('layer[fc->fc] = softmax')
    lines.append('netconfig=end')
    lines.append('input_shape = 3,224,224')
    return '\n'.join(lines) + '\n'


def _conv_relu(lines, src, dst, nch, ksize, stride=1, pad=0):
    """conv:{dst} + relu; the layer is named after its output node."""
    lines.append(f'layer[{src}->{dst}] = conv:{dst}')
    lines.append(f'  nchannel = {nch}')
    lines.append(f'  kernel_size = {ksize}')
    if stride != 1:
        lines.append(f'  stride = {stride}')
    if pad:
        lines.append(f'  pad = {pad}')
    lines.append(f'layer[{dst}->{dst}] = relu')
    return dst


def _pool(lines, src, dst, kind, ksize, stride, pad=0):
    lines.append(f'layer[{src}->{dst}] = {kind}')
    lines.append(f'  kernel_size = {ksize}')
    lines.append(f'  stride = {stride}')
    if pad:
        lines.append(f'  pad = {pad}')
    return dst


def _inception_v1(lines, src, prefix, n1, n3r, n3, n5r, n5, proj):
    """Original GoogLeNet inception module: 1x1 / 3x3 / 5x5 / pool-proj
    branches, channel-concatenated (4 inputs, the reference ch_concat
    maximum)."""
    b1 = _conv_relu(lines, src, f'{prefix}_1x1', n1, 1)
    b3r = _conv_relu(lines, src, f'{prefix}_3x3r', n3r, 1)
    b3 = _conv_relu(lines, b3r, f'{prefix}_3x3', n3, 3,
                    pad=1)
    b5r = _conv_relu(lines, src, f'{prefix}_5x5r', n5r, 1)
    b5 = _conv_relu(lines, b5r, f'{prefix}_5x5', n5, 5,
                    pad=2)
    bp = _pool(lines, src, f'{prefix}_pool', 'max_pooling', 3, 1, pad=1)
    bpp = _conv_relu(lines, bp, f'{prefix}_proj', proj, 1)
    dst = f'{prefix}_out'
    lines.append(f'layer[{b1},{b3},{b5},{bpp}->{dst}] = ch_concat')
    return dst


def _aux_head(lines, src, prefix, num_class):
    """GoogLeNet auxiliary classifier: avgpool5/3 -> 1x1 conv -> fc1024 ->
    dropout 0.7 -> fc -> softmax with grad_scale 0.3 (training-time
    regularizer; its loss adds to the main softmax's)."""
    _pool(lines, src, f'{prefix}_pool', 'avg_pooling', 5, 3)
    top = _conv_relu(lines, f'{prefix}_pool', f'{prefix}_conv', 128, 1)
    lines.append(f'layer[{top}->{prefix}_flat] = flatten')
    lines.append(f'layer[{prefix}_flat->{prefix}_fc1] = fullc:{prefix}_fc1')
    lines.append('  nhidden = 1024')
    lines.append(f'layer[{prefix}_fc1->{prefix}_fc1] = relu')
    lines.append(f'layer[{prefix}_fc1->{prefix}_fc1] = dropout')
    lines.append('  threshold = 0.7')
    lines.append(f'layer[{prefix}_fc1->{prefix}_fc2] = fullc:{prefix}_fc2')
    lines.append(f'  nhidden = {num_class}')
    lines.append(f'layer[{prefix}_fc2->{prefix}_fc2] = softmax')
    lines.append('  grad_scale = 0.3')


def googlenet_conf(num_class: int = 1000, aux_heads: bool = True) -> str:
    """Original GoogLeNet (inception v1): LRN instead of BN, and two
    auxiliary softmax classifiers (grad_scale 0.3) feeding the summed
    training loss — exercising the framework's multi-loss graphs."""
    lines = ['netconfig=start']
    top = _conv_relu(lines, '0', 'conv1', 64, 7, stride=2, pad=3)
    _pool(lines, top, 'pool1', 'max_pooling', 3, 2)
    lines.append('layer[pool1->pool1] = lrn')
    lines.append('  local_size = 5')
    top = _conv_relu(lines, 'pool1', 'conv2r', 64, 1)
    top = _conv_relu(lines, top, 'conv2', 192, 3, pad=1)
    lines.append(f'layer[{top}->{top}] = lrn')
    lines.append('  local_size = 5')
    _pool(lines, top, 'pool2', 'max_pooling', 3, 2)
    top = _inception_v1(lines, 'pool2', 'in3a', 64, 96, 128, 16, 32, 32)
    top = _inception_v1(lines, top, 'in3b', 128, 128, 192, 32, 96, 64)
    _pool(lines, top, 'pool3', 'max_pooling', 3, 2)
    top = _inception_v1(lines, 'pool3', 'in4a', 192, 96, 208, 16, 48, 64)
    if aux_heads:
        _aux_head(lines, top, 'aux1', num_class)
    top = _inception_v1(lines, top, 'in4b', 160, 112, 224, 24, 64, 64)
    top = _inception_v1(lines, top, 'in4c', 128, 128, 256, 24, 64, 64)
    top = _inception_v1(lines, top, 'in4d', 112, 144, 288, 32, 64, 64)
    if aux_heads:
        _aux_head(lines, top, 'aux2', num_class)
    top = _inception_v1(lines, top, 'in4e', 256, 160, 320, 32, 128, 128)
    _pool(lines, top, 'pool4', 'max_pooling', 3, 2)
    top = _inception_v1(lines, 'pool4', 'in5a', 256, 160, 320, 32, 128, 128)
    top = _inception_v1(lines, top, 'in5b', 384, 192, 384, 48, 128, 128)
    _pool(lines, top, 'gpool', 'avg_pooling', 7, 1)
    lines.append('layer[gpool->gpool_flat] = flatten')
    lines.append('layer[gpool_flat->gpool_flat] = dropout')
    lines.append('  threshold = 0.4')
    lines.append('layer[gpool_flat->fc] = fullc:loss3_fc')
    lines.append(f'  nhidden = {num_class}')
    lines.append('layer[fc->fc] = softmax')
    lines.append('netconfig=end')
    lines.append('input_shape = 3,224,224')
    return '\n'.join(lines) + '\n'


def vgg16_conf(num_class: int = 1000) -> str:
    """VGG-16 (configuration D, Simonyan & Zisserman 2014) — the era's
    third headline ImageNet family alongside AlexNet and GoogLeNet.  The
    reference ships no VGG conf; this follows the cxxnet-era model-zoo
    arrangement: five 3x3-conv blocks (2-2-3-3-3) with 2x2 max pooling,
    then fc4096-fc4096-fc{num_class} with dropout."""
    blocks = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
    lines = ['netconfig=start']
    for b, (reps, ch) in enumerate(blocks, start=1):
        for r in range(1, reps + 1):
            lines += [f'layer[+1] = conv:conv{b}_{r}',
                      '  kernel_size = 3',
                      '  pad = 1',
                      f'  nchannel = {ch}',
                      'layer[+1] = relu']
        lines += ['layer[+1] = max_pooling',
                  '  kernel_size = 2',
                  '  stride = 2']
    lines += ['layer[+1] = flatten']
    for i, nh in ((6, 4096), (7, 4096)):
        lines += [f'layer[+1] = fullc:fc{i}',
                  f'  nhidden = {nh}',
                  'layer[+1] = relu',
                  'layer[+0] = dropout',
                  '  threshold = 0.5']
    lines += [f'layer[+1] = fullc:fc8',
              f'  nhidden = {num_class}',
              'layer[+0] = softmax',
              'netconfig=end',
              'input_shape = 3,224,224']
    return '\n'.join(lines) + '\n'
