"""TransformerLM — the long-context / multi-dimensional-parallelism
flagship.

The reference framework tops out at data parallelism over GPUs
(SURVEY.md §2.5); this model family is where the TPU build goes past it:
one ``shard_map`` over a ``(pipe, data, seq, model)`` mesh runs the FULL
training step with every collective explicit and riding ICI:

* **dp**   — batch sharded over ``data``; gradient pmean over data+seq,
* **pp**   — transformer blocks stacked on a leading stage axis sharded
  over ``pipe``; GPipe microbatch schedule (parallel/pipeline.py),
* **sp**   — sequence sharded over ``seq``; exact ring attention
  (parallel/sequence.py) rotates K/V blocks with ``ppermute``,
* **tp**   — attention heads and FFN hidden sharded over ``model``;
  row-parallel output projections finish with ``psum``,
* **ep**   — switch-MoE FFN, experts sharded over ``data`` with
  all_to_all dispatch/combine (parallel/moe.py).

Because everything lives in one shard_map body, the strategies compose:
ring attention runs inside a pipeline stage inside the microbatch scan.
Backward is ``jax.value_and_grad`` straight through (collectives
transpose to collectives); the SGD update runs sharded in the same body,
so optimizer state never leaves the device that owns the shard.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import functools
import math
import os
import threading
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nnet.quantize import qdot, qtake
from ..parallel.moe import moe_ffn_local
from ..parallel.pipeline import pipeline_stage_loop, split_microbatches
from ..parallel.sequence import _local_attention, _ring_attention_local

try:                                    # jax >= 0.5 spelling
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

AXES = ('pipe', 'data', 'seq', 'model')

# --- serving-side tensor parallelism (graftshard, doc/serving.md
# "Sharded serving") -------------------------------------------------------
#
# The decode engine serves a COLUMN-sharded param tree over a 1xN
# ('data', 'model') mesh: every matmul weight's last (output-feature)
# axis is split over 'model' — wq/wk/wv along attention heads, wo/w2
# along d_model, w1 along d_ff, head along vocab, embed along d_model —
# and the residual stream is pulled back to replicated with an explicit
# sharding constraint BEFORE any op that would contract over a sharded
# axis.  That constraint lowers to an all-gather: pure data movement, no
# arithmetic.  The payoff is the stream-twin contract — every float
# reduction (matmul K-loops, layernorm moments, softmax sums) runs over
# fully-replicated operands in the exact operand order of the
# single-device program, so sharded logits are BITWISE-equal to
# unsharded ones at any shard count (tests/test_serve_shard.py).  The
# training path (`_stage_fn`) keeps its psum-based row-parallel layout:
# training tolerates reduction-order drift, serving twins do not.
#
# The active serve mesh rides a thread-local rather than the config:
# `TransformerConfig` must stay `dataclasses.astuple`-able (generate()'s
# program-cache key), and tracing happens on whichever thread first
# calls the jitted program — the engine wraps each traced body in
# :func:`shard_scope`, so concurrent prefill workers tracing different
# programs cannot see each other's mesh.
_SHARD_TLS = threading.local()


@contextlib.contextmanager
def shard_scope(mesh):
    """Activate ``mesh`` as the serve-shard mesh for ops traced inside
    this scope (``None`` = single-device: every hook is an identity)."""
    prev = getattr(_SHARD_TLS, 'mesh', None)
    _SHARD_TLS.mesh = mesh
    try:
        yield
    finally:
        _SHARD_TLS.mesh = prev


def serve_shard_mesh():
    """The serve-shard mesh active on this thread (None = off)."""
    return getattr(_SHARD_TLS, 'mesh', None)


def _rep(x):
    """Constrain a (possibly model-sharded) activation to fully
    replicated — the all-gather boundary of the column-parallel serving
    layout.  Identity when no serve-shard mesh is active, so training,
    ``generate`` and the single-device engines compile byte-identical
    programs."""
    mesh = serve_shard_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))


@dataclass
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 64
    num_heads: int = 4
    d_ff: int = 128
    num_stages: int = 2          # pipeline stages == transformer blocks
    seq_len: int = 64
    num_experts: int = 0         # 0 = dense FFN; >0 = switch-MoE FFN
    capacity_factor: float = 2.0
    balance_loss_weight: float = 0.01   # Switch aux-loss weight (MoE only)
    attn: str = 'ring'           # 'ring' | 'local'
    causal: bool = True
    num_microbatches: int = 4
    dtype: object = jnp.float32
    remat: bool = False          # rematerialize each block in backward:
    # activations of a stage are recomputed instead of stored, cutting
    # per-block activation HBM to O(1) blocks — the lever that lets long
    # sequences fit (pairs with ring attention's O(s) memory)


def init_params(rng: np.random.RandomState, cfg: TransformerConfig):
    """Stage params stacked on axis 0 (the ``pipe``-sharded axis)."""
    s, d, f, v = cfg.num_stages, cfg.d_model, cfg.d_ff, cfg.vocab_size

    def init(*shape, scale=None):
        scale = scale or 1.0 / math.sqrt(shape[-2] if len(shape) > 1
                                         else shape[-1])
        return jnp.asarray(rng.randn(*shape) * scale, cfg.dtype)

    stages = {
        'ln1_scale': jnp.ones((s, d), cfg.dtype),
        'ln1_bias': jnp.zeros((s, d), cfg.dtype),
        'wq': init(s, d, d), 'wk': init(s, d, d), 'wv': init(s, d, d),
        'wo': init(s, d, d),
        'ln2_scale': jnp.ones((s, d), cfg.dtype),
        'ln2_bias': jnp.zeros((s, d), cfg.dtype),
    }
    if cfg.num_experts:
        e = cfg.num_experts
        stages['gate'] = init(s, d, e)
        stages['w1'] = init(s, e, d, f)
        stages['w2'] = init(s, e, f, d, scale=1.0 / math.sqrt(f))
    else:
        stages['w1'] = init(s, d, f)
        stages['w2'] = init(s, f, d, scale=1.0 / math.sqrt(f))
    return {
        'embed': init(v, d, scale=0.02),
        'head': init(d, v),
        'stages': stages,
    }


def param_specs(cfg: TransformerConfig):
    """PartitionSpecs over AXES for every leaf."""
    col = P('pipe', None, 'model')       # qkv: heads sharded over model
    stages = {
        'ln1_scale': P('pipe', None), 'ln1_bias': P('pipe', None),
        'wq': col, 'wk': col, 'wv': col,
        'wo': P('pipe', 'model', None),  # row-parallel out-proj -> psum
        'ln2_scale': P('pipe', None), 'ln2_bias': P('pipe', None),
    }
    if cfg.num_experts:
        stages['gate'] = P('pipe', None, None)
        stages['w1'] = P('pipe', 'data', None, None)   # ep over data axis
        stages['w2'] = P('pipe', 'data', None, None)
    else:
        stages['w1'] = P('pipe', None, 'model')        # col-parallel
        stages['w2'] = P('pipe', 'model', None)        # row-parallel
    return {'embed': P(None, None), 'head': P(None, None),
            'stages': stages}


def _map_with_specs(fn, tree, specs):
    """Apply ``fn(leaf, spec)`` over parallel nested dicts (PartitionSpec
    is a tuple subclass, so jax.tree.map would descend into it)."""
    if isinstance(tree, dict):
        return {k: _map_with_specs(fn, v, specs[k]) for k, v in tree.items()}
    return fn(tree, specs)


def _layer_norm(x, scale, bias):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + 1e-6) * scale + bias).astype(x.dtype)


def _stage_fn(p, x, *, cfg: TransformerConfig, tp: int, sp: int):
    """One transformer block on the local activation shard.
    x: (mb_local, s_local, D).  p: this stage's params (leading dim
    squeezed).  Collectives: 'seq' (ring attention), 'model' (psum for
    row-parallel projections), 'data' (MoE all_to_all).
    Returns (y, aux): aux carries the MoE balance loss / drop fraction
    (zeros for dense FFN) and is accumulated by the pipeline loop."""
    mb, s_loc, d = x.shape
    h_local = cfg.num_heads // tp        # heads owned by this model rank
    hd = d // cfg.num_heads

    # --- attention ---------------------------------------------------------
    y = _layer_norm(x, p['ln1_scale'], p['ln1_bias'])
    q = (y @ p['wq']).reshape(mb, s_loc, h_local, hd)
    k = (y @ p['wk']).reshape(mb, s_loc, h_local, hd)
    v = (y @ p['wv']).reshape(mb, s_loc, h_local, hd)
    if cfg.attn == 'ring' and sp > 1:
        attn = _ring_attention_local(q, k, v, axis_name='seq',
                                     causal=cfg.causal)
    else:
        mask = None
        if cfg.causal:
            mask = jnp.tril(jnp.ones((s_loc, s_loc), bool))[None, None]
        attn = _local_attention(q, k, v, 1.0 / math.sqrt(hd), mask)
    attn = attn.reshape(mb, s_loc, h_local * hd)
    out = attn @ p['wo']                  # row-parallel: partial sums
    if tp > 1:
        out = lax.psum(out, 'model')
    x = x + out

    # --- ffn ---------------------------------------------------------------
    y = _layer_norm(x, p['ln2_scale'], p['ln2_bias'])
    if cfg.num_experts:
        yf = y.reshape(mb * s_loc, d)
        ff, aux = moe_ffn_local(yf, p['gate'], p['w1'], p['w2'],
                                axis_name='data',
                                capacity_factor=cfg.capacity_factor)
        ff = ff.reshape(mb, s_loc, d)
    else:
        ff = jax.nn.relu(y @ p['w1']) @ p['w2']
        if tp > 1:
            ff = lax.psum(ff, 'model')
        aux = {'balance_loss': jnp.float32(0.0),
               'drop_frac': jnp.float32(0.0)}
    return x + ff, aux


def _loss_local(params, tokens, labels, *, cfg, tp, sp):
    """Local shard loss: embed -> pipelined blocks -> head -> mean NLL
    (+ weighted MoE balance loss).  Returns (loss, aux)."""
    h = jnp.take(params['embed'], tokens, axis=0)        # (b, s, D)
    xs = split_microbatches(h, cfg.num_microbatches)
    stage = functools.partial(_stage_fn, cfg=cfg, tp=tp, sp=sp)
    if cfg.remat:
        # recompute the block in backward instead of storing its
        # activations; collectives (ring ppermute, psum, all_to_all)
        # replay under remat, so this composes with all four axes
        stage = jax.checkpoint(stage)
    hs, aux = pipeline_stage_loop(stage, params['stages'], xs,
                                  axis_name='pipe',
                                  num_stages=cfg.num_stages, has_aux=True)
    h = hs.reshape(h.shape)
    logits = (h @ params['head']).astype(jnp.float32)     # (b, s, V)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)
    loss = nll.mean()
    if cfg.num_experts:
        loss = loss + cfg.balance_loss_weight * aux['balance_loss']
    return loss, aux


def _make_step_body(cfg: TransformerConfig, mesh: Mesh, lr: float):
    """The per-rank train-step body of :func:`make_train_step`:
    (params, tokens, labels) -> (new_params, loss, aux), all local
    shards.  NOTE: :func:`make_multi_train_step` does NOT use this — it
    scans :func:`reference_loss` (see its docstring for why); optimizer
    changes here must be mirrored there."""
    tp = mesh.shape['model']
    sp = mesh.shape['seq']
    if cfg.num_heads % tp:
        raise ValueError('num_heads must divide model axis')
    if sp > 1 and cfg.attn != 'ring':
        raise ValueError(
            f"attn='{cfg.attn}' on a seq-sharded mesh (seq={sp}) would "
            "attend block-diagonally; use attn='ring'")
    specs = param_specs(cfg)

    n_ranks = (mesh.shape['pipe'] * mesh.shape['data']
               * mesh.shape['seq'] * mesh.shape['model'])

    def _replicated_axes(spec: P) -> Tuple[str, ...]:
        used = {a for part in spec if part is not None
                for a in ((part,) if isinstance(part, str) else part)}
        return tuple(a for a in AXES if a not in used)

    def body(params, tokens, labels):
        (loss, aux), grads = jax.value_and_grad(
            functools.partial(_loss_local, cfg=cfg, tp=tp, sp=sp),
            has_aux=True)(params, tokens, labels)
        # Per-rank autodiff yields d(sum of every rank's local loss)/
        # d(local shard) — collective transposes already crossed ranks.
        # Tie replicas back together: sum each leaf's gradient over the
        # axes it is replicated on, then normalize by the total rank
        # count so the result is the gradient of the *mean* loss.
        # Validated against the single-device oracle in
        # tests/test_transformer_parallel.py.
        def tie(g, spec):
            rep = _replicated_axes(spec)
            if rep:
                g = lax.psum(g, rep)
            return g / n_ranks
        grads = _map_with_specs(tie, grads, specs)
        new_params = jax.tree.map(
            lambda w, g: (w - lr * g).astype(w.dtype), params, grads)
        aux = jax.tree.map(lambda v: lax.pmean(v, AXES), aux)
        return new_params, lax.pmean(loss, AXES), aux

    return body, specs


def make_train_step(cfg: TransformerConfig, mesh: Mesh, lr: float = 0.1):
    """Jitted full train step: (params, tokens, labels) ->
    (new_params, loss, aux).  tokens/labels are global (B, seq_len) int32;
    aux reports ``balance_loss`` (unweighted) and ``drop_frac`` summed over
    MoE blocks (zeros for dense FFN)."""
    body, specs = _make_step_body(cfg, mesh, lr)
    tok_spec = P('data', 'seq')
    fn = shard_map(body, mesh=mesh,
                   in_specs=(specs, tok_spec, tok_spec),
                   out_specs=(specs, P(), {'balance_loss': P(),
                                           'drop_frac': P()}),
                   check_vma=False)
    return jax.jit(fn)


def make_multi_train_step(cfg: TransformerConfig, n_steps: int,
                          lr: float = 0.1):
    """Single-device jitted ``n_steps``-step training loop in ONE
    dispatch: (params, tok_stack, lab_stack) -> (new_params, last_loss),
    the stacks (nstack, B, seq_len) int32 cycled round-robin — the
    transformer counterpart of ``NetTrainer.compile_multi_step``, used by
    bench.py (per-step dispatch over the dev-harness tunnel measures the
    link, not the chip) and by single-chip pre-staged pipelines.  Built
    on :func:`reference_loss` (the oracle the mesh step is tested
    against): a ``lax.scan`` whose body contains a shard_map does not
    lower on this jax version (internally-jitted jnp helpers become
    closed_calls the lowering cache misses), and a single chip needs no
    mesh anyway."""

    def multi(params, tok_stack, lab_stack):
        nstack = tok_stack.shape[0]

        def sbody(p, t):
            tok = lax.dynamic_index_in_dim(tok_stack, t % nstack,
                                           keepdims=False)
            lab = lax.dynamic_index_in_dim(lab_stack, t % nstack,
                                           keepdims=False)
            loss, grads = jax.value_and_grad(reference_loss)(p, tok, lab,
                                                             cfg)
            p = jax.tree.map(
                lambda w, g: (w - lr * g).astype(w.dtype), p, grads)
            return p, loss

        params, losses = lax.scan(sbody, params, jnp.arange(n_steps))
        return params, losses[-1]

    jitted = jax.jit(multi, donate_argnums=(0,))
    jitted.n_steps = n_steps
    return jitted


def build_transformer_mesh(n_devices: int,
                           pp: int, dp: int, sp: int, tp: int,
                           devices=None) -> Mesh:
    if pp * dp * sp * tp != n_devices:
        raise ValueError(f'pp*dp*sp*tp = {pp * dp * sp * tp} '
                         f'!= {n_devices} devices')
    devs = np.asarray(devices if devices is not None
                      else jax.devices()[:n_devices])
    return Mesh(devs.reshape(pp, dp, sp, tp), AXES)


def param_shapes(cfg: TransformerConfig):
    """ShapeDtypeStructs mirroring ``init_params`` — shapes without
    allocating anything (test-pinned against init_params)."""
    s, d, f, v = cfg.num_stages, cfg.d_model, cfg.d_ff, cfg.vocab_size

    def sds(*shape):
        return jax.ShapeDtypeStruct(shape, cfg.dtype)

    stages = {
        'ln1_scale': sds(s, d), 'ln1_bias': sds(s, d),
        'wq': sds(s, d, d), 'wk': sds(s, d, d), 'wv': sds(s, d, d),
        'wo': sds(s, d, d),
        'ln2_scale': sds(s, d), 'ln2_bias': sds(s, d),
    }
    if cfg.num_experts:
        e = cfg.num_experts
        stages['gate'] = sds(s, d, e)
        stages['w1'] = sds(s, e, d, f)
        stages['w2'] = sds(s, e, f, d)
    else:
        stages['w1'] = sds(s, d, f)
        stages['w2'] = sds(s, f, d)
    return {'embed': sds(v, d), 'head': sds(d, v), 'stages': stages}


def abstract_params(params, cfg: TransformerConfig, mesh: Mesh):
    """Sharding-annotated ShapeDtypeStructs for ``params`` — the restore
    target for sharded checkpoints (nnet/sharded_ckpt.py): orbax lays each
    shard straight onto its mesh position, no full-replica host copy.
    ``params=None`` derives shapes from the config (``param_shapes``), so
    resume never materializes a throwaway replica."""
    from jax.sharding import NamedSharding
    if params is None:
        params = param_shapes(cfg)
    return _map_with_specs(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                          sharding=NamedSharding(mesh, s)),
        params, param_specs(cfg))


def _stage_attn(p, h, cfg: TransformerConfig, mask):
    """One block's attention half on a single device: ln1 -> qkv ->
    attention -> residual out-proj -> ln2.  THE single copy of the
    block math — :func:`reference_loss` and :func:`generate`'s prefill
    both run through here, so they cannot drift.  Returns
    ``(h, y2, k, v)`` (k/v for the decode cache)."""
    mb, s, d = h.shape
    hd = d // cfg.num_heads
    y = _layer_norm(h, p['ln1_scale'], p['ln1_bias'])
    # matmuls route through the quantized-leaf dispatcher: a plain
    # array takes the native ``x @ w`` (bitwise-identical — training and
    # reference paths are untouched); an int8 QuantLeaf (serve.dtype,
    # nnet/quantize.py) runs the W8A8 leg
    q = qdot(y, p['wq']).reshape(mb, s, cfg.num_heads, hd)
    k = qdot(y, p['wk']).reshape(mb, s, cfg.num_heads, hd)
    v = qdot(y, p['wv']).reshape(mb, s, cfg.num_heads, hd)
    attn = _local_attention(q, k, v, 1.0 / math.sqrt(hd), mask)
    # serve-shard boundary: gather the head-sharded attention output
    # before contracting over d_model, re-replicate wo's column-sharded
    # output before the residual add (no-ops off-mesh)
    h = h + _rep(qdot(_rep(attn.reshape(mb, s, d)), p['wo']))
    y2 = _layer_norm(h, p['ln2_scale'], p['ln2_bias'])
    return h, y2, k, v


def _nodrop_moe_ffn(y2, p, gather: bool):
    """No-drop top-1 switch routing: gate-probability-scaled expert
    output (the same per-token math as ``switch_gate``'s
    ``combine = dispatch * gate_prob``, parallel/moe.py) WITHOUT the
    capacity bound — at inference the capacity bucket is a training-time
    load-balancing artifact (a handful of live tokens makes
    ``capacity = ceil(cf*N/E)`` round to 0-1 and drop arbitrarily).

    ``gather=True`` gathers each token's expert weights directly —
    O(tokens) weight copies, right for the decode step's single live
    token.  ``gather=False`` uses a one-hot dispatch einsum (no weight
    duplication, E-way activation buffer like ``moe_ffn_local``) —
    right for the prefill's b*s0 tokens."""
    probs = jax.nn.softmax(qdot(y2, p['gate']).astype(jnp.float32),
                           axis=-1)
    ex = jnp.argmax(probs, axis=-1)                        # (n,)
    pg = jnp.take_along_axis(probs, ex[:, None], axis=-1)  # (n, 1)
    if gather:
        w1 = jnp.take(p['w1'], ex, axis=0)                 # (n, d, f)
        w2 = jnp.take(p['w2'], ex, axis=0)                 # (n, f, d)
        hmid = jax.nn.relu(jnp.einsum('nd,ndf->nf', y2, w1))
        out = jnp.einsum('nf,nfd->nd', hmid, w2)
    else:
        oh = jax.nn.one_hot(ex, p['w1'].shape[0], dtype=y2.dtype)
        buf = jnp.einsum('ne,nd->end', oh, y2)             # (E, n, d)
        hmid = jax.nn.relu(jnp.einsum('end,edf->enf', buf, p['w1']))
        out = jnp.einsum('enf,efd,ne->nd', hmid, p['w2'], oh)
    return (pg * out.astype(jnp.float32)).astype(y2.dtype)


# compiled decode programs keyed by (cfg, bucketed shapes, sampling):
# generate() is called repeatedly (sampling loops, tests) and must not
# re-trace — and the jitted fn takes params as an ARGUMENT so weights are
# inputs, not baked-in XLA constants.  Two guards keep the cache from
# retaining one compiled program per distinct request shape forever:
# prompt/new-token lengths are BUCKETED into power-of-two size classes
# before keying (below), and the cache itself is a small LRU
# (``CXXNET_GEN_CACHE_MAX``, default 8) — a varying-prompt sampling loop
# touches a handful of entries, evicting cold programs instead of
# growing without bound.
_GEN_CACHE: 'collections.OrderedDict' = collections.OrderedDict()

# hit/miss tallies for the program cache — serving telemetry
# (serve stats / bench receipts) reads these through gen_cache_stats()
# so a retrace storm under live traffic is visible, not silent
_GEN_STATS = {'hit': 0, 'miss': 0}


def gen_cache_stats(reset: bool = False) -> dict:
    """Snapshot (optionally reset) the ``generate`` program-cache
    hit/miss counters; serving surfaces export them onto a
    ``utils.metric.StatSet`` (``gen_cache.hit`` / ``gen_cache.miss``)."""
    out = dict(_GEN_STATS)
    if reset:
        _GEN_STATS['hit'] = _GEN_STATS['miss'] = 0
    return out


def _gen_cache_max() -> int:
    return max(1, int(os.environ.get('CXXNET_GEN_CACHE_MAX', '8')))


def _size_class(n: int, floor: int = 1) -> int:
    """Bucket a length into its size class: the next power of two (the
    prompt axis floors at 8; ``max_new`` uses the full {1,2,4,8,...}
    ladder — a 1-token request must not pay 8 decode steps).  EXACT
    under bucketing (see ``generate``): extra decode steps are computed
    and trimmed (decode is sequential — token t never depends on later
    steps), and a bucketed prompt is LEFT-padded with masked-out slots
    (the model has no positional encoding, so a uniform slot shift with
    pads excluded from every attention is the identical computation on
    the real tokens).  ``CXXNET_GEN_BUCKETS=0`` disables bucketing
    (exact shapes — e.g. bench.py's K-vs-1 decode quotient)."""
    b = max(1, floor)
    while b < n:
        b <<= 1
    return b


def generate(params, prompt, max_new: int, cfg: TransformerConfig,
             temperature: float = 0.0, rng=None, eos_id: int = None):
    """KV-cached autoregressive decode (single device) — the LM family's
    ``task=pred`` analog (the reference predicts with ``TransformPred``
    argmax, ``nnet_impl:286-298``; an LM predicts by decoding).

    Two phases under one jit: a vectorized prefill runs the whole prompt
    through :func:`_stage_attn` (the same block math as
    :func:`reference_loss`) capturing each stage's K/V, then
    ``lax.scan`` emits ``max_new`` tokens, each step attending over the
    cache — O(total) work per token instead of re-running the full
    forward.  Dense configs match the training forward exactly; MoE
    configs route through :func:`_nodrop_moe_ffn` (gate-prob-scaled
    top-1, NO capacity drops), which equals the training math except at
    tokens training's capacity bound would have dropped.
    ``temperature=0`` is greedy argmax; ``>0`` samples
    ``jax.random.categorical(logits/T, rng)``.  Requires
    ``cfg.causal`` (autoregressive decode is meaningless for a
    bidirectional model).

    ``prompt``: (batch, s0) int32; returns (batch, max_new) int32.
    ``eos_id``: per-row early stop — every position after a row's first
    emitted eos is eos (shapes stay static under jit; trim host-side).
    """
    if not cfg.causal:
        raise ValueError('generate() requires a causal config')
    if temperature > 0 and rng is None:
        raise ValueError('temperature>0 sampling needs an rng key')
    prompt = jnp.asarray(prompt, jnp.int32)
    b, s0 = prompt.shape
    if os.environ.get('CXXNET_GEN_BUCKETS', '1') != '0':
        s0b, mnb = _size_class(s0, floor=8), _size_class(max_new)
    else:
        s0b, mnb = s0, max_new
    w = s0b - s0                    # left-pad width (0 = exact shape)
    if w:
        prompt = jnp.pad(prompt, ((0, 0), (w, 0)))
    key = (dataclasses.astuple(cfg), b, s0b, mnb, float(temperature),
           eos_id)
    run = _GEN_CACHE.get(key)
    if run is None:
        _GEN_STATS['miss'] += 1
        run = _GEN_CACHE[key] = _build_generate(
            cfg, b, s0b, mnb, temperature, eos_id)
    else:
        _GEN_STATS['hit'] += 1
        _GEN_CACHE.move_to_end(key)     # LRU touch
    # enforce the bound on EVERY call (hits included): an env value that
    # shrinks mid-process takes effect on the next call, not the next miss
    while len(_GEN_CACHE) > _gen_cache_max():
        _GEN_CACHE.popitem(last=False)
    # the pad width is a traced VALUE, not a shape: every w for the same
    # bucket reuses one compiled program.  Sampling keys are split for
    # the REQUESTED horizon and zero-padded to the bucket (split(rng, n)
    # prefixes are not stable across n), so the first max_new draws
    # match the unbucketed schedule exactly; the padded tail's draws are
    # trimmed with the extra tokens.
    if temperature > 0:
        keys = jax.random.split(rng, max_new + 1)
        if mnb > max_new:
            keys = jnp.concatenate(
                [keys, jnp.zeros((mnb - max_new,) + keys.shape[1:],
                                 keys.dtype)])
    else:
        keys = jnp.zeros((mnb + 1, 2), jnp.uint32)
    return run(params, prompt, keys, jnp.int32(w))[:, :max_new]


def _gen_ffn(cfg: TransformerConfig, p, y2, gather: bool):
    """Inference-path FFN for one stage: dense nets run the training
    math; MoE nets route through the no-drop top-1 gate."""
    mb, s, d = y2.shape
    if cfg.num_experts:
        return _nodrop_moe_ffn(y2.reshape(mb * s, d), p,
                               gather).reshape(mb, s, d)
    # serve-shard boundaries around the d_ff contraction (see _rep)
    return _rep(qdot(_rep(jax.nn.relu(qdot(y2, p['w1']))), p['w2']))


def prefill_kv(params, prompt, w, cfg: TransformerConfig):
    """Vectorized prompt prefill — the whole (possibly left-padded)
    prompt through :func:`_stage_attn` in one pass, capturing each
    stage's K/V.  THE single copy of the prefill math: ``generate``'s
    compiled program and the serve decode engine's per-request prefill
    (serve/decode.py) both run through here.

    ``prompt``: (b, s0) int32 with the first ``w`` slots bucket padding
    (``w`` is a traced value — every pad width shares one program).
    Returns ``(ks, vs, logits0)``: ks/vs (num_stages, b, s0, heads, hd)
    cache rows for positions [0, s0), logits0 (b, vocab) float32 for the
    last position (the first generated token's distribution)."""
    b, s0 = prompt.shape
    h = _rep(qtake(params['embed'], prompt))
    # causal over the real tokens only: the first ``w`` slots are
    # bucket padding (generate() left-pads), excluded from every
    # real query.  Each PAD query attends just its own slot — an
    # all-masked softmax row is NaN, and 0 * NaN cached-V rows would
    # poison real outputs downstream.  ``w`` is traced, so w=0
    # reduces to the plain tril without a separate program.
    ar = jnp.arange(s0)
    mask = ((ar[None, :] <= ar[:, None]) & (ar[None, :] >= w)
            | (ar[None, :] == ar[:, None]) & (ar[:, None] < w)
            )[None, None]
    ks, vs = [], []
    for i in range(cfg.num_stages):
        p = jax.tree.map(lambda a, i=i: a[i], params['stages'])
        h, y2, k, v = _stage_attn(p, h, cfg, mask)
        ks.append(k)
        vs.append(v)
        h = h + _gen_ffn(cfg, p, y2, gather=False)
    logits0 = _rep(qdot(h[:, -1], params['head'])).astype(jnp.float32)
    return jnp.stack(ks), jnp.stack(vs), logits0


def prefill_tail_kv(params, prefix_ks, prefix_vs, tail, w,
                    cfg: TransformerConfig):
    """Prefix-shared prompt prefill: run ONLY the prompt's tail through
    the block walk, attending over the already-cached prefix K/V
    (serve/decode.py "Prefix sharing" — the prefix rows came out of an
    earlier request's :func:`prefill_kv` over the identical token span,
    so recomputing them would be pure waste).

    ``prefix_ks``/``prefix_vs``: (num_stages, b, t0, heads, hd) cache
    rows for positions ``[0, t0)``.  ``tail``: (b, tt) int32 tokens at
    positions ``[t0, t0 + tt)`` — every tail position must be a REAL
    token (the caller only shares prefixes that cover all bucket-pad
    slots, so ``t0 >= w``).  ``w`` is the traced left-pad width.

    Deliberately mirrors :func:`prefill_kv`'s math — ``_local_attention``
    in the operand dtype, ``_gen_ffn(gather=False)``, the same mask rule
    for real queries — so the tail rows and last-position logits are the
    ones the full prefill would have produced (row-for-row: each tail
    query's softmax sees exactly the positions ``[w, pos]``).  Returns
    ``(ks_tail, vs_tail, logits0)``: the (num_stages, b, tt, heads, hd)
    cache rows for the tail positions and the (b, vocab) f32 logits of
    the last position."""
    b, tt = tail.shape
    t0 = prefix_ks.shape[2]
    hd = cfg.d_model // cfg.num_heads
    h = _rep(qtake(params['embed'], tail))
    # query i sits at global position t0 + i; it attends cache positions
    # [w, t0 + i] — the same set full prefill's mask grants a real query
    gq = t0 + jnp.arange(tt)
    ar = jnp.arange(t0 + tt)
    mask = ((ar[None, :] <= gq[:, None])
            & (ar[None, :] >= w))[None, None]
    ks, vs = [], []
    for i in range(cfg.num_stages):
        p = jax.tree.map(lambda a, i=i: a[i], params['stages'])
        y = _layer_norm(h, p['ln1_scale'], p['ln1_bias'])
        q = qdot(y, p['wq']).reshape(b, tt, cfg.num_heads, hd)
        k = qdot(y, p['wk']).reshape(b, tt, cfg.num_heads, hd)
        v = qdot(y, p['wv']).reshape(b, tt, cfg.num_heads, hd)
        kf = jnp.concatenate([prefix_ks[i], k], axis=1)
        vf = jnp.concatenate([prefix_vs[i], v], axis=1)
        attn = _local_attention(q, kf, vf, 1.0 / math.sqrt(hd), mask)
        h = h + _rep(qdot(_rep(attn.reshape(b, tt, cfg.d_model)),
                          p['wo']))
        y2 = _layer_norm(h, p['ln2_scale'], p['ln2_bias'])
        ks.append(k)
        vs.append(v)
        h = h + _gen_ffn(cfg, p, y2, gather=False)
    logits0 = _rep(qdot(h[:, -1], params['head'])).astype(jnp.float32)
    return jnp.stack(ks), jnp.stack(vs), logits0


def verify_step(params, cfg: TransformerConfig, toks, kc, vc, t, w):
    """A (b, K)-token WINDOW through the decode block walk in one pass —
    the speculative-decoding verify entry (serve/decode.py "Speculative
    decoding") and the multi-token generalization of :func:`decode_step`
    (K=1 reduces to the same shapes and cast points).

    ``toks``: (b, K) int32, the tokens consumed at positions
    ``[t, t + K)`` per row (window slot k consumes ``toks[:, k]`` at
    position ``t + k``).  ``kc``/``vc``: dense (num_stages, b, total,
    heads, hd) caches; all K rows are written before attending, and
    window query ``k`` masks the cache to ``[w, t + k]`` — its own row
    and earlier, never a later draft's — so each window position
    computes exactly what a sequential :func:`decode_step` at that
    position would (the greedy spec-decode token-equality hinges on
    this; the masking rule is the same ``(ar <= t) & (ar >= w)`` with
    ``t`` per query).  ``t``/``w`` are (b,) int32 per-row vectors.

    Returns ``(logits, kc, vc, knew, vnew)``: logits (b, K, vocab) f32 —
    row k is the next-token distribution after consuming window slots
    ``0..k`` — and knew/vnew (num_stages, b, K, heads, hd), the rows
    written at ``[t, t + K)`` (the paged engine scatters those into its
    page pool)."""
    total = kc.shape[2]
    b, K = toks.shape
    hd = cfg.d_model // cfg.num_heads
    scale = 1.0 / math.sqrt(hd)
    ar = jnp.arange(total)
    tq = t[:, None] + jnp.arange(K)[None, :]               # (b, K)
    live = ((ar[None, None, :] <= tq[:, :, None])
            & (ar[None, None, :] >= w[:, None, None]))[:, None]  # (b,1,K,T)
    state = {'kc': kc, 'vc': vc}
    knews, vnews = [], []
    bi = jnp.arange(b)[:, None]

    def attend(i, p, q, k, v):
        kc = state['kc'].at[i, bi, tq].set(k)
        vc = state['vc'].at[i, bi, tq].set(v)
        state['kc'], state['vc'] = kc, vc
        ki, vi = kc[i], vc[i]
        s_ = jnp.einsum('bqhd,bkhd->bhqk', q, ki) * scale
        s_ = jnp.where(live, s_, -jnp.inf)
        knews.append(k)
        vnews.append(v)
        return jnp.einsum(
            'bhqk,bkhd->bqhd',
            jax.nn.softmax(s_.astype(jnp.float32),
                           axis=-1).astype(ki.dtype), vi)

    logits = _window_tokens(params, cfg, toks, attend)
    return (logits, state['kc'], state['vc'], jnp.stack(knews),
            jnp.stack(vnews))


def verify_step_paged(params, cfg: TransformerConfig, toks, kpool, vpool,
                      table, t, w):
    """:func:`verify_step` straight over the PAGED pool — the flash twin
    (``serve.flash_decode``): each stage scatters its K new K/V rows
    into their physical pages and hands attention to
    ``ops.pallas_kernels.paged_flash_verify``, which reads the pages in
    place with the same per-query live masking.  Returns
    ``(logits, kpool, vpool)`` (the new rows are already in the pool).
    Bitwise-equal to gather + :func:`verify_step` (pinned in
    tests/test_serve_spec.py)."""
    from ..ops.pallas_kernels import paged_flash_verify
    b, K = toks.shape
    ps = kpool.shape[2]
    hd = cfg.d_model // cfg.num_heads
    scale = 1.0 / math.sqrt(hd)
    tq = t[:, None] + jnp.arange(K)[None, :]               # (b, K)
    page = table[jnp.arange(b)[:, None], tq // ps]
    off = tq % ps
    state = {'k': kpool, 'v': vpool}

    def attend(i, p, q, k, v):
        kp = state['k'].at[i, page, off].set(k)
        vp = state['v'].at[i, page, off].set(v)
        state['k'], state['v'] = kp, vp
        return paged_flash_verify(q, kp[i], vp[i], table, t, w, scale)

    logits = _window_tokens(params, cfg, toks, attend)
    return logits, state['k'], state['v']


def _window_tokens(params, cfg: TransformerConfig, toks, attend):
    """The (b, K)-window block walk shared by :func:`verify_step` and
    :func:`verify_step_paged` — :func:`_decode_token`'s body widened to
    K tokens (same projection/FFN/head call sites, ``attend`` supplies
    the cache write + attention per stage), with the head applied to
    EVERY window position instead of just the last."""
    b, K = toks.shape
    hd = cfg.d_model // cfg.num_heads
    h = _rep(qtake(params['embed'], toks))
    for i in range(cfg.num_stages):
        p = jax.tree.map(lambda a, i=i: a[i], params['stages'])
        y = _layer_norm(h, p['ln1_scale'], p['ln1_bias'])
        q = qdot(y, p['wq']).reshape(b, K, cfg.num_heads, hd)
        k = qdot(y, p['wk']).reshape(b, K, cfg.num_heads, hd)
        v = qdot(y, p['wv']).reshape(b, K, cfg.num_heads, hd)
        attn = attend(i, p, q, k, v)
        h = h + _rep(qdot(_rep(attn.reshape(b, K, cfg.d_model)),
                          p['wo']))
        y2 = _layer_norm(h, p['ln2_scale'], p['ln2_bias'])
        h = h + _gen_ffn(cfg, p, y2, gather=True)
    return _rep(qdot(h, params['head'])).astype(jnp.float32)


def _decode_token(params, cfg: TransformerConfig, tok, attend):
    """THE per-token block walk — embed -> [ln1 -> qkv -> attend -> out
    proj -> ln2 -> ffn] per stage -> head.  ``attend(i, p, q, k, v)``
    supplies stage ``i``'s cache write + attention ((b, 1, heads, hd) in
    and out); :func:`decode_step` (dense cache) and
    :func:`decode_step_paged` (page pool + flash kernel) are both thin
    attend-closures over this one body, so the cache layouts cannot
    drift from each other or from the shared projection math."""
    b = tok.shape[0]
    hd = cfg.d_model // cfg.num_heads
    h = _rep(qtake(params['embed'], tok[:, None]))
    for i in range(cfg.num_stages):
        p = jax.tree.map(lambda a, i=i: a[i], params['stages'])
        y = _layer_norm(h, p['ln1_scale'], p['ln1_bias'])
        q = qdot(y, p['wq']).reshape(b, 1, cfg.num_heads, hd)
        k = qdot(y, p['wk']).reshape(b, 1, cfg.num_heads, hd)
        v = qdot(y, p['wv']).reshape(b, 1, cfg.num_heads, hd)
        attn = attend(i, p, q, k, v)
        h = h + _rep(qdot(_rep(attn.reshape(b, 1, cfg.d_model)),
                          p['wo']))
        y2 = _layer_norm(h, p['ln2_scale'], p['ln2_bias'])
        h = h + _gen_ffn(cfg, p, y2, gather=True)
    return _rep(qdot(h[:, -1], params['head'])).astype(jnp.float32)


def decode_step(params, cfg: TransformerConfig, tok, kc, vc, t, w):
    """One KV-cached decode step over a DENSE cache — the
    single-token-step entry the serve decode engine drives
    (serve/decode.py) and the body of ``generate``'s scan: one copy of
    the per-token block math, so the two cannot drift.

    ``tok``: (b,) int32, the token consumed this step.  ``kc``/``vc``:
    (num_stages, b, total, heads, hd) caches; this step's K/V is written
    at position ``t`` before attending.  ``t``/``w`` are traced values —
    scalars (every row at the same position: ``generate``) or (b,)
    vectors (per-row positions and pad widths: the decode engine's
    slots, each mid-stream at its own offset).  Cache positions outside
    ``[w, t]`` are masked out of the attention (the paged-attention
    masking rule: a slot's unwritten/bucket-pad positions never
    contribute).

    Returns ``(logits, kc, vc, knew, vnew)``: logits (b, vocab) float32
    for the next token, the updated caches, and knew/vnew
    (num_stages, b, heads, hd) — just the rows written at ``t`` (the
    paged engine scatters those into its page pool; ``generate`` keeps
    the dense caches and ignores them)."""
    total = kc.shape[2]
    b = tok.shape[0]
    hd = cfg.d_model // cfg.num_heads
    scale = 1.0 / math.sqrt(hd)
    per_row = jnp.ndim(t) > 0
    ar = jnp.arange(total)
    if per_row:
        live = ((ar[None, :] <= t[:, None])
                & (ar[None, :] >= w[:, None]))[:, None, None, :]
    else:
        # cache slots [0, w) hold bucket-pad K/V: never attended
        live = ((ar <= t) & (ar >= w))[None, None, None, :]
    state = {'kc': kc, 'vc': vc}
    knews, vnews = [], []

    def attend(i, p, q, k, v):
        kc, vc = state['kc'], state['vc']
        if per_row:
            kc = kc.at[i, jnp.arange(b), t].set(k[:, 0])
            vc = vc.at[i, jnp.arange(b), t].set(v[:, 0])
        else:
            kc = jax.lax.dynamic_update_slice(
                kc, k[None], (i, 0, t, 0, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v[None], (i, 0, t, 0, 0))
        state['kc'], state['vc'] = kc, vc
        ki, vi = kc[i], vc[i]
        # (b, heads, 1, total) scores over the cache
        s_ = jnp.einsum('bqhd,bkhd->bhqk', q, ki) * scale
        s_ = jnp.where(live, s_, -jnp.inf)
        knews.append(k[:, 0])
        vnews.append(v[:, 0])
        return jnp.einsum(
            'bhqk,bkhd->bqhd',
            jax.nn.softmax(s_.astype(jnp.float32),
                           axis=-1).astype(ki.dtype), vi)

    logits = _decode_token(params, cfg, tok, attend)
    return (logits, state['kc'], state['vc'], jnp.stack(knews),
            jnp.stack(vnews))


def decode_step_paged(params, cfg: TransformerConfig, tok, kpool, vpool,
                      table, t, w):
    """One decode step straight over the PAGED pool — the flash twin of
    :func:`decode_step` (``serve.flash_decode``, doc/serving.md "Flash
    paged decode").  Instead of gathering every slot's pages into a
    dense cache, each stage scatters the new K/V row into its physical
    page and hands attention to ``ops.pallas_kernels.paged_flash_decode``,
    which reads the pages in place via the page table.  ``t``/``w`` are
    (b,) per-slot vectors (this is an engine-only entry; ``generate``
    keeps the dense scan).  Returns ``(logits, kpool, vpool)`` — the new
    rows are already in the pool, so there is no knew/vnew leg.
    Bitwise-equal to gather + :func:`decode_step` by construction of the
    kernel's final softmax (pinned in tests/test_serve_decode.py)."""
    from ..ops.pallas_kernels import paged_flash_decode
    b = tok.shape[0]
    ps = kpool.shape[2]
    hd = cfg.d_model // cfg.num_heads
    scale = 1.0 / math.sqrt(hd)
    page = table[jnp.arange(b), t // ps]
    off = t % ps
    state = {'k': kpool, 'v': vpool}

    def attend(i, p, q, k, v):
        kp = state['k'].at[i, page, off].set(k[:, 0])
        vp = state['v'].at[i, page, off].set(v[:, 0])
        state['k'], state['v'] = kp, vp
        return paged_flash_decode(q[:, 0], kp[i], vp[i], table, t, w,
                                  scale)[:, None]

    logits = _decode_token(params, cfg, tok, attend)
    return logits, state['k'], state['v']


def _build_generate(cfg: TransformerConfig, b: int, s0: int,
                    max_new: int, temperature: float, eos_id=None):
    total = s0 + max_new
    hd = cfg.d_model // cfg.num_heads

    def pick(logits, r):
        if temperature > 0:
            return jax.random.categorical(r, logits / temperature,
                                          axis=-1)
        return jnp.argmax(logits, axis=-1)

    @jax.jit
    def run(params, prompt, keys, w):
        # --- prefill: full prompt in one pass, K/V captured per stage
        ks, vs, logits0 = prefill_kv(params, prompt, w, cfg)
        kc = jnp.zeros((cfg.num_stages, b, total, cfg.num_heads, hd),
                       ks.dtype)
        vc = jnp.zeros_like(kc)
        kc = kc.at[:, :, :s0].set(ks)
        vc = vc.at[:, :, :s0].set(vs)

        tok0 = pick(logits0, keys[0] if temperature > 0 else None)
        rngs = keys[1:]
        done0 = (tok0 == eos_id if eos_id is not None
                 else jnp.zeros((b,), bool))

        # --- decode: one token per scan step, attending over the cache
        def step(carry, inp):
            tok, done, kc, vc = carry
            t, r = inp
            logits, kc, vc, _, _ = decode_step(params, cfg, tok, kc, vc,
                                               t, w)
            nxt = pick(logits, r if temperature > 0 else None)
            if eos_id is not None:
                # a finished row keeps emitting eos (static shapes under
                # jit: the scan always runs max_new steps)
                nxt = jnp.where(done, eos_id, nxt)
                done = done | (nxt == eos_id)
            return (nxt, done, kc, vc), tok

        ts = jnp.arange(s0, total)
        _, toks = jax.lax.scan(step, (tok0, done0, kc, vc), (ts, rngs))
        # step j consumes generated token j and emits it; the carry's
        # final pick (token max_new) is past the requested horizon
        return toks.T

    return run


def reference_loss(params, tokens, labels, cfg: TransformerConfig):
    """Single-device oracle: same math, no mesh, sequential stages —
    including the weighted MoE balance loss the distributed step adds."""
    h = jnp.take(params['embed'], tokens, axis=0)
    balance = jnp.float32(0.0)
    for i in range(cfg.num_stages):
        p = jax.tree.map(lambda a: a[i], params['stages'])
        mb, s, d = h.shape
        mask = None
        if cfg.causal:
            mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
        h, y, _, _ = _stage_attn(p, h, cfg, mask)
        if cfg.num_experts:
            from ..parallel.moe import moe_ffn_reference
            ff, aux = moe_ffn_reference(y.reshape(mb * s, d), p['gate'],
                                        p['w1'], p['w2'],
                                        capacity_factor=cfg.capacity_factor)
            h = h + ff.reshape(mb, s, d)
            balance = balance + aux['balance_loss']
        else:
            h = h + jax.nn.relu(y @ p['w1']) @ p['w2']
    logits = (h @ params['head']).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1).mean()
    if cfg.num_experts:
        nll = nll + cfg.balance_loss_weight * balance
    return nll
