"""Trainer core: NetConfig grammar, graph executor, trainer, checkpoints."""

from .net import LabelInfo, Net
from .net_config import NetConfig
from .trainer import NetTrainer
