"""Model-blob serialization (checkpoint weight payload).

Mirrors the reference layout (``SaveModel`` per layer: LayerParam struct +
weight tensors, ``fullc_layer-inl.hpp:46-60``): the blob is the
concatenation of every non-shared layer's record, in layer order.  Weight
layouts on disk follow the reference conventions so tooling stays
interoperable:

* fullc ``wmat``: ``(nhidden, nin)`` (in-memory we keep ``(nin, nhidden)``),
* conv ``wmat``: ``(ngroup, nch/g, nin/g * kh * kw)`` im2col layout
  (in-memory HWIO),
* 1-D ``bias``/slope tensors unchanged.

Tensors are stored self-describing as (uint32 ndim, uint32 shape[ndim],
float32 data), matching mshadow's shape+data ``SaveBinary`` convention.
The LayerParam struct (328 bytes: 18 fields + 64 reserved ints,
``layer/param.h:15-76``) is written for layers that save it in the
reference (fullc, conv, bias, fixconn); batch_norm/prelu save tensors only.
"""

from __future__ import annotations

import contextlib
import os
import struct
from typing import Callable, Dict

import jax.numpy as jnp
import numpy as np

from ..layers import base as lbase

_LAYER_PARAM = struct.Struct('<ifif f iiiiiiiii iiii 64i')
assert _LAYER_PARAM.size == 328


def _pack_layer_param(p: lbase.LayerParam) -> bytes:
    return _LAYER_PARAM.pack(
        p.num_hidden, p.init_sigma, p.init_sparse, p.init_uniform,
        p.init_bias, p.num_channel, p.random_type, p.num_group,
        p.kernel_height, p.kernel_width, p.stride, p.pad_y, p.pad_x,
        p.no_bias, p.temp_col_max, p.silent, p.num_input_channel,
        p.num_input_node, *([0] * 64))


def _write_tensor(out: bytearray, arr: np.ndarray) -> None:
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    out += struct.pack('<I', arr.ndim)
    out += struct.pack(f'<{arr.ndim}I', *arr.shape)
    out += arr.tobytes()


def _read_tensor(buf: bytes, pos: int):
    (ndim,) = struct.unpack_from('<I', buf, pos)
    pos += 4
    shape = struct.unpack_from(f'<{ndim}I', buf, pos)
    pos += 4 * ndim
    n = int(np.prod(shape)) if ndim else 1
    arr = np.frombuffer(buf, np.float32, count=n, offset=pos).reshape(shape)
    pos += 4 * n
    return arr.copy(), pos


# layers whose reference SaveModel begins with the LayerParam struct
_SAVES_PARAM_STRUCT = {lbase.kFullConnect, lbase.kConv, lbase.kBias,
                       lbase.kFixConnect}


def layer_fields(type_id: int):
    """Field save order per layer type (reference SaveModel order)."""
    if type_id in (lbase.kFullConnect, lbase.kConv, lbase.kBatchNorm):
        return ('wmat', 'bias')
    if type_id in (lbase.kPRelu, lbase.kBias):
        return ('bias',)
    return ()


def to_disk_layout(type_id: int, field: str, arr: np.ndarray,
                   num_group: int) -> np.ndarray:
    if type_id == lbase.kFullConnect and field == 'wmat':
        return arr.T                                  # (nin,nh) → (nh,nin)
    if type_id == lbase.kConv and field == 'wmat':
        kh, kw, cin_g, cout = arr.shape
        g = num_group
        # HWIO → (g, cout/g, cin_g, kh, kw) → (g, cout/g, cin_g*kh*kw)
        a = arr.transpose(3, 2, 0, 1).reshape(g, cout // g, cin_g, kh, kw)
        return a.reshape(g, cout // g, cin_g * kh * kw)
    return arr


def from_disk_layout(type_id: int, field: str, arr: np.ndarray,
                     layer) -> np.ndarray:
    if type_id == lbase.kFullConnect and field == 'wmat':
        return arr.T
    if type_id == lbase.kConv and field == 'wmat':
        g, cout_g, flat = arr.shape
        p = layer.param
        cin_g = flat // (p.kernel_height * p.kernel_width)
        a = arr.reshape(g, cout_g, cin_g, p.kernel_height, p.kernel_width)
        return a.transpose(3, 4, 2, 0, 1).reshape(
            p.kernel_height, p.kernel_width, cin_g, g * cout_g)
    return arr


def host_params(params) -> Dict[str, Dict[str, np.ndarray]]:
    """Materialize a param tree on host — the device→host half of
    serialization, split out so an async save (runtime/async_ckpt.py) can
    run it on the background writer instead of the step loop."""
    return {k: {f: np.asarray(v) for f, v in d.items()}
            for k, d in params.items()}


def params_to_blob(net, params) -> bytes:
    return serialize_blob(net, host_params(params))


def serialize_blob(net, host: Dict[str, Dict[str, np.ndarray]]) -> bytes:
    """Serialize an already-host-resident param snapshot to the reference
    model blob layout — pure CPU work, safe on a background thread (reads
    only the net's static layer structure)."""
    out = bytearray()
    for i, info in enumerate(net.cfg.layers):
        if net.layer_primary[i] != i or info.type == lbase.kSharedLayer:
            continue
        layer = net.layers[i]
        fields = layer_fields(info.type)
        if not fields:
            continue
        if info.type in _SAVES_PARAM_STRUCT:
            out += _pack_layer_param(layer.param)
        lp = host.get(str(i), {})
        for f in fields:
            if f not in lp:   # e.g. no_bias fullc still saves a bias slot
                n = layer.param.num_channel or max(layer.param.num_hidden, 1)
                arr = np.zeros((n,), np.float32)
            else:
                arr = to_disk_layout(info.type, f, lp[f],
                                     layer.param.num_group)
            _write_tensor(out, arr)
    return bytes(out)


def blob_to_raw(cfg_layers, blob: bytes) -> Dict[str, Dict[str, np.ndarray]]:
    """Parse a blob into disk-layout arrays keyed by layer index/field."""
    params: Dict[str, Dict[str, np.ndarray]] = {}
    pos = 0
    for i, info in enumerate(cfg_layers):
        if info.type == lbase.kSharedLayer:
            continue
        fields = layer_fields(info.type)
        if not fields:
            continue
        if info.type in _SAVES_PARAM_STRUCT:
            pos += _LAYER_PARAM.size
        rec = {}
        for f in fields:
            arr, pos = _read_tensor(blob, pos)
            rec[f] = arr
        params[str(i)] = rec
    return params


def record_to_memory(layer, type_id: int,
                     rec: Dict[str, np.ndarray]) -> Dict:
    """Disk-layout record → in-memory param dict for a built layer."""
    out = {}
    for f, arr in rec.items():
        if f == 'bias' and layer.param.no_bias and \
                type_id in (lbase.kFullConnect, lbase.kConv):
            continue   # slot present on disk but unused in memory
        out[f] = jnp.asarray(from_disk_layout(type_id, f, arr, layer))
    return out


# --- fault-tolerant model-file I/O ---------------------------------------
#
# The reference SaveModel wrote straight through the destination handle: a
# crash mid-write left a truncated file under the final name, which a later
# ``continue=1`` scan happily loaded.  All model-file writes now go through
# write-to-temp + fsync + atomic rename (a reader can only ever observe a
# complete file), and both directions are wrapped in the configurable
# retry-with-backoff policy from ``runtime.faults`` (doc/fault_tolerance.md).


@contextlib.contextmanager
def atomic_write(path: str):
    """Open a temp file next to ``path`` for writing; on clean exit fsync
    it, atomically rename it over ``path``, and fsync the directory so the
    rename itself survives a crash.  On error the temp file is removed and
    ``path`` is untouched — a partially-written checkpoint is never
    visible under the final name."""
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f'.{os.path.basename(path)}.tmp.{os.getpid()}')
    try:
        with open(tmp, 'wb') as f:
            yield f
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        try:
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass   # directory fsync is best-effort (not all FSes allow it)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def save_model_file(path: str, write_fn: Callable, retry=None) -> str:
    """Atomically write a model file: ``write_fn(fileobj)`` produces the
    bytes; the whole write retries under ``retry`` (default
    ``faults.DEFAULT_IO_RETRY``), with each attempt first passing through
    the fault-injection hook so injected storage errors exercise the same
    retry path real ones take."""
    from ..runtime import faults
    retry = faults.DEFAULT_IO_RETRY if retry is None else retry

    def attempt():
        faults.checkpoint_write_attempt(path)
        with atomic_write(path) as f:
            write_fn(f)

    retry.call(attempt, op_name=f'save_model:{os.path.basename(path)}')
    return os.fspath(path)


def publish_model_file(path: str, write_fn: Callable, retry=None) -> str:
    """Atomic model-file publish for hot-reload watchers (the online
    pipeline's serving checkpoints, doc/online.md): like
    :func:`save_model_file` + :func:`write_model_digest`, but the digest
    sidecar is computed from the staged bytes and committed BEFORE the
    model file is renamed into place.  A watcher polling the directory
    can therefore never observe a model without its digest — the
    save-then-digest order of the train CLI leaves a brief no-sidecar
    window in which the registry's "unverified-but-plausible" policy
    would adopt the file unchecked.  The ``corrupt_model`` chaos hook
    fires on the STAGED file, between digest and rename, so an injected
    corruption is deterministically caught by digest verification —
    there is no instant at which the poisoned bytes are visible
    unverifiable."""
    import json

    from ..runtime import faults
    retry = faults.DEFAULT_IO_RETRY if retry is None else retry
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    tmp = os.path.join(d, f'.{os.path.basename(path)}.pub.{os.getpid()}')

    def attempt():
        faults.checkpoint_write_attempt(path)
        os.makedirs(d, exist_ok=True)
        try:
            with open(tmp, 'wb') as f:
                write_fn(f)
                f.flush()
                os.fsync(f.fileno())
            digest = {'size': os.path.getsize(tmp),
                      'crc32': file_crc32(tmp)}
            with atomic_write(model_digest_path(path)) as f:
                f.write(json.dumps(digest).encode())
            # chaos hook on the STAGED file: the digest above recorded
            # the good bytes, so a truncation here is caught by verify
            # the moment the file becomes visible
            faults.model_committed(path, staged=tmp)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    retry.call(attempt, op_name=f'publish_model:{os.path.basename(path)}')
    return path


def read_model_file(path: str, read_fn: Callable, retry=None):
    """Read a model file with retry: ``read_fn(fileobj)``'s return value is
    passed through.  A missing file raises immediately (not retryable —
    absence is a state, not a transient)."""
    from ..runtime import faults
    retry = faults.DEFAULT_IO_RETRY if retry is None else retry
    if not os.path.exists(path):
        raise FileNotFoundError(path)

    def attempt():
        with open(path, 'rb') as f:
            return read_fn(f)

    return retry.call(attempt, op_name=f'read_model:{os.path.basename(path)}')


def model_digest_path(path: str) -> str:
    return os.fspath(path) + '.crc32'


def file_crc32(path: str) -> int:
    """Chunked crc32 of a file's bytes."""
    import zlib
    crc = 0
    with open(path, 'rb') as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def write_model_digest(path: str) -> str:
    """Write the ``<model>.crc32`` integrity sidecar (JSON ``{size,
    crc32}``) next to a just-saved model file, atomically.  The model
    rename already guarantees *completeness*; the digest additionally
    catches silent byte corruption between writer and a hot-reloading
    reader (``serve/registry.py`` verifies it before swapping a new
    checkpoint into a live engine)."""
    import json

    from ..runtime import faults
    digest = {'size': os.path.getsize(path), 'crc32': file_crc32(path)}
    side = model_digest_path(path)
    with atomic_write(side) as f:
        f.write(json.dumps(digest).encode())
    # commit point for chaos drills: file + sidecar both durable — the
    # corrupt_model event truncates the model HERE so a hot-reloading
    # registry must catch the mismatch (runtime/faults.py)
    faults.model_committed(path)
    return side


def verify_model_digest(path: str):
    """Return None when ``path`` matches its digest sidecar (or no
    sidecar exists — unverified-but-plausible, the same policy as the
    sharded-checkpoint verifier), else a human-readable reason."""
    import json
    side = model_digest_path(path)
    if not os.path.exists(side):
        return None
    try:
        with open(side, 'rb') as f:
            digest = json.load(f)
        size = os.path.getsize(path)
    except (OSError, ValueError) as e:
        return f'unreadable digest sidecar: {e!r}'
    if not isinstance(digest, dict) \
            or not isinstance(digest.get('size'), int) \
            or not isinstance(digest.get('crc32'), int):
        # malformed-but-valid JSON must be a REASON, not a crash — the
        # registry blacklists on reasons; an escaping TypeError would
        # retry the broken sidecar forever
        return f'malformed digest sidecar: {digest!r}'
    if size != digest['size']:
        return f'size {size} != recorded {digest["size"]}'
    crc = file_crc32(path)
    if crc != digest['crc32']:
        return f'crc32 {crc:#x} != recorded {digest["crc32"]:#x}'
    return None


def blob_to_params(net, blob: bytes):
    raw = blob_to_raw(net.cfg.layers, blob)
    params = {}
    for i, info in enumerate(net.cfg.layers):
        key = str(i)
        if key not in raw:
            continue
        params[key] = record_to_memory(net.layers[i], info.type, raw[key])
    return params
