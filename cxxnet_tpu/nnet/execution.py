"""ExecutionPlan: ONE composable step loop for every training run.

PR 5 introduced ``steps_per_dispatch=K`` (one ``lax.scan`` dispatch per K
staged batches — zero per-step link RTT) but guarded it with a fallback
matrix that demoted to per-step whenever ``supervise``, ``update_period>1``
or ``eval_train`` metrics were on — i.e. on every production run.  This
module is the μ-cuDNN lesson (PAPERS.md) applied to the loop itself: the
fast path must COMPOSE with the real workload's constraints, not exclude
them.

* :class:`ExecutionPlan` resolves the requested K once per run into an
  effective plan.  The only remaining static demotions are profiling
  (``profile_dir`` — a trace window cannot bracket steps inside one
  dispatch) and ``test_io`` (nothing is dispatched at all); everything
  else — gradient accumulation, supervised recovery, train metrics,
  async saves — now rides the scan (``trainer.compile_multi_step``).
* :class:`WindowedStepper` is the loop body both the plain round and the
  supervised round drive: feed batches one at a time, it stages them
  (async H2D), dispatches a K-window (or per-step with the classic
  one-batch lookahead when K=1), and handles the one RUNTIME demotion —
  an ``attachtxt`` chain attaching ``extra_data`` mid-round — for the
  CURRENT round only (the next round re-probes; nothing is permanently
  mutated).
* ``scan_strict=1`` turns any demotion into a typed
  ``runtime.faults.ScanStrictError`` so production configs can assert
  they actually got the scanned path instead of discovering a silent
  10x dispatch-overhead regression in a dashboard.

``DEMOTION_REASONS`` is the programmatic registry of every way a plan can
demote; ``tests/test_execution_plan.py`` asserts it matches the documented
matrix in ``doc/trainer.md`` so the docs cannot silently rot.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..runtime import faults

#: Every way the scanned K-dispatch path can demote to per-step, keyed by
#: the reason tag `scan_strict` errors and fallback notes carry.  This IS
#: the fallback matrix (doc/trainer.md keeps the prose copy; a tier-1
#: drift test pins the two together).
DEMOTION_REASONS = {
    'profile_dir': 'the trace window brackets per-step dispatches — '
                   'inside a scanned window there is nothing to '
                   'start/stop the profiler between',
    'test_io': 'test_io=1 dispatches no compute at all',
    'extra_data': 'the scan body carries data+label+mask only; an '
                  'attachtxt chain\'s extra_data demotes this round '
                  '(re-probed next round)',
}

#: Reasons resolved once at plan creation (config/run shape) vs. detected
#: mid-round from the batch stream.
STATIC_REASONS = ('profile_dir', 'test_io')
RUNTIME_REASONS = ('extra_data',)


class ExecutionPlan:
    """The resolved step-loop shape for one training run.

    Build via :meth:`resolve`; then ask for one :class:`WindowedStepper`
    per round (:meth:`round_stepper`) — per-round steppers are what make
    the ``extra_data`` demotion a round property instead of a permanent
    trainer mutation.  Compiled multi-step programs are cached on the
    plan across rounds (keyed by (K, train_eval))."""

    def __init__(self, requested_k: int, k: int, strict: bool = False,
                 silent: bool = False):
        self.requested_k = int(requested_k)
        self.k = int(k)
        self.strict = bool(strict)
        self.silent = bool(silent)
        self._noted = set()
        self._scan_fns = {}

    @classmethod
    def resolve(cls, requested_k: int, profiling: bool = False,
                test_io: bool = False, strict: bool = False,
                silent: bool = False) -> 'ExecutionPlan':
        """Resolve the effective plan for this run.  Raises
        ``faults.ScanStrictError`` when ``strict`` and a static demotion
        applies; otherwise demotions print one note per reason."""
        k = max(1, int(requested_k))
        reason = None
        if k > 1:
            if test_io:
                reason = 'test_io'
            elif profiling:
                reason = 'profile_dir'
        plan = cls(requested_k=k, k=(1 if reason else k), strict=strict,
                   silent=silent)
        if reason is not None:
            plan.demote(reason)
        # the run's plan choice is /statusz state: one provider per
        # process, latest resolve wins (one plan per run by contract)
        from ..obs import get_hub
        get_hub().register_status(
            'execution_plan',
            lambda p=plan: {'requested_k': p.requested_k, 'k': p.k,
                            'scanned': p.scanned,
                            'demotions': sorted(p._noted),
                            # compiler truth (obs/programs.py): the
                            # per-step HLO flops of whatever program
                            # this plan is actually dispatching
                            'flops_per_step': p.flops_per_step()})
        return plan

    @property
    def scanned(self) -> bool:
        return self.k > 1

    def flops_per_step(self) -> float:
        """Ledger flops/step of the trainer this plan last built a
        stepper for (0.0 before the first round or first compile).
        analyzed_only: this renders on the /statusz endpoint thread,
        which must never block on a lazy AOT analysis probe — it
        reports 0.0 until the MFU line (or /programs) fills the
        entry."""
        trainer = getattr(self, '_trainer', None)
        if trainer is None:
            return 0.0
        return trainer.train_step_flops(analyzed_only=True)

    def demote(self, reason: str) -> None:
        """Register a demotion: typed error under ``scan_strict=1``,
        otherwise a once-per-reason stdout note (a run that demotes for
        reason A must still report a later, different reason B)."""
        if self.strict:
            raise faults.ScanStrictError(reason, DEMOTION_REASONS[reason])
        self.note(reason)

    def note(self, reason: str) -> Optional[str]:
        """The fallback note for ``reason`` — printed (unless silent) and
        returned the FIRST time each reason occurs, None after."""
        if reason in self._noted:
            return None
        self._noted.add(reason)
        msg = (f'steps_per_dispatch={self.requested_k} falls back to '
               f'per-step: {DEMOTION_REASONS[reason]}')
        if not self.silent:
            print(msg, flush=True)
        return msg

    def scan_fn(self, trainer, train_eval: bool):
        key = (self.k, bool(train_eval))
        if key not in self._scan_fns:
            self._scan_fns[key] = trainer.compile_multi_step(
                self.k, train_eval=train_eval)
        return self._scan_fns[key]

    def round_stepper(self, trainer, before_dispatch=None,
                      lookahead: int = 1) -> 'WindowedStepper':
        """A fresh stepper for one round's batches.  ``lookahead`` only
        shapes the per-step (K=1 / demoted) path: 1 = the classic
        one-batch H2D lookahead of the plain loop, 0 = dispatch
        immediately (the supervised loop, whose recovery re-winds by
        DISPATCHED steps and simply discards staged-but-undispatched
        work)."""
        self._trainer = trainer        # /statusz flops_per_step source
        scan = None
        if self.scanned:
            armed = bool(trainer.eval_train and len(trainer.train_metric))
            scan = self.scan_fn(trainer, armed)
        return WindowedStepper(trainer, k=self.k, scan_fn=scan,
                               lookahead=lookahead,
                               before_dispatch=before_dispatch,
                               on_demote=self.demote)


class WindowedStepper:
    """One round's step loop at window granularity — THE loop body.

    ``feed(batch)`` stages the batch (async H2D enqueue) and dispatches
    whenever a window fills; ``finish()`` drains the tail on the per-step
    path (bitwise-identical, so epoch length need not divide K).  With
    ``k=1`` it IS the per-step loop (with ``lookahead`` staged batches
    riding ahead of the dispatch), so plain, scanned, and supervised
    rounds all drive this one implementation.

    ``feed``/``finish`` return the number of updates dispatched by that
    call, so callers (the supervisor's periodic-save cadence) can detect
    window boundaries without peeking inside."""

    def __init__(self, trainer, k: int = 1, scan_fn=None,
                 lookahead: int = 1,
                 before_dispatch: Optional[Callable[[int], None]] = None,
                 on_demote: Optional[Callable[[str], None]] = None):
        if k > 1 and scan_fn is None:
            raise ValueError('k>1 needs a compile_multi_step scan_fn')
        self.trainer = trainer
        self.k = int(k)
        self.scan_fn = scan_fn
        self.lookahead = max(0, int(lookahead))
        self.before_dispatch = before_dispatch or (lambda _u: None)
        self.on_demote = on_demote or (lambda _reason: None)
        self.window = []
        self.updates = 0
        self.demoted = False

    def _step_one(self, staged) -> None:
        from ..obs import span
        self.before_dispatch(self.updates)
        with span('train.dispatch', 'train', k=1, update=self.updates):
            self.trainer.update_staged(staged)
        self.updates += 1

    def feed(self, batch) -> int:
        """Stage one batch; dispatch whatever became due.  Returns the
        updates applied by THIS call (0 while a window is filling)."""
        staged = self.trainer.stage_batch(batch)
        u0 = self.updates
        if self.k > 1 and not self.demoted and staged[2]:
            # extra_data (attachtxt): the scan body can't carry it —
            # demote THIS round only, mid-epoch, WITHOUT re-winding the
            # iterator (strict mode raises instead)
            self.demoted = True
            self.on_demote('extra_data')
            for st in self.window:
                self._step_one(st)
            self.window = []
        if self.k == 1 or self.demoted:
            self.window.append(staged)
            while len(self.window) > self.lookahead:
                self._step_one(self.window.pop(0))
        else:
            self.window.append(staged)
            if len(self.window) == self.k:
                # no tracer hook inside a window: profile_dir demotes at
                # resolve time (a trace window can't bracket steps inside
                # one dispatch).  The span brackets the DISPATCH (host
                # enqueue of one scanned window), never a step inside
                # it — which is why it composes where profile_dir must
                # demote (doc/observability.md)
                from ..obs import span
                with span('train.dispatch', 'train', k=self.k,
                          update=self.updates):
                    self.trainer.update_staged_window(self.scan_fn,
                                                      self.window)
                self.updates += self.k
                self.window = []
        return self.updates - u0

    def finish(self) -> int:
        """Drain staged-but-undispatched batches per-step (the short
        epoch tail, or the K=1 lookahead's last batch).  Returns the
        updates applied."""
        u0 = self.updates
        window, self.window = self.window, []
        for st in window:
            self._step_one(st)
        return self.updates - u0

    def discard(self) -> None:
        """Drop staged-but-undispatched batches without dispatching —
        for callers whose step budget is already met (the supervisor's
        ``n_steps`` break)."""
        self.window = []


def measured_probe(trainer, requested_k: int, batches,
                   repeats: int = 2) -> float:
    """One grafttune stage-2 measurement: steps/sec of THIS trainer
    driving the REAL plan/stepper path at ``requested_k``.

    Resolves a silent plan (a probe must not spam fallback notes or
    register itself as the run's /statusz plan choice... it does —
    latest-resolve-wins means the tuner's final resolve at the chosen K
    leaves the right plan registered), runs one untimed warm-up pass
    over ``batches`` (compiles the scan program outside the clock), then
    times ``repeats`` full passes and returns the BEST pass's
    updates/sec — min-wall over repeats, the same noise policy as
    bench.py.  The trainer's params advance (probes are measurement,
    not state management); callers that need pristine params snapshot
    and restore around the sweep."""
    import time as _time
    plan = ExecutionPlan.resolve(requested_k, strict=False, silent=True)

    def one_pass() -> int:
        stepper = plan.round_stepper(trainer, lookahead=0)
        done = 0
        for b in batches:
            done += stepper.feed(b)
        return done + stepper.finish()

    one_pass()                          # warm-up: compile outside the clock
    best = float('inf')
    updates = 0
    for _ in range(max(1, int(repeats))):
        t0 = _time.perf_counter()
        updates = one_pass()
        best = min(best, _time.perf_counter() - t0)
    if best <= 0 or updates <= 0:
        raise faults.TuneProbeError(
            f'k={requested_k}', RuntimeError('probe produced no updates'))
    return updates / best
