"""Inference-time conv+BN folding (the graftfuse DAG rewrite).

At serving time a BatchNorm behind a convolution is an affine map the
conv can absorb: ``w' = w * scale`` (output-channel axis) and
``b' = b * scale + shift`` with ``scale = gamma/sqrt(var+eps)``,
``shift = beta - mean*scale`` (layers/norm.py ``fold_scale_shift``).
One HLO op replaces three, and the PredictEngine's ProgramLedger entry
shows the fused program's compiler-truth flops/bytes (`/programs`).

**The frozen-stats caveat.**  This codebase reproduces the reference's
BatchNorm exactly, and the reference keeps NO running averages —
evaluation normalizes with *current-minibatch* statistics
(doc/layer.md:258 parity quirk).  A static fold therefore cannot equal
the live BN on arbitrary batches; it must **freeze** the statistics of
one calibration batch at fold time.  The pass runs the unfused net once
on the calibration batch, captures each BN's input, folds its
batch statistics into the conv, and then **proves** the rewrite: the
folded forward (BN retired to a pass-through via ``Net.forward``'s
``identity_layers``) must match the unfused forward on the calibration
batch within the pinned ``FOLD_RTOL``/``FOLD_ATOL`` — never looser at a
call site (the PR 10 quant rule) — or ``FoldError`` is raised and the
caller keeps the unfused graph.  On any *other* batch the folded net is
a fixed-statistics approximation; that is a semantic choice the serving
layer opts into explicitly (``serve.fold_bn=1``), not a silent default.

The params tree keeps its treedef: the BN's (now unused) slope/bias
stay in place, so checkpoint loading, hot-swap shape checks, and the
quantizer all see the structure they expect.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..layers import ForwardContext
from ..layers.conv import ConvolutionLayer
from ..layers.norm import BatchNormLayer, fold_scale_shift

#: pinned fold-vs-unfused equality tolerances on the calibration batch
#: (f32 serving): scaling w before the conv vs scaling the conv's output
#: reorders one multiply against the reduction, so equality is pinned
#: here, once, and asserted by the pass itself AND the tests/bench.
FOLD_RTOL = 1e-4
FOLD_ATOL = 1e-5


class FoldError(RuntimeError):
    """The folded forward failed its pinned equality proof."""


def plan_conv_bn_pairs(net) -> List[Tuple[int, int]]:
    """Statically find foldable (conv, batch_norm) layer pairs.

    Eligibility: a 1-in/1-out conv **with a bias** (the fold needs a
    bias to absorb the shift without changing the params treedef) whose
    output (node, version) is read by exactly ONE layer — a 1-in/1-out
    BatchNorm — with neither layer's params shared (folding shared
    weights would corrupt the other use site).
    """
    pairs: List[Tuple[int, int]] = []
    reads, writes = net._node_version_maps()
    readers: Dict[tuple, List[int]] = {}
    for i, rs in enumerate(reads):
        for nv in rs:
            readers.setdefault(nv, []).append(i)
    shared = {p for p in net.layer_primary
              if net.layer_primary.count(p) > 1}
    for i, layer in enumerate(net.layers):
        if not isinstance(layer, ConvolutionLayer):
            continue
        info = net.cfg.layers[i]
        if (layer.param.no_bias != 0 or len(info.nindex_in) != 1
                or len(info.nindex_out) != 1 or i in shared
                or net.layer_primary[i] != i):
            continue
        out_nv = next(iter(writes[i]))
        rd = readers.get(out_nv, [])
        if len(rd) != 1:
            continue
        b = rd[0]
        binfo = net.cfg.layers[b]
        if (isinstance(net.layers[b], BatchNormLayer)
                and len(binfo.nindex_in) == 1
                and len(binfo.nindex_out) == 1
                and b not in shared and net.layer_primary[b] == b):
            pairs.append((i, b))
    return pairs


def _top_node(net) -> int:
    return net.cfg.layers[-1].nindex_out[-1]


def fold_params(net, params, calib_batch, *, compute_dtype=jnp.float32,
                extra_data=None, verify: bool = True):
    """Fold every plannable conv+BN pair of ``net`` into new params.

    Runs the unfused forward once on ``calib_batch`` (eager, eval mode)
    to capture each BN's input, freezes its minibatch statistics into
    the preceding conv's weights/bias, and (unless ``verify=False``)
    proves the folded forward equal to the unfused one on the same
    batch within the pinned tolerances.

    Returns ``(folded_params, report)`` where ``report`` carries the
    folded pair names, the retired BN layer indices (feed them to
    ``Net.forward(identity_layers=...)``), and the measured proof error.
    A net with no foldable pairs returns the params unchanged.
    """
    pairs = plan_conv_bn_pairs(net)
    report = {'pairs': [], 'bn_layers': frozenset(),
              'max_abs_err': 0.0, 'rtol': FOLD_RTOL, 'atol': FOLD_ATOL}
    if not pairs:
        return params, report
    ctx = ForwardContext(is_train=False, rng=jax.random.PRNGKey(0),
                         compute_dtype=compute_dtype)
    capture = {b: None for (_, b) in pairs}
    values, _ = net.forward(params, calib_batch, ctx,
                            extra_data=extra_data, capture=capture)
    folded = {k: dict(v) for k, v in params.items()}
    for conv_i, bn_i in pairs:
        bn = net.layers[bn_i]
        xin = capture[bn_i][0].astype(jnp.float32)
        axes = tuple(range(xin.ndim - 1))
        # EXACTLY BatchNormLayer.forward's statistics spelling
        mean = jnp.mean(xin, axis=axes)
        var = jnp.mean((xin - mean) ** 2, axis=axes)
        bp = params[str(bn_i)]
        scale, shift = fold_scale_shift(
            bp['wmat'].astype(jnp.float32), bp['bias'].astype(jnp.float32),
            mean, var, bn.eps)
        cp = params[str(conv_i)]
        w, b = cp['wmat'], cp['bias']
        folded[str(conv_i)]['wmat'] = (
            w.astype(jnp.float32) * scale).astype(w.dtype)
        folded[str(conv_i)]['bias'] = (
            b.astype(jnp.float32) * scale + shift).astype(b.dtype)
        report['pairs'].append(
            (net.cfg.layers[conv_i].name or str(conv_i),
             net.cfg.layers[bn_i].name or str(bn_i)))
    bn_layers = frozenset(b for (_, b) in pairs)
    report['bn_layers'] = bn_layers
    if verify:
        fvalues, _ = net.forward(folded, calib_batch, ctx,
                                 extra_data=extra_data,
                                 identity_layers=bn_layers)
        top = _top_node(net)
        ref, got = values[top], fvalues[top]
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        bound = FOLD_ATOL + FOLD_RTOL * float(
            jnp.max(jnp.abs(ref.astype(jnp.float32))))
        report['max_abs_err'] = err
        if err > bound:
            raise FoldError(
                f'conv+BN fold failed its equality proof on the '
                f'calibration batch: max|Δ|={err:.3e} > {bound:.3e} '
                f'(pinned rtol={FOLD_RTOL}, atol={FOLD_ATOL}) for pairs '
                f'{report["pairs"]}')
    return folded, report
