"""Functional neural-net graph executor.

The TPU-native replacement for the reference's per-device mutable replica
(``src/nnet/neural_net-inl.hpp:22-250``): where the reference allocates node
tensors and sweeps Forward/Backprop over connections in place, this builds a
**pure function** of ``(params, batch, labels, rng)`` that XLA compiles into
one fused program.  Backward comes from ``jax.grad`` of the summed loss —
per-layer hand-written gradients are unnecessary because every loss layer's
scalar is constructed so its autodiff gradient equals the reference's
hand-set one (see layers/loss.py).

Layout: activations are NHWC; the input node accepts NCHW host batches
(the reference/data-pipeline layout) and transposes once on device.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import sys

import jax
import jax.numpy as jnp
import numpy as np

from ..layers import ForwardContext, NodeSpec, create_layer
from ..layers.base import kSharedLayer, Layer
from ..layers.common import SplitLayer
from ..layers.loss import LossLayerBase
from .net_config import NetConfig

Params = Dict[str, Dict[str, jax.Array]]


class LabelInfo:
    """Named label-field views over the raw label matrix
    (``layer/layer.h:77-121`` + slicing at ``nnet_impl-inl.hpp:271-285``)."""

    def __init__(self, label_mat, name_map: Dict[str, int],
                 ranges: List[tuple]):
        self._mat = label_mat
        self._name_map = name_map
        self._ranges = ranges

    def field(self, name: str):
        if name not in self._name_map:
            raise KeyError(f'unknown label target = {name}')
        a, b = self._ranges[self._name_map[name]]
        return self._mat[:, a:b]


class Net:
    """A compiled-graph view of a NetConfig."""

    def __init__(self, cfg: NetConfig):
        self.cfg = cfg
        self.layers: List[Layer] = []
        self.layer_primary: List[int] = []   # index of the params owner
        # instantiate layers; shared entries alias the primary layer object
        # (neural_net-inl.hpp:216-250)
        for i, info in enumerate(cfg.layers):
            if info.type == kSharedLayer:
                primary = cfg.layers[info.primary_layer_index]
                layer = self.layers[info.primary_layer_index]
                if not layer.allow_sharing():
                    raise ValueError(
                        f'layer {primary.name} does not allow sharing')
                self.layers.append(layer)
                self.layer_primary.append(info.primary_layer_index)
            else:
                self.layers.append(create_layer(info.type, name=info.name))
                self.layer_primary.append(i)
        # configure: global defaults first, then layer-scoped pairs
        # (neural_net-inl.hpp:252-264)
        for i, layer in enumerate(self.layers):
            if self.layer_primary[i] != i:
                continue
            for name, val in cfg.defcfg:
                layer.set_param(name, val)
            for name, val in cfg.layercfg[i]:
                layer.set_param(name, val)
        # split layers need their fan-out before shape inference
        for i, info in enumerate(cfg.layers):
            if isinstance(self.layers[i], SplitLayer):
                self.layers[i].set_num_outputs(len(info.nindex_out))
        self._infer_shapes()
        self._build_sibling_fusion()
        self._build_blockdiag_fusion()
        self._build_convact_fusion()

    # --- horizontal fusion ------------------------------------------------
    def _build_sibling_fusion(self) -> None:
        """Group sibling 1x1 convolutions for horizontally fused execution.

        Inception-style towers launch several small 1x1 convs off the same
        trunk node (``concat_layer-inl.hpp:55-78`` context); each is a
        skinny matmul whose output-channel count (16..96) underfills the
        128-wide MXU.  Executing one conv with the weights concatenated
        along the output axis and splitting the result is mathematically
        identical per output channel (each column's contraction is
        unchanged) and fills the systolic array.  Eligibility: ungrouped
        1x1, stride 1, no padding, single in/out, homogeneous bias-ness.
        Disable with ``fuse_siblings = 0``.
        """
        from ..layers.conv import ConvolutionLayer
        enabled = 1
        tp = 1
        for name, val in self.cfg.defcfg:
            if name == 'fuse_siblings':
                enabled = int(val)
            if name == 'tensor_parallel':
                tp = int(val)
        self._sibling_groups: Dict[int, List[int]] = {}
        if not enabled or tp > 1:
            # under tensor parallelism the member wmats are sharded on
            # exactly the axis fusion concatenates (mesh.py
            # P(None,None,None,'model')), and member widths don't align
            # to shard boundaries — fusing would force GSPMD to
            # all-gather what the col/row pairing keeps sharded
            return
        groups: Dict[tuple, List[int]] = {}
        for i, info in enumerate(self.cfg.layers):
            layer = self.layers[i]
            if not isinstance(layer, ConvolutionLayer):
                continue
            p = layer.param
            if (p.kernel_height, p.kernel_width, p.stride, p.pad_y,
                    p.pad_x, p.num_group) != (1, 1, 1, 0, 0, 1):
                continue
            if len(info.nindex_in) != 1 or len(info.nindex_out) != 1:
                continue
            groups.setdefault((info.nindex_in[0], p.no_bias), []).append(i)
        for (node, _), members in groups.items():
            if len(members) < 2:
                continue
            # the grouping is sound only if the input node keeps ONE value
            # across the group's span: the config language allows in-place
            # rewrites (layer[a->a] = ...), after which a later member
            # would legally read the REWRITTEN value while the fused conv
            # ran on the old one.  Reject the group if any layer within
            # [first, last] member positions writes the node.
            lo, hi = members[0], members[-1]
            rewritten = any(
                node in self.cfg.layers[w].nindex_out
                for w in range(lo, hi + 1))
            if rewritten:
                continue
            for m in members:
                self._sibling_groups[m] = members

    def _fused_sibling_outputs(self, params: Params, x, members: List[int]):
        """One 1x1 conv over the concatenated weights, split back into the
        member layers' outputs (same order)."""
        widths = [self.layers[m].param.num_channel for m in members]
        w = jnp.concatenate(
            [self._layer_params(params, m)['wmat'] for m in members],
            axis=3).astype(x.dtype)
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding=((0, 0), (0, 0)),
            dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
        if self.layers[members[0]].param.no_bias == 0:
            b = jnp.concatenate(
                [self._layer_params(params, m)['bias'] for m in members]
            ).astype(x.dtype)
            out = out + b
        out = out.astype(x.dtype)
        splits = np.cumsum(widths)[:-1]
        return jnp.split(out, splits, axis=-1)

    # --- cross-input block-diagonal fusion --------------------------------
    def _build_blockdiag_fusion(self) -> None:
        """Fuse convolutions that read DIFFERENT inputs into one wide conv
        with a block-diagonal weight.

        Sibling fusion (above) only reaches convs sharing a trunk node; the
        remaining narrow convs in an inception module (the 3x3/5x5 tower
        convs, the pool projection) each consume their own reduce output,
        so their 16..128-wide outputs underfill the 128-lane MXU per pass
        no matter the batch (BASELINE.md "Why GoogLeNet sits at MFU 0.15").
        Concatenating the inputs channel-wise and embedding each member's
        weight as a diagonal block (smaller kernels zero-padded spatially
        into the group's max kernel, with input padding grown to match)
        computes exactly the same outputs while filling the array — at the
        cost of the zero blocks' redundant FLOPs, which is why this is
        OFF by default and flipped per measured receipt only.

        ``fuse_blockdiag = in3a_3x3+in3a_5x5;in3b_3x3+in3b_5x5`` names the
        groups explicitly (layer names, ``+`` within a group, ``;`` between
        groups).  Members must be ungrouped single-in/single-out convs with
        equal stride and bias-ness, equal input spatial dims, and a shared
        ``2*pad - kernel`` extent on each axis (which makes the padded
        output grids coincide).  Because config order may interleave a
        member's producers between the members (the builder emits reduce
        convs lazily), the execution order is re-scheduled to make group
        members contiguous; a node-version simulation validates that the
        reorder preserves the reference's sequential in-place semantics
        (``layer[a->a]`` rewrites) exactly, and raises otherwise.
        """
        from ..layers.conv import ConvolutionLayer
        spec_str, tp = '0', 1
        for name, val in self.cfg.defcfg:
            if name == 'fuse_blockdiag':
                spec_str = str(val).strip()
            if name == 'tensor_parallel':
                tp = int(val)
        self._blockdiag_groups: Dict[int, List[int]] = {}
        self._exec_order: List[int] = list(range(len(self.cfg.layers)))
        if spec_str in ('', '0'):
            return
        if tp > 1:
            # unlike sibling fusion (default-on, silently skipped), this
            # spec is explicit opt-in: refusing loudly keeps a "fused"
            # receipt from actually measuring the unfused plan
            raise ValueError(
                'fuse_blockdiag is incompatible with tensor_parallel>1 '
                '(member wmats are sharded on the output-channel axis the '
                'fusion concatenates); remove one of the two settings')
        reads, writes = self._node_version_maps()
        if spec_str == 'auto' or spec_str.startswith('auto:'):
            # auto:<maxwidth> — one candidate group per concat layer: the
            # member convs feeding it whose output width <= maxwidth
            # (the MXU-underfilling towers).  Groups that fail any
            # eligibility/schedule check are skipped, not fatal — auto
            # must hold on arbitrary nets.  Default maxwidth 96: <128
            # lanes AND at/below the narrowest width class the GoogLeNet
            # breakdown receipt can indict.
            if ':' in spec_str:
                try:
                    maxw = int(spec_str.split(':', 1)[1])
                except ValueError:
                    raise ValueError(
                        f'fuse_blockdiag: bad auto maxwidth in '
                        f'{spec_str!r} — use auto or auto:<int>') from None
            else:
                maxw = 96
            for members in self._auto_blockdiag_candidates(
                    ConvolutionLayer, writes, maxw):
                self._register_blockdiag_group(
                    members, ConvolutionLayer, reads, writes, strict=False)
        else:
            byname: Dict[str, int] = {}
            for i, info in enumerate(self.cfg.layers):
                if info.name and info.name not in byname:
                    byname[info.name] = i
            for gspec in spec_str.split(';'):
                names = [s.strip() for s in gspec.split('+') if s.strip()]
                if len(names) < 2:
                    raise ValueError(
                        f'fuse_blockdiag: group {gspec!r} needs >=2 '
                        f'layer names')
                members = []
                for nm in names:
                    if nm not in byname:
                        raise ValueError(
                            f'fuse_blockdiag: no layer named {nm!r}')
                    members.append(byname[nm])
                self._register_blockdiag_group(
                    sorted(members), ConvolutionLayer, reads, writes,
                    strict=True)
        self._verify_blockdiag_final(reads, writes)
        # a fusion receipt must be able to tell "measured" from "never
        # engaged": with the knob set, say what actually formed (lands in
        # the committed bench .log next to the receipt JSON)
        groups = self._blockdiag_group_set()
        print(f'fuse_blockdiag={spec_str}: {len(groups)} group(s) formed'
              + ('' if groups else ' — NO fusion engaged'),
              file=sys.stderr)

    def _blockdiag_group_set(self):
        """The distinct groups (each member maps to its whole group)."""
        return {tuple(g) for g in self._blockdiag_groups.values()}

    def _register_blockdiag_group(self, members, conv_cls, reads, writes,
                                  strict: bool) -> None:
        """Validate + schedule one group; ``strict`` raises on failure
        (explicit specs fail loud), else the group is skipped."""
        try:
            self._check_blockdiag_group(members, conv_cls, reads, writes)
            new_order = self._reorder_contiguous(
                self._exec_order, members, reads, writes)
            for m in members:
                if m in self._blockdiag_groups:
                    raise ValueError(
                        f'fuse_blockdiag: layer '
                        f'{self.cfg.layers[m].name!r} appears in two '
                        f'groups')
        except ValueError:
            if strict:
                raise
            return
        self._exec_order = new_order
        for m in members:
            self._blockdiag_groups[m] = members

    def _auto_blockdiag_candidates(self, conv_cls, writes, maxw: int):
        """One candidate group per concat layer: the convs producing its
        input nodes (through in-place activations) with output width
        <= maxw, not already sibling-fused."""
        producer: Dict[int, int] = {}     # node -> conv layer writing v1
        for i, layer in enumerate(self.layers):
            if isinstance(layer, conv_cls):
                for (n, v) in writes[i]:
                    if v == 1:
                        producer[n] = i
        for i, info in enumerate(self.cfg.layers):
            if self.layers[i].type_name not in ('concat', 'ch_concat'):
                continue
            members = []
            for n in info.nindex_in:
                m = producer.get(n)
                if (m is None or m in self._sibling_groups
                        or m in self._blockdiag_groups):
                    continue
                if self.layers[m].param.num_channel <= maxw:
                    members.append(m)
            if len(members) >= 2:
                yield sorted(members)

    def _node_version_maps(self):
        """Per-layer (node, version) read/write sets under the sequential
        config-order semantics; versions count in-place rewrites."""
        ver: Dict[int, int] = {}
        reads, writes = [], []
        for info in self.cfg.layers:
            reads.append(frozenset((n, ver.get(n, 0))
                                   for n in info.nindex_in))
            w = set()
            for n in info.nindex_out:
                ver[n] = ver.get(n, 0) + 1
                w.add((n, ver[n]))
            writes.append(frozenset(w))
        return reads, writes

    def _check_blockdiag_group(self, members, conv_cls, reads, writes):
        layers = [self.layers[m] for m in members]
        infos = [self.cfg.layers[m] for m in members]
        for m, l, info in zip(members, layers, infos):
            if not isinstance(l, conv_cls):
                raise ValueError(
                    f'fuse_blockdiag: layer {info.name!r} is not a conv')
            if (l.param.num_group != 1 or len(info.nindex_in) != 1
                    or len(info.nindex_out) != 1):
                raise ValueError(
                    f'fuse_blockdiag: {info.name!r} must be an ungrouped '
                    f'1-in/1-out conv')
            if m in self._sibling_groups:
                # explicit blockdiag spec wins: dissolve the sibling group
                for s in self._sibling_groups.pop(m):
                    self._sibling_groups.pop(s, None)
        p0 = layers[0].param
        for l, info in zip(layers[1:], infos[1:]):
            p = l.param
            if p.stride != p0.stride or p.no_bias != p0.no_bias:
                raise ValueError(
                    f'fuse_blockdiag: {info.name!r} stride/bias mismatch')
            if (2 * p.pad_y - p.kernel_height
                    != 2 * p0.pad_y - p0.kernel_height
                    or 2 * p.pad_x - p.kernel_width
                    != 2 * p0.pad_x - p0.kernel_width):
                raise ValueError(
                    f'fuse_blockdiag: {info.name!r} output grid mismatch '
                    f'(2*pad-kernel must match across the group)')
        s0 = self.node_specs[infos[0].nindex_in[0]]
        for info in infos[1:]:
            s = self.node_specs[info.nindex_in[0]]
            if (s.y, s.x) != (s0.y, s0.x):
                raise ValueError(
                    f'fuse_blockdiag: {info.name!r} input spatial mismatch')
        # chain fusion is semantically different (members run on the
        # group's shared pre-state): no member may feed another member
        member_writes = frozenset().union(*(writes[m] for m in members))
        for m, info in zip(members, infos):
            if reads[m] & member_writes:
                raise ValueError(
                    f'fuse_blockdiag: {info.name!r} consumes another '
                    f'member\'s output — chain fusion is not supported')

    def _verify_blockdiag_final(self, reads, writes) -> None:
        """Cross-group safety net: a LATER group's reorder re-schedules the
        whole order and could split an earlier group's members apart — and
        the per-layer version validator cannot see that, because the fused
        execution reads ALL member inputs at the first member's exec
        position (not each member's own).  Re-verify every group against
        the FINAL order: members contiguous, every input version produced
        before the group starts, and no rewriter of an input node runs
        before the group starts."""
        pos = {l: k for k, l in enumerate(self._exec_order)}
        for members in self._blockdiag_group_set():
            names = [self.cfg.layers[m].name for m in members]
            ps = sorted(pos[m] for m in members)
            if ps != list(range(ps[0], ps[-1] + 1)):
                raise ValueError(
                    f'fuse_blockdiag: groups {names} were torn apart by a '
                    'later group\'s reorder — no safe combined schedule; '
                    'reorder or split the group specs')
            start = ps[0]
            need = set().union(*(reads[m] for m in members))
            for l in range(len(self.cfg.layers)):
                for (n, v) in writes[l]:
                    for (n2, v2) in need:
                        if n != n2:
                            continue
                        if v <= v2 and pos[l] >= start:
                            raise ValueError(
                                f'fuse_blockdiag: group {names} input is '
                                'not produced before the fused execution '
                                'point in the combined schedule')
                        if v > v2 and pos[l] < start:
                            raise ValueError(
                                f'fuse_blockdiag: group {names} would read '
                                'a stale in-place-rewritten input in the '
                                'combined schedule')

    def _reorder_contiguous(self, order, members, reads, writes):
        """Move the non-member layers between the group's members out of
        the way (dependents after, independents before), then verify the
        new order replays the exact same node-version reads/writes as
        config order."""
        pos = {l: k for k, l in enumerate(order)}
        lo = min(pos[m] for m in members)
        hi = max(pos[m] for m in members)
        seg = [order[k] for k in range(lo, hi + 1) if order[k] not in members]
        # version-aware dependence closure: the members (plus anything
        # transitively forced after them) form a "moved-later" set; a
        # segment layer must follow it iff it (a) reads a version the set
        # writes, (b) rewrites a node past a version the set still reads,
        # or (c) writes a later version of a node the set writes.  Node
        # versions give the direction — a producer of a member's input
        # writes an EARLIER version and correctly stays in front.
        after: List[int] = []
        after_reads = set().union(*(reads[m] for m in members))
        after_writes = set().union(*(writes[m] for m in members))
        before: List[int] = []
        for l in seg:
            true_dep = bool(set(reads[l]) & after_writes)
            anti_dep = any(n1 == n2 and v2 < v1
                           for (n1, v1) in writes[l]
                           for (n2, v2) in after_reads)
            ww_dep = any(n1 == n2 and v2 < v1
                         for (n1, v1) in writes[l]
                         for (n2, v2) in after_writes)
            if true_dep or anti_dep or ww_dep:
                after.append(l)
                after_reads |= set(reads[l])
                after_writes |= set(writes[l])
            else:
                before.append(l)
        new_order = (order[:lo] + before + sorted(members, key=pos.get)
                     + after + order[hi + 1:])
        # full semantic validation: every layer must read/write the same
        # node versions as in config order
        ver: Dict[int, int] = {}
        for l in new_order:
            info = self.cfg.layers[l]
            got_r = frozenset((n, ver.get(n, 0)) for n in info.nindex_in)
            got_w = set()
            for n in info.nindex_out:
                ver[n] = ver.get(n, 0) + 1
                got_w.add((n, ver[n]))
            if got_r != reads[l] or frozenset(got_w) != writes[l]:
                raise ValueError(
                    'fuse_blockdiag: no safe schedule exists for group '
                    f'{[self.cfg.layers[m].name for m in members]} — layer '
                    f'{info.name or l!r} would observe different node '
                    'versions after the reorder')
        return new_order

    def _fused_blockdiag_outputs(self, params: Params, values,
                                 members: List[int]):
        """One conv over channel-concatenated inputs and a block-diagonal
        weight, split back into the member layers' outputs."""
        infos = [self.cfg.layers[m] for m in members]
        layers = [self.layers[m] for m in members]
        xs = [values[info.nindex_in[0]] for info in infos]
        x = jnp.concatenate(xs, axis=-1)
        kh = max(l.param.kernel_height for l in layers)
        kw = max(l.param.kernel_width for l in layers)
        p0 = layers[0].param
        ph = p0.pad_y + (kh - p0.kernel_height) // 2
        pw = p0.pad_x + (kw - p0.kernel_width) // 2
        cins = [v.shape[-1] for v in xs]
        couts = [l.param.num_channel for l in layers]
        w = jnp.zeros((kh, kw, sum(cins), sum(couts)), x.dtype)
        ci = co = 0
        for l, m, cin in zip(layers, members, cins):
            wm = self._layer_params(params, m)['wmat'].astype(x.dtype)
            oh = (kh - l.param.kernel_height) // 2
            ow = (kw - l.param.kernel_width) // 2
            w = w.at[oh:oh + l.param.kernel_height,
                     ow:ow + l.param.kernel_width,
                     ci:ci + cin, co:co + l.param.num_channel].set(wm)
            ci += cin
            co += l.param.num_channel
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(p0.stride, p0.stride),
            padding=((ph, ph), (pw, pw)),
            dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
        if p0.no_bias == 0:
            b = jnp.concatenate(
                [self._layer_params(params, m)['bias'] for m in members]
            ).astype(x.dtype)
            out = out + b
        out = out.astype(x.dtype)
        splits = np.cumsum(couts)[:-1]
        return jnp.split(out, splits, axis=-1)

    # --- vertical conv+bias+act fusion ------------------------------------
    def _build_convact_fusion(self) -> None:
        """Pair eligible conv layers with their exclusive in-place relu
        reader for the fused Pallas conv+bias+act block
        (``ops/pallas_cnn.py``; ``fuse = auto|1|0`` net param, default
        auto — the tri-state ``pallas_mode()`` gate decides at trace
        time via ``conv_use_fused``).

        Pairing is static and conservative: the conv must be an
        ungrouped-or-grouped 1-in/1-out conv on the native lowering with
        ``micro_batch=1`` (microbatching and the fused block are
        mutually exclusive — the fused kernel has its own tiling), not a
        member of a sibling/blockdiag group, and its output
        (node, version) must be read by exactly ONE layer: a 1-in/1-out
        relu that rewrites the node **in place** (``layer[a->a]``).  The
        in-place restriction keeps ``node_values`` observably identical
        to the unfused run — a non-in-place relu would leave the conv's
        node holding an activated value the unfused graph never writes
        there.  ``fuse=1`` additionally routes unpaired eligible convs
        through the fused block with an identity activation
        (``_convact_solo``) — the forced mode IS the CPU validation
        path, so it exercises the bias fusion alone too.
        """
        from ..layers.common import ReluLayer
        from ..layers.conv import ConvolutionLayer
        fuse, tp = 'auto', 1
        for name, val in self.cfg.defcfg:
            if name == 'fuse':
                fuse = str(val).strip()
            if name == 'tensor_parallel':
                tp = int(val)
        self._fuse_knob = fuse
        self._convact_pairs: Dict[int, int] = {}   # conv idx -> relu idx
        self._convact_solo: set = set()
        if fuse == '0' or tp > 1:
            # under GSPMD a pallas_call is an opaque custom call with no
            # sharding rule — same scoping as lrn_auto_mode
            return
        reads, writes = self._node_version_maps()
        readers: Dict[tuple, List[int]] = {}
        for i, rs in enumerate(reads):
            for nv in rs:
                readers.setdefault(nv, []).append(i)
        for i, layer in enumerate(self.layers):
            if not isinstance(layer, ConvolutionLayer):
                continue
            info = self.cfg.layers[i]
            if (i in self._sibling_groups or i in self._blockdiag_groups
                    or len(info.nindex_in) != 1
                    or len(info.nindex_out) != 1
                    or layer._lowering() != 'native'
                    or layer.param.micro_batch > 1):
                continue
            out_nv = next(iter(writes[i]))
            rd = readers.get(out_nv, [])
            if len(rd) != 1:
                if fuse == '1':
                    self._convact_solo.add(i)
                continue
            r = rd[0]
            rinfo = self.cfg.layers[r]
            if (isinstance(self.layers[r], ReluLayer)
                    and len(rinfo.nindex_in) == 1
                    and rinfo.nindex_out == rinfo.nindex_in):
                self._convact_pairs[i] = r
            elif fuse == '1':
                self._convact_solo.add(i)

    def _fused_convact_outputs(self, lp, x, i: int, act: str):
        """One fused Pallas conv+bias+act dispatch for layer ``i``."""
        from ..ops.pallas_cnn import fused_conv_bias_act
        p = self.layers[i].param
        w = lp['wmat'].astype(x.dtype)
        b = lp['bias'].astype(x.dtype) if p.no_bias == 0 else None
        out = fused_conv_bias_act(
            x, w, b, (p.stride, p.stride),
            ((p.pad_y, p.pad_y), (p.pad_x, p.pad_x)), p.num_group, act)
        return [out.astype(x.dtype)]

    # --- shape inference --------------------------------------------------
    def _infer_shapes(self) -> None:
        cfg = self.cfg
        specs: List[Optional[NodeSpec]] = [None] * cfg.num_nodes
        c, y, x = cfg.input_shape
        if c * y * x == 0:
            raise ValueError('must set input_shape before building the net')
        specs[0] = NodeSpec(c, y, x)
        # extra data nodes in_1..in_k
        for k in range(cfg.extra_data_num):
            ec, ey, ex = cfg.extra_shape[3 * k:3 * k + 3]
            specs[1 + k] = NodeSpec(ec, ey, ex)
        for i, info in enumerate(cfg.layers):
            ins = []
            for j in info.nindex_in:
                if specs[j] is None:
                    raise ValueError(
                        f'layer {i} consumes node {j} before it is produced')
                ins.append(specs[j])
            outs = self.layers[i].infer_shapes(ins)
            if len(outs) != len(info.nindex_out):
                raise ValueError(
                    f'layer {i} ({self.layers[i].type_name}): produced '
                    f'{len(outs)} outputs, expected {len(info.nindex_out)}')
            for j, spec in zip(info.nindex_out, outs):
                if specs[j] is not None and j not in info.nindex_in:
                    if specs[j] != spec:
                        raise ValueError(f'node {j} shape conflict')
                specs[j] = spec
        self.node_specs = specs

    # --- params -----------------------------------------------------------
    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> Params:
        params: Params = {}
        cfg = self.cfg
        for i, info in enumerate(cfg.layers):
            if self.layer_primary[i] != i:
                continue
            ins = [self.node_specs[j] for j in info.nindex_in]
            p = self.layers[i].init_params(jax.random.fold_in(rng, i), ins,
                                           dtype)
            if p:
                params[str(i)] = p
        return params

    def _layer_params(self, params: Params, i: int):
        return params.get(str(self.layer_primary[i]), {})

    # --- forward / loss ---------------------------------------------------
    def _input_to_device_layout(self, batch, compute_dtype=jnp.float32):
        """Host batches arrive NCHW (c,y,x per instance); convert to the
        on-device layout (NHWC images, flat matrices) and activation dtype.
        Integer (uint8 pixel) batches are welcome — shipping raw bytes and
        casting on device quarters host->device traffic."""
        batch = batch.astype(compute_dtype)
        if batch.ndim == 2:
            spec = self.node_specs[0]
            if not spec.is_mat:
                # a conv-shaped net fed flat vectors dies later inside a
                # dot_general with a useless shape message — name the
                # actual fix here (hit via iter=mnist, whose default
                # input_flat=1 flattens, matching the reference)
                raise ValueError(
                    f'input batch is flat ({batch.shape[1]}-vectors) but '
                    f'input_shape expects {spec.c}x{spec.y}x{spec.x} '
                    f'images — set input_flat=0 on the data iterator or '
                    f'use a flat input_shape')
            return batch
        if batch.ndim == 4:
            spec = self.node_specs[0]
            if spec.is_mat:
                return batch.reshape(batch.shape[0], -1)
            if batch.shape[1:] != (spec.c, spec.y, spec.x):
                # a conv-shaped net fed mislaid data (classic: iter=mnist
                # keeps its reference default input_flat=1 and emits
                # (n,1,1,784)) dies later inside a dot_general/conv with
                # a useless shape message — name the actual fix here
                raise ValueError(
                    f'input batch {batch.shape[1:]} does not match '
                    f'input_shape {spec.c},{spec.y},{spec.x} — for '
                    f'iter=mnist set input_flat=0 to keep images unflat')
            return jnp.transpose(batch, (0, 2, 3, 1))
        raise ValueError(f'bad input batch rank {batch.ndim}')

    def forward(self, params: Params, batch, ctx: ForwardContext,
                labels: Optional[LabelInfo] = None, loss_mask=None,
                extra_data=None, capture=None,
                identity_layers=frozenset()):
        """Run the graph.  Returns (node_values, total_loss).

        ``node_values[j]`` holds every node's final value (post loss-layer
        transforms, like the reference's in-place nodes).  ``total_loss`` is
        the sum of loss-layer scalars (0.0 if the graph has none or labels
        were not supplied).  ``extra_data`` feeds nodes ``in_1..in_k`` when
        ``extra_data_num`` is configured (NCHW host layout, like the input).

        ``capture`` (conv+BN fold support, nnet/fold.py): a dict whose
        keys are layer indices — each listed layer's input list is
        stored under its key before the layer runs.  ``identity_layers``
        replaces the listed 1-in layers with a pass-through (how the
        fold pass retires a folded BN without rewriting the graph
        indices the params tree is keyed by).
        """
        cfg = self.cfg
        values: List[Optional[jax.Array]] = [None] * cfg.num_nodes
        values[0] = self._input_to_device_layout(batch, ctx.compute_dtype)
        if cfg.extra_data_num:
            if extra_data is None or len(extra_data) < cfg.extra_data_num:
                raise ValueError(
                    f'net requires {cfg.extra_data_num} extra_data inputs '
                    f'(batch.extra_data) but got '
                    f'{0 if extra_data is None else len(extra_data)}')
            for k in range(cfg.extra_data_num):
                ex = extra_data[k]
                spec = self.node_specs[1 + k]
                if ex.ndim == 4 and not spec.is_mat:
                    ex = jnp.transpose(ex, (0, 2, 3, 1))
                elif ex.ndim > 2 and spec.is_mat:
                    ex = ex.reshape(ex.shape[0], -1)
                values[1 + k] = ex
        total_loss = jnp.asarray(0.0, jnp.float32)
        fused: Dict[int, jax.Array] = {}
        fused_bd: Dict[int, jax.Array] = {}
        fused_act: set = set()   # relus whose act ran inside their conv
        use_fused = bool(self._convact_pairs or self._convact_solo)
        if use_fused:
            from ..ops.pallas_cnn import conv_use_fused
            use_fused = conv_use_fused(self._fuse_knob,
                                       spmd_devices=ctx.spmd_devices)
        for i in self._exec_order:
            info = cfg.layers[i]
            layer = self.layers[i]
            lctx = ForwardContext(is_train=ctx.is_train, rng=ctx.rng,
                                  layer_index=i, round=ctx.round,
                                  max_round=ctx.max_round,
                                  compute_dtype=ctx.compute_dtype,
                                  spmd_devices=ctx.spmd_devices)
            lp = self._layer_params(params, i)
            ins = [values[j] for j in info.nindex_in]
            if capture is not None and i in capture:
                capture[i] = ins
            if isinstance(layer, LossLayerBase) and labels is not None:
                total_loss = total_loss + layer.loss(
                    lp, ins, labels.field(layer.target), lctx, loss_mask)
            if i in identity_layers:
                outs = [ins[0]]
            elif i in fused_act:
                outs = [ins[0]]   # activation already applied in the conv
            elif use_fused and i in self._convact_pairs:
                outs = self._fused_convact_outputs(lp, ins[0], i, 'relu')
                fused_act.add(self._convact_pairs[i])
            elif use_fused and i in self._convact_solo:
                outs = self._fused_convact_outputs(lp, ins[0], i,
                                                   'identity')
            elif i in self._sibling_groups:
                if i not in fused:   # first member: run the fused conv
                    members = self._sibling_groups[i]
                    for m, v in zip(members, self._fused_sibling_outputs(
                            params, ins[0], members)):
                        fused[m] = v
                outs = [fused[i]]
            elif i in self._blockdiag_groups:
                if i not in fused_bd:   # first member in exec order
                    members = self._blockdiag_groups[i]
                    for m, v in zip(members, self._fused_blockdiag_outputs(
                            params, values, members)):
                        fused_bd[m] = v
                outs = [fused_bd[i]]
            else:
                outs = layer.forward(lp, ins, lctx)
            for j, v in zip(info.nindex_out, outs):
                values[j] = v
        return values, total_loss

    def node_index(self, name: str) -> int:
        """Resolve a node by name or ``top[-k]`` syntax
        (``nnet_impl-inl.hpp:200-223``)."""
        if name.startswith('top[-') and name.endswith(']'):
            k = int(name[5:-1])
            return self.cfg.layers[-k].nindex_out[-1] if k > 0 else -1
        if name in self.cfg.node_name_map:
            return self.cfg.node_name_map[name]
        raise ValueError(f'unknown node name {name}')

    def make_label_info(self, label_mat) -> LabelInfo:
        return LabelInfo(label_mat, self.cfg.label_name_map,
                         self.cfg.label_range)
