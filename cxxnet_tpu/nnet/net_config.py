"""Network-architecture configuration: the ``netconfig`` grammar + binary format.

Parses the reference's layer-DAG config language
(``src/nnet/nnet_config.h:207-386``):

* ``layer[0->1] = conv:name`` — explicit node indices/names, comma lists for
  multi-input/-output layers,
* ``layer[+1] = relu`` — one new node after the current top node;
  ``layer[+1:tag]`` names it; ``layer[+0]`` is a self-loop,
* ``layer[...] = share[tag]`` — weight sharing with a previously named layer,
* pairs following a ``layer[...]`` line configure that layer; pairs outside
  ``netconfig=start/end`` are global defaults replayed into every layer,
* ``label_vec[a,b) = name`` maps label columns to named fields,
* ``input_shape = c,y,x`` fixes the input node geometry.

The binary ``SaveNet/LoadNet`` layout (``nnet_config.h:126-191``) is kept
byte-compatible: NetParam struct (with 31 reserved ints), node-name strings,
and per-layer (type, primary_layer_index, name, nindex_in, nindex_out).
"""

from __future__ import annotations

import re
import struct
from dataclasses import dataclass, field
from typing import BinaryIO, Dict, List, Tuple

import numpy as np

from ..layers.base import get_layer_type, kSharedLayer
from ..utils import io_stream

ConfigEntry = Tuple[str, str]

# NetParam: int num_nodes, num_layers; uint32 input_shape[3]; int init_end,
# extra_data_num; int reserved[31]  (nnet_config.h:28-50)
_NET_PARAM = struct.Struct('<ii3Iii' + '31i')


@dataclass
class LayerEntry:
    """One layer's structural record (LayerInfo, nnet_config.h:52-83)."""

    type: int = 0
    primary_layer_index: int = -1
    name: str = ''
    nindex_in: List[int] = field(default_factory=list)
    nindex_out: List[int] = field(default_factory=list)

    def struct_eq(self, other: 'LayerEntry') -> bool:
        return (self.type == other.type
                and self.primary_layer_index == other.primary_layer_index
                and self.name == other.name
                and self.nindex_in == other.nindex_in
                and self.nindex_out == other.nindex_out)


class NetConfig:
    """Records network structure + per-layer and global configuration."""

    def __init__(self):
        self.num_nodes = 0
        self.num_layers = 0
        self.input_shape = (0, 0, 0)        # (c, y, x)
        self.init_end = 0
        self.extra_data_num = 0
        self.extra_shape: List[int] = []
        self.layers: List[LayerEntry] = []
        self.node_names: List[str] = []
        # training-only state (not serialized)
        self.node_name_map: Dict[str, int] = {}
        self.layer_name_map: Dict[str, int] = {}
        self.updater_type = 'sgd'
        self.sync_type = 'simple'
        self.label_name_map: Dict[str, int] = {'label': 0}
        self.label_range: List[Tuple[int, int]] = [(0, 1)]
        self.defcfg: List[ConfigEntry] = []
        self.layercfg: List[List[ConfigEntry]] = []

    # --- global params ----------------------------------------------------
    def _set_global_param(self, name: str, val: str) -> None:
        if name == 'updater':
            self.updater_type = val
        if name == 'sync':
            self.sync_type = val
        m = re.match(r'label_vec\[(\d+),(\d+)\)$', name)
        if m:
            self.label_range.append((int(m.group(1)), int(m.group(2))))
            self.label_name_map[val] = len(self.label_range) - 1

    # --- the layer[...] grammar ------------------------------------------
    def _get_node_index(self, name: str, alloc_unknown: bool) -> int:
        if name in self.node_name_map:
            return self.node_name_map[name]
        if not alloc_unknown:
            raise ValueError(
                f'ConfigError: undefined node name {name}; input of a layer '
                f'must be the output of an earlier layer')
        idx = len(self.node_names)
        self.node_name_map[name] = idx
        self.node_names.append(name)
        return idx

    def _get_layer_info(self, name: str, val: str, top_node: int,
                        cfg_layer_index: int) -> LayerEntry:
        inf = LayerEntry()
        m_plus = re.match(r'layer\[\+(\d+)(?::([^\]]+))?\]$', name)
        m_arrow = re.match(r'layer\[([^-\]]+)->([^\]]+)\]$', name)
        if m_plus:
            if top_node < 0:
                raise ValueError(
                    'ConfigError: layer[+1] used but the previous layer has '
                    'more than one output; use layer[in->out] instead')
            inc = int(m_plus.group(1))
            inf.nindex_in.append(top_node)
            if m_plus.group(2) is not None and inc == 1:
                inf.nindex_out.append(
                    self._get_node_index(m_plus.group(2), True))
            elif inc == 0:
                inf.nindex_out.append(top_node)
            else:
                inf.nindex_out.append(
                    self._get_node_index(f'!node-after-{top_node}', True))
        elif m_arrow:
            for tok in m_arrow.group(1).split(','):
                inf.nindex_in.append(self._get_node_index(tok, False))
            for tok in m_arrow.group(2).split(','):
                inf.nindex_out.append(self._get_node_index(tok, True))
        else:
            raise ValueError(f'ConfigError: invalid layer format {name}')

        ltype, _, tag = val.partition(':')
        layer_name = tag
        inf.type = get_layer_type(ltype)
        if inf.type == kSharedLayer:
            m_share = re.search(r'\[([^\]]+)\]', ltype)
            if not m_share:
                raise ValueError(
                    'ConfigError: shared layer must specify tag to share with')
            share_tag = m_share.group(1)
            if share_tag not in self.layer_name_map:
                raise ValueError(
                    f'ConfigError: shared layer tag {share_tag} not defined')
            inf.primary_layer_index = self.layer_name_map[share_tag]
        elif layer_name:
            if layer_name in self.layer_name_map:
                if self.layer_name_map[layer_name] != cfg_layer_index:
                    raise ValueError(
                        'ConfigError: layer name in configuration does not '
                        'match the name stored in model')
            else:
                self.layer_name_map[layer_name] = cfg_layer_index
            inf.name = layer_name
        return inf

    # --- configure (replay of ordered pairs) ------------------------------
    def configure(self, cfg: List[ConfigEntry]) -> None:
        """Replay ordered (name, val) pairs (``Configure``,
        nnet_config.h:207-289).  May be called again on a loaded model, in
        which case the structure must match."""
        self.defcfg = []
        self.layercfg = [[] for _ in self.layers] if self.init_end else []
        if not self.node_names and not self.node_name_map:
            self.node_names.append('in')
            self.node_name_map['in'] = 0
        self.node_name_map['0'] = 0
        netcfg_mode = 0
        cfg_top_node = 0
        cfg_layer_index = 0
        for name, val in cfg:
            if name == 'extra_data_num':
                num = int(val)
                for i in range(num):
                    nm = f'in_{i + 1}'
                    if nm not in self.node_name_map:
                        self.node_names.append(nm)
                        self.node_name_map[nm] = i + 1
                self.extra_data_num = num
            if name.startswith('extra_data_shape['):
                x, y, z = (int(t) for t in val.split(','))
                self.extra_shape += [x, y, z]
            if self.init_end == 0 and name == 'input_shape':
                c, y, x = (int(t) for t in val.split(','))
                self.input_shape = (c, y, x)
            if netcfg_mode != 2:
                self._set_global_param(name, val)
            if name == 'netconfig' and val == 'start':
                netcfg_mode = 1
            if name == 'netconfig' and val == 'end':
                netcfg_mode = 0
            if name.startswith('layer['):
                info = self._get_layer_info(name, val, cfg_top_node,
                                            cfg_layer_index)
                netcfg_mode = 2
                if self.init_end == 0:
                    assert len(self.layers) == cfg_layer_index
                    self.layers.append(info)
                    self.layercfg.append([])
                else:
                    if cfg_layer_index >= len(self.layers):
                        raise ValueError('config layer index exceeds bound')
                    if not info.struct_eq(self.layers[cfg_layer_index]):
                        raise ValueError(
                            'config does not match existing network structure')
                cfg_top_node = (info.nindex_out[0]
                                if len(info.nindex_out) == 1 else -1)
                cfg_layer_index += 1
                continue
            if netcfg_mode == 2:
                if self.layers[cfg_layer_index - 1].type == kSharedLayer:
                    raise ValueError(
                        'do not set parameters in a shared layer; set them '
                        'in the primary layer')
                self.layercfg[cfg_layer_index - 1].append((name, val))
            else:
                self.defcfg.append((name, val))
        if self.init_end == 0:
            self._init_net()

    def _init_net(self) -> None:
        self.num_layers = len(self.layers)
        n = 0
        for info in self.layers:
            for j in info.nindex_in + info.nindex_out:
                n = max(n, j + 1)
        self.num_nodes = n
        assert self.num_nodes == len(self.node_names), \
            'num_nodes inconsistent with node_names'
        self.init_end = 1

    def get_layer_index(self, name: str) -> int:
        if name not in self.layer_name_map:
            raise ValueError(f'unknown layer name {name}')
        return self.layer_name_map[name]

    # --- binary format (checkpoint interop) -------------------------------
    def save_net(self, f: BinaryIO) -> None:
        f.write(_NET_PARAM.pack(self.num_nodes, self.num_layers,
                                self.input_shape[0], self.input_shape[1],
                                self.input_shape[2], self.init_end,
                                self.extra_data_num, *([0] * 31)))
        if self.extra_data_num != 0:
            io_stream.write_vector(f, np.asarray(self.extra_shape, np.int32))
        assert self.num_layers == len(self.layers)
        assert self.num_nodes == len(self.node_names)
        for nm in self.node_names:
            io_stream.write_string(f, nm)
        for info in self.layers:
            f.write(struct.pack('<ii', info.type, info.primary_layer_index))
            io_stream.write_string(f, info.name)
            io_stream.write_vector(f, np.asarray(info.nindex_in, np.int32))
            io_stream.write_vector(f, np.asarray(info.nindex_out, np.int32))

    def load_net(self, f: BinaryIO) -> None:
        raw = f.read(_NET_PARAM.size)
        if len(raw) < _NET_PARAM.size:
            raise EOFError('NetConfig: invalid model file')
        vals = _NET_PARAM.unpack(raw)
        self.num_nodes, self.num_layers = vals[0], vals[1]
        self.input_shape = (vals[2], vals[3], vals[4])
        self.init_end, self.extra_data_num = vals[5], vals[6]
        if self.extra_data_num != 0:
            self.extra_shape = list(io_stream.read_vector(f, np.int32))
        self.node_names = [io_stream.read_string(f).decode('utf-8')
                           for _ in range(self.num_nodes)]
        self.node_name_map = {nm: i for i, nm in enumerate(self.node_names)}
        self.layers = []
        self.layer_name_map = {}
        for i in range(self.num_layers):
            t, pli = struct.unpack('<ii', f.read(8))
            nm = io_stream.read_string(f).decode('utf-8')
            nin = [int(v) for v in io_stream.read_vector(f, np.int32)]
            nout = [int(v) for v in io_stream.read_vector(f, np.int32)]
            entry = LayerEntry(t, pli, nm, nin, nout)
            if t == kSharedLayer:
                if nm:
                    raise ValueError('SharedLayer must not have a name')
            elif nm:
                if nm in self.layer_name_map:
                    raise ValueError(f'duplicated layer name: {nm}')
                self.layer_name_map[nm] = i
            self.layers.append(entry)
        self.layercfg = [[] for _ in self.layers]
        self.defcfg = []
