"""Quantized inference parameters: int8 / bf16 storage tiers.

The serving tier this module implements (doc/serving.md "Quantized
inference") trades a bounded accuracy delta for device memory: a model
loaded at ``serve.dtype=int8`` keeps roughly 1/4 the resident bytes of
its f32 twin, so the ``MemoryBudgeter`` fits ~4x more models per chip
before evicting.  Quantization happens ONCE, at load/swap time (the
engines call :func:`quantize_tree` inside ``place_params``) — the hot
path never re-quantizes weights.

Two tiers:

* **bf16** — every float leaf cast to bfloat16 (2x).  Pure storage/
  compute dtype change; no extra machinery.
* **int8** — symmetric per-channel weight-only quantization of matmul
  weights: ``q = round(x / scale)`` with ``scale = max|x| / 127`` taken
  over the contraction axis (``axis=-2``), so each output channel keeps
  its own dynamic range; leading stack axes (the transformer's stage
  axis) are preserved, which is what lets ``jax.tree.map(lambda a: a[i])``
  slice a stacked :class:`QuantLeaf` per stage exactly like a plain
  array.  Non-matmul leaves (layernorm scales, biases) stay in the
  compute dtype — quantizing them saves nothing and costs accuracy.

:class:`QuantLeaf` is a registered pytree node (children: ``q`` int8 +
``scale`` f32), so quantized trees flow through ``jit`` / ``device_put``
/ ``tree.leaves`` unchanged — ``sum(l.nbytes for l in leaves)`` is the
TRUE quantized footprint the budgeter sees.

Execution: consumers route matmuls through :func:`qdot` and embedding
gathers through :func:`qtake` — ``models/transformer.py`` does at every
inference matmul site (``_stage_attn``, ``_gen_ffn``,
``_nodrop_moe_ffn``'s gate, ``prefill_kv``'s head, and the
``_decode_token`` block walk).  For a plain array ``qdot(x, w)`` IS
``x @ w`` (the
training path is bitwise untouched); for a :class:`QuantLeaf` it runs
W8A8: dynamic per-row symmetric activation quantization, an int8 x int8
matmul with exact int32 accumulation — the Pallas MXU kernel
(``ops.pallas_kernels.pallas_int8_matmul``) when Pallas is forced on,
``lax.dot_general`` otherwise, BITWISE-identical either way (integer
adds carry no rounding) — and one f32 rescale.  Determinism is the
point: a quantized model's outputs are a pure function of its int8
weights, identical across Pallas modes and join orders, so the decode
engine's streams still have an EXACT offline twin
(``transformer.generate`` over the same quantized tree); the accuracy
delta vs f32 is policed separately by the tolerance twins
(tests/test_quantize.py) whose thresholds are pinned, never silently
loosened.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ['QuantLeaf', 'quantize_leaf', 'quantize_tree',
           'dequantize_tree', 'qdot', 'qtake', 'tree_nbytes',
           'parse_serve_dtype', 'SERVE_DTYPES', 'LM_MATMUL_KEYS',
           'quantize_lm_tree', 'shard_put']

SERVE_DTYPES = ('f32', 'bf16', 'int8')

#: transformer-tree leaf names consumed through ``qdot``/``qtake`` —
#: the int8 tier quantizes exactly these (MoE expert stacks ``w1``/``w2``
#: at ndim 4 are einsum-consumed and stay unquantized)
LM_MATMUL_KEYS = ('embed', 'head', 'wq', 'wk', 'wv', 'wo',
                  'w1', 'w2', 'gate')


def parse_serve_dtype(value: str) -> str:
    """Validate a ``serve.dtype`` key value ('f32' aliases 'float32')."""
    text = str(value).strip().lower()
    if text in ('', 'f32', 'float32', 'fp32'):
        return 'f32'
    if text in ('bf16', 'bfloat16'):
        return 'bf16'
    if text == 'int8':
        return 'int8'
    raise ValueError(
        f'serve.dtype must be one of {SERVE_DTYPES}, got {value!r}')


@jax.tree_util.register_pytree_node_class
class QuantLeaf:
    """A symmetric per-channel int8 tensor: ``x ~= q * scale`` with
    ``scale`` broadcast along the contraction axis (``axis=-2``).
    ``out_dtype`` is the compute dtype dequantized values take."""

    __slots__ = ('q', 'scale', 'out_dtype')

    def __init__(self, q, scale, out_dtype=jnp.float32):
        self.q = q
        self.scale = scale
        self.out_dtype = jnp.dtype(out_dtype)

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), (self.out_dtype,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, aux[0])

    # -- array-ish surface -------------------------------------------------
    @property
    def shape(self):
        return tuple(self.q.shape)

    @property
    def nbytes(self) -> int:
        return int(self.q.nbytes) + int(self.scale.nbytes)

    def dequantize(self, dtype=None):
        """Exact ``q * scale`` expansion (deterministic: the only float
        op is one multiply per element)."""
        s = jnp.expand_dims(self.scale, -2)
        return (self.q.astype(jnp.float32) * s).astype(
            self.out_dtype if dtype is None else dtype)

    def __repr__(self):
        return (f'QuantLeaf(int8 {self.shape}, scale '
                f'{tuple(self.scale.shape)}, out={self.out_dtype})')


def quantize_leaf(x, out_dtype=jnp.float32) -> QuantLeaf:
    """Symmetric per-channel int8 quantization over ``axis=-2`` (the
    contraction axis of ``x @ w``): every output channel — and every
    entry of any leading stack axis — gets its own ``max|x|/127``
    scale.  Dead channels (all-zero) take scale 1 so ``q`` stays 0."""
    xf = np.asarray(jax.device_get(x), np.float32)
    if xf.ndim < 2:
        raise ValueError(f'quantize_leaf needs ndim >= 2, got {xf.shape}')
    amax = np.max(np.abs(xf), axis=-2)
    scale = np.where(amax == 0.0, 1.0, amax / 127.0).astype(np.float32)
    q = np.clip(np.round(xf / np.expand_dims(scale, -2)),
                -127, 127).astype(np.int8)
    return QuantLeaf(q, scale, out_dtype)


def _map_named(fn, tree, name=''):
    """Depth-first map over a nested-dict tree with the leaf's own key
    (both the trainer's layer->field dicts and the transformer tree are
    nested dicts of arrays)."""
    if isinstance(tree, dict):
        return {k: _map_named(fn, v, k) for k, v in tree.items()}
    return fn(name, tree)


def _default_quant_key(name: str, leaf) -> bool:
    """The generic (netconfig/CNN) int8 rule: weight-shaped leaves
    (ndim >= 2) quantize; vectors (biases, norm scales) stay float."""
    return getattr(leaf, 'ndim', 0) >= 2


def lm_quant_key(name: str, leaf) -> bool:
    """The transformer rule: exactly the ``qdot``/``qtake``-consumed
    matmul leaves (MoE 4D expert stacks excluded — einsum-consumed)."""
    return (name in LM_MATMUL_KEYS
            and 2 <= getattr(leaf, 'ndim', 0) <= 3)


def quantize_tree(tree, mode: str, *, out_dtype=None, quant_key=None):
    """Quantize a HOST param tree into its serving storage tier.

    ``mode``: ``'f32'`` (identity), ``'bf16'`` (float leaves cast), or
    ``'int8'`` (leaves passing ``quant_key`` become :class:`QuantLeaf`;
    the rest cast to ``out_dtype``).  ``out_dtype`` defaults to f32 for
    the generic rule and is the compute dtype quantized consumers
    produce."""
    mode = parse_serve_dtype(mode)
    if mode == 'f32':
        return tree
    out_dtype = jnp.dtype(jnp.float32 if out_dtype is None else out_dtype)
    key = _default_quant_key if quant_key is None else quant_key

    def one(name, leaf):
        # jnp.issubdtype, not np: bfloat16 is outside numpy's hierarchy
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            return leaf
        if mode == 'bf16':
            return jnp.asarray(leaf, jnp.bfloat16)
        if key(name, leaf):
            return quantize_leaf(leaf, out_dtype)
        return jnp.asarray(leaf, out_dtype)

    return _map_named(one, tree)


def quantize_lm_tree(tree, mode: str, *, out_dtype=None):
    """Quantize a transformer param tree into its serving tier under the
    LM matmul-leaf rule — the one call the decode engine makes for BOTH
    its target and its speculative-decode draft tree (serve/decode.py),
    so the two models always land on the same storage tier and the
    greedy verify math consumes them through the identical ``qdot``
    dispatch."""
    return quantize_tree(tree, mode, out_dtype=out_dtype,
                         quant_key=lm_quant_key)


def dequantize_tree(tree, dtype=None):
    """Expand every :class:`QuantLeaf` (and optionally cast every float
    leaf to ``dtype``) — the weight-only execution path's per-forward
    step, and the host-side reference for exact twins."""

    def one(leaf):
        if isinstance(leaf, QuantLeaf):
            return leaf.dequantize(dtype)
        if dtype is not None and jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating):
            return jnp.asarray(leaf, dtype)
        return leaf

    return jax.tree.map(one, tree,
                        is_leaf=lambda n: isinstance(n, QuantLeaf))


def shard_put(leaf, mesh, spec):
    """Device-put one param leaf with a full-rank ``PartitionSpec``
    over ``mesh`` (the graftshard tensor-parallel placement,
    doc/serving.md "Sharded serving").

    A plain array takes ``spec`` directly.  A :class:`QuantLeaf` must
    keep its two children CO-SHARDED: ``q`` takes ``spec``, and
    ``scale`` — whose shape is ``q``'s with the contraction axis
    (``-2``) dropped — takes ``spec`` with that same entry dropped, so
    every per-output-channel scale lives on the device that owns its
    channels and ``qdot``'s rescale multiply never crosses devices."""
    from jax.sharding import NamedSharding, PartitionSpec

    def put(arr, parts):
        return jax.device_put(arr, NamedSharding(mesh,
                                                 PartitionSpec(*parts)))

    if isinstance(leaf, QuantLeaf):
        parts = tuple(spec) + (None,) * (leaf.q.ndim - len(tuple(spec)))
        return QuantLeaf(put(leaf.q, parts),
                         put(leaf.scale, parts[:-2] + parts[-1:]),
                         leaf.out_dtype)
    return put(leaf, tuple(spec))


def tree_nbytes(tree) -> int:
    """True storage bytes of a (possibly quantized) tree — QuantLeaf
    flattens to its int8 payload + scales, so plain leaf summation IS
    the quantized footprint."""
    return int(sum(l.nbytes for l in jax.tree.leaves(tree)))


def _int8_mm(aq, bq):
    """int8 x int8 -> int32, Pallas MXU kernel when forced on, XLA
    ``dot_general`` otherwise — bitwise-identical either way (exact
    integer accumulation; pinned in tests/test_quantize.py)."""
    from ..ops import pallas_kernels as PK
    if PK.pallas_enabled() and PK.pltpu is not None:
        return PK.pallas_int8_matmul(aq, bq)
    return jax.lax.dot_general(aq, bq, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)


def qdot(x, w):
    """``x @ w`` through the quantized-leaf dispatcher.

    Plain array ``w``: returns ``x @ w`` — the native op, bitwise
    untouched (this is why the training/reference paths can share the
    call site).  :class:`QuantLeaf` ``w`` (2D, post-stage-slice): W8A8 —
    per-row symmetric activation quantization, exact-int32 int8 matmul,
    one f32 rescale, result in ``w.out_dtype``."""
    if not isinstance(w, QuantLeaf):
        return x @ w
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    xs = jnp.where(amax == 0.0, jnp.float32(1.0), amax / 127.0)
    xq = jnp.clip(jnp.round(xf / xs), -127, 127).astype(jnp.int8)
    lead = xq.shape[:-1]
    acc = _int8_mm(xq.reshape(-1, xq.shape[-1]), w.q)
    out = (acc.astype(jnp.float32) * xs.reshape(-1, 1)
           * w.scale[None, :])
    return out.reshape(*lead, w.q.shape[-1]).astype(w.out_dtype)


def qtake(emb, idx):
    """Embedding-row gather through the dispatcher: plain arrays take
    ``jnp.take``; an int8 embedding gathers its rows and dequantizes
    just those (``scale`` is per-channel over the embedding dim, so it
    broadcasts across gathered rows)."""
    if not isinstance(emb, QuantLeaf):
        return jnp.take(emb, idx, axis=0)
    rows = jnp.take(emb.q, idx, axis=0).astype(jnp.float32)
    return (rows * emb.scale).astype(emb.out_dtype)
