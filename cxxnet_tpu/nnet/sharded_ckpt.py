"""Sharded checkpointing for mesh-partitioned models.

Two checkpoint systems coexist deliberately:

* the CNN trainer keeps the reference's byte-compatible single-file model
  format (``nnet/checkpoint.py`` — interop with reference-era tooling is
  the contract there);
* the beyond-reference distributed models (the 4D-parallel transformer)
  use orbax: every leaf is written with its sharding metadata, saves are
  atomic (temp dir + rename by orbax), and restore lays shards directly
  onto the target mesh — no host gathering a full replica, which is the
  property that matters once a model outgrows one host.

Directory layout: ``<ckpt_dir>/step_<n>/`` per save; ``latest_step`` scans
for the newest complete one (the ``continue=1`` idiom, reborn sharded).
"""

from __future__ import annotations

import json
import os
import re
import zlib
from typing import List, Optional, Tuple

import jax
import numpy as np

from ..runtime import faults


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp


_CK = None


def _shared_ck():
    """One StandardCheckpointer per process: its async-commit machinery is
    reused across the training loop's periodic saves."""
    global _CK
    if _CK is None:
        _CK = _checkpointer().StandardCheckpointer()
    return _CK


def _epath(p: str):
    """Filesystem-agnostic path (local or cloud URL) via etils epath —
    an orbax dependency, so always present where this module works."""
    from etils import epath
    return epath.Path(p)


_STEP_RE = re.compile(r'^step_(\d+)$')


def step_dir(ckpt_dir: str, step: int) -> str:
    return os.fspath(_epath(ckpt_dir) / f'step_{step}')


def _absolute(p) -> str:
    # orbax requires absolute paths for local saves; cloud URLs pass
    # through untouched
    s = os.fspath(p)
    return s if '://' in s else os.path.abspath(s)


def _scan_steps(ckpt_dir: str, suffix: str = '') -> List[int]:
    """Step numbers of ``step_<n><suffix>`` dirs, newest first.  One
    scan serves intact and quarantined sets alike; orbax writes into a
    tmp dir and renames on commit, so a plain ``step_N`` dir is
    complete, and anything else (temp, ``.corrupt``) fails the anchored
    match."""
    base = _epath(ckpt_dir)
    if not base.exists():
        return []
    steps = []
    for child in base.iterdir():
        name = child.name
        if suffix:
            if not name.endswith(suffix):
                continue
            name = name[:-len(suffix)]
        m = _STEP_RE.match(name)
        if m and child.is_dir():
            steps.append(int(m.group(1)))
    return sorted(steps, reverse=True)


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Newest complete checkpoint step in ``ckpt_dir`` (None if empty)."""
    steps = _scan_steps(ckpt_dir)
    return steps[0] if steps else None


def all_steps(ckpt_dir: str) -> List[int]:
    """Every complete checkpoint step in ``ckpt_dir``, newest first.
    Quarantined (``.corrupt``-suffixed) and in-flight temp dirs don't
    match ``step_<n>`` and are skipped."""
    return _scan_steps(ckpt_dir)


def quarantined_steps(ckpt_dir: str) -> List[int]:
    """Steps with a ``step_<n>.corrupt`` quarantine dir, newest first —
    the post-mortem set, so retention policies can bound it."""
    return _scan_steps(ckpt_dir, '.corrupt')


# --- integrity digest ----------------------------------------------------
#
# orbax's temp-dir + rename makes the *directory* appear atomically, but a
# later bit-rot / truncation of a shard file inside it is silent:
# tensorstore has no whole-file checksum we can rely on across drivers.
# Every committed checkpoint therefore gets a ``ckpt_digest.json`` sidecar
# (relpath -> [size, crc32]) written AFTER the commit lands; restore-side
# verification (``verify_step_dir``) catches truncated/flipped shards and
# lets ``restore_resilient`` fall back to the newest intact step.

_DIGEST_NAME = 'ckpt_digest.json'
_PENDING_DIGEST: List[Tuple[int, str]] = []


def _payload_files(path: str) -> List[str]:
    out = []
    for root, _dirs, files in os.walk(path):
        for f in files:
            if f == _DIGEST_NAME:
                continue
            out.append(os.path.relpath(os.path.join(root, f), path))
    return sorted(out)


def _file_crc(p: str) -> int:
    crc = 0
    with open(p, 'rb') as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def write_digest(path: str) -> None:
    digest = {rel: [os.path.getsize(os.path.join(path, rel)),
                    _file_crc(os.path.join(path, rel))]
              for rel in _payload_files(path)}
    from .checkpoint import atomic_write
    with atomic_write(os.path.join(path, _DIGEST_NAME)) as f:
        f.write(json.dumps(digest).encode())


def verify_step_dir(path: str) -> Optional[str]:
    """Integrity-check one committed checkpoint dir; returns None when it
    verifies, else a human-readable reason.  A checkpoint written before
    digests existed (no sidecar) is treated as unverified-but-plausible:
    restore may still try it (and fall back if orbax rejects it)."""
    dig = os.path.join(path, _DIGEST_NAME)
    if not os.path.exists(dig):
        return None
    try:
        with open(dig) as f:
            digest = json.load(f)
    except (OSError, ValueError) as e:
        return f'unreadable digest: {e!r}'
    for rel, (size, crc) in digest.items():
        p = os.path.join(path, rel)
        if not os.path.exists(p):
            return f'missing shard file: {rel}'
        if os.path.getsize(p) != size:
            return f'truncated shard file: {rel}'
        if _file_crc(p) != crc:
            return f'corrupt shard file: {rel}'
    return None


# --- native tree format ---------------------------------------------------
#
# The async save path (runtime/async_ckpt.py) writes checkpoints WITHOUT
# orbax: one raw-bytes file per leaf (parallel, each through
# ``checkpoint.atomic_write``) plus a JSON manifest mapping tree paths to
# (file, dtype, shape), committed by directory rename — the same
# step_<n>-appears-atomically contract orbax gives, with the write
# parallelism under our control and no event-loop machinery on the hot
# path.  Both formats share ``ckpt_digest.json`` and the step-dir naming,
# so verification, quarantine, pruning, and resilient fallback treat them
# identically; ``restore_sharded`` dispatches on the manifest's presence.

_MANIFEST_NAME = 'tree_manifest.json'
_PACKED_NAME = 'packed_leaves.bin'
_PACK_LIMIT = 1 << 18        # leaves under 256 KiB share one blob file


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _flat_with_paths(tree) -> List[Tuple[str, object]]:
    """(path-string, leaf) pairs in deterministic tree order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def _write_leaf(dirpath: str, fname: str,
                data) -> Tuple[int, int]:
    """Plain write+fsync of one leaf into the UNCOMMITTED temp dir — the
    directory rename is the atomic unit, so a per-leaf atomic_write dance
    would only add a rename and two fsyncs per file.  Returns
    (size, crc32) computed from the in-memory bytes, so the digest never
    re-reads what it just wrote."""
    with open(os.path.join(dirpath, fname), 'wb') as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    size = data.nbytes if isinstance(data, np.ndarray) else len(data)
    return size, zlib.crc32(data) & 0xFFFFFFFF


def save_tree_native(ckpt_dir: str, step: int, host_flat_tree, retry=None,
                     pool=None) -> str:
    """Write a host-materialized pytree as a native ``step_<n>``
    checkpoint: leaves in parallel over ``pool`` (a ThreadPoolExecutor;
    None = sequential), manifest last, then one directory rename commits
    the whole step.  An existing dir for the step is REPLACED (same
    contract as the supervisor's sync save).  The write retries whole
    under ``retry`` and passes through the fault-injection hook; the
    crc32 integrity sidecar (same ``ckpt_digest.json`` format
    ``verify_step_dir`` checks) is accumulated from the in-memory bytes
    during the write — no second read pass — and lands via
    ``atomic_write`` after the commit, then ``shard_committed`` fires:
    identical recovery surface to the orbax path."""
    path = _absolute(step_dir(ckpt_dir, step))
    tmp = f'{path}.tmp.{os.getpid()}'
    # np.require, not ascontiguousarray: the latter promotes 0-d leaves
    # (counters) to shape (1,), which would change the restored tree
    flat = [(keystr, np.require(np.asarray(leaf), requirements='C'))
            for keystr, leaf in _flat_with_paths(host_flat_tree)]
    retry = faults.DEFAULT_IO_RETRY if retry is None else retry
    digest = {}

    def attempt():
        import shutil
        faults.checkpoint_write_attempt(path)
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        digest.clear()
        manifest = {}
        jobs = []
        # small leaves (biases, counters — most of the tree's FILE count,
        # none of its bytes) pack into one blob: per-file fsync cost, not
        # bandwidth, is what bounds the background writer's latency
        packed, off = [], 0
        for i, (keystr, arr) in enumerate(flat):
            if arr.nbytes < _PACK_LIMIT:
                manifest[keystr] = {'file': _PACKED_NAME,
                                    'dtype': str(arr.dtype),
                                    'shape': list(arr.shape),
                                    'offset': off}
                packed.append(arr)
                off += arr.nbytes
                continue
            fname = f'leaf_{i:05d}.bin'
            manifest[keystr] = {'file': fname, 'dtype': str(arr.dtype),
                                'shape': list(arr.shape)}
            if pool is None:
                digest[fname] = list(_write_leaf(tmp, fname, arr))
            else:
                jobs.append((fname, pool.submit(_write_leaf, tmp, fname,
                                                arr)))
        if packed:
            # .tobytes(), never bytes(): bytes() of a 0-d integer array
            # routes through __index__ and yields that many NUL bytes
            blob = b''.join(a.tobytes() for a in packed)
            if pool is None:
                digest[_PACKED_NAME] = list(
                    _write_leaf(tmp, _PACKED_NAME, blob))
            else:
                jobs.append((_PACKED_NAME,
                             pool.submit(_write_leaf, tmp, _PACKED_NAME,
                                         blob)))
        for fname, j in jobs:
            digest[fname] = list(j.result())
        mbytes = json.dumps(manifest).encode()
        digest[_MANIFEST_NAME] = [len(mbytes),
                                  zlib.crc32(mbytes) & 0xFFFFFFFF]
        _write_leaf(tmp, _MANIFEST_NAME, mbytes)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        os.replace(tmp, path)
        try:   # make the commit rename itself durable (best effort,
               # same policy as checkpoint.atomic_write)
            dfd = os.open(os.path.dirname(path), os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass

    try:
        retry.call(attempt, op_name=f'save_native:step_{step}')
    finally:
        if os.path.isdir(tmp):
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
    from .checkpoint import atomic_write
    with atomic_write(os.path.join(path, _DIGEST_NAME)) as f:
        f.write(json.dumps(digest).encode())
    faults.shard_committed(step, path)
    return path


def _restore_native(path: str, like):
    """Load a native-format step dir, placing every leaf per ``like``:
    jax leaves (or sharding-annotated ShapeDtypeStructs) are device_put
    with their sharding; host leaves stay numpy."""
    with open(os.path.join(path, _MANIFEST_NAME)) as f:
        manifest = json.load(f)
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    out = []
    packed = None                # the shared small-leaf blob, read once
    for kpath, leaf in flat:
        key = jax.tree_util.keystr(kpath)
        ent = manifest.get(key)
        if ent is None:
            raise ValueError(
                f'native checkpoint {path} has no leaf {key!r} '
                f'(restoring under a changed structure?)')
        dt = _np_dtype(ent['dtype'])
        n = int(np.prod(ent['shape'])) if ent['shape'] else 1
        if ent['file'] == _PACKED_NAME:
            if packed is None:
                with open(os.path.join(path, _PACKED_NAME), 'rb') as f:
                    packed = f.read()
            arr = np.frombuffer(packed, dt, count=n,
                                offset=ent.get('offset', 0)).reshape(
                ent['shape'])
            writable = False     # frombuffer views are read-only
        else:
            # big leaves stream straight from disk, one at a time —
            # holding every file's bytes until unflatten would double
            # peak restore memory on exactly the big-model case the
            # format exists for
            arr = np.fromfile(os.path.join(path, ent['file']), dtype=dt,
                              count=n).reshape(ent['shape'])
            writable = True
        sharding = getattr(leaf, 'sharding', None)
        if sharding is not None:
            arr = jax.device_put(arr, sharding)
        elif not writable:
            arr = arr.copy()
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


def _flush_pending_digests() -> None:
    while _PENDING_DIGEST:
        step, path = _PENDING_DIGEST.pop()
        if os.path.isdir(path):
            write_digest(path)
            faults.shard_committed(step, path)


def save_sharded(ckpt_dir: str, step: int, params, block: bool = True,
                 retry: Optional[faults.RetryPolicy] = None) -> str:
    """Write ``params`` (a pytree of possibly-sharded jax.Arrays) at
    ``step``; returns the checkpoint path.  ``block=False`` lets the
    commit overlap subsequent training steps (the previous pending save is
    always completed first); callers must ``wait_for_saves()`` before
    exit or before reading the checkpoint back.

    The write is atomic (orbax temp-dir + rename: ``step_<n>`` only ever
    names a complete checkpoint), retried under ``retry`` (default
    ``faults.DEFAULT_IO_RETRY``), and followed by an integrity digest
    sidecar once the commit lands."""
    path = _absolute(step_dir(ckpt_dir, step))
    ck = _shared_ck()
    retry = faults.DEFAULT_IO_RETRY if retry is None else retry

    def attempt():
        faults.checkpoint_write_attempt(path)
        ck.wait_until_finished()      # at most one save in flight
        _flush_pending_digests()
        ck.save(path, params)

    retry.call(attempt, op_name=f'save_sharded:step_{step}')
    _PENDING_DIGEST.append((step, path))
    if block:
        ck.wait_until_finished()
        _flush_pending_digests()
    return path


def wait_for_saves() -> None:
    """Block until every async ``save_sharded(..., block=False)`` commit
    has landed (and its integrity digest is written)."""
    if _CK is not None:
        _CK.wait_until_finished()
        _flush_pending_digests()


def _abstract_like(like):
    ocp = _checkpointer()

    def to_abstract(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        return ocp.utils.to_shape_dtype_struct(x)

    return jax.tree.map(to_abstract, like)


def restore_sharded(ckpt_dir: str, like, step: Optional[int] = None,
                    retry: Optional[faults.RetryPolicy] = None):
    """Restore the checkpoint at ``step`` (default: latest) with every
    leaf placed per ``like``'s shapes/dtypes/shardings — ``like`` is a
    pytree of sharding-annotated ``jax.ShapeDtypeStruct`` (e.g.
    ``models.transformer.abstract_params``) or of live sharded arrays.
    The storage read retries under ``retry`` (default
    ``faults.DEFAULT_IO_RETRY``).  Returns (params, step)."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f'no checkpoints under {ckpt_dir}')
    path = _absolute(step_dir(ckpt_dir, step))
    # absence is a state, not a transient — fail now instead of sleeping
    # through the backoff schedule probing a dir that was never written
    # (cloud URLs skip the check and rely on the backend's error)
    if '://' not in path and not os.path.isdir(path):
        raise FileNotFoundError(f'no checkpoint dir {path}')
    retry = faults.DEFAULT_IO_RETRY if retry is None else retry
    if '://' not in path and \
            os.path.exists(os.path.join(path, _MANIFEST_NAME)):
        # async-written native format (runtime/async_ckpt.py): restored
        # with the same retry/placement contract as the orbax path
        params = retry.call(lambda: _restore_native(path, like),
                            op_name=f'restore_sharded:step_{step}')
        return params, step
    target = _abstract_like(like)
    params = retry.call(
        lambda: _shared_ck().restore(path, target),
        op_name=f'restore_sharded:step_{step}')
    return params, step


def quarantine_step(ckpt_dir: str, step: int, reason: str) -> None:
    """Rename a bad ``step_<n>`` dir to ``step_<n>.corrupt`` so every
    future ``latest_step``/``all_steps`` scan skips it without re-paying
    verification, while the bytes stay around for post-mortem."""
    src = _absolute(step_dir(ckpt_dir, step))
    if os.path.isdir(src):
        dst = src + '.corrupt'
        if os.path.exists(dst):
            import shutil
            shutil.rmtree(dst, ignore_errors=True)
        os.replace(src, dst)
    faults.global_failure_log().record(
        'ckpt_quarantined', f'step {step}: {reason}', step=step)


def restore_resilient(ckpt_dir: str, like,
                      retry: Optional[faults.RetryPolicy] = None):
    """Restore the newest checkpoint that passes integrity verification,
    falling back step by step: a corrupt/truncated shard (or an orbax
    restore failure) quarantines that step and tries the next older one.
    Raises ``faults.CheckpointCorruptError`` when nothing under
    ``ckpt_dir`` is restorable.  Returns (params, step)."""
    steps = all_steps(ckpt_dir)
    if not steps:
        raise FileNotFoundError(f'no checkpoints under {ckpt_dir}')
    log = faults.global_failure_log()
    quarantined = 0
    last_err: Optional[BaseException] = None
    for step in steps:
        path = _absolute(step_dir(ckpt_dir, step))
        reason = verify_step_dir(path)
        if reason is not None:
            quarantine_step(ckpt_dir, step, reason)
            quarantined += 1
            continue
        try:
            return restore_sharded(ckpt_dir, like, step, retry=retry)
        except (faults.RetryError, OSError, ValueError) as e:
            # NOT a quarantine: the digest verified, so the bytes are
            # intact — this failure is environmental (storage outage
            # outlasting the retry budget) or caller-side (restoring
            # under a changed net config raises ValueError on every
            # step).  Renaming the dir would destroy the only good
            # recovery point over a fault that may clear; skip it for
            # this call and leave the scan state alone.
            last_err = e
            log.record('ckpt_restore_failed', repr(e), step=step)
    if not quarantined and last_err is not None:
        # zero corruption was found — reporting CheckpointCorruptError
        # here would send the operator down the wrong runbook for what
        # is an outage or a caller-side mismatch
        raise last_err
    raise faults.CheckpointCorruptError(
        f'no intact checkpoint under {ckpt_dir} '
        f'({quarantined} of {len(steps)} candidates quarantined, '
        f'rest unrestorable)')
