"""Sharded checkpointing for mesh-partitioned models.

Two checkpoint systems coexist deliberately:

* the CNN trainer keeps the reference's byte-compatible single-file model
  format (``nnet/checkpoint.py`` — interop with reference-era tooling is
  the contract there);
* the beyond-reference distributed models (the 4D-parallel transformer)
  use orbax: every leaf is written with its sharding metadata, saves are
  atomic (temp dir + rename by orbax), and restore lays shards directly
  onto the target mesh — no host gathering a full replica, which is the
  property that matters once a model outgrows one host.

Directory layout: ``<ckpt_dir>/step_<n>/`` per save; ``latest_step`` scans
for the newest complete one (the ``continue=1`` idiom, reborn sharded).
"""

from __future__ import annotations

import os
import re
from typing import Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp


_CK = None


def _shared_ck():
    """One StandardCheckpointer per process: its async-commit machinery is
    reused across the training loop's periodic saves."""
    global _CK
    if _CK is None:
        _CK = _checkpointer().StandardCheckpointer()
    return _CK


def _epath(p: str):
    """Filesystem-agnostic path (local or cloud URL) via etils epath —
    an orbax dependency, so always present where this module works."""
    from etils import epath
    return epath.Path(p)


_STEP_RE = re.compile(r'^step_(\d+)$')


def step_dir(ckpt_dir: str, step: int) -> str:
    return os.fspath(_epath(ckpt_dir) / f'step_{step}')


def _absolute(p) -> str:
    # orbax requires absolute paths for local saves; cloud URLs pass
    # through untouched
    s = os.fspath(p)
    return s if '://' in s else os.path.abspath(s)


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Newest complete checkpoint step in ``ckpt_dir`` (None if empty)."""
    base = _epath(ckpt_dir)
    if not base.exists():
        return None
    steps = []
    for child in base.iterdir():
        m = _STEP_RE.match(child.name)
        # orbax writes into a tmp dir and renames on commit, so a plain
        # step_N dir is complete
        if m and child.is_dir():
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def save_sharded(ckpt_dir: str, step: int, params, block: bool = True) -> str:
    """Write ``params`` (a pytree of possibly-sharded jax.Arrays) at
    ``step``; returns the checkpoint path.  ``block=False`` lets the
    commit overlap subsequent training steps (the previous pending save is
    always completed first); callers must ``wait_for_saves()`` before
    exit or before reading the checkpoint back."""
    path = _absolute(step_dir(ckpt_dir, step))
    ck = _shared_ck()
    ck.wait_until_finished()          # at most one save in flight
    ck.save(path, params)
    if block:
        ck.wait_until_finished()
    return path


def wait_for_saves() -> None:
    """Block until every async ``save_sharded(..., block=False)`` commit
    has landed."""
    if _CK is not None:
        _CK.wait_until_finished()


def restore_sharded(ckpt_dir: str, like, step: Optional[int] = None):
    """Restore the checkpoint at ``step`` (default: latest) with every
    leaf placed per ``like``'s shapes/dtypes/shardings — ``like`` is a
    pytree of sharding-annotated ``jax.ShapeDtypeStruct`` (e.g.
    ``models.transformer.abstract_params``) or of live sharded arrays.
    Returns (params, step)."""
    ocp = _checkpointer()
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f'no checkpoints under {ckpt_dir}')

    def to_abstract(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        return ocp.utils.to_shape_dtype_struct(x)

    target = jax.tree.map(to_abstract, like)
    params = _shared_ck().restore(_absolute(step_dir(ckpt_dir, step)),
                                  target)
    return params, step
