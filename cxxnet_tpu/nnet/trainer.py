"""The trainer: jitted train/eval steps over a device mesh.

TPU-native replacement for ``CXXNetThreadTrainer``
(``src/nnet/nnet_impl-inl.hpp:16-455``).  Where the reference runs one
pthread + model replica per GPU and syncs gradients through mshadow-ps
Push/PullReq, here a single jitted train step is partitioned over a
``jax.sharding.Mesh``: the batch is sharded along the ``data`` axis,
parameters are replicated, and XLA inserts the ICI all-reduce for the
gradients (the WFBP comm/compute overlap of ``async_updater-inl.hpp`` is
subsumed by XLA's latency-hiding scheduler).  The optimizer runs on-device
inside the same program — the TPU analogue of ``update_on_server``.

Reference semantics preserved:
* ``update_period`` — gradients accumulate across k minibatches; the
  optimizer applies on the k-th (``nnet_impl:149-150,181-184``),
* ``epoch_counter`` counts optimizer updates and drives LR schedules, and is
  saved in checkpoints,
* metrics: ``metric = error`` / ``metric[label,node] = logloss`` config
  forms; train metrics from forward outputs when ``eval_train=1``; eval
  excludes ``num_batch_padd`` padded instances,
* model file layout (``SaveModel``, nnet_impl:82-87): NetConfig +
  epoch_counter (int64) + length-prefixed blob of per-layer weights.
"""

from __future__ import annotations

import os
import re
import struct
from typing import BinaryIO, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..layers import ForwardContext
from ..parallel.mesh import (batch_sharding, build_mesh, param_shardings,
                             replicated_sharding)
from ..updater import (apply_updates, create_updater_hyper, init_opt_state)
from ..utils.metric import MetricSet
from . import checkpoint
from .net import Net
from .net_config import NetConfig

ConfigEntry = Tuple[str, str]


def _apply_input_norm(data, norm):
    """Device-side input normalization for raw uint8 batches
    (``device_normalize=1``): the augment stage's ``(x - mean) * scale``
    (``iter_augment_proc-inl.hpp:199-231``) applied inside the jitted
    step.  ``norm`` is ``()`` (host already normalized — no-op) or a
    ``(mean, scale)`` pair of device arrays; the pytree structure keys
    the jit cache, so the two paths compile separately.  f32 math before
    the net's compute-dtype cast, same rounding order as the host path."""
    if not norm:
        return data
    mean, scale = norm
    return (data.astype(jnp.float32) - mean) * scale


def parse_devices(val: str) -> List[int]:
    """Parse ``dev = tpu:0-3`` / ``dev = gpu:0,2`` / ``dev = cpu``
    (``nnet_impl-inl.hpp:31-55``).  Device ordinals index ``jax.devices()``;
    the device *kind* prefix is advisory (everything runs on the JAX default
    backend)."""
    if ':' not in val:
        return []
    devs = val.split(':', 1)[1]
    m = re.match(r'^(\d+)-(\d+)$', devs)
    if m:
        return list(range(int(m.group(1)), int(m.group(2)) + 1))
    return [int(t) for t in devs.split(',') if t]


class NetTrainer:
    """Config-driven trainer (INetTrainer surface, ``nnet/nnet.h:18-92``)."""

    def __init__(self, cfg: Optional[List[ConfigEntry]] = None):
        self.batch_size = 100
        self.update_period = 1
        self.sample_counter = 0
        self.eval_train = 1
        self.epoch_counter = 0
        self.seed = 0
        self.round = 0
        self.max_round = 1
        self.tensor_parallel = 1
        self.test_on_server = 0
        self.inference_only = 0    # skip optimizer-state allocation (serve)
        self.pred_buckets = None   # closed batch-size ladder for predict
        self.nan_action = 'none'
        self.nan_breaker = 0       # consecutive non-finite losses -> raise
        self.nan_streak = 0        # current consecutive non-finite count
        self._pending_loss = None  # (step, device loss) deferred one step
        self.compute_dtype = jnp.float32
        self.devices: List[int] = []
        self.metric = MetricSet()
        self.train_metric = MetricSet()
        self.eval_nodes: List[Tuple[str, int]] = []
        self.cfg: List[ConfigEntry] = []
        self.net_cfg = NetConfig()
        self.net: Optional[Net] = None
        self.params = None
        self.opt_state = None
        self.grad_acc = None
        self._mesh: Optional[Mesh] = None
        self._train_step_fn = None
        self._forward_fn = None
        self._pending_train_eval = None
        self._ones_mask_cache: Dict[int, object] = {}
        self._stack_jit = None     # device-side batch stacker (scanned loop)
        self._norm_dev = {}        # per-spec staged (mean, scale) consts
        if cfg:
            for name, val in cfg:
                self.set_param(name, val)

    # --- configuration ----------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        if name == 'dev':
            self.devices = parse_devices(val)
        if name == 'batch_size':
            self.batch_size = int(val)
        if name == 'update_period':
            self.update_period = int(val)
        if name == 'eval_train':
            self.eval_train = int(val)
        if name == 'seed':
            self.seed = int(val)
        if name == 'max_round':
            self.max_round = int(val)
        if name == 'tensor_parallel':
            self.tensor_parallel = int(val)
        if name == 'test_on_server':
            self.test_on_server = int(val)
        if name == 'inference_only':
            # serving-path trainers hold params only: no optimizer moments
            # or grad accumulator are ever allocated (serve/engine.py)
            self.inference_only = int(val)
        if name == 'pred_buckets':
            # bound the predict compile cache: every predict/extract batch
            # is padded to the smallest bucket that fits (oversize splits
            # into max-bucket chunks), so ad-hoc wrapper/C-ABI callers with
            # arbitrary batch sizes trace at most len(buckets) programs
            # (doc/serving.md).  Empty/0 disables.
            from ..utils.bucketing import parse_buckets
            v = val.strip()
            self.pred_buckets = None if v in ('', '0', 'none') \
                else parse_buckets(v)
        if name == 'nan_action':
            if val not in ('none', 'skip', 'halt'):
                raise ValueError(
                    f'nan_action must be none|skip|halt, got {val}')
            self.nan_action = val
        if name == 'nan_breaker':
            self.nan_breaker = int(val)
        if name == 'use_pallas':
            # process-wide tri-state read by ops.pallas_kernels.pallas_mode:
            # 1 = force every pallas path, 0 = disable even the measured
            # winners, auto (default) = per-op profitability gates
            if val.strip().lower() == 'auto':
                os.environ.pop('CXXNET_PALLAS', None)
            else:
                os.environ['CXXNET_PALLAS'] = val
        if name == 'compute_type':
            table = {'float32': jnp.float32, 'bfloat16': jnp.bfloat16,
                     'float16': jnp.float16}
            if val not in table:
                raise ValueError(f'unknown compute_type {val}')
            self.compute_dtype = table[val]
        if name == 'metric' or name.startswith('metric['):
            # forms: metric / metric[field] / metric[field,node]; the node
            # part may itself contain brackets (top[-1]), so split on the
            # first comma and strip the outermost brackets only
            if name == 'metric':
                field, node = 'label', ''
            else:
                # strip exactly one outer bracket: the node part may itself
                # end in one (metric[extra,top[-1]])
                inner = name[len('metric['):]
                if inner.endswith(']'):
                    inner = inner[:-1]
                field, _, node = inner.partition(',')
            self.metric.add_metric(val, field)
            self.train_metric.add_metric(val, field)
            self.eval_nodes.append((node, 0 if node else -1))
        self.cfg.append((name, val))

    # --- construction -----------------------------------------------------
    def _build_mesh(self) -> Mesh:
        # in a multi-process jax.distributed world, jax.devices() spans
        # every host, but this trainer must pick devices THIS process
        # can feed (host data is device_put from here) — so both the
        # default and an explicit dev= list index the LOCAL device set
        # there (the per-worker view, matching the reference's
        # one-worker-per-host deployment); gradients cross hosts at the
        # elastic/ps layer, not through the mesh
        all_devs = (jax.local_devices() if jax.process_count() > 1
                    else jax.devices())
        if self.devices:
            picked = [all_devs[i % len(all_devs)] for i in self.devices]
            # de-dup while preserving order (e.g. dev=tpu:0-3 on 1 chip)
            seen, devs = set(), []
            for d in picked:
                if d.id not in seen:
                    seen.add(d.id)
                    devs.append(d)
        else:
            devs = [all_devs[0]]
        return build_mesh(devs, tp=self.tensor_parallel)

    def _resolve_eval_nodes(self) -> List[int]:
        out = []
        last = self.net.cfg.layers[-1].nindex_out[-1]
        for name, _ in self.eval_nodes:
            out.append(last if name == '' else self.net.node_index(name))
        return out

    def init_net(self) -> None:
        """Build Net + updater hypers from the accumulated config."""
        self.net_cfg.configure(self.cfg)
        self.net = Net(self.net_cfg)
        self._mesh = self._build_mesh()
        self._eval_node_ids = self._resolve_eval_nodes()
        # per-weight tag-scoped hyperparameters
        self.hypers: Dict[str, Dict[str, object]] = {}
        for i, layer in enumerate(self.net.layers):
            if self.net.layer_primary[i] != i:
                continue
            fields = layer.param_fields
            if not fields:
                continue
            self.hypers[str(i)] = {
                tag: create_updater_hyper(self.net_cfg.updater_type, tag,
                                          self.net_cfg.defcfg,
                                          self.net_cfg.layercfg[i])
                for tag in fields}
        self._rng = jax.random.PRNGKey(self.seed)
        self._compile_steps()

    def init_model(self) -> None:
        self.init_net()
        self.params = self.net.init_params(jax.random.fold_in(self._rng, 0xC0FFEE))
        self._post_params_init()

    def _post_params_init(self) -> None:
        shardings = param_shardings(self.net, self.params, self._mesh)
        put = lambda tree: jax.tree.map(  # noqa: E731
            jax.device_put, tree, shardings)
        self.params = put(self.params)
        if self.inference_only:
            # serving holds params only — roughly 1/3 the device memory of
            # a momentum trainer, 1/4 of Adam; update() refuses below
            self.opt_state = None
            self.grad_acc = None
            return
        opt = init_opt_state(self.net_cfg.updater_type, self.params)
        self.opt_state = {k: put(v) for k, v in opt.items()}
        self.grad_acc = put(jax.tree.map(jnp.zeros_like, self.params))

    def _norm_args(self, batch):
        """Device constants for a deferred-normalization batch: ``()`` when
        none needed (host-normalized float32, or raw uint8 bench data with
        no spec).  Keyed on the spec alone — raw data is usually uint8 but
        an active affine warp yields raw float32, which still needs the
        deferred (x-mean)*scale.  Built once — the spec is chain-constant."""
        spec = getattr(batch, 'norm_spec', None)
        if spec is None:
            return ()
        cached = self._norm_dev.get(id(spec))
        if cached is not None and cached[0] is spec:
            self._norm_dev[id(spec)] = self._norm_dev.pop(id(spec))  # LRU
            return cached[1]
        mean = spec.resolved_mean()
        sh = replicated_sharding(self._mesh)
        consts = (jax.device_put(jnp.asarray(mean), sh),
                  jax.device_put(jnp.float32(spec.scale), sh))
        # keyed per spec instance (train and eval chains may normalize
        # differently); the spec ref pins the id against reuse.  Bounded:
        # a trainer cycling many iterators must not pin every spec's
        # device consts for its lifetime
        if len(self._norm_dev) >= 8:
            self._norm_dev.pop(next(iter(self._norm_dev)))
        self._norm_dev[id(spec)] = (spec, consts)
        return consts

    def _shard_batch(self, data: np.ndarray, cast: bool = True):
        data = np.asarray(data)
        if data.dtype == np.float64:
            data = data.astype(np.float32)
        elif (cast and data.dtype == np.float32
              and self.compute_dtype == jnp.bfloat16):
            # ship activations at compute precision (host-side cast via
            # ml_dtypes): halves H2D traffic
            import ml_dtypes
            data = data.astype(ml_dtypes.bfloat16)
        return jax.device_put(jnp.asarray(data), batch_sharding(self._mesh))

    # --- jitted steps -----------------------------------------------------
    def _make_loss_fn(self):
        net = self.net
        eval_ids = self._eval_node_ids
        compute_dtype = self.compute_dtype
        max_round = self.max_round
        spmd = self._mesh.devices.size

        def loss_fn(params, data, label, extra, mask, rng, rnd, norm=()):
            data = _apply_input_norm(data, norm)
            ctx = ForwardContext(is_train=True, rng=rng, round=rnd,
                                 max_round=max_round,
                                 compute_dtype=compute_dtype,
                                 spmd_devices=spmd)
            values, loss = net.forward(params, data, ctx,
                                       labels=net.make_label_info(label),
                                       loss_mask=mask, extra_data=extra)
            return loss, [values[i] for i in eval_ids]

        return loss_fn

    def _claim_programs(self) -> None:
        """Claim this trainer's ledger program names (obs/programs.py):
        every compiled executable registers its compile wall-ms + HLO
        cost/memory into the process-wide ProgramLedger, served on
        ``/programs`` and read back by :meth:`train_step_flops`."""
        from ..obs.programs import get_ledger
        led = get_ledger()
        self._prog_step = led.program('train.step')
        self._prog_forward = led.program('train.forward')
        self._prog_multi = led.program('train.multi_step')
        self._prog_multi_fwd = led.program('train.multi_forward')
        self._prog_grad = None        # claimed on first compile_grad_step
        self._prog_apply = None       # claimed on first compile_apply_grad

    def _compile_steps(self) -> None:
        updater_type = self.net_cfg.updater_type
        hypers = self.hypers
        loss_fn = self._make_loss_fn()
        self._claim_programs()

        nan_skip = self.nan_action == 'skip'

        def train_step(params, opt_state, grad_acc, data, label, extra, mask,
                       rng, epoch, rnd, do_update, norm=()):
            (loss, evals), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, data, label, extra, mask,
                                       rng, rnd, norm)
            if nan_skip:
                # failure detection beyond the reference's NaN-zeroing clip
                # (sgd_updater-inl.hpp:15-22): a non-finite loss — or a
                # finite loss whose backward overflowed (0*inf etc.) —
                # poisons the weights; drop this batch's contribution
                ok = jnp.isfinite(loss)
                for g in jax.tree.leaves(grads):
                    ok &= jnp.all(jnp.isfinite(g))
                grads = jax.tree.map(
                    lambda g: jnp.where(ok, g, jnp.zeros_like(g)), grads)
            grad_acc = jax.tree.map(jnp.add, grad_acc, grads)
            if do_update:
                params, opt_state = apply_updates(
                    updater_type, hypers, params, grad_acc, opt_state, epoch)
                grad_acc = jax.tree.map(jnp.zeros_like, grad_acc)
            return params, opt_state, grad_acc, loss, evals

        net = self.net
        compute_dtype = self.compute_dtype
        max_round = self.max_round

        spmd = self._mesh.devices.size

        def forward_step(params, data, extra, rnd, norm=()):
            data = _apply_input_norm(data, norm)
            ctx = ForwardContext(is_train=False, rng=None, round=rnd,
                                 max_round=max_round,
                                 compute_dtype=compute_dtype,
                                 spmd_devices=spmd)
            values, _ = net.forward(params, data, ctx, extra_data=extra)
            return values

        # ledger-routed jit (obs/programs.py): the plain jax.jit C++
        # dispatch, plus a /programs row per compiled signature
        self._train_step_fn = self._prog_step.jit(
            train_step, static_argnames=('do_update',),
            donate_argnums=(0, 1, 2))
        self._forward_fn = self._prog_forward.jit(forward_step)
        self._stack_jit = None     # mesh may have changed: rebuild lazily

    def compile_multi_step(self, n_steps: int, train_eval: bool = False):
        """Jitted ``n_steps``-training-step function: ONE dispatch runs the
        whole loop on device via ``lax.scan`` over the (params, opt_state,
        grad_acc) carry, cycling round-robin through a leading-axis stack
        of pre-staged batches.

        Exists because per-step dispatch does not pipeline over the remote
        chip tunnel (each call costs the full link RTT, ~7 ms, regardless
        of the op), so any per-dispatch measurement bottoms out at the
        link latency — and because a scanned inner loop is also the natural
        production shape when the input pipeline pre-stages batch stacks.
        Counterpart of the reference's tight in-process hot loop
        (``nnet_impl-inl.hpp:141-185``), which never pays a per-step
        dispatch boundary either.

        Composes with the production constraints the per-step path
        carries (the ExecutionPlan contract, doc/trainer.md):

        * ``update_period = P`` — the gradient accumulator rides the scan
          carry; step ``t`` adds its grads and the optimizer applies (and
          the epoch counter advances) only when ``(sc0 + t + 1) % P == 0``
          — the EXACT per-step cadence, so windows need not align with
          accumulation boundaries (a partial accumulation carries across
          dispatches through the trainer's live ``grad_acc``).
        * ``train_eval=True`` — each step's eval-node outputs ride the
          scan's stacked ys, so ``eval_train=1`` train metrics cost ONE
          host readback per dispatch instead of one per step
          (:meth:`update_staged_window` defers it one dispatch, mirroring
          the per-step deferred readback).

        Returns ``fn(params, opt_state, grad_acc, data_stack, label_stack,
        base_rng, epoch0, sc0, mask_stack, rnd) -> (params, opt_state,
        grad_acc, losses, evals)`` with ``fn.n_steps`` / ``fn.train_eval``
        attached; drive it through :meth:`update_n_on_device` to keep
        trainer counters coherent (round-dependent layers and tail-batch
        masks follow the same semantics as the per-step :meth:`update`
        path: ``rnd`` is traced, ``mask_stack`` rides the batch stack).

        Step ``t`` derives its dropout key as ``fold_in(base_rng,
        1 + (sc0 + t) * 131 + rnd)`` — the EXACT key the per-step
        :meth:`update_staged` path computes at sample counter ``sc0+t``,
        so a K-step dispatch is bitwise-identical to K per-step
        dispatches even for stochastic nets; ``losses`` is the full
        ``(n_steps,)`` per-step loss vector so the divergence gate sees
        every step, not just the last.
        """
        loss_fn = self._make_loss_fn()
        updater_type = self.net_cfg.updater_type
        hypers = self.hypers
        nan_skip = self.nan_action == 'skip'
        period = max(1, int(self.update_period))

        def multi_step(params, opt_state, grad_acc, data_stack, label_stack,
                       base_rng, epoch0, sc0, mask_stack, rnd, norm=()):
            nstack = data_stack.shape[0]

            def body(carry, t):
                params, opt_state, grad_acc, epoch = carry
                data = jax.lax.dynamic_index_in_dim(
                    data_stack, t % nstack, keepdims=False)
                label = jax.lax.dynamic_index_in_dim(
                    label_stack, t % nstack, keepdims=False)
                mask = jax.lax.dynamic_index_in_dim(
                    mask_stack, t % nstack, keepdims=False)
                rng = jax.random.fold_in(base_rng, 1 + (sc0 + t) * 131 + rnd)
                (loss, evals), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, data, label, (), mask,
                                           rng, rnd, norm)
                if nan_skip:
                    ok = jnp.isfinite(loss)
                    for g in jax.tree.leaves(grads):
                        ok &= jnp.all(jnp.isfinite(g))
                    grads = jax.tree.map(
                        lambda g: jnp.where(ok, g, jnp.zeros_like(g)), grads)
                # accumulate-then-apply, exactly as the per-step path: the
                # 0+g add is kept even at P=1 so the float ops match
                # bitwise (the per-step train_step always adds into the
                # zeroed accumulator before applying)
                grad_acc = jax.tree.map(jnp.add, grad_acc, grads)
                if period == 1:
                    params, opt_state = apply_updates(
                        updater_type, hypers, params, grad_acc, opt_state,
                        epoch)
                    grad_acc = jax.tree.map(jnp.zeros_like, grad_acc)
                    epoch = epoch + 1
                else:
                    def _apply(args):
                        p, o, g, e = args
                        p, o = apply_updates(updater_type, hypers, p, g, o,
                                             e)
                        return p, o, jax.tree.map(jnp.zeros_like, g), e + 1

                    params, opt_state, grad_acc, epoch = jax.lax.cond(
                        (sc0 + t + 1) % period == 0, _apply,
                        lambda args: args,
                        (params, opt_state, grad_acc, epoch))
                ys = (loss, tuple(evals) if train_eval else ())
                return (params, opt_state, grad_acc, epoch), ys

            (params, opt_state, grad_acc, _), (losses, evals) = jax.lax.scan(
                body, (params, opt_state, grad_acc, epoch0),
                jnp.arange(n_steps))
            return params, opt_state, grad_acc, losses, evals

        # one ledger entry per (K, train_eval) window shape.  steps=1,
        # NOT n_steps: the window is a lax.scan and XLA cost analysis
        # counts a While body ONCE, so the reported flops already ARE
        # one step's — dividing by K would under-report MFU K-fold
        wrapped = self._prog_multi.jit(
            multi_step, donate_argnums=(0, 1, 2),
            key=f'k{n_steps}{"e" if train_eval else ""}')

        def multi_fn(params, opt_state, grad_acc, data_stack, label_stack,
                     base_rng, epoch0, sc0, mask_stack, rnd, norm=()):
            return wrapped(params, opt_state, grad_acc, data_stack,
                           label_stack, base_rng, epoch0, sc0,
                           mask_stack, rnd, norm)

        multi_fn.n_steps = n_steps
        multi_fn.train_eval = train_eval
        multi_fn.update_period = period
        return multi_fn

    def compile_multi_forward(self, n_steps: int):
        """Jitted ``n_steps``-forward-only function (the pred/extract/
        evaluate compute path — ``is_train=False``, no grads, no
        optimizer): ONE dispatch scans over a pre-staged batch stack and
        returns a f32 checksum of the top node, whose fetch is the
        completion barrier.  Same rationale as :meth:`compile_multi_step`
        (per-dispatch timing over the dev-harness tunnel measures the
        link); used by ``bench.py eval_alexnet`` to time eval throughput
        at net level (the fc8-class Pallas forward gate —
        ``ops.pallas_kernels.fullc_use_pallas`` — only ever engages on
        this path)."""
        net = self.net
        compute_dtype = self.compute_dtype
        max_round = self.max_round
        spmd = self._mesh.devices.size
        top = net.cfg.layers[-1].nindex_out[-1]

        def multi_fwd(params, data_stack, rnd, norm=()):
            nstack = data_stack.shape[0]

            def body(acc, t):
                data = jax.lax.dynamic_index_in_dim(
                    data_stack, t % nstack, keepdims=False)
                data = _apply_input_norm(data, norm)
                ctx = ForwardContext(is_train=False, rng=None, round=rnd,
                                     max_round=max_round,
                                     compute_dtype=compute_dtype,
                                     spmd_devices=spmd)
                values, _ = net.forward(params, data, ctx)
                return acc + jnp.sum(values[top].astype(jnp.float32)), None

            acc, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(n_steps))
            return acc

        # steps=1 for the same reason as compile_multi_step: the scan
        # body is counted once by XLA cost analysis
        wrapped = self._prog_multi_fwd.jit(multi_fwd, key=f'k{n_steps}')

        def fwd_fn(params, data_stack, rnd=0, norm=()):
            return wrapped(params, data_stack, rnd, norm)

        fwd_fn.n_steps = n_steps
        return fwd_fn

    def compile_grad_step(self):
        """Jitted ``(params, data, label, extra, mask, rng, rnd, norm)
        -> (loss, grads)``: the forward/backward of ``train_step``
        WITHOUT the optimizer apply or accumulator — the elastic
        multi-host runtime (``parallel/elastic.py``) computes one
        gradient contribution per micro-shard of the global batch,
        exchanges them across hosts, and applies the fixed-order
        combination through :meth:`compile_apply_grad`.  Nothing is
        donated: params are reused across every shard of a step."""
        loss_fn = self._make_loss_fn()

        def grad_step(params, data, label, extra, mask, rng, rnd,
                      norm=()):
            (loss, _evals), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, data, label, extra, mask,
                                       rng, rnd, norm)
            return loss, grads

        if self._prog_grad is None:
            from ..obs.programs import get_ledger
            self._prog_grad = get_ledger().program('train.grad_step')
        return self._prog_grad.jit(grad_step)

    def compile_apply_grad(self):
        """Jitted ``(params, opt_state, grads, epoch) -> (params,
        opt_state)``: ONE optimizer step over an already-combined
        gradient tree.  The elastic runtime feeds it the cross-host
        shard sum — every host applies the identical bytes, so the
        replicated params stay bitwise equal with no broadcast."""
        updater_type = self.net_cfg.updater_type
        hypers = self.hypers

        def apply_grad(params, opt_state, grads, epoch):
            params, opt_state = apply_updates(
                updater_type, hypers, params, grads, opt_state, epoch)
            return params, opt_state

        if self._prog_apply is None:
            from ..obs.programs import get_ledger
            self._prog_apply = get_ledger().program('train.apply_grad')
        return self._prog_apply.jit(apply_grad, donate_argnums=(0, 1))

    def shard_batch_stack(self, stack: np.ndarray, cast: bool = True):
        """Stage a (nstack, batch, ...) stack of batches on device with the
        batch axis (axis 1) sharded over the mesh's data axis."""
        stack = np.asarray(stack)
        if stack.dtype == np.float64:
            stack = stack.astype(np.float32)
        elif (cast and stack.dtype == np.float32
              and self.compute_dtype == jnp.bfloat16):
            import ml_dtypes
            stack = stack.astype(ml_dtypes.bfloat16)
        sh = NamedSharding(self._mesh, P(None, 'data'))
        return jax.device_put(jnp.asarray(stack), sh)

    def update_n_on_device(self, multi_fn, data_stack, label_stack,
                           n_steps: int = None, mask_stack=None, norm=(),
                           train_eval=None):
        """Run a :meth:`compile_multi_step` function over pre-staged stacks,
        keeping epoch/sample counters coherent.  ``n_steps`` defaults to —
        and must match — the step count baked into ``multi_fn`` at compile
        time, so the counters can never desynchronize from the steps
        actually executed.  ``mask_stack`` (nstack, batch) defaults to
        all-ones (no tail-batch pads).  ``norm``: stacks of RAW (un-
        normalized) pixels from a ``device_normalize=1`` chain need the
        deferred (mean, scale) device constants — pass
        ``trainer._norm_args(batch)`` of any batch carrying the chain's
        spec; the default () means the stack is already normalized.
        ``train_eval``: a ``(label_infos, ns)`` pair (one per step) when
        ``multi_fn`` was compiled with ``train_eval=True`` — the stacked
        eval-node outputs then feed ``train_metric`` exactly as K per-step
        readbacks would, deferred one dispatch.  Returns the last loss
        (device scalar — fetching it is a real completion barrier)."""
        if self.inference_only:
            raise RuntimeError(
                'trainer was built inference_only=1 (no optimizer state); '
                'it can predict/evaluate but not train')
        compiled = getattr(multi_fn, 'n_steps', None)
        if n_steps is None:
            n_steps = compiled
        elif compiled is not None and n_steps != compiled:
            raise ValueError(
                f'n_steps={n_steps} does not match the step count '
                f'{compiled} compiled into multi_fn')
        if mask_stack is None:
            mask_stack = self._ones_mask_stack(data_stack.shape[:2])
        sc0 = self.sample_counter
        old_pending = self._pending_train_eval
        self._pending_train_eval = None
        (self.params, self.opt_state, self.grad_acc, losses, evals) = \
            multi_fn(self.params, self.opt_state, self.grad_acc, data_stack,
                     label_stack, self._rng, self.epoch_counter, sc0,
                     mask_stack, self.round, norm)
        # the accumulation cadence BAKED INTO the compiled body, not the
        # live config — a multi_fn compiled before an update_period tweak
        # applies the optimizer on its compile-time cadence, and the host
        # epoch counter must follow the same one
        period = getattr(multi_fn, 'update_period',
                         max(1, self.update_period))
        if period == 1:
            self.epoch_counter += n_steps
        else:
            # optimizer applications this window — same cadence the scan
            # body's in-graph counter followed
            self.epoch_counter += sum(
                1 for t in range(n_steps) if (sc0 + t + 1) % period == 0)
        self.sample_counter += n_steps
        if train_eval is not None:
            label_infos, ns = train_eval
            # window-shaped pending (dict-tagged): one readback per
            # dispatch, drained one dispatch late like the per-step path
            self._pending_train_eval = {
                'losses': losses, 'evals': evals,
                'infos': label_infos, 'ns': ns}
        if old_pending is not None:
            self._drain_train_eval(old_pending)
        self._gate_losses(losses, sc0)
        return losses[-1]

    def _gate_losses(self, losses, sc0: int) -> None:
        """Divergence gate over a scanned dispatch's per-step losses.
        Only when something can act on them (halt / breaker / NaN
        injection — same arming rule as ``_observe_loss``) does this
        fetch the loss vector (ONE host sync per K-step dispatch, the
        scanned path's analogue of the per-step deferred check); every
        step feeds ``_check_loss`` so ``nan_at_step``-style events and
        consecutive-NaN streaks land on the exact step index."""
        from ..runtime import faults
        plan = faults.active_plan()
        inject = plan is not None and plan.has_nan_events()
        if self.nan_action != 'halt' and not self.nan_breaker and not inject:
            return
        for t, loss in enumerate(np.asarray(losses)):
            self._check_loss(sc0 + t, loss)

    def _ones_mask_stack(self, shape):
        """Cached on-device all-ones (nstack, batch) loss-mask stack for
        :meth:`update_n_on_device` — the common no-pad case costs no
        per-call H2D transfer."""
        key = ('stack',) + tuple(shape)
        cached = self._ones_mask_cache.get(key)
        if cached is None:
            cached = self.shard_batch_stack(
                np.ones(shape, np.float32), cast=False)
            self._ones_mask_cache[key] = cached
        return cached

    def _device_stack(self, arrays):
        """Stack already-staged per-batch device arrays (batch axis
        sharded over ``data``) into the (nstack, batch, ...) layout
        :meth:`compile_multi_step` scans — a device-side op, so the
        per-batch async H2D transfers :meth:`stage_batch` enqueued are
        never re-shipped over the host link."""
        if self._stack_jit is None:
            sh = NamedSharding(self._mesh, P(None, 'data'))
            # lint: allow(jit-ledger): trivial on-device restage (one stack op, no flops worth a ledger row); shapes bounded by the K ladder
            self._stack_jit = jax.jit(lambda *xs: jnp.stack(xs),
                                      out_shardings=sh)
        return self._stack_jit(*arrays)

    def update_staged_window(self, multi_fn, staged_list):
        """Drive one :meth:`compile_multi_step` dispatch over a window of
        K batches staged by :meth:`stage_batch` — the production scanned
        hot loop (``steps_per_dispatch``, doc/trainer.md).  The staged
        handles' async H2D transfers overlap earlier dispatches; here
        they are stacked on device and the whole window runs as ONE
        program: zero per-step dispatch/link RTT.  Tail-batch loss masks
        ride the stack, so ``round_batch=0`` pad rows stay out of the
        gradients exactly as on the per-step path.  Counters, LR
        schedule, dropout keys and the divergence gate all match K
        per-step calls bitwise.  Returns the window's last loss (device
        scalar)."""
        if self.inference_only:
            raise RuntimeError(
                'trainer was built inference_only=1 (no optimizer state); '
                'it can predict/evaluate but not train')
        if len(staged_list) != multi_fn.n_steps:
            raise ValueError(
                f'window of {len(staged_list)} batches does not match the '
                f'step count {multi_fn.n_steps} compiled into multi_fn')
        for s in staged_list:
            if s[2]:
                raise ValueError(
                    'scanned dispatch does not carry extra_data '
                    '(attachtxt chains); use the per-step path')
        train_eval = None
        armed = bool(self.eval_train and len(self.train_metric))
        if armed and not getattr(multi_fn, 'train_eval', False):
            raise ValueError(
                'eval_train=1 with train metrics needs a multi_fn compiled '
                'with train_eval=True, or the window\'s metrics are lost')
        if getattr(multi_fn, 'train_eval', False):
            infos = [_HostLabelInfo(s[4], self.net_cfg.label_name_map,
                                    self.net_cfg.label_range)
                     for s in staged_list]
            ns = [s[5] - s[6] for s in staged_list]
            train_eval = (infos, ns)
        data_stack = self._device_stack([s[0] for s in staged_list])
        label_stack = self._device_stack([s[1] for s in staged_list])
        mask_stack = self._device_stack([s[3] for s in staged_list])
        return self.update_n_on_device(
            multi_fn, data_stack, label_stack, mask_stack=mask_stack,
            norm=staged_list[0][7], train_eval=train_eval)

    # --- training ---------------------------------------------------------
    def start_round(self, round_: int) -> None:
        self.round = round_
        if self.test_on_server:
            bad = self.check_weight_consistency()
            if bad:
                raise RuntimeError(
                    f'{bad} weight tensors diverged across replicas')

    def check_weight_consistency(self) -> int:
        """``test_on_server`` analog (``async_updater-inl.hpp:144-154``).

        The reference had every worker fetch the server's weight copy at
        round start and compare.  Here there is no server: the invariant is
        that every device holding a replica of the same parameter shard
        agrees bitwise (catching nondeterministic collectives or sharding
        bugs).  Returns the number of mismatching tensors; mismatches are
        reported on stderr like the reference's CheckWeight_.
        """
        import sys
        bad = 0
        for lk, fields in self.params.items():
            for fk, arr in fields.items():
                seen: Dict[str, np.ndarray] = {}
                for sh in arr.addressable_shards:
                    key = str(sh.index)
                    d = np.asarray(sh.data)
                    if key in seen:
                        if not np.array_equal(seen[key], d, equal_nan=True):
                            bad += 1
                            sys.stderr.write(
                                f'weight inconsistent: layer {lk} field {fk} '
                                f'(device {sh.device})\n')
                            break
                    else:
                        seen[key] = d
        return bad

    def stage_batch(self, batch):
        """Begin the async host->device staging of a batch: every
        ``device_put`` here only ENQUEUES its transfer, so calling this
        for batch i+1 before dispatching batch i's step overlaps the host
        link with compute (the H2D half of the reference's prefetch
        design, ``iter_thread_buffer``; the device half is
        :meth:`update_staged`).  Returns an opaque handle for
        :meth:`update_staged`.  Safe because the batch adapters allocate
        fresh arrays per batch (io/iter_batch.py)."""
        norm = self._norm_args(batch)
        # raw (uncentered) pixels must not be pre-cast to bf16: values
        # ~128 lose ~0.4% relative each, which mean-subtraction amplifies
        # ~100x.  uint8 ships as-is; raw f32 (affine path) ships f32 and
        # is centered on device before any compute-dtype cast.
        data = self._shard_batch(batch.data, cast=not norm)
        label = self._shard_batch(batch.label, cast=False)
        extra = tuple(self._shard_batch(e) for e in batch.extra_data)
        # synthetic pad rows of a short tail batch (round_batch=0) carry
        # zero loss-mask so they contribute nothing to grads; real rows —
        # including round_batch=1 wrapped instances, which the reference
        # trains on (nnet_impl:141-170) — keep the reference's per-instance
        # 1/batch_size weight
        bs = batch.batch_size
        if batch.num_batch_padd and getattr(batch, 'pad_synthetic', False):
            mask = np.ones(bs, np.float32)
            mask[bs - batch.num_batch_padd:] = 0.0
            mask = self._shard_batch(mask, cast=False)
        else:
            mask = self._ones_mask(bs)
        host_label = (np.asarray(batch.label)
                      if self.eval_train and len(self.train_metric) else None)
        return (data, label, extra, mask, host_label, bs,
                batch.num_batch_padd, norm)

    def update(self, batch) -> None:
        """One minibatch through forward/backward/(maybe) update —
        the reference hot loop (``nnet_impl:141-185``)."""
        self.update_staged(self.stage_batch(batch))

    def update_staged(self, staged) -> None:
        """Dispatch the training step for a batch staged by
        :meth:`stage_batch`."""
        if self.inference_only:
            raise RuntimeError(
                'trainer was built inference_only=1 (no optimizer state); '
                'it can predict/evaluate but not train')
        (data, label, extra, mask, host_label, bs, num_batch_padd,
         norm) = staged
        do_update = (self.sample_counter + 1) % self.update_period == 0
        rng = jax.random.fold_in(self._rng, 1 + self.sample_counter * 131 +
                                 self.round)
        old_pending = self._pending_train_eval
        self._pending_train_eval = None
        (self.params, self.opt_state, self.grad_acc, loss, evals) = \
            self._train_step_fn(self.params, self.opt_state, self.grad_acc,
                                data, label, extra, mask, rng,
                                self.epoch_counter, self.round,
                                do_update=do_update, norm=norm)
        self._observe_loss(loss)
        if host_label is not None:
            # defer this step's metric readback one step: by the next
            # update() (or evaluate()) the values are already on host, so
            # no per-step device sync — the analogue of the reference's
            # reuse of already-copied eval nodes (nnet_impl:174-180)
            label_info = _HostLabelInfo(host_label,
                                        self.net_cfg.label_name_map,
                                        self.net_cfg.label_range)
            self._pending_train_eval = (
                loss, evals, label_info, bs - num_batch_padd)
        if old_pending is not None:
            self._drain_train_eval(old_pending)
        if do_update:
            self.epoch_counter += 1
        self.sample_counter += 1

    def _observe_loss(self, loss) -> None:
        """Host-side divergence gate over the step's loss.

        Extends the ``nan_action`` gate beyond per-batch ``skip`` (which
        only zeroes the poisoned gradients in-graph): ``halt`` raises
        ``DivergenceError`` with step/loss context on the first
        non-finite loss, and a nonzero ``nan_breaker`` is a
        consecutive-NaN circuit breaker — after k non-finite losses in a
        row the error raises regardless of ``nan_action``, so a
        supervisor can skip transient spikes but abort-and-restore on
        sustained divergence.  Only engages when something can act on
        the value (halt, a breaker, or an active NaN-injection fault
        plan).

        The check is deferred ONE step (the same idiom as the deferred
        train-metric readback above): this step's device value is
        stashed and the previous step's — materialized on host by now —
        is inspected, so the gate adds no per-step blocking sync.
        Divergence therefore surfaces one update late; callers settle
        the final pending value with :meth:`flush_divergence_check`."""
        from ..runtime import faults
        plan = faults.active_plan()
        inject = plan is not None and plan.has_nan_events()
        if self.nan_action != 'halt' and not self.nan_breaker and not inject:
            return
        prev, self._pending_loss = (self._pending_loss,
                                    (self.sample_counter, loss))
        if prev is not None:
            self._check_loss(*prev)

    def flush_divergence_check(self) -> None:
        """Settle the deferred divergence gate — call after a batch
        loop's last ``update``, or the final step's loss goes
        unchecked."""
        prev, self._pending_loss = self._pending_loss, None
        if prev is not None:
            self._check_loss(*prev)

    def reset_transient_state(self) -> None:
        """Clear per-step in-flight state a fault may have poisoned —
        the supervisor calls this before restoring a checkpoint.  Keeps
        the reset next to the state it protects: the deferred metric
        readback, the deferred divergence gate, and the NaN streak.
        Train metrics are not part of the exact-resume tree, so they are
        cleared too — replayed batches must not double-count (the
        recovered round reports metrics over the post-restore pass
        only)."""
        self._pending_train_eval = None
        self._pending_loss = None
        self.nan_streak = 0
        self.train_metric.clear()

    def _check_loss(self, step: int, loss) -> None:
        from ..runtime import faults
        lf = float(loss)
        plan = faults.active_plan()
        if plan is not None:
            lf = plan.on_loss(step, lf)
        if np.isfinite(lf):
            self.nan_streak = 0
            return
        self.nan_streak += 1
        if self.nan_action == 'halt' or (
                self.nan_breaker and self.nan_streak >= self.nan_breaker):
            raise faults.DivergenceError(step, lf, self.nan_streak)

    def flush_train_metrics(self) -> None:
        """Force the one-step-deferred train-metric readback (see
        ``update``); after this, ``train_metric`` reflects every update so
        far.  ``evaluate`` calls it implicitly."""
        if self._pending_train_eval is not None:
            pending, self._pending_train_eval = self._pending_train_eval, None
            self._drain_train_eval(pending)

    def _ones_mask(self, bs: int):
        """Cached on-device all-ones loss mask — the no-pad common case
        costs no per-step H2D transfer."""
        cached = self._ones_mask_cache.get(bs)
        if cached is None:
            cached = self._shard_batch(np.ones(bs, np.float32), cast=False)
            self._ones_mask_cache[bs] = cached
        return cached

    def _drain_train_eval(self, pending) -> None:
        if isinstance(pending, dict):
            # a scanned window's stacked eval outputs: ONE readback, then
            # the per-step host math in step order — bitwise the same
            # metric accumulation as K per-step drains
            losses = np.asarray(pending['losses'])
            evals = [np.asarray(e) for e in pending['evals']]
            for t, (info, n) in enumerate(zip(pending['infos'],
                                              pending['ns'])):
                if self.nan_action == 'skip' and not np.isfinite(losses[t]):
                    continue
                self.train_metric.add_eval([e[t][:n] for e in evals],
                                           info.slice(n))
            return
        loss, evals, label_info, n = pending
        if self.nan_action == 'skip' and not np.isfinite(float(loss)):
            return  # poisoned batch: its NaN outputs would wreck the
                    # round's train metrics along with the weights
        self.train_metric.add_eval(
            [np.asarray(e)[:n] for e in evals], label_info.slice(n))

    def update_on_device(self, data, label, norm=()) -> None:
        """One training step over batches already resident on device (jax
        arrays with the right shardings).  Used by benchmarks and by data
        pipelines that pre-stage batches to hide host->device latency.
        ``norm``: required (as from :meth:`_norm_args`) when ``data`` is
        RAW pixels from a ``device_normalize=1`` chain."""
        do_update = (self.sample_counter + 1) % self.update_period == 0
        rng = jax.random.fold_in(self._rng, 1 + self.sample_counter * 131 +
                                 self.round)
        (self.params, self.opt_state, self.grad_acc, _, _) = \
            self._train_step_fn(self.params, self.opt_state, self.grad_acc,
                                data, label, (), None, rng,
                                self.epoch_counter, self.round,
                                do_update=do_update, norm=norm)
        if do_update:
            self.epoch_counter += 1
        self.sample_counter += 1

    def train_step_flops(self, data=None, label=None,
                         analyzed_only=False) -> float:
        """HLO-estimated FLOPs of one full optimizer step (fwd + bwd +
        update).  Reads the LIVE program ledger first (obs/programs.py):
        any step this trainer already compiled — per-step or scanned
        window, whose While body XLA cost analysis counts once, so
        its flops are already per-step — answers for free,
        instead of lowering+compiling a throwaway program per call.
        Only when nothing has compiled yet (and ``data``/``label`` are
        given — the bench-facing signature) does it compile one probe,
        through the same ledger wrap so even the probe gets a
        ``/programs`` row.  ``analyzed_only=True`` never triggers the
        lazy AOT analysis — the render-thread spelling (/statusz
        providers), which reports 0.0 until some detailed reader has
        filled the entries.  Returns 0.0 when the backend exposes no
        cost model."""
        best = 0.0
        for prog in (self._prog_multi, self._prog_step):
            for e in prog.entries(analyze=not analyzed_only):
                if e.flops > 0:
                    # prefer the biggest per-step figure: the do_update
                    # (full optimizer) step dominates its no-update twin
                    best = max(best, e.flops / e.steps)
        if analyzed_only:
            return best
        if best > 0:
            return best
        if data is None or label is None:
            return 0.0
        rng = jax.random.fold_in(self._rng, 0)
        try:
            entry = self._train_step_fn.ensure_compiled(
                self.params, self.opt_state, self.grad_acc, data, label,
                (), None, rng, self.epoch_counter, self.round,
                do_update=True)
            return float(entry.flops) if entry is not None else 0.0
        except (AttributeError, KeyError, TypeError, ValueError,
                NotImplementedError, RuntimeError) as e:
            # backends without a cost model surface it many ways; record
            # the miss instead of swallowing it so a supervisor's failure
            # log shows why MFU reads 0
            from ..runtime import faults
            faults.global_failure_log().record(
                'cost_analysis', f'train_step_flops unavailable: {e!r}')
            return 0.0

    # --- evaluation / prediction ------------------------------------------
    def _forward_nodes_async(self, batch, node_ids: List[int]):
        """Launch the forward pass; returns device arrays (no readback)."""
        extra = tuple(self._shard_batch(e) for e in batch.extra_data)
        norm = self._norm_args(batch)
        # raw uncentered pixels: same no-bf16-precast rule as stage_batch
        values = self._forward_fn(self.params,
                                  self._shard_batch(batch.data,
                                                    cast=not norm),
                                  extra, self.round, norm=norm)
        return [values[i] for i in node_ids]

    def _forward_nodes(self, batch, node_ids: List[int]) -> List[np.ndarray]:
        return [np.asarray(v)
                for v in self._forward_nodes_async(batch, node_ids)]

    def evaluate(self, data_iter, name: str) -> str:
        """Run metrics over an iterator; returns the reference's stderr
        format ``\\tname-metric:value``.  Like the reference
        (``nnet_impl:224-245``), the pending train metrics are prepended
        (and cleared) when ``eval_train`` is set; ``data_iter=None``
        returns just the train part."""
        ret = ''
        self.flush_train_metrics()
        if self.eval_train and len(self.train_metric):
            ret += self.train_metric.print('train')
            self.train_metric.clear()
        if data_iter is None:
            return ret
        self.metric.clear()
        # one-batch software pipeline: batch i+1's forward is enqueued
        # before batch i's outputs are read back, so the device computes
        # while the host blocks on the transfer (the reference's
        # eval-request copies overlap the same way, nnet_impl:232-241)
        pending = None

        def _consume(p):
            outs, label_info, n = p
            self.metric.add_eval([np.asarray(o)[:n] for o in outs],
                                 label_info.slice(n))

        for batch in data_iter:
            outs = self._forward_nodes_async(batch, self._eval_node_ids)
            n = batch.batch_size - batch.num_batch_padd
            label_info = _HostLabelInfo(np.asarray(batch.label),
                                        self.net_cfg.label_name_map,
                                        self.net_cfg.label_range)
            prev, pending = pending, (outs, label_info, n)
            if prev is not None:
                _consume(prev)
        if pending is not None:
            _consume(pending)
        return ret + self.metric.print(name)

    def _forward_node_bucketed(self, batch, nid: int) -> np.ndarray:
        """One node's host output with the batch split/padded onto the
        ``pred_buckets`` ladder (``utils/bucketing.py``): the jitted
        forward only ever sees bucket shapes, so a stream of arbitrary
        request sizes compiles at most ``len(pred_buckets)`` programs
        instead of one per novel shape.  Pad rows are sliced off before
        concatenation; returns all ``batch.batch_size`` rows (callers
        trim ``num_batch_padd`` exactly as on the unbucketed path)."""
        from ..utils.bucketing import chunk_plan, pad_rows
        ddim = int(self._mesh.shape['data'])
        bad = [b for b in self.pred_buckets if b % ddim]
        if bad:
            # same invariant PredictEngine enforces at construction: a
            # padded batch must shard evenly over the mesh data axis
            raise ValueError(
                f'pred_buckets {bad} do not divide the mesh data axis '
                f'({ddim} devices); pick multiples so padded batches '
                f'shard evenly')
        norm = self._norm_args(batch)
        data = np.asarray(batch.data)
        extras = [np.asarray(e) for e in batch.extra_data]
        outs = []
        for off, take, b in chunk_plan(data.shape[0], self.pred_buckets):
            d = self._shard_batch(pad_rows(data[off:off + take], b),
                                  cast=not norm)
            ex = tuple(self._shard_batch(pad_rows(e[off:off + take], b))
                       for e in extras)
            values = self._forward_fn(self.params, d, ex, self.round,
                                      norm=norm)
            outs.append(np.asarray(values[nid])[:take])
        if not outs:
            return np.empty((0,), np.float32)
        return np.concatenate(outs, axis=0)

    def predict(self, batch) -> np.ndarray:
        """Argmax of the final node per instance (``TransformPred``,
        nnet_impl:286-298)."""
        last = self.net.cfg.layers[-1].nindex_out[-1]
        if self.pred_buckets:
            out = self._forward_node_bucketed(batch, last)
        else:
            out = self._forward_nodes(batch, [last])[0]
        n = batch.batch_size - batch.num_batch_padd
        out = out[:n]
        return self._pred_transform(out)

    @staticmethod
    def _pred_transform(out: np.ndarray) -> np.ndarray:
        if out.ndim > 1 and out.shape[1] != 1:
            return np.argmax(out, axis=1).astype(np.float32)
        return out.reshape(-1).astype(np.float32)

    def forward_stream(self, batches, nid: int):
        """Generator of one node's per-batch host outputs, pad rows
        trimmed, with a one-batch software pipeline: batch i+1's forward
        is enqueued before batch i's readback blocks, so the device
        computes under the host transfer — the pred/extract analog of
        :meth:`evaluate`'s overlap (reference eval-request overlap,
        nnet_impl:232-241).  When ``pred_buckets`` is set the stream
        routes through the bucketed forward instead (trading the
        one-batch overlap for the bounded compile cache) — otherwise an
        iterator with varying batch sizes would still trace novel-shape
        programs and defeat the ladder."""
        if self.pred_buckets:
            for batch in batches:
                out = self._forward_node_bucketed(batch, nid)
                yield out[:batch.batch_size - batch.num_batch_padd]
            return
        pending = None
        for batch in batches:
            outs = self._forward_nodes_async(batch, [nid])
            prev, pending = pending, (
                outs[0], batch.batch_size - batch.num_batch_padd)
            if prev is not None:
                yield np.asarray(prev[0])[:prev[1]]
        if pending is not None:
            yield np.asarray(pending[0])[:pending[1]]

    def predict_stream(self, batches):
        """Pipelined :meth:`predict` over a batch iterator."""
        last = self.net.cfg.layers[-1].nindex_out[-1]
        for out in self.forward_stream(batches, last):
            yield self._pred_transform(out)

    def extract_feature(self, batch, node_name: str) -> np.ndarray:
        nid = self.net.node_index(node_name)
        if self.pred_buckets:
            out = self._forward_node_bucketed(batch, nid)
        else:
            out = self._forward_nodes(batch, [nid])[0]
        n = batch.batch_size - batch.num_batch_padd
        return out[:n]

    # --- checkpointing ----------------------------------------------------
    def save_training_state(self, ckpt_dir: str, step: int,
                            block: bool = True, retry=None) -> str:
        """Beyond-reference EXACT resume state: params + optimizer state
        (momentum/Adam moments) + gradient accumulator + counters, via the
        sharded orbax path (nnet/sharded_ckpt.py).  The reference model
        file deliberately drops optimizer state (``nnet_impl:82-87`` saves
        layer blobs only — parity preserved in :meth:`save_model`); this
        sidecar makes ``continue=1`` bit-exact mid-momentum.  Works for
        mesh-sharded state (shards save/restore in place)."""
        from . import sharded_ckpt
        tree = {'params': self.params, 'opt_state': self.opt_state,
                'grad_acc': self.grad_acc,
                'counters': {
                    # numpy (not jnp): int64 survives regardless of the
                    # jax x64 flag
                    'epoch': np.asarray(self.epoch_counter, np.int64),
                    'sample': np.asarray(self.sample_counter, np.int64),
                    'round': np.asarray(self.round, np.int64)}}
        return sharded_ckpt.save_sharded(ckpt_dir, step, tree, block=block,
                                         retry=retry)

    def snapshot_training_state(self):
        """Donation-safe snapshot of the exact-resume tree (same structure
        as :meth:`save_training_state`) for the async save path: every
        device leaf is copied into a fresh buffer (a cheap, non-blocking
        dispatch — the compiled ``train_step`` donates params/opt_state/
        grad_acc, so handing the LIVE arrays to a background writer would
        hand it buffers the very next step invalidates), counters are
        copied eagerly.  Any validity gate (e.g. the supervisor's
        NaN-streak rule) must be resolved BEFORE taking the snapshot —
        once taken, the writer will commit it."""
        from ..runtime.async_ckpt import snapshot_tree
        return snapshot_tree(
            {'params': self.params, 'opt_state': self.opt_state,
             'grad_acc': self.grad_acc,
             'counters': {
                 'epoch': np.asarray(self.epoch_counter, np.int64),
                 'sample': np.asarray(self.sample_counter, np.int64),
                 'round': np.asarray(self.round, np.int64)}})

    def load_training_state(self, ckpt_dir: str,
                            step: Optional[int] = None,
                            restore_params: bool = False,
                            fallback: bool = False, retry=None) -> int:
        """Restore :meth:`save_training_state` output (latest step by
        default) into this initialized trainer; returns the step.

        By default only the OPTIMIZER side (opt_state, grad_acc,
        counters) is adopted — the weights stay whatever the caller
        loaded (normally the reference model file, which the sidecar's
        params duplicate).  That makes a stale sidecar (left behind by an
        older run in the same dir) at worst a wrong-momentum bug instead
        of silently resuming on the wrong WEIGHTS.  Pass
        ``restore_params=True`` to adopt the sidecar's params too (e.g.
        when restoring without a model file).

        ``fallback=True`` restores resiliently: the newest step that
        passes integrity verification wins, corrupt ones are quarantined
        (``sharded_ckpt.restore_resilient``) — the supervisor's
        restore-last-good path."""
        from . import sharded_ckpt
        like = {'params': self.params, 'opt_state': self.opt_state,
                'grad_acc': self.grad_acc,
                'counters': {'epoch': np.asarray(0, np.int64),
                             'sample': np.asarray(0, np.int64),
                             'round': np.asarray(0, np.int64)}}
        if fallback:
            tree, got = sharded_ckpt.restore_resilient(ckpt_dir, like,
                                                       retry=retry)
        else:
            tree, got = sharded_ckpt.restore_sharded(ckpt_dir, like, step,
                                                     retry=retry)
        if restore_params:
            self.params = tree['params']
        self.opt_state = tree['opt_state']
        self.grad_acc = tree['grad_acc']
        c = tree['counters']
        self.epoch_counter = int(c['epoch'])
        self.sample_counter = int(c['sample'])
        self.round = int(c['round'])
        return got

    def model_header(self) -> bytes:
        """The model-file preamble ahead of the weight blob (NetConfig +
        epoch_counter) — cheap host bytes; an async save captures them at
        snapshot time while the blob serializes in the background."""
        import io as _io
        b = _io.BytesIO()
        self.net_cfg.save_net(b)
        b.write(struct.pack('<q', self.epoch_counter))
        return b.getvalue()

    @staticmethod
    def write_model_bytes(fo: BinaryIO, header: bytes,
                          blob: bytes) -> None:
        """THE model-file layout, in one place: header, u64 blob length,
        blob — sync :meth:`save_model` and the CLI's async writer both
        route through here, so the formats can never drift apart."""
        fo.write(header)
        fo.write(struct.pack('<Q', len(blob)))
        fo.write(blob)

    def save_model(self, fo: BinaryIO) -> None:
        self.write_model_bytes(
            fo, self.model_header(),
            checkpoint.params_to_blob(self.net, self.params))

    def load_model(self, fi: BinaryIO) -> None:
        self.net_cfg = NetConfig()
        self.net_cfg.load_net(fi)
        (self.epoch_counter,) = struct.unpack('<q', fi.read(8))
        (blob_len,) = struct.unpack('<Q', fi.read(8))
        blob = fi.read(blob_len)
        # init_net reconfigures the loaded structure (validating it against
        # the config) and rebuilds net/mesh/hypers/compiled steps
        self.init_net()
        self.params = checkpoint.blob_to_params(self.net, blob)
        self._post_params_init()

    def copy_model_from(self, fi: BinaryIO) -> None:
        """Finetune: name-matched layer copy + epoch reset
        (``nnet_impl:101-134``)."""
        self.init_model()
        old_cfg = NetConfig()
        old_cfg.load_net(fi)
        fi.read(8)  # old epoch_counter, discarded (reset to 0)
        (blob_len,) = struct.unpack('<Q', fi.read(8))
        blob = fi.read(blob_len)
        self.epoch_counter = 0
        old_raw = checkpoint.blob_to_raw(old_cfg.layers, blob)
        params = jax.device_get(self.params)
        for i, old_info in enumerate(old_cfg.layers):
            if not old_info.name or str(i) not in old_raw:
                continue
            for j, new_info in enumerate(self.net_cfg.layers):
                if new_info.name == old_info.name:
                    print(f'Copying layer {old_info.name}')
                    params[str(j)] = checkpoint.record_to_memory(
                        self.net.layers[j], new_info.type, old_raw[str(i)])
        self.params = params
        self._post_params_init()


class _HostLabelInfo:
    """Host-side label field view used by metrics."""

    def __init__(self, mat: np.ndarray, name_map, ranges):
        self._mat = mat
        self._name_map = name_map
        self._ranges = ranges

    def slice(self, n: int) -> '_HostLabelInfo':
        return _HostLabelInfo(self._mat[:n], self._name_map, self._ranges)

    def field(self, name: str) -> np.ndarray:
        a, b = self._ranges[self._name_map[name]]
        return self._mat[:, a:b]
