"""graftscope: the unified telemetry subsystem (doc/observability.md).

Three legs, one hub:

* :mod:`~cxxnet_tpu.obs.hub` — the process-wide :class:`TelemetryHub`
  (StatSet registry, span flight recorder, Prometheus/statusz
  renderers, fault-triggered postmortem dumps, Chrome trace export),
* :mod:`~cxxnet_tpu.obs.endpoints` — the ``/metrics`` + ``/statusz`` +
  ``/healthz`` http thread (``obs.port=`` in the CLI),
* the ``span()`` / ``record_event()`` instrumentation every layer
  (io chain, train loop, serve request lifecycle, elastic protocol)
  records through.
"""

from .hub import (TelemetryHub, format_report, get_hub, install_hub,
                  next_trace_id, record_event, span)

__all__ = ['TelemetryHub', 'format_report', 'get_hub', 'install_hub',
           'next_trace_id', 'record_event', 'span', 'ObsServer']


def __getattr__(name):
    # endpoints import http.server lazily — embedders that never serve
    # telemetry pay nothing for it
    if name == 'ObsServer':
        from .endpoints import ObsServer
        return ObsServer
    raise AttributeError(name)
