"""graftscope: the unified telemetry subsystem (doc/observability.md).

Three legs, one hub:

* :mod:`~cxxnet_tpu.obs.hub` — the process-wide :class:`TelemetryHub`
  (StatSet registry, span flight recorder, Prometheus/statusz
  renderers, fault-triggered postmortem dumps, Chrome trace export),
* :mod:`~cxxnet_tpu.obs.endpoints` — the ``/metrics`` + ``/statusz`` +
  ``/healthz`` + ``/slos`` http thread (``obs.port=`` in the CLI),
* the ``span()`` / ``record_event()`` instrumentation every layer
  (io chain, train loop, serve request lifecycle, elastic protocol)
  records through,
* graftwatch — :mod:`~cxxnet_tpu.obs.history` (the ``obs.sample_every``
  gauge-history sampler), :mod:`~cxxnet_tpu.obs.slo` (the declarative
  ``slo.<name>=`` burn-rate engine with typed OK/AT_RISK/BREACHED
  verdicts), and :mod:`~cxxnet_tpu.obs.fleet` (the elastic launcher's
  merged rank-labeled scrape + per-host-lane trace merge),
* graftprof — :mod:`~cxxnet_tpu.obs.programs` (the compiler-truth
  :class:`ProgramLedger`: per-executable HLO cost/memory rows on
  ``/programs``, the recompile sentinel, ``hbm.*`` device-memory
  gauges, the MFU peak-FLOPs table, and the on-demand
  ``/profile?ms=N`` session).
"""

from .hub import (TelemetryHub, format_report, get_hub, install_hub,
                  next_trace_id, record_event, span)

__all__ = ['TelemetryHub', 'format_report', 'get_hub', 'install_hub',
           'next_trace_id', 'record_event', 'span', 'ObsServer',
           'GaugeHistory', 'GaugeSampler', 'SLOEngine', 'SLOSpec',
           'ProgramLedger', 'get_ledger', 'install_ledger']


def __getattr__(name):
    # endpoints/history/slo/programs import lazily — embedders that
    # never serve telemetry or evaluate SLOs pay nothing for them
    if name == 'ObsServer':
        from .endpoints import ObsServer
        return ObsServer
    if name in ('GaugeHistory', 'GaugeSampler'):
        from . import history
        return getattr(history, name)
    if name in ('SLOEngine', 'SLOSpec'):
        from . import slo
        return getattr(slo, name)
    if name in ('ProgramLedger', 'get_ledger', 'install_ledger'):
        from . import programs
        return getattr(programs, name)
    raise AttributeError(name)
