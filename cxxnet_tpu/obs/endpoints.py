"""Live telemetry endpoints: ``/metrics``, ``/statusz``, ``/healthz``,
``/slos``.

A stdlib ``http.server`` thread (no new dependencies) serving the
process-wide :class:`~cxxnet_tpu.obs.hub.TelemetryHub`:

* ``/metrics`` — Prometheus text exposition format rendered live from
  every registered ``StatSet`` (the machine-readable gauges ROADMAP
  item 5's SLO autoscaler consumes), including the SLO engine's
  ``cxxnet_slo_verdict{tag=...}`` / ratio rows when one is attached,
* ``/statusz`` — one JSON snapshot: registry state machines, freshness,
  page-pool/refcount/spec counters, elastic generation + membership,
  execution-plan choice — whatever the subsystems registered,
* ``/slos`` — the attached SLO engines' typed verdicts (state, burn
  ratios, breach counts, window samples, verdict history) as one JSON
  object; ``{}`` when no engine is attached,
* ``/healthz`` — LIVENESS: always HTTP 200 while the process serves.
  The body is ``ok``, or ``degraded`` while any SLO is BREACHED — so a
  probe (or the future autoscaler) reads health without parsing
  ``/slos``, while restart-on-non-200 semantics stay untouched (a
  degraded process is alive and must keep serving).

One serving thread (named ``cxxnet-obs-*`` so the test suite's
thread-leak fixture holds the line on lifecycle); requests are handled
serially — metrics scrapes are small and rare, and a single thread
keeps shutdown deterministic.  ``port=0`` binds an ephemeral port
(exposed as :attr:`ObsServer.port`, and announced into ``port_file``
when given — how each elastic rank tells the launcher's fleet scraper
where it lives); binding is loopback-only by default — fronting a
fleet-visible scrape endpoint is a deployment concern, not the hub's.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Callable, Dict, Optional, Tuple

__all__ = ['ObsServer', 'EndpointThread', 'PROM_CTYPE', 'TEXT_CTYPE',
           'JSON_CTYPE', 'json_body']

PROM_CTYPE = 'text/plain; version=0.0.4; charset=utf-8'
TEXT_CTYPE = 'text/plain; charset=utf-8'
JSON_CTYPE = 'application/json'

#: path -> (content type, zero-arg render returning the body bytes)
Routes = Dict[str, Tuple[str, Callable[[], bytes]]]


def json_body(obj) -> bytes:
    """One canonical JSON body spelling for every obs endpoint."""
    return (json.dumps(obj, sort_keys=True, default=str)
            + '\n').encode('utf-8')


class _RoutedHandler(BaseHTTPRequestHandler):
    # quiet: scrape access logs are noise on the CLI's stderr
    def log_message(self, fmt, *args):  # noqa: D102 — stdlib override
        pass

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header('Content-Type', ctype)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — stdlib naming
        routes: Routes = self.server.routes
        path, _, query = self.path.partition('?')
        try:
            route = routes.get(path)
            if route is None:
                known = ' '.join(sorted(routes))
                self._reply(404, TEXT_CTYPE,
                            f'not found: {known}\n'.encode('utf-8'))
            else:
                ctype, render = route
                if getattr(render, 'wants_query', False):
                    from urllib.parse import parse_qs
                    body = render(parse_qs(query))
                else:
                    body = render()
                self._reply(200, ctype, body)
        # lint: allow(fault-taxonomy): an endpoint render error must answer 500 to the scraper, never kill the serving thread
        except Exception as e:
            try:
                self._reply(500, TEXT_CTYPE,
                            f'error: {e!r}\n'.encode('utf-8'))
            except OSError:
                pass                 # client went away mid-error


class EndpointThread:
    """The shared endpoint scaffolding every obs server rides: one
    bound stdlib ``HTTPServer`` + a named daemon serving thread,
    route-table dispatch (404 lists the known paths, a render error
    answers 500), and an idempotent :meth:`close` that joins the
    thread.  Requests are handled serially — scrapes are small and
    rare, and a single thread keeps shutdown deterministic.  Thread
    names start ``cxxnet-obs-`` so the test suite's leak fixture holds
    the line on lifecycle."""

    def __init__(self, routes: Routes, port: int = 0,
                 host: str = '127.0.0.1',
                 thread_prefix: str = 'cxxnet-obs'):
        self._srv = HTTPServer((host, int(port)), _RoutedHandler)
        self._srv.routes = routes
        self.host = host
        self.port = int(self._srv.server_address[1])
        self._closed = False
        self._thread = threading.Thread(
            target=self._srv.serve_forever, kwargs={'poll_interval': 0.1},
            daemon=True, name=f'{thread_prefix}-{self.port}')
        self._thread.start()

    @property
    def url(self) -> str:
        return f'http://{self.host}:{self.port}'

    def close(self, timeout: Optional[float] = None) -> bool:
        """Stop serving and join the thread (idempotent); returns True
        once the thread exited."""
        if not self._closed:
            self._closed = True
            self._srv.shutdown()
            self._srv.server_close()
        if self._thread is threading.current_thread():
            return False
        self._thread.join(timeout)
        return not self._thread.is_alive()


def _programs_body() -> bytes:
    from .programs import get_ledger
    return json_body(get_ledger().view())


def _profile_render(out_dir: str):
    """``/profile?ms=N`` — start one single-flight on-demand
    ``jax.profiler`` window into ``out_dir`` (obs/programs.py
    ProfilerSession); answers ``busy`` while one (or a config-driven
    TraceWindow) is running."""
    def render(query: dict) -> bytes:
        from .programs import profile_session
        ms = float(query.get('ms', ['1000'])[0])
        return json_body(profile_session().start(out_dir, ms=ms))
    render.wants_query = True
    return render


class ObsServer(EndpointThread):
    """The per-process telemetry endpoint thread over a
    :class:`~cxxnet_tpu.obs.hub.TelemetryHub`.  ``port=0`` = ephemeral
    (read :attr:`port` after construction); ``port_file=`` atomically
    writes the bound port for out-of-process discovery (the elastic
    launcher reads one per rank)."""

    def __init__(self, hub, port: int = 0, host: str = '127.0.0.1',
                 port_file: Optional[str] = None, profile_dir:
                 Optional[str] = None):
        self.hub = hub
        routes = {
            '/healthz': (TEXT_CTYPE,
                         lambda: f'{hub.health()}\n'.encode('utf-8')),
            '/metrics': (PROM_CTYPE,
                         lambda: hub.metrics_text().encode('utf-8')),
            '/statusz': (JSON_CTYPE, lambda: json_body(hub.status())),
            '/slos': (JSON_CTYPE, lambda: json_body(hub.slos_view())),
            # compiler-truth ledger (obs/programs.py): every compiled
            # executable's cost/memory row, live
            '/programs': (JSON_CTYPE, _programs_body),
        }
        if profile_dir:
            routes['/profile'] = (JSON_CTYPE, _profile_render(profile_dir))
        super().__init__(routes, port=port, host=host)
        if port_file:
            # temp+rename: a concurrent reader sees the whole port or
            # no file, never a partial write
            tmp = f'{port_file}.tmp.{os.getpid()}'
            with open(tmp, 'w', encoding='utf-8') as f:
                f.write(f'{self.port}\n')
            os.replace(tmp, port_file)
