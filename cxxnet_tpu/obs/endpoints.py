"""Live telemetry endpoints: ``/metrics``, ``/statusz``, ``/healthz``.

A stdlib ``http.server`` thread (no new dependencies) serving the
process-wide :class:`~cxxnet_tpu.obs.hub.TelemetryHub`:

* ``/metrics`` — Prometheus text exposition format rendered live from
  every registered ``StatSet`` (the machine-readable gauges ROADMAP
  item 5's SLO autoscaler consumes),
* ``/statusz`` — one JSON snapshot: registry state machines, freshness,
  page-pool/refcount/spec counters, elastic generation + membership,
  execution-plan choice — whatever the subsystems registered,
* ``/healthz`` — liveness (``ok``).

One serving thread (named ``cxxnet-obs-*`` so the test suite's
thread-leak fixture holds the line on lifecycle); requests are handled
serially — metrics scrapes are small and rare, and a single thread
keeps shutdown deterministic.  ``port=0`` binds an ephemeral port
(exposed as :attr:`ObsServer.port`); binding is loopback-only by
default — fronting a fleet-visible scrape endpoint is a deployment
concern, not the hub's.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Optional

__all__ = ['ObsServer']


class _Handler(BaseHTTPRequestHandler):
    # quiet: scrape access logs are noise on the CLI's stderr
    def log_message(self, fmt, *args):  # noqa: D102 — stdlib override
        pass

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header('Content-Type', ctype)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — stdlib naming
        hub = self.server.hub
        path = self.path.split('?', 1)[0]
        try:
            if path == '/healthz':
                self._reply(200, 'text/plain; charset=utf-8', b'ok\n')
            elif path == '/metrics':
                body = hub.metrics_text().encode('utf-8')
                self._reply(200, 'text/plain; version=0.0.4; '
                                 'charset=utf-8', body)
            elif path == '/statusz':
                body = (json.dumps(hub.status(), sort_keys=True,
                                   default=str) + '\n').encode('utf-8')
                self._reply(200, 'application/json', body)
            else:
                self._reply(404, 'text/plain; charset=utf-8',
                            b'not found: /metrics /statusz /healthz\n')
        # lint: allow(fault-taxonomy): an endpoint render error must answer 500 to the scraper, never kill the serving thread
        except Exception as e:
            try:
                self._reply(500, 'text/plain; charset=utf-8',
                            f'error: {e!r}\n'.encode('utf-8'))
            except OSError:
                pass                 # client went away mid-error


class ObsServer:
    """The telemetry endpoint thread.  ``port=0`` = ephemeral (read
    :attr:`port` after construction); :meth:`close` is idempotent and
    joins the serving thread."""

    def __init__(self, hub, port: int = 0, host: str = '127.0.0.1'):
        self.hub = hub
        self._srv = HTTPServer((host, int(port)), _Handler)
        self._srv.hub = hub
        self.host = host
        self.port = int(self._srv.server_address[1])
        self._closed = False
        self._thread = threading.Thread(
            target=self._srv.serve_forever, kwargs={'poll_interval': 0.1},
            daemon=True, name=f'cxxnet-obs-{self.port}')
        self._thread.start()

    @property
    def url(self) -> str:
        return f'http://{self.host}:{self.port}'

    def close(self, timeout: Optional[float] = None) -> bool:
        """Stop serving and join the thread (idempotent); returns True
        once the thread exited."""
        if not self._closed:
            self._closed = True
            self._srv.shutdown()
            self._srv.server_close()
        if self._thread is threading.current_thread():
            return False
        self._thread.join(timeout)
        return not self._thread.is_alive()
