"""Fleet-wide observability: scrape every rank, serve one merged view.

PR 11's elastic runtime runs N worker processes, each with its own
isolated TelemetryHub + ObsServer — exactly the cross-host blind spot
the distributed-training literature blames most multi-host debugging
pain on.  This module is the launcher-side cure (doc/observability.md
"Fleet view"):

* :class:`FleetScraper` — polls each rank's loopback ``/metrics`` /
  ``/statusz`` / ``/healthz``, merges the Prometheus text into ONE
  exposition with a ``rank`` label on every sample
  (``cxxnet_elastic_steps{rank="1"} 42``), and aggregates label-less
  gauges across ranks into ``fleet.<name>.min/.max/.mean/.sum`` —
  the sampler source fleet-scoped SLOs (``slo.x = fleet...``) evaluate
  burn rates over.  A dead rank degrades to absence (its rows drop,
  ``ranks_alive`` dips, ``/statusz`` marks it) — the scrape itself
  survives any single rank's death by construction.
* :class:`FleetServer` — the merged endpoints on the launcher:
  ``/metrics`` (rank-labeled union), ``/statusz`` (per-rank health,
  generation, membership + the fleet SLO verdicts), ``/healthz``
  (``degraded`` while a fleet SLO is BREACHED, still 200), ``/slos``.
* :func:`merge_chrome_traces` — folds each rank's exported Chrome
  trace into one Perfetto file with one process lane per host (pid =
  rank, ``process_name`` = ``host rank R``), so a cross-host timeline
  reads as lanes instead of N files.

Discovery is file-based: each worker's ObsServer announces its
ephemeral port into ``CXXNET_OBS_PORT_FILE`` (endpoints.py), one file
per rank, re-written by respawned incarnations — the launcher polls
the files from its existing supervision loop.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.request
from typing import Dict, List, Optional

from .endpoints import (JSON_CTYPE, PROM_CTYPE, TEXT_CTYPE,
                        EndpointThread, json_body)

__all__ = ['FleetScraper', 'FleetServer', 'merge_chrome_traces',
           'merge_metrics', 'parse_gauges']

#: one Prometheus sample line: name, optional {labels}, value
_SAMPLE_RE = re.compile(
    r'^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?\s+(\S+)$')


def parse_gauges(text: str) -> Dict[str, float]:
    """Label-less samples of one exposition as ``{name: value}`` with
    the ``cxxnet_`` prefix stripped (labeled rows are per-tag detail;
    fleet aggregation reads the totals)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith('#'):
            continue
        m = _SAMPLE_RE.match(line.strip())
        if m is None or m.group(2):
            continue
        name = m.group(1)
        if name.startswith('cxxnet_'):
            name = name[len('cxxnet_'):]
        try:
            out[name] = float(m.group(3))
        except ValueError:
            continue
    return out


def merge_metrics(texts: Dict[int, Optional[str]]) -> str:
    """Merge per-rank expositions into one: every sample gains a
    ``rank`` label (first position, so per-rank series never collide),
    ``# TYPE`` lines dedupe, metric names sort."""
    types: Dict[str, str] = {}
    samples: Dict[str, List[str]] = {}
    for rank in sorted(texts):
        text = texts[rank]
        if not text:
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith('# TYPE '):
                parts = line.split()
                if len(parts) >= 3:
                    types.setdefault(parts[2], line)
                continue
            if line.startswith('#'):
                continue
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue
            name, labels, value = m.groups()
            inner = f'rank="{rank}"'
            if labels:
                inner = f'{inner},{labels[1:-1]}' if labels != '{}' \
                    else inner
            samples.setdefault(name, []).append(
                f'{name}{{{inner}}} {value}')
    lines: List[str] = []
    for name in sorted(samples):
        lines.append(types.get(name, f'# TYPE {name} gauge'))
        lines.extend(samples[name])
    return '\n'.join(lines) + '\n' if lines else ''


def merge_chrome_traces(paths: Dict[int, str],
                        out_path: str) -> Optional[str]:
    """Fold per-rank Chrome traces into one Perfetto file: rank R's
    events move to ``pid=R`` with a ``process_name`` metadata row
    (``host rank R``), so each host renders as its own lane group.
    Unreadable/missing inputs (a killed incarnation never exports) are
    skipped; returns ``out_path``, or None when nothing merged."""
    merged: List[dict] = []
    for rank in sorted(paths):
        try:
            with open(paths[rank], encoding='utf-8') as f:
                events = json.load(f).get('traceEvents', [])
        except (OSError, ValueError):
            continue
        for e in events:
            e = dict(e)
            e['pid'] = rank
            merged.append(e)
        merged.append({'ph': 'M', 'name': 'process_name', 'pid': rank,
                       'tid': 0, 'args': {'name': f'host rank {rank}'}})
    if not merged:
        return None
    with open(out_path, 'w', encoding='utf-8') as f:
        json.dump({'traceEvents': merged, 'displayTimeUnit': 'ms'}, f,
                  default=str)
    return out_path


class FleetScraper:
    """Poll each registered rank's ObsServer and merge (module
    docstring).  Thread-safe: the launcher loop registers targets and
    paces sampling while the FleetServer thread scrapes per GET."""

    def __init__(self, timeout: float = 2.0):
        self.timeout = float(timeout)
        self._lock = threading.Lock()
        self._targets: Dict[int, str] = {}     # guarded-by: _lock
        self._alive: Dict[int, bool] = {}      # guarded-by: _lock
        self._errors = 0                       # guarded-by: _lock
        self._last_texts: Dict[int, str] = {}  # guarded-by: _lock

    def add_target(self, rank: int, url: str) -> None:
        """Register (or re-register after a respawn) one rank's base
        URL, e.g. ``http://127.0.0.1:43121``."""
        with self._lock:
            self._targets[int(rank)] = url.rstrip('/')

    def targets(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._targets)

    def scrape_errors(self) -> int:
        with self._lock:
            return self._errors

    def alive(self) -> Dict[int, bool]:
        """Rank -> did its last scrape answer."""
        with self._lock:
            return dict(self._alive)

    def last_merged(self) -> str:
        """The newest-known exposition PER RANK merged into one (for
        consumers reading after the run): each rank's rows are from its
        newest successful scrape, so a staggered teardown — or a rank
        that died mid-run — can never shrink the post-run artifact to a
        partial fleet (the live :meth:`merged_metrics` is where a dead
        rank's rows drop).  Empty until any rank ever answered."""
        with self._lock:
            texts = dict(self._last_texts)
            alive = sum(1 for v in self._alive.values() if v)
            total = len(self._targets)
            errors = self._errors
        if not texts:
            return ''
        return merge_metrics(texts) + self._self_gauges(alive, total,
                                                        errors)

    @staticmethod
    def _self_gauges(alive: int, total: int, errors: int) -> str:
        """The fleet self-gauge suffix both expositions share (the
        live merge and the post-run snapshot must never drift)."""
        return ('# TYPE cxxnet_fleet_ranks_alive gauge\n'
                f'cxxnet_fleet_ranks_alive {alive}\n'
                '# TYPE cxxnet_fleet_ranks_total gauge\n'
                f'cxxnet_fleet_ranks_total {total}\n'
                '# TYPE cxxnet_fleet_scrape_errors_total gauge\n'
                f'cxxnet_fleet_scrape_errors_total {errors}\n')

    def _get(self, url: str) -> str:
        with urllib.request.urlopen(url, timeout=self.timeout) as r:
            return r.read().decode('utf-8', 'replace')

    def scrape(self, path: str = '/metrics') -> Dict[int, Optional[str]]:
        """One pass over every target; a rank that does not answer maps
        to None (and is marked not alive) — one dead rank never stalls
        or fails the fleet view."""
        out: Dict[int, Optional[str]] = {}
        for rank, url in sorted(self.targets().items()):
            try:
                text = self._get(url + path)
                out[rank] = text
                with self._lock:
                    self._alive[rank] = True
                    if path == '/metrics':
                        self._last_texts[rank] = text
            except (OSError, ValueError):
                out[rank] = None
                with self._lock:
                    self._alive[rank] = False
                    self._errors += 1
        return out

    def merged_metrics(self) -> str:
        """Live rank-labeled union of every rank's ``/metrics``, plus
        the fleet self-gauges."""
        texts = self.scrape()
        alive = sum(1 for t in texts.values() if t)
        with self._lock:
            errors = self._errors
        return merge_metrics(texts) + self._self_gauges(
            alive, len(texts), errors)

    def source(self) -> Dict[str, float]:
        """The fleet gauge dict a :class:`GaugeSampler` records —
        cross-rank aggregates under the ``fleet.`` set: for every
        label-less gauge present on any rank, ``fleet.<name>.min`` /
        ``.max`` / ``.mean`` / ``.sum``, plus membership counts.  The
        grammar's fleet-scoped SLOs (steps/sec floor = a ``.rate`` over
        ``fleet.elastic_steps.max``; a latency-distribution ceiling
        reads the rank's already-rendered quantile row, underscore-
        joined exactly as on ``/metrics``: ``fleet.serve_wait_ms_p99.max``)
        read these."""
        texts = self.scrape()
        per = {r: parse_gauges(t) for r, t in texts.items() if t}
        out: Dict[str, float] = {
            'fleet.ranks_alive': float(len(per)),
            'fleet.ranks_total': float(len(texts)),
        }
        names = set()
        for gauges in per.values():
            names.update(gauges)
        for name in names:
            vals = [per[r][name] for r in per if name in per[r]]
            if not vals:
                continue
            out[f'fleet.{name}.min'] = min(vals)
            out[f'fleet.{name}.max'] = max(vals)
            out[f'fleet.{name}.mean'] = sum(vals) / len(vals)
            out[f'fleet.{name}.sum'] = float(sum(vals))
        return out

    def statusz(self) -> dict:
        """Per-rank fleet health: liveness, the rank's ``/healthz``
        body, and its ``/statusz`` elastic section (generation, steps,
        incarnation, membership shards) when it answers."""
        ranks: Dict[str, dict] = {}
        for rank, url in sorted(self.targets().items()):
            entry: Dict[str, object] = {'url': url}
            try:
                entry['health'] = self._get(url + '/healthz').strip()
                st = json.loads(self._get(url + '/statusz'))
                entry['alive'] = True
                entry['elastic'] = st.get('status', {}).get('elastic')
                entry['uptime_s'] = st.get('uptime_s')
            except (OSError, ValueError):
                # deliberately NOT counted into _errors: that gauge
                # means "metrics scrapes that failed" — a /statusz
                # render probing a dead rank must not inflate it at
                # the dashboard's poll rate
                entry['alive'] = False
            ranks[str(rank)] = entry
        return ranks


class FleetServer(EndpointThread):
    """The launcher's merged telemetry endpoint thread (loopback, like
    ObsServer, riding the same :class:`EndpointThread` scaffolding;
    ``port=0`` ephemeral).  ``engine`` (optional) is the fleet-scoped
    :class:`~cxxnet_tpu.obs.slo.SLOEngine` behind ``/slos`` and the
    degraded ``/healthz``."""

    def __init__(self, scraper: FleetScraper, engine=None, port: int = 0,
                 host: str = '127.0.0.1'):
        self.scraper = scraper
        self.engine = engine
        super().__init__({
            '/healthz': (TEXT_CTYPE, self._healthz),
            '/metrics': (PROM_CTYPE,
                         lambda: scraper.merged_metrics()
                         .encode('utf-8')),
            '/slos': (JSON_CTYPE,
                      lambda: json_body({} if engine is None
                                        else engine.status_view())),
            '/statusz': (JSON_CTYPE, self._statusz),
        }, port=port, host=host, thread_prefix='cxxnet-obs-fleet')

    def _healthz(self) -> bytes:
        body = 'ok'
        if self.engine is not None and self.engine.breached():
            body = 'degraded'
        return f'{body}\n'.encode('utf-8')

    def _statusz(self) -> bytes:
        return json_body({
            'ranks': self.scraper.statusz(),
            'targets': {str(r): u for r, u in
                        self.scraper.targets().items()},
            'scrape_errors': self.scraper.scrape_errors(),
            'slos': ({} if self.engine is None
                     else self.engine.status_view()),
        })
