"""Gauge history: bounded time-series rings over the telemetry hub.

The hub's ``/metrics`` render answers "what is the value *now*"; an SLO
verdict needs "what has it been doing over the last W seconds".  This
module is the bridge — a :class:`GaugeSampler` thread snapshots every
registered gauge on a fixed cadence (``obs.sample_every``, monotonic
clock) into per-gauge :class:`GaugeHistory` rings, each bounded to the
newest ``maxlen`` points, with windowed rate/quantile reductions the
SLO engine (obs/slo.py) evaluates burn rates over.

Keys are spelled exactly as on ``/metrics`` minus the ``cxxnet_``
prefix, dot-joined: ``<set>.<key>`` for counters/gauges (bracket tags
kept verbatim, ``serve.rows[b8]``), and distributions expand to
``<set>.<key>.p50/.p99/.mean/.n`` per tick — so an operator can read a
gauge off a scrape and point an SLO at the same spelling.

The sampler can be *driven* instead of threaded (``maybe_tick`` /
``tick``): the elastic launcher paces fleet sampling from its own poll
loop, and tests pass explicit ``now`` timestamps for deterministic
window arithmetic.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ['GaugeHistory', 'GaugeSampler', 'hub_source']

#: window reductions the SLO grammar may suffix onto a base gauge key
REDUCERS = ('rate', 'mean', 'min', 'max', 'p50', 'p99')


def hub_source(hub) -> Callable[[], Dict[str, float]]:
    """The default sampler source: one flat gauge snapshot of ``hub``
    (every registered StatSet, refreshed, plus the hub self-gauges)."""
    return hub.gauge_snapshot


class GaugeHistory:
    """Per-gauge bounded rings of ``(t_monotonic, value)`` points.
    Thread-safe: the sampler records while the SLO engine (and the
    ``/statusz`` render) read windows concurrently."""

    def __init__(self, maxlen: int = 512):
        self._lock = threading.Lock()
        self._maxlen = max(2, int(maxlen))
        self._rings: Dict[str, collections.deque] = {}  # guarded-by: _lock

    def record(self, now: float, values: Dict[str, float]) -> None:
        """Append one sample per key at time ``now`` (monotonic s)."""
        now = float(now)
        with self._lock:
            for key, v in values.items():
                ring = self._rings.get(key)
                if ring is None:
                    ring = self._rings[key] = collections.deque(
                        maxlen=self._maxlen)
                ring.append((now, float(v)))

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def has(self, key: str) -> bool:
        with self._lock:
            return key in self._rings

    def latest(self, key: str) -> Optional[Tuple[float, float]]:
        with self._lock:
            ring = self._rings.get(key)
            return ring[-1] if ring else None

    def window(self, key: str, seconds: float,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Points of ``key`` with ``t >= now - seconds`` (oldest first).
        ``seconds <= 0`` returns just the newest point — the per-sample
        degenerate window.  ``now`` defaults to the newest point's
        timestamp, so a paused sampler still reports its last window."""
        with self._lock:
            ring = self._rings.get(key)
            pts = list(ring) if ring else []
        if not pts:
            return []
        if seconds <= 0:
            return pts[-1:]
        cut = (pts[-1][0] if now is None else float(now)) - float(seconds)
        return [p for p in pts if p[0] >= cut]

    def rate(self, key: str, seconds: float,
             now: Optional[float] = None) -> Optional[float]:
        """Windowed first-to-last rate of change per second (the
        counter-slope reduction: steps/sec, tokens/sec); None with
        fewer than two points or zero elapsed time."""
        pts = self.window(key, seconds, now)
        if len(pts) < 2:
            return None
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)

    def reduce(self, key: str, reducer: str, seconds: float,
               now: Optional[float] = None) -> Optional[float]:
        """One reduced value over the window: ``rate`` (slope) or
        ``mean``/``min``/``max``/``p50``/``p99`` over the point values;
        None when the window holds no usable data."""
        if reducer == 'rate':
            return self.rate(key, seconds, now)
        pts = self.window(key, seconds, now)
        if not pts:
            return None
        vals = np.asarray([v for _t, v in pts], dtype=np.float64)
        if reducer == 'mean':
            return float(vals.mean())
        if reducer == 'min':
            return float(vals.min())
        if reducer == 'max':
            return float(vals.max())
        if reducer == 'p50':
            return float(np.quantile(vals, 0.5))
        if reducer == 'p99':
            return float(np.quantile(vals, 0.99))
        raise ValueError(f'unknown reducer {reducer!r} '
                         f'(choose from {REDUCERS})')


class GaugeSampler:
    """The sampling loop: every ``period`` seconds pull one gauge dict
    from ``source`` (idiomatically :func:`hub_source`), record it into
    :attr:`history`, and run the tick listeners (the SLO engine).  Runs
    as a ``cxxnet-obs-sampler`` daemon thread via :meth:`start`, or
    caller-paced via :meth:`maybe_tick` (the elastic launcher's loop) /
    :meth:`tick` (tests, with explicit ``now``)."""

    def __init__(self, source: Callable[[], Dict[str, float]],
                 period: float = 0.25,
                 history: Optional[GaugeHistory] = None,
                 maxlen: int = 512):
        self.source = source
        self.period = max(0.01, float(period))
        self.history = GaugeHistory(maxlen) if history is None else history
        self._lock = threading.Lock()
        self._listeners: List[Callable] = []   # guarded-by: _lock
        self._ticks = 0                        # guarded-by: _lock
        self._errors = 0                       # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._next = 0.0        # maybe_tick pacing (caller thread only)

    def add_listener(self, fn: Callable) -> Callable:
        """Register ``fn(now, history)`` to run after every tick."""
        with self._lock:
            self._listeners.append(fn)
        return fn

    def stats(self) -> Tuple[int, int]:
        """``(ticks, errors)`` so far."""
        with self._lock:
            return self._ticks, self._errors

    def tick(self, now: Optional[float] = None) -> None:
        """One sample + listener pass, at ``now`` (default monotonic)."""
        now = time.monotonic() if now is None else float(now)
        try:
            values = self.source()
        # lint: allow(fault-taxonomy): a broken gauge source must degrade this one sample, never kill the sampling loop
        except Exception:
            with self._lock:
                self._errors += 1
            return
        self.history.record(now, values)
        with self._lock:
            self._ticks += 1
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(now, self.history)
            # lint: allow(fault-taxonomy): a broken tick listener must not take the sampler (or its sibling listeners) down with it
            except Exception:
                with self._lock:
                    self._errors += 1

    def maybe_tick(self, now: Optional[float] = None) -> bool:
        """Caller-paced ticking: sample only when a full period elapsed
        since the last one (the launcher drives this from its existing
        poll loop instead of spawning a thread)."""
        now = time.monotonic() if now is None else float(now)
        if now < self._next:
            return False
        self._next = now + self.period
        self.tick(now)
        return True

    def start(self) -> 'GaugeSampler':
        """Start the sampling thread (idempotent)."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name='cxxnet-obs-sampler')
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            self.tick()

    def close(self, timeout: Optional[float] = None) -> bool:
        """Stop and join the sampling thread (idempotent); True once it
        exited.  The history stays readable after close."""
        self._stop.set()
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()
