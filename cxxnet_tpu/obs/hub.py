"""graftscope — the unified telemetry hub (doc/observability.md).

The repo grew a dozen disconnected observability surfaces: ``StatSet``
gauges formatted into eval-line strings, six near-duplicate ``report()``
formatters, a ``FailureLog``, and a jax-profiler ``TraceWindow``.  None
of them could answer "what is this *running* process doing right now"
or "what happened in the five seconds before that fault".  This module
is the one place they all meet:

* **TelemetryHub** — a process-wide registry that owns every live
  ``utils.metric.StatSet`` (io chain, batcher, decode engine,
  registry/fleet, freshness, elastic) plus JSON *status providers*
  (registry state machines, execution-plan choice, elastic membership).
  One hub per process; subsystems register as they come up and the
  ``/metrics`` + ``/statusz`` endpoints (obs/endpoints.py) render from
  it live.
* **Flight recorder** — an always-on, bounded ring of structured span
  events ``(name, subsystem, trace_id, t_start_ns, dur_ns, thread,
  attrs)`` stamped with ``time.monotonic_ns()``.  Recording is
  lock-cheap: each thread appends to its own bounded deque (the GIL
  makes the append atomic); the hub's lock is taken once per thread
  lifetime plus at read time.  :meth:`TelemetryHub.dump` writes the
  merged ring + failure log + stat snapshots as one JSON postmortem —
  armed via :meth:`arm_flight_recorder`, it fires automatically when a
  ``TrainingFault`` (or supervisor give-up) reaches a ``FailureLog``,
  and :meth:`arm_signal_dump` adds ``SIGUSR1`` for live processes.
* **Spans** — :meth:`span` is a context manager (and decorator):
  ``with span('decode.prefill', 'decode', trace_id=req.trace_id): ...``
  Spans nest; a child with no explicit ``trace_id`` inherits the
  innermost enclosing span's on the same thread, and request ids thread
  across threads explicitly (``ServeRequest.trace_id``).  graftlint's
  ``span-hygiene`` rule enforces the grammar: context-manager form
  only, never inside a jitted/scanned scope (a span body is host code
  by definition).
* **Chrome trace export** — :meth:`export_chrome_trace` writes the ring
  as Chrome trace-event JSON that loads in Perfetto next to an XLA
  trace.  Unlike ``profile_dir`` it composes with
  ``steps_per_dispatch``: spans bracket *dispatches*, not steps, so the
  scan-demotion matrix is untouched.

:func:`format_report` is the ONE eval-line formatter every subsystem
``report()`` delegates to, so key spelling cannot drift between the
batcher, decode engine, registries, freshness tracker and io chain.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ['TelemetryHub', 'get_hub', 'install_hub', 'span',
           'record_event', 'next_trace_id', 'format_report',
           'format_report_parts']


# --- the one eval-line formatter -------------------------------------------

def format_report(prefix: str, stats) -> str:
    """Render a ``utils.metric.StatSet`` snapshot in the canonical
    eval-line format (``\\tprefix-key:value``; distributions expand to
    ``.p50/.p99/.mean/.n``).  Every subsystem ``report()`` — batcher,
    decode engine, registry, fleet, freshness, io — formats through
    this one function, so the key spelling the autoscaler and the tests
    read cannot drift between subsystems."""
    counters, samples = stats.snapshot()
    return format_report_parts(prefix, counters, samples)


def format_report_parts(prefix: str, counters: dict, samples: dict) -> str:
    """The renderer behind :func:`format_report`, over already-snapshot
    state — the atomic drain path (``StatSet.print_and_clear``) feeds
    it the swapped-out epoch directly."""
    out = []
    for key in sorted(counters):
        out.append(f'\t{prefix}-{key}:{counters[key]:g}')
    for key in sorted(samples):
        arr = np.asarray(samples[key])
        out.append(f'\t{prefix}-{key}.p50:{np.quantile(arr, 0.5):g}')
        out.append(f'\t{prefix}-{key}.p99:{np.quantile(arr, 0.99):g}')
        out.append(f'\t{prefix}-{key}.mean:{arr.mean():g}')
        out.append(f'\t{prefix}-{key}.n:{arr.size:g}')
    return ''.join(out)


# --- spans ------------------------------------------------------------------

class _Span:
    """One live span (context-manager form).  ``attrs`` may be mutated
    inside the ``with`` block; the record is written at exit (errors
    stamp ``attrs['error']`` with the exception type).  A disabled hub
    is honored at ENTER time, so the decorator form — which re-enters a
    fresh span per call — respects ``hub.enabled`` flips either way."""

    __slots__ = ('_hub', 'name', 'subsystem', 'trace_id', 'attrs', '_t0',
                 '_off')

    def __init__(self, hub: 'TelemetryHub', name: str, subsystem: str,
                 trace_id: Optional[str], attrs: dict):
        self._hub = hub
        self.name = name
        self.subsystem = subsystem
        self.trace_id = trace_id
        self.attrs = attrs
        self._t0 = 0
        self._off = False

    def __enter__(self):
        h = self._hub
        if not h.enabled:
            self._off = True
            return self
        stack = h._span_stack()
        if self.trace_id is None and stack:
            self.trace_id = stack[-1][0]     # inherit the enclosing span's
        stack.append((self.trace_id, self.name))
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, et, ev, tb):
        if self._off:
            return False
        dur = time.monotonic_ns() - self._t0
        h = self._hub
        stack = h._span_stack()
        if stack:
            stack.pop()
        if et is not None:
            self.attrs['error'] = et.__name__
        if len(stack) >= 1:
            self.attrs.setdefault('parent', stack[-1][1])
        h._record(self.name, self.subsystem, self.trace_id, self._t0, dur,
                  self.attrs)
        return False

    def __call__(self, fn):
        """Decorator form: each call runs under a FRESH span (with the
        enabled check re-evaluated at call time, not decoration time)."""
        import functools
        hub, name, subsystem = self._hub, self.name, self.subsystem
        trace_id, attrs = self.trace_id, self.attrs

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with _Span(hub, name, subsystem, trace_id, dict(attrs)):
                return fn(*args, **kwargs)
        return wrapped


# --- the hub ---------------------------------------------------------------

class TelemetryHub:
    """Process-wide telemetry registry + flight recorder (module
    docstring).  Thread-safe throughout; recording is per-thread
    lock-free (bounded deques), the hub lock guards only the
    registries and the read/merge/dump paths."""

    #: default flight-recorder ring size (events retained, newest win)
    DEFAULT_RING = 4096
    #: per-process flight dumps retained on disk (oldest pruned)
    DEFAULT_KEEP = 8

    def __init__(self, ring_events: int = DEFAULT_RING):
        self._lock = threading.Lock()
        self._ring = max(16, int(ring_events))
        self.enabled = True            # bench A/B switch; True in prod
        self._tls = threading.local()
        # (thread, deque) per recording thread; dead threads' events are
        # folded into _retired so a dump still sees their tail
        self._bufs: List[Tuple[threading.Thread,
                               collections.deque]] = []   # guarded-by: _lock
        self._retired: collections.deque = collections.deque(
            maxlen=self._ring)                            # guarded-by: _lock
        # bumped by set_ring (under _lock); READ lock-free on the
        # record hot path — a GIL-atomic int compare, worst case one
        # record lands in a pre-resize buffer the merge still sees
        self._gen = 0
        self._stats: Dict[str, Tuple[object, Optional[Callable]]] = {}
        self._status: Dict[str, Callable[[], object]] = {}
        self._trace_n = 0              # guarded-by: _lock
        # events_n is bumped LOCK-FREE on the record hot path: it is a
        # telemetry tally (the ring is the source of truth), and under
        # the GIL a rare lost increment costs a count, never a tear
        self._events_n = 0
        self._t0_ns = time.monotonic_ns()
        # flight-recorder dump state
        self._dump_dir: Optional[str] = None
        self._dump_keep = self.DEFAULT_KEEP
        self._dump_seq = 0             # guarded-by: _lock
        self.dumps: List[str] = []     # guarded-by: _lock
        self._listener = None
        # SLO engines (obs/slo.py) attached via attach_slo: what /slos
        # merges, /healthz degrades on, and a postmortem dump includes
        self._slo_engines: List[object] = []   # guarded-by: _lock

    # -- StatSet / status registries ---------------------------------------
    def register_stats(self, name: str, stats,
                       refresh: Optional[Callable[[], object]] = None):
        """Register a live ``StatSet`` under ``name`` (idempotent: the
        same object re-registers as a no-op; a different object under
        the same name replaces it — subsystems restart).  ``refresh``
        (optional) runs before each render so pull-style gauges
        (registry swap stamps, fleet ledger) are current."""
        with self._lock:
            self._stats[name] = (stats, refresh)
        return stats

    def unregister_stats(self, name: str) -> None:
        with self._lock:
            self._stats.pop(name, None)

    def stat_sets(self) -> Dict[str, object]:
        with self._lock:
            return {k: v[0] for k, v in self._stats.items()}

    def attach_slo(self, engine) -> None:
        """Put an SLO engine (obs/slo.py) on the hub's roster: its
        verdicts merge into ``/slos`` and :meth:`slos_view`, a BREACHED
        objective flips :meth:`health` to ``degraded``, and every
        flight dump carries its window samples + verdict history."""
        with self._lock:
            if engine not in self._slo_engines:
                self._slo_engines.append(engine)

    def detach_slo(self, engine) -> None:
        with self._lock:
            try:
                self._slo_engines.remove(engine)
            except ValueError:
                pass

    def slo_engines(self) -> List[object]:
        with self._lock:
            return list(self._slo_engines)

    def slos_view(self) -> dict:
        """Every attached engine's verdicts merged into one dict (the
        ``/slos`` body); empty when no engine is attached."""
        out: Dict[str, object] = {}
        for eng in self.slo_engines():
            try:
                out.update(eng.status_view())
            # lint: allow(fault-taxonomy): a broken engine view must degrade its own entries, never the endpoint or a postmortem dump
            except Exception as e:
                out[f'error:{type(eng).__name__}'] = repr(e)
        return out

    def health(self) -> str:
        """``'ok'``, or ``'degraded'`` while any attached SLO engine
        holds a BREACHED objective.  Both answer HTTP 200 — ``/healthz``
        stays a *liveness* probe (a degraded process is alive and still
        serving); readiness-style consumers read the body or ``/slos``."""
        for eng in self.slo_engines():
            try:
                if eng.breached():
                    return 'degraded'
            # lint: allow(fault-taxonomy): health must fail open (alive) when a verdict read breaks, never take the endpoint down
            except Exception:
                continue
        return 'ok'

    def register_status(self, name: str, provider: Callable[[], object]):
        """Register a ``/statusz`` JSON provider (a zero-arg callable
        returning something JSON-able); same name replaces."""
        with self._lock:
            self._status[name] = provider
        return provider

    def unregister_status(self, name: str) -> None:
        with self._lock:
            self._status.pop(name, None)

    # -- trace ids / span recording ----------------------------------------
    def next_trace_id(self) -> str:
        with self._lock:
            self._trace_n += 1
            return f't{self._trace_n:06d}'

    def _span_stack(self) -> list:
        stack = getattr(self._tls, 'stack', None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_trace_id(self) -> Optional[str]:
        stack = self._span_stack()
        return stack[-1][0] if stack else None

    def _buf(self) -> collections.deque:
        buf = getattr(self._tls, 'buf', None)
        gen = self._gen
        if buf is None or getattr(self._tls, 'gen', -1) != gen:
            buf = self._tls.buf = collections.deque(maxlen=self._ring)
            self._tls.gen = gen
            self._tls.tname = threading.current_thread().name
            with self._lock:
                self._bufs.append((threading.current_thread(), buf))
                if len(self._bufs) > 64:
                    self._prune_bufs_locked()
        return buf

    def _prune_bufs_locked(self) -> None:  # requires-lock: _lock
        live = []
        for t, buf in self._bufs:
            if t.is_alive():
                live.append((t, buf))
            else:
                self._retired.extend(buf)
        self._bufs = live

    def span(self, name: str, subsystem: str = 'app',
             trace_id: Optional[str] = None, **attrs):
        """A context-manager span (also usable as a decorator).  With no
        ``trace_id`` it inherits the innermost enclosing span's on this
        thread (cross-thread propagation is explicit —
        ``ServeRequest.trace_id``).  ``enabled`` is honored at enter
        time (see :class:`_Span`)."""
        return _Span(self, name, subsystem, trace_id, attrs)

    def record_event(self, name: str, subsystem: str = 'app',
                     trace_id: Optional[str] = None,
                     t_start_ns: Optional[int] = None, dur_ns: int = 0,
                     **attrs) -> None:
        """Record one already-measured (or instantaneous) event without
        opening a span — the hot-path spelling (per-request queue waits,
        io batch intervals)."""
        if not self.enabled:
            return
        now = time.monotonic_ns()
        self._record(name, subsystem, trace_id,
                     now if t_start_ns is None else int(t_start_ns),
                     int(dur_ns), attrs)

    def _record(self, name, subsystem, trace_id, t0_ns, dur_ns,
                attrs) -> None:
        buf = self._buf()
        buf.append({
            'name': name, 'subsystem': subsystem, 'trace_id': trace_id,
            't_start_ns': int(t0_ns), 'dur_ns': int(dur_ns),
            'thread': self._tls.tname,
            'attrs': attrs})
        self._events_n += 1

    def set_ring(self, n: int) -> None:
        """Resize the flight-recorder ring (affects the merged view
        immediately; per-thread buffers adopt the new bound as they are
        next touched)."""
        n = max(16, int(n))
        with self._lock:
            self._ring = n
            self._retired = collections.deque(self._retired, maxlen=n)
            self._bufs = [(t, collections.deque(b, maxlen=n))
                          for t, b in self._bufs]
            # every thread's cached ref is now stale: the generation
            # bump makes each re-register a fresh buffer on its next
            # record (_buf), so no event is ever appended to a deque
            # the merge no longer sees
            self._gen += 1

    def events(self, limit: Optional[int] = None) -> List[dict]:
        """The merged flight-recorder ring, oldest first, bounded by the
        ring size (newest win)."""
        with self._lock:
            chunks = [list(self._retired)] + [list(b) for _t, b in
                                              self._bufs]
            bound = self._ring if limit is None else min(self._ring,
                                                         int(limit))
        merged: List[dict] = []
        for c in chunks:
            merged.extend(c)
        merged.sort(key=lambda e: e['t_start_ns'])
        return merged[-bound:]

    # -- renderers ---------------------------------------------------------
    def _refreshed_snapshots(self):
        with self._lock:
            regs = sorted(self._stats.items())
        out = []
        for name, (stats, refresh) in regs:
            if refresh is not None:
                try:
                    refresh()
                # lint: allow(fault-taxonomy): a broken gauge refresher must degrade that one stat set, never the whole /metrics render
                except Exception:
                    pass
            counters, samples = stats.snapshot()
            out.append((name, counters, samples))
        return out

    #: newest samples per distribution a sampler tick reduces over —
    #: bounds the per-tick cost no matter how large an uncleared
    #: serving StatSet grows (a full copy-and-sort of a ~100k-sample
    #: latency list at 20 Hz measurably taxed the decode hot path)
    SAMPLE_TAIL = 512

    def gauge_snapshot(self) -> Dict[str, float]:
        """One flat ``{'<set>.<key>': value}`` snapshot of every
        registered StatSet (refreshed) plus the hub self-gauges — the
        sampler source behind ``obs.sample_every`` (obs/history.py).
        Distributions expand to ``.p50/.p99/.mean`` over the newest
        :attr:`SAMPLE_TAIL` samples (recent behavior is what a
        time-series ring wants, and the bounded read keeps the tick
        O(tail) off the recording threads' lock) plus ``.n`` = total
        retained count, so history keys spell exactly like their
        ``/metrics`` rows."""
        out: Dict[str, float] = {}
        with self._lock:
            regs = sorted(self._stats.items())
        for name, (stats, refresh) in regs:
            if refresh is not None:
                try:
                    refresh()
                # lint: allow(fault-taxonomy): a broken gauge refresher must degrade that one stat set, never the sampler tick
                except Exception:
                    pass
            view = getattr(stats, 'tail_view', None)
            if view is not None:
                counters, samples = view(self.SAMPLE_TAIL)
            else:   # duck-typed stats object: unbounded fallback
                counters, samples = stats.snapshot()
                samples = {k: (v, len(v)) for k, v in samples.items()}
            for key, v in counters.items():
                out[f'{name}.{key}'] = float(v)
            for key, (vals, n) in samples.items():
                arr = np.asarray(vals, dtype=np.float64)
                out[f'{name}.{key}.p50'] = float(np.quantile(arr, 0.5))
                out[f'{name}.{key}.p99'] = float(np.quantile(arr, 0.99))
                out[f'{name}.{key}.mean'] = float(arr.mean())
                out[f'{name}.{key}.n'] = float(n)
        out['obs.events_recorded'] = float(self._events_n)
        out['obs.uptime_s'] = (time.monotonic_ns() - self._t0_ns) / 1e9
        return out

    @staticmethod
    def _prom_name(set_name: str, key: str) -> Tuple[str, str]:
        """``('serve', 'latency_ms[b8]') -> ('cxxnet_serve_latency_ms',
        '{tag="b8"}')`` — bracket suffixes become a ``tag`` label, every
        other character outside ``[a-zA-Z0-9_]`` folds to ``_``."""
        import re
        label = ''
        m = re.match(r'^(.*?)\[([^\]]*)\]$', key)
        if m:
            key = m.group(1)
            tag = m.group(2).replace('\\', '\\\\').replace('"', '\\"')
            label = f'{{tag="{tag}"}}'
        base = re.sub(r'[^a-zA-Z0-9_]', '_', f'{set_name}_{key}')
        return f'cxxnet_{base}', label

    def metrics_text(self) -> str:
        """The whole hub in Prometheus text exposition format — every
        gauge a scraper (or ROADMAP item 5's SLO autoscaler) consumes.
        Counters/gauges export as-is; distributions export
        ``_p50/_p99/_mean/_count`` gauges over the retained samples."""
        series: Dict[str, List[Tuple[str, float]]] = {}

        def put(mname: str, label: str, value: float) -> None:
            series.setdefault(mname, []).append((label, float(value)))

        for name, counters, samples in self._refreshed_snapshots():
            for key, v in counters.items():
                mname, label = self._prom_name(name, key)
                put(mname, label, v)
            for key, vals in samples.items():
                arr = np.asarray(vals)
                mname, label = self._prom_name(name, key)
                put(f'{mname}_p50', label, float(np.quantile(arr, 0.5)))
                put(f'{mname}_p99', label, float(np.quantile(arr, 0.99)))
                put(f'{mname}_mean', label, float(arr.mean()))
                put(f'{mname}_count', label, float(arr.size))
        with self._lock:
            put('cxxnet_obs_events_recorded', '', float(self._events_n))
            put('cxxnet_obs_ring_events', '', float(self._ring))
        put('cxxnet_obs_uptime_seconds', '',
            (time.monotonic_ns() - self._t0_ns) / 1e9)
        lines: List[str] = []
        for mname in sorted(series):
            lines.append(f'# TYPE {mname} gauge')
            for label, value in sorted(series[mname]):
                lines.append(f'{mname}{label} {value:g}')
        return '\n'.join(lines) + '\n'

    def status(self) -> dict:
        """The ``/statusz`` JSON snapshot: uptime, every registered stat
        set's counters, every status provider's view, recorder state."""
        with self._lock:
            providers = sorted(self._status.items())
            dumps = list(self.dumps)
            events_n = self._events_n
            ring = self._ring
        status: Dict[str, object] = {}
        for name, provider in providers:
            try:
                status[name] = provider()
            # lint: allow(fault-taxonomy): a broken provider must degrade its own /statusz entry, never the endpoint
            except Exception as e:
                status[name] = {'error': repr(e)}
        stats = {name: counters
                 for name, counters, _s in self._refreshed_snapshots()}
        return {
            'uptime_s': (time.monotonic_ns() - self._t0_ns) / 1e9,
            'pid': os.getpid(),
            'ring_events': ring,
            'events_recorded': events_n,
            'events_buffered': len(self.events()),
            'stats': stats,
            'status': status,
            'flight_dumps': dumps,
        }

    # -- flight-recorder dumps ---------------------------------------------
    def configure_dump(self, dump_dir: str,
                       keep: int = DEFAULT_KEEP) -> None:
        self._dump_dir = os.fspath(dump_dir)
        self._dump_keep = max(1, int(keep))

    def dump(self, reason: str, log=None) -> Optional[str]:
        """Write one flight-record JSON (ring + failure log + stat
        snapshots) to the configured dump dir; returns its path (None
        when no dir is configured).  Bounded: only the newest ``keep``
        dumps per process survive."""
        if self._dump_dir is None:
            return None
        if log is None:
            from ..runtime import faults
            log = faults.global_failure_log()
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        import re
        tag = re.sub(r'[^a-zA-Z0-9_.-]', '_', str(reason))[:48]
        payload = {
            'reason': str(reason),
            'seq': seq,
            'pid': os.getpid(),
            'monotonic_ns': time.monotonic_ns(),
            'events': self.events(),
            'failure_log': [
                {'kind': r.kind, 'detail': r.detail, 'step': r.step,
                 'monotonic': r.monotonic} for r in log.records()],
            'stats': {name: counters for name, counters, _s in
                      self._refreshed_snapshots()},
        }
        slos = self.slos_view()
        if slos:
            # the breaching window's samples + verdict history ride
            # every postmortem (the SLO-drill acceptance contract)
            payload['slos'] = slos
        os.makedirs(self._dump_dir, exist_ok=True)
        path = os.path.join(self._dump_dir,
                            f'flight_{os.getpid()}_{seq:03d}_{tag}.json')
        with open(path, 'w', encoding='utf-8') as f:
            json.dump(payload, f, default=str)
        with self._lock:
            self.dumps.append(path)
            while len(self.dumps) > self._dump_keep:
                old = self.dumps.pop(0)
                try:
                    os.unlink(old)
                except OSError:
                    pass
        return path

    def arm_flight_recorder(self, dump_dir: str,
                            keep: int = DEFAULT_KEEP) -> None:
        """Arm automatic postmortems: any ``TrainingFault`` or
        ``SLOBreachError`` subclass kind (or a supervisor give-up)
        reaching a ``FailureLog`` dumps the flight record to
        ``dump_dir`` — every chaos drill, SLO breach, and real incident
        ships its own postmortem.  Idempotent; :meth:`disarm` removes
        the listener."""
        from ..runtime import faults
        self.configure_dump(dump_dir, keep=keep)
        if self._listener is not None:
            return

        def listener(rec, log):
            if rec.kind != 'giving_up' \
                    and rec.kind not in faults.training_fault_kinds() \
                    and rec.kind not in faults.slo_breach_kinds():
                return
            try:
                self.dump(rec.kind, log=log)
            # lint: allow(fault-taxonomy): a failed postmortem write must never break the training/serving path that faulted
            except Exception:
                pass

        self._listener = listener
        faults.add_failure_listener(listener)

    def disarm(self) -> None:
        """Remove the failure-log dump listener (tests, CLI teardown)."""
        if self._listener is not None:
            from ..runtime import faults
            faults.remove_failure_listener(self._listener)
            self._listener = None

    def arm_signal_dump(self) -> bool:
        """``kill -USR1 <pid>`` dumps the flight record of a live
        process.  Main-thread only (signal module contract); returns
        False where unavailable (Windows, embedded interpreters)."""
        import signal
        if not hasattr(signal, 'SIGUSR1'):
            return False
        try:
            signal.signal(signal.SIGUSR1,
                          lambda _s, _f: self.dump('SIGUSR1'))
        except ValueError:      # not the main thread
            return False
        return True

    # -- Chrome trace export ------------------------------------------------
    def export_chrome_trace(self, path: str) -> str:
        """Write the flight-recorder ring as Chrome trace-event JSON
        (``ph: X`` complete events, microsecond timestamps).  Loads in
        Perfetto / chrome://tracing — side by side with an XLA
        ``profile_dir`` trace, since both clocks count monotonic time
        (align on a shared landmark span; doc/observability.md)."""
        events = self.events()
        tids: Dict[str, int] = {}
        trace: List[dict] = []
        pid = os.getpid()
        for e in events:
            tid = tids.setdefault(e['thread'], len(tids) + 1)
            args = dict(e['attrs'])
            if e['trace_id'] is not None:
                args['trace_id'] = e['trace_id']
            trace.append({
                'name': e['name'], 'cat': e['subsystem'], 'ph': 'X',
                'ts': e['t_start_ns'] / 1e3,
                'dur': max(e['dur_ns'], 1) / 1e3,
                'pid': pid, 'tid': tid, 'args': args})
        for tname, tid in tids.items():
            trace.append({'ph': 'M', 'name': 'thread_name', 'pid': pid,
                          'tid': tid, 'args': {'name': tname}})
        with open(path, 'w', encoding='utf-8') as f:
            json.dump({'traceEvents': trace, 'displayTimeUnit': 'ms'},
                      f, default=str)
        return path


# --- the process-wide hub ---------------------------------------------------

_HUB: Optional[TelemetryHub] = None
_HUB_LOCK = threading.Lock()


def get_hub() -> TelemetryHub:
    """The process-wide hub (created on first use)."""
    global _HUB
    h = _HUB
    if h is None:
        with _HUB_LOCK:
            if _HUB is None:
                _HUB = TelemetryHub()
            h = _HUB
    return h


def install_hub(hub: Optional[TelemetryHub]) -> Optional[TelemetryHub]:
    """Swap the process-wide hub (tests); returns the previous one.
    ``None`` resets to a fresh default on next :func:`get_hub`."""
    global _HUB
    with _HUB_LOCK:
        prev, _HUB = _HUB, hub
    return prev


def span(name: str, subsystem: str = 'app',
         trace_id: Optional[str] = None, **attrs):
    """Module-level convenience for ``get_hub().span(...)`` — the one
    spelling production code uses (graftlint's span-hygiene rule keys
    on it)."""
    return get_hub().span(name, subsystem, trace_id, **attrs)


def record_event(name: str, subsystem: str = 'app',
                 trace_id: Optional[str] = None,
                 t_start_ns: Optional[int] = None, dur_ns: int = 0,
                 **attrs) -> None:
    get_hub().record_event(name, subsystem, trace_id,
                           t_start_ns=t_start_ns, dur_ns=dur_ns, **attrs)


def next_trace_id() -> str:
    return get_hub().next_trace_id()
