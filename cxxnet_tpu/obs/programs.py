"""graftprof — compiler-truth observability (doc/observability.md
"Programs, memory, and MFU").

graftscope/graftwatch made the *runtime* observable; this module makes
the **compiler's** truth observable.  Every load-bearing compiled
executable in the process — the trainer's per-step / scanned-window /
grad / apply programs, PredictEngine's bucket ladder, DecodeEngine's
prefill / decode / verify / spec programs — registers into one
process-wide :class:`ProgramLedger`:

* **program ledger** — each call site claims a :class:`LedgerProgram`
  (a name plus an optional declared shape-key bound) and routes its
  ``jax.jit`` through :meth:`LedgerProgram.jit`.  Dispatch stays the
  plain jit C++ fast path — byte-for-byte the pre-ledger call, so
  every bitwise twin is untouched and the steady-state tax is one
  Python frame; a trace-time hook registers each XLA compilation
  (name, shape-key, signature, sentinel) as it happens, and the
  compiler-truth numbers — compile wall-ms, ``cost_analysis()``
  flops / bytes-accessed, ``memory_analysis()`` argument / output /
  temp / peak bytes — fill lazily via an AOT probe
  (``lower().compile()`` from a ShapeDtypeStruct skeleton) on first
  READ of an entry, never on the dispatch path.  Served raw on
  ``/programs``, summarized in ``/statusz``, exported as gauges on
  ``/metrics`` (so every one is SLO-able through the graftwatch
  engine for free; the cost/memory gauges fill once their entry has
  been read — counts and the sentinel are always live).
* **recompile sentinel** — a program whose compile count exceeds its
  declared bound bumps ``recompiles_total`` and records the typed
  ``faults.RecompileStormError`` kind; ``obs.recompile=raise`` raises
  it at the offending call site (default ``warn``).
* **device-memory gauges** — :class:`DeviceMemory` fills ``hbm.*``
  per-device bytes_in_use / peak / headroom-fraction from
  ``device.memory_stats()``, with a cpu-safe ``jax.live_arrays()``
  fallback (``hbm.supported`` says which source answered).  Registered
  as an ordinary hub StatSet, the existing history sampler and the
  fleet scraper pick it up unchanged (rank labels for free).
* **MFU** — :func:`peak_flops` is the per-platform peak-FLOPs table
  (``CXXNET_PEAK_TFLOPS`` overrides); :func:`mfu` divides ledger
  flops/step × measured steps/sec by it.  The train eval line and
  bench receipts both read it from here so the denominators can't
  drift.
* **on-demand profiler** — :class:`ProfilerSession` backs the
  ``/profile?ms=N`` endpoint: a single-flight ``jax.profiler`` trace
  into the obs dir, mutually exclusive with a config-driven
  ``profile_dir`` TraceWindow (``utils/profiler.acquire_trace``) and
  deliberately NOT demoting the scanned dispatch — an on-demand trace
  observes the program shape that is actually live.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ['ProgramLedger', 'LedgerProgram', 'ProgramEntry', 'get_ledger',
           'install_ledger', 'peak_bytes_for', 'DeviceMemory',
           'register_hbm', 'ProfilerSession', 'profile_session',
           'peak_flops', 'mfu', 'PEAK_BF16_TFLOPS']


# --- per-platform peak FLOPs (MFU denominators) -----------------------------

#: bf16 peak TFLOP/s by TPU generation (marketing peak).  THE table —
#: bench.py and the train eval line both divide by it.
PEAK_BF16_TFLOPS: Tuple[Tuple[str, float], ...] = (
    ('v6', 918.0), ('v5p', 459.0), ('v5', 197.0), ('v4', 275.0),
)


def peak_flops(device=None) -> float:
    """Peak bf16 FLOP/s of one chip.  ``CXXNET_PEAK_TFLOPS`` overrides
    (how a CPU run or an untabulated part gets an honest denominator);
    0.0 on CPU with no override — MFU is then unreported, never faked."""
    env = os.environ.get('CXXNET_PEAK_TFLOPS')
    if env:
        return float(env) * 1e12
    import jax
    if device is None:
        devs = jax.devices()
        if not devs:
            return 0.0
        device = devs[0]
    if device.platform == 'cpu':
        return 0.0
    kind = getattr(device, 'device_kind', '').lower().replace(' ', '')
    for key, tflops in PEAK_BF16_TFLOPS:
        if key in kind:
            return tflops * 1e12
    return 197e12                        # v5e-class default


def mfu(flops_per_step: float, steps_per_sec: float,
        device=None) -> Optional[float]:
    """Model FLOPs utilization, or None when the peak (or the flops)
    is unknown — the null-not-NaN receipt rule, applied to gauges."""
    peak = peak_flops(device)
    if peak <= 0 or flops_per_step <= 0 or steps_per_sec <= 0:
        return None
    return flops_per_step * steps_per_sec / peak


# --- the ledger -------------------------------------------------------------

class ProgramEntry:
    """One (program name, shape-key) row of the ledger.  Created at
    trace time with the cheap fields (name, key, signature, counts);
    the compiler-truth fields (flops, bytes, compile_ms) fill lazily on
    first read through :meth:`ProgramLedger.ensure_analyzed`."""

    __slots__ = ('name', 'shape_key', 'signature', 'compile_ms', 'flops',
                 'bytes_accessed', 'argument_bytes', 'output_bytes',
                 'temp_bytes', 'peak_bytes', 'compiles', 'steps', 'seq',
                 '_skel', '_wrapper', '_analyzed')

    def __init__(self, name: str, shape_key: str, signature: str,
                 steps: int, seq: int):
        self.name = name
        self.shape_key = shape_key
        self.signature = signature
        self.compile_ms = 0.0
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.argument_bytes = 0
        self.output_bytes = 0
        self.temp_bytes = 0
        self.peak_bytes = 0
        self.compiles = 0
        self.steps = max(1, int(steps))
        self.seq = seq
        self._skel = None
        self._wrapper = None
        self._analyzed = False

    def view(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__
                if not k.startswith('_')}


def _describe(skel) -> str:
    """Compact human signature for the /programs row: the first few
    array leaves of the skeleton as ``dtype[shape]``."""
    import jax
    parts = []
    for x in jax.tree.leaves(skel):
        shape = getattr(x, 'shape', None)
        dtype = getattr(x, 'dtype', None)
        if shape is None or dtype is None:
            continue
        parts.append(f'{dtype}[{",".join(str(s) for s in shape)}]')
        if len(parts) >= 6:
            parts.append('…')
            break
    return ','.join(parts)


#: bench A/B switch (bench.py obs mode): True suppresses the
#: trace-time recording hook, so the measured "ledger tax" is exactly
#: the wrapper's real per-call cost (one Python frame + this flag
#: check), not a proxy.  Never True in production.
_RAW_JIT = False


def set_raw_jit(flag: bool) -> bool:
    """Flip the bench-only raw-jit bypass; returns the previous value."""
    global _RAW_JIT
    prev, _RAW_JIT = _RAW_JIT, bool(flag)
    return prev


#: set while a lazy AOT analysis probe re-traces a wrapped fn: the
#: trace hook must not count the probe as a fresh compilation
_PROBE_TLS = threading.local()


class _WrappedJit:
    """The ledger-routed replacement for a direct ``jax.jit`` call
    site.  Dispatch IS the plain ``jax.jit`` C++ fast path —
    byte-for-byte the pre-ledger call, so the wrapper's steady-state
    cost is one Python frame (~100 ns) and every bitwise twin is
    untouched by construction.  Compiler truth is harvested OFF the
    hot path: a trace-time hook inside the jitted fn fires once per
    XLA compilation (the idiom PredictEngine's ``compile_count``
    always used), capturing a ``ShapeDtypeStruct`` skeleton of the
    args and registering the entry + recompile sentinel immediately;
    the expensive ``cost_analysis()`` / ``memory_analysis()`` numbers
    are filled lazily — an AOT ``lower().compile()`` from the
    skeleton runs only when somebody actually reads the entry
    (``/programs`` render, ``train_step_flops``, bench receipts),
    never on the dispatch path.  ``fixed=True`` documents a program
    whose signature is static by construction (the decode step over
    preallocated pools); dispatch is identical either way."""

    def __init__(self, program: 'LedgerProgram', fn, key=None, key_fn=None,
                 static_argnames=(), donate_argnums=(), steps: int = 1,
                 fixed: bool = False):
        import jax
        kw = {}
        if static_argnames:
            kw['static_argnames'] = tuple(static_argnames)
        if donate_argnums:
            kw['donate_argnums'] = tuple(donate_argnums)
        self._program = program
        self._static = tuple(static_argnames)
        self._key = key
        self._key_fn = key_fn
        self._steps = max(1, int(steps))
        self._fixed = bool(fixed)
        self._compiles = 0             # guarded-by: _lock
        self._lock = threading.Lock()

        def traced(*args, **kwargs):
            # runs at TRACE time only (once per XLA compilation, args
            # are tracers) — never inside the compiled program
            self._on_trace(args, kwargs)
            return fn(*args, **kwargs)

        self._jit = jax.jit(traced, **kw)

    @staticmethod
    def _skeleton(x):
        import jax
        if hasattr(x, 'shape') and hasattr(x, 'dtype'):
            return jax.ShapeDtypeStruct(
                tuple(x.shape), x.dtype,
                weak_type=getattr(x, 'weak_type', False))
        return x                       # static / python-scalar leaf

    def _on_trace(self, args, kwargs) -> None:
        if getattr(_PROBE_TLS, 'active', False) or _RAW_JIT:
            return
        import jax
        skel = jax.tree.map(self._skeleton, (args, kwargs))
        key = self._key
        if key is None and self._key_fn is not None:
            key = str(self._key_fn(args, kwargs))
        with self._lock:
            self._compiles += 1
        self._program._record(key, skel, self, steps=self._steps)

    def __call__(self, *args, **kwargs):
        # the C++ jit fast path, raw or not: _RAW_JIT (the bench A/B
        # twin) only suppresses the trace hook, so the measured "tax"
        # is exactly this wrapper frame
        return self._jit(*args, **kwargs)

    def _analyze(self, skel) -> tuple:
        """AOT-compile the skeleton signature and return
        ``(compile_ms, compiled)`` — the lazy analysis probe, run off
        the hot path by :meth:`ProgramLedger.ensure_analyzed`."""
        args, kwargs = skel
        _PROBE_TLS.active = True
        try:
            t0 = time.monotonic()
            compiled = self._jit.lower(*args, **kwargs).compile()
            return (time.monotonic() - t0) * 1e3, compiled
        finally:
            _PROBE_TLS.active = False

    def ensure_compiled(self, *args, **kwargs) -> Optional['ProgramEntry']:
        """Register (and analyze) this signature WITHOUT executing —
        the ``train_step_flops`` probe path; returns the newest entry.
        Never runs the program: donated buffers stay live."""
        import jax
        skel = jax.tree.map(self._skeleton, (args, kwargs))
        key = self._key
        if key is None and self._key_fn is not None:
            key = str(self._key_fn(args, kwargs))
        with self._lock:
            self._compiles += 1
        entry = self._program._record(key, skel, self,
                                      steps=self._steps)
        if entry is not None:
            self._program.ledger.ensure_analyzed(entry)
        return self._program.newest_entry()

    def _cache_size(self) -> int:
        """Compilations seen by this wrapper — the same surface jax's
        jit wrapper exposes, kept so the compile-cache bound tests
        read one number either way."""
        with self._lock:
            return self._compiles


class LedgerProgram:
    """One named program family in the ledger (claimed via
    :meth:`ProgramLedger.program`).  ``bound`` is the declared shape-key
    bound the recompile sentinel enforces: more compiles than ``bound``
    (novel keys OR re-traces of a known one) is a storm."""

    def __init__(self, ledger: 'ProgramLedger', name: str,
                 bound: Optional[int] = None):
        self.ledger = ledger
        self.name = name
        self.bound = None if bound is None else int(bound)
        # compiles/_keys/_warned are mutated only inside the LEDGER's
        # record_compile (under its lock); reads are monotonic tallies
        self.compiles = 0
        self._keys: set = set()
        self._warned = False

    def jit(self, fn, *, key=None, key_fn=None, static_argnames=(),
            donate_argnums=(), steps: int = 1,
            fixed: bool = False) -> _WrappedJit:
        """Wrap ``fn`` as a ledger-routed jitted program.  ``key`` (or
        ``key_fn(args, kwargs)``) names the shape-key of each compile
        (default: auto ``v<N>``); ``steps`` is the per-entry flops
        normalization (a K-step scanned window registers steps=K)."""
        return _WrappedJit(self, fn, key=key, key_fn=key_fn,
                           static_argnames=static_argnames,
                           donate_argnums=donate_argnums, steps=steps,
                           fixed=fixed)

    def _record(self, key, skel, wrapper, steps=1):
        return self.ledger.record_trace(self, key, skel, wrapper,
                                        steps=steps)

    def retire(self) -> None:
        """Owner is shutting down: stop all future AOT probes of this
        family's entries (see :meth:`ProgramLedger.retire_program`)."""
        self.ledger.retire_program(self.name)

    def compile_headroom(self) -> Optional[int]:
        """Compiles this family can still absorb before the recompile
        sentinel calls a storm (``bound - compiles``); None = unbounded.
        The online tuner's re-plan guard reads this BEFORE compiling a
        candidate (doc/autotune.md "Recompile budget")."""
        if self.bound is None:
            return None
        with self.ledger._lock:
            return self.bound - self.compiles

    def entries(self, analyze: bool = True) -> List[ProgramEntry]:
        return self.ledger.entries_for(self.name, analyze=analyze)

    def newest_entry(self) -> Optional[ProgramEntry]:
        es = self.entries()
        return es[-1] if es else None

    def flops_per_step(self) -> float:
        """Newest flops-bearing entry's flops, normalized by its step
        count — 0.0 when nothing compiled (or the backend has no cost
        model)."""
        for e in reversed(self.entries()):
            if e.flops > 0:
                return e.flops / e.steps
        return 0.0

    def argument_bytes(self) -> int:
        """Newest entry's argument bytes (the compiled program's true
        resident working set) — what ``budget_drift`` cross-checks the
        closed-form ``resident_bytes()`` ledgers against."""
        e = self.newest_entry()
        return e.argument_bytes if e is not None else 0


class ProgramLedger:
    """Process-wide registry of compiled executables (module
    docstring).  Thread-safe; entries are bounded (oldest pruned) so a
    long test session or a model-cycling fleet cannot grow it without
    bound."""

    MAX_ENTRIES = 512

    def __init__(self):
        self._lock = threading.Lock()
        self._analyze_lock = threading.Lock()   # serializes AOT probes
        self._recompile = 'warn'       # obs.recompile: warn | raise | off
        self._names: Dict[str, int] = {}          # guarded-by: _lock
        self._entries: 'collections.OrderedDict[Tuple[str, str], ProgramEntry]' = \
            collections.OrderedDict()             # guarded-by: _lock
        self._seq = 0                  # guarded-by: _lock
        self.compiles_total = 0        # guarded-by: _lock
        self.recompiles_total = 0      # guarded-by: _lock
        self.compile_ms_total = 0.0    # guarded-by: _lock
        self._stats = None

    # -- program claims ----------------------------------------------------
    def program(self, name: str,
                bound: Optional[int] = None) -> LedgerProgram:
        """Claim a program name.  A re-claimed base name gets a ``#N``
        suffix (each engine/trainer instance owns its own sentinel
        state and its own entries; the ledger keeps both histories)."""
        with self._lock:
            n = self._names.get(name, 0)
            self._names[name] = n + 1
            full = name if n == 0 else f'{name}#{n + 1}'
        return LedgerProgram(self, full, bound=bound)

    def retire_program(self, name: str) -> None:
        """Drop the analysis hooks of every entry under ``name`` — called
        when the owning engine closes.  Rows and any compiler truth
        already probed stay in the ledger views; un-probed entries are
        marked analyzed with zeros (the failed-probe policy), so a later
        :meth:`entries` sweep never AOT-compiles a dead program — the
        owner's mesh/devices may be gone, and re-lowering a stale SPMD
        skeleton late in the process is exactly the probe that can take
        the whole XLA client down."""
        with self._analyze_lock:       # exclude an in-flight probe
            with self._lock:
                for (n, _k), e in self._entries.items():
                    if n == name:
                        e._wrapper = None
                        e._skel = None
                        e._analyzed = True

    def set_recompile(self, mode: str) -> None:
        if mode not in ('warn', 'raise', 'off'):
            raise ValueError(
                f'obs.recompile must be warn|raise|off, got {mode!r}')
        self._recompile = mode

    @property
    def recompile_mode(self) -> str:
        return self._recompile

    # -- recording ---------------------------------------------------------
    @staticmethod
    def _cost_dict(compiled) -> dict:
        try:
            ca = compiled.cost_analysis()
        # lint: allow(fault-taxonomy): backends without a cost model surface it many ways; the entry degrades to zeros, the program still runs
        except Exception:
            return {}
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return dict(ca or {})

    @staticmethod
    def _memory(compiled):
        try:
            return compiled.memory_analysis()
        # lint: allow(fault-taxonomy): memory_analysis is optional per backend; the entry degrades to zeros, the program still runs
        except Exception:
            return None

    def record_trace(self, program: LedgerProgram, key, skel, wrapper,
                     steps: int = 1) -> Optional[ProgramEntry]:
        """Register one XLA compilation of ``program`` (fired by the
        wrapper's trace-time hook — args are a ShapeDtypeStruct
        skeleton).  Cheap by design: counts, sentinel, and the human
        signature only; cost/memory analysis is deferred to
        :meth:`ensure_analyzed`.  Under ``obs.recompile=raise`` a storm
        raises ``faults.RecompileStormError`` at the offending call
        site."""
        signature = _describe(skel)
        with self._lock:
            program.compiles += 1
            if key is None:
                key = f'v{len(program._keys)}'
            program._keys.add(key)
            ek = (program.name, str(key))
            entry = self._entries.get(ek)
            if entry is None:
                self._seq += 1
                entry = ProgramEntry(program.name, str(key), signature,
                                     steps, self._seq)
                self._entries[ek] = entry
                while len(self._entries) > self.MAX_ENTRIES:
                    self._entries.popitem(last=False)
            else:
                self._entries.move_to_end(ek)
            entry.compiles += 1
            entry.signature = signature
            entry.steps = max(1, int(steps))
            entry._skel = skel
            entry._wrapper = wrapper
            entry._analyzed = False      # a fresh compile: re-probe
            self.compiles_total += 1
            storm = (program.bound is not None
                     and program.compiles > program.bound
                     and self._recompile != 'off')
            if storm:
                self.recompiles_total += 1
            warn_now = storm and not program._warned \
                and self._recompile == 'warn'
            if warn_now:
                program._warned = True
            mode = self._recompile
        from .hub import record_event
        record_event(f'compile.{program.name}', 'obs', key=str(key))
        if storm and mode != 'off':
            from ..runtime import faults
            err = faults.RecompileStormError(program.name, key,
                                             program.bound,
                                             program.compiles)
            faults.global_failure_log().record('RecompileStormError',
                                               str(err))
            if mode == 'raise':
                raise err
            if warn_now:
                import sys
                sys.stderr.write(f'obs: {err}\n')
        return entry

    def ensure_analyzed(self,
                        entry: Optional[ProgramEntry]
                        ) -> Optional[ProgramEntry]:
        """Fill the compiler-truth fields of ``entry`` (flops, bytes,
        compile wall-ms) by AOT-compiling its recorded skeleton — run
        on first READ of an entry (``/programs``, ``train_step_flops``,
        ``budget_drift``, bench receipts), never on the dispatch path.
        The probe re-traces through the wrapper with the hook
        suppressed, so counts and the sentinel never see it.
        Idempotent; a failed probe marks the entry analyzed with zeros
        (the program itself keeps running)."""
        if entry is None or entry._analyzed:
            return entry
        with self._analyze_lock:
            self._probe_and_fill(entry)
        return entry

    def _probe_and_fill(self, entry: ProgramEntry) -> None:  # requires-lock: _analyze_lock
        """One entry's AOT probe + compiler-truth fill — the body both
        :meth:`ensure_analyzed` and the batched sweep share.  A failed
        (or wrapper-less) probe marks the entry analyzed with zeros."""
        if entry._analyzed:
            return
        wrapper, skel = entry._wrapper, entry._skel
        if wrapper is None:
            entry._analyzed = True
            return
        try:
            ms, compiled = wrapper._analyze(skel)
        # lint: allow(fault-taxonomy): the analysis probe degrades to a zero-filled row; the program itself already compiled and runs
        except Exception:
            entry._analyzed = True
            return
        self._fill(entry, ms, compiled)

    def _fill(self, entry: ProgramEntry, ms: float, compiled) -> None:
        cost = self._cost_dict(compiled)
        mem = self._memory(compiled)
        arg = out = temp = peak = 0
        if mem is not None:
            arg = int(getattr(mem, 'argument_size_in_bytes', 0) or 0)
            out = int(getattr(mem, 'output_size_in_bytes', 0) or 0)
            temp = int(getattr(mem, 'temp_size_in_bytes', 0) or 0)
            peak = int(getattr(mem, 'peak_size_in_bytes', 0) or 0)
            if peak == 0:
                # XLA:CPU reports no live-range peak; argument+
                # output+temp is the honest upper bound of what the
                # program holds at once
                peak = arg + out + temp
        with self._lock:
            entry.compile_ms = float(ms)
            entry.flops = float(cost.get('flops', 0.0) or 0.0)
            entry.bytes_accessed = float(
                cost.get('bytes accessed', 0.0) or 0.0)
            entry.argument_bytes = arg
            entry.output_bytes = out
            entry.temp_bytes = temp
            entry.peak_bytes = peak
            entry._analyzed = True
            self.compile_ms_total += float(ms)

    def ensure_analyzed_batch(self, names=None, workers: int = 4) -> int:
        """Batched AOT analysis: fill every unanalyzed entry (of the
        program families in ``names``, or all of them) by fanning the
        lowerings out over a short-lived worker pool instead of
        serializing N probes on the caller thread — the autotuner's
        stage-1 sweep and the ``/programs`` first-read both need the
        whole ledger's compiler truth at once (doc/autotune.md).

        Holds ``_analyze_lock`` for the sweep, so concurrent single
        :meth:`ensure_analyzed` calls serialize against it exactly as
        before; each probe thread re-traces with the hook suppressed
        (``_PROBE_TLS`` is thread-local), so counts and the recompile
        sentinel never see the batch.  Returns how many entries this
        call analyzed (failed probes count — they are marked analyzed
        with zeros, same as the single-entry path)."""
        wanted = None if names is None else set(names)
        with self._lock:
            todo = sorted(
                (e for (n, _k), e in self._entries.items()
                 if not e._analyzed and (wanted is None or n in wanted)),
                key=lambda e: e.seq)
        if not todo:
            return 0
        with self._analyze_lock:
            todo = [e for e in todo if not e._analyzed]
            if not todo:
                return 0
            probed = []
            results = {}                 # seq -> (ms, compiled)
            res_lock = threading.Lock()

            def probe(entry):
                wrapper, skel = entry._wrapper, entry._skel
                if wrapper is None:
                    return
                try:
                    ms, compiled = wrapper._analyze(skel)
                # lint: allow(fault-taxonomy): a failed batch probe degrades that one row to zeros, like the single-entry path
                except Exception:
                    return
                with res_lock:
                    results[entry.seq] = (ms, compiled)

            n_workers = max(1, min(int(workers), len(todo)))
            if n_workers == 1:
                for e in todo:
                    probe(e)
            else:
                queue = list(todo)
                q_lock = threading.Lock()

                def drain():
                    while True:
                        with q_lock:
                            if not queue:
                                return
                            e = queue.pop(0)
                        probe(e)

                threads = [threading.Thread(
                    target=drain, name=f'cxxnet-obs-aot-{i}', daemon=True)
                    for i in range(n_workers)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            for e in todo:
                got = results.get(e.seq)
                if got is None:
                    # wrapper-less or failed probe: analyzed-with-zeros,
                    # exactly like the single-entry path
                    e._analyzed = True
                else:
                    self._fill(e, got[0], got[1])
                probed.append(e)
        return len(probed)

    # -- views -------------------------------------------------------------
    def entries_for(self, name: str,
                    analyze: bool = True) -> List[ProgramEntry]:
        """Entries of one program family.  ``analyze=False`` skips the
        lazy AOT probe — the read-only spelling for render threads
        (/statusz providers, gauge refreshes) that must never block on
        an XLA compile; unanalyzed entries then report zero flops."""
        if analyze:
            self.ensure_analyzed_batch(names=(name,))
        with self._lock:
            return sorted((e for (n, _k), e in self._entries.items()
                           if n == name), key=lambda e: e.seq)

    def entries(self) -> List[ProgramEntry]:
        # the /programs first read: one batched sweep, not N serialized
        # lowerings on the render thread
        self.ensure_analyzed_batch()
        with self._lock:
            return sorted(self._entries.values(), key=lambda e: e.seq)

    def view(self) -> dict:
        """The ``/programs`` body: every entry plus the totals."""
        entries = self.entries()
        with self._lock:
            totals = (self.compiles_total, self.recompiles_total,
                      self.compile_ms_total)
        return {
            'programs': [e.view() for e in entries],
            'compiles_total': totals[0],
            'recompiles_total': totals[1],
            'compile_ms_total': round(totals[2], 3),
            'recompile_mode': self._recompile,
        }

    def summary(self) -> dict:
        """The ``/statusz`` (and bench-receipt) one-liner: counts and
        compile cost, no per-entry detail."""
        with self._lock:
            n = len(self._entries)
            totals = (self.compiles_total, self.recompiles_total,
                      self.compile_ms_total)
        return {
            'programs': n,
            'compiles_total': totals[0],
            'recompiles_total': totals[1],
            'compile_ms_total': round(totals[2], 3),
            'recompile_mode': self._recompile,
        }

    @staticmethod
    def _base_name(name: str) -> str:
        return name.split('#', 1)[0]

    def _refresh_stats(self) -> None:
        stats = self._stats
        if stats is None:
            return
        with self._lock:
            entries = list(self._entries.values())
            stats_tuples = (len(self._entries), self.compiles_total,
                            self.recompiles_total, self.compile_ms_total)
        stats.gauge('programs', stats_tuples[0])
        stats.gauge('compiles_total', stats_tuples[1])
        stats.gauge('recompiles_total', stats_tuples[2])
        stats.gauge('compile_ms_total', round(stats_tuples[3], 3))
        # base-name aggregation keeps the /metrics label cardinality
        # bounded by the dozen-odd program families, not the entry cap.
        # Cost/memory gauges cover ANALYZED entries only (a render must
        # never trigger AOT probes from the sampler thread); counts
        # above are always live, and the detailed readers (/programs,
        # train_step_flops, budget_drift) fill the rest on first read
        agg: Dict[str, List[float]] = {}
        for e in sorted(entries, key=lambda e: e.seq):
            a = agg.setdefault(self._base_name(e.name), [0.0, 0.0, 0.0])
            a[0] = max(a[0], e.flops / e.steps)
            a[1] = max(a[1], float(e.peak_bytes))
            a[2] += e.compile_ms * e.compiles
        for base, (flops, peakb, cms) in agg.items():
            stats.gauge(f'flops[{base}]', flops)
            stats.gauge(f'peak_bytes[{base}]', peakb)
            stats.gauge(f'compile_ms[{base}]', round(cms, 3))

    def register_into(self, hub) -> None:
        """Join the telemetry hub: a ``programs`` StatSet on
        ``/metrics`` (and thereby the history sampler / SLO engine /
        fleet view) plus a ``programs`` ``/statusz`` provider."""
        if self._stats is None:
            from ..utils.metric import StatSet
            self._stats = StatSet()
        hub.register_stats('programs', self._stats,
                           refresh=self._refresh_stats)
        hub.register_status('programs', self.summary)


# --- device-memory (hbm.*) gauges -------------------------------------------

class DeviceMemory:
    """Per-device memory gauges (``hbm.*``): ``bytes_in_use[dN]`` /
    ``peak_bytes[dN]`` / ``headroom_frac[dN]`` from
    ``device.memory_stats()`` where the runtime exposes it (TPU/GPU),
    falling back to a ``jax.live_arrays()`` walk on CPU
    (``supported=0``; peak is then the in-process monotone max, and
    headroom is unreported — there is no limit to be under)."""

    def __init__(self):
        self._peak_seen: Dict[int, float] = {}

    def fill(self, stats) -> None:
        """Refresh hook: write the current per-device gauges into
        ``stats`` (called per /metrics render and per sampler tick)."""
        import jax
        fallback = None
        for i, dev in enumerate(jax.local_devices()):
            tag = f'd{i}'
            try:
                ms = dev.memory_stats()
            # lint: allow(fault-taxonomy): a backend without memory_stats must degrade to the live-array fallback, never kill the render
            except Exception:
                ms = None
            if ms and 'bytes_in_use' in ms:
                in_use = float(ms['bytes_in_use'])
                peak = float(ms.get('peak_bytes_in_use', in_use))
                stats.gauge(f'bytes_in_use[{tag}]', in_use)
                stats.gauge(f'peak_bytes[{tag}]', peak)
                limit = float(ms.get('bytes_limit', 0.0))
                if limit > 0:
                    stats.gauge(f'limit_bytes[{tag}]', limit)
                    stats.gauge(f'headroom_frac[{tag}]',
                                max(0.0, 1.0 - in_use / limit))
                stats.gauge('supported', 1)
            else:
                if fallback is None:
                    fallback = self._live_bytes()
                in_use = fallback.get(dev.id, 0.0)
                peak = max(self._peak_seen.get(dev.id, 0.0), in_use)
                self._peak_seen[dev.id] = peak
                stats.gauge(f'bytes_in_use[{tag}]', in_use)
                stats.gauge(f'peak_bytes[{tag}]', peak)
                stats.gauge('supported', 0)

    @staticmethod
    def _live_bytes() -> Dict[int, float]:
        """CPU fallback: bytes of every live ``jax.Array`` attributed
        per device from its addressable shards — a model-sharded array
        adds each device's OWN shard bytes, a replicated one its full
        bytes on EVERY device it occupies.  (An even split over the
        device set undercounts replicated arrays N-fold, which is
        exactly the error the sharded-serving budget reconciliation
        would trip over.)"""
        import jax
        out: Dict[int, float] = {}
        for arr in jax.live_arrays():
            try:
                for sh in arr.addressable_shards:
                    out[sh.device.id] = (out.get(sh.device.id, 0.0)
                                         + sh.data.nbytes)
            # lint: allow(fault-taxonomy): a deleted/donated array mid-walk must not kill the gauge fill
            except Exception:
                continue
        return out


def register_hbm(hub):
    """Register the ``hbm`` StatSet (with a :class:`DeviceMemory`
    refresh) into ``hub``; returns the StatSet.  The history sampler
    and fleet scraper consume it with zero extra wiring."""
    from ..utils.metric import StatSet
    dm = DeviceMemory()
    stats = StatSet()
    hub.register_stats('hbm', stats, refresh=lambda: dm.fill(stats))
    return stats


# --- on-demand profiler session ---------------------------------------------

class ProfilerSession:
    """Single-flight on-demand ``jax.profiler`` window — the
    ``/profile?ms=N`` endpoint's engine.  One trace at a time per
    process, mutually exclusive with a config-driven ``profile_dir``
    TraceWindow through ``utils/profiler.acquire_trace``; a second
    request while one runs answers ``busy`` instead of corrupting the
    active trace.  The stop rides a named daemon timer thread so the
    requesting scrape returns immediately."""

    MIN_MS = 50.0
    MAX_MS = 60_000.0

    def __init__(self):
        self._lock = threading.Lock()
        self._active: Optional[str] = None   # guarded-by: _lock
        self._seq = 0                        # guarded-by: _lock
        self.sessions = 0                    # guarded-by: _lock

    def start(self, out_dir: str, ms: float = 1000.0) -> dict:
        """Begin one bounded trace into ``out_dir``; returns a JSON-able
        result (``started``/``path``/``ms``, or ``busy`` naming the
        holder)."""
        from ..utils import profiler as _prof
        ms = min(self.MAX_MS, max(self.MIN_MS, float(ms)))
        with self._lock:
            if self._active is not None:
                return {'started': False, 'busy': self._active}
            if not _prof.acquire_trace('obs.profile'):
                return {'started': False,
                        'busy': _prof.trace_owner() or 'profile_dir'}
            self._seq += 1
            path = os.path.join(out_dir,
                                f'profile_{os.getpid()}_{self._seq:03d}')
            self._active = path
        try:
            import jax
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
        except BaseException:
            # release the slot BEFORE clearing _active: a racing
            # start() keeps answering busy until both are undone, so
            # the slot can never be released out from under a session
            # that just acquired it
            _prof.release_trace('obs.profile')
            with self._lock:
                self._active = None
            raise
        t = threading.Thread(target=self._stop_after, args=(ms / 1e3,),
                             daemon=True, name='cxxnet-obs-profile')
        t.start()
        return {'started': True, 'path': path, 'ms': ms}

    def _stop_after(self, seconds: float) -> None:
        from ..utils import profiler as _prof
        time.sleep(seconds)
        try:
            import jax
            jax.profiler.stop_trace()
        # lint: allow(fault-taxonomy): a failed trace stop must still release the single-flight slot or /profile wedges forever
        except Exception:
            pass
        finally:
            # release-then-clear, in that order: until _active clears a
            # racing start() answers busy, so this thread can never
            # release the slot out from under a session that just
            # acquired it (the hazard of the reverse order)
            _prof.release_trace('obs.profile')
            with self._lock:
                self._active = None
                self.sessions += 1

    def status(self) -> dict:
        with self._lock:
            return {'active': self._active, 'sessions': self.sessions}


_PROFILE: Optional[ProfilerSession] = None
_LEDGER: Optional[ProgramLedger] = None
_MOD_LOCK = threading.Lock()


def profile_session() -> ProfilerSession:
    """The process-wide profiler session (created on first use)."""
    global _PROFILE
    p = _PROFILE
    if p is None:
        with _MOD_LOCK:
            if _PROFILE is None:
                _PROFILE = ProfilerSession()
            p = _PROFILE
    return p


def get_ledger() -> ProgramLedger:
    """The process-wide program ledger (created on first use)."""
    global _LEDGER
    led = _LEDGER
    if led is None:
        with _MOD_LOCK:
            if _LEDGER is None:
                _LEDGER = ProgramLedger()
            led = _LEDGER
    return led


def install_ledger(ledger: Optional[ProgramLedger]
                   ) -> Optional[ProgramLedger]:
    """Swap the process-wide ledger (tests); returns the previous one.
    ``None`` resets to a fresh default on next :func:`get_ledger`."""
    global _LEDGER
    with _MOD_LOCK:
        prev, _LEDGER = _LEDGER, ledger
    return prev


def peak_bytes_for(name: str, ledger: Optional[ProgramLedger] = None) -> int:
    """Compiler-truth peak HBM bytes of one program family: the max
    ``memory_analysis`` peak over every analyzed entry whose base name
    matches ``name`` (``#N`` re-claim suffixes included).  The one
    number the ``micro_batch`` bench sweep and the autotuner's memory
    gate compare across candidate splits — 0 when nothing under the
    name has compiled yet (never a guess)."""
    led = ledger if ledger is not None else get_ledger()
    led.ensure_analyzed_batch()
    peak = 0
    for e in led.entries():
        if ProgramLedger._base_name(e.name) == name:
            peak = max(peak, int(e.peak_bytes))
    return peak
