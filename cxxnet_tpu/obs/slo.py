"""Declarative SLOs with multi-window burn-rate verdicts.

The config grammar (doc/observability.md "SLOs and burn rates")::

    slo.<name> = <set>.<key><op><threshold>@<window>[:burn]

    slo.fresh    = online.freshness_s.p99<=0.25@60
    slo.progress = fleet.elastic_steps.max.rate>=2@30:2

``<set>.<key>`` names a gauge exactly as sampled into the
:class:`~cxxnet_tpu.obs.history.GaugeHistory` (the ``/metrics``
spelling minus ``cxxnet_``); a trailing ``.rate``/``.mean``/``.min``/
``.max``/``.p50``/``.p99`` that does not name a sampled key itself is a
*window reduction* over the base gauge.  ``@<window>`` is the long
evaluation window in seconds; ``@0`` declares a *per-sample* spec fed
directly through :meth:`SLOEngine.observe` (the freshness path — every
violating sample is its own breach).

**Verdicts.**  Evaluation is the SRE multi-window burn-rate shape, the
standard fix for turning raw gauges into actionable alarms without
flapping: over the long window W and a short window W/12 compute the
*violating fraction* of samples (for reduced specs the reduction either
violates or not — fraction 1 or 0), and compare both against the alarm
fraction ``f = min(1, burn * budget)`` (budget defaults to 10% of the
window).  Typed verdict:

* ``BREACHED`` — both windows at or past ``f``: the violation is
  sustained *and* still happening,
* ``AT_RISK``  — exactly one window past ``f``: either a fresh spike
  the long window has not absorbed yet, or a recovering breach whose
  budget is still spent,
* ``OK``       — neither (including "no samples yet").

A transition *into* BREACHED records the typed
:class:`~cxxnet_tpu.runtime.faults.SLOBreachError` kind into the
failure log — which arms the flight-recorder postmortem, so every
breach ships the window samples and verdict history that explain it —
and counts one breach; re-evaluating an ongoing breach does not flood
the log.
"""

from __future__ import annotations

import collections
import re
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..runtime import faults
from ..utils.metric import StatSet
from .history import REDUCERS, GaugeHistory

__all__ = ['SLOSpec', 'SLOEngine', 'OK', 'AT_RISK', 'BREACHED',
           'summary_lines']

OK = 'OK'
AT_RISK = 'AT_RISK'
BREACHED = 'BREACHED'

_STATE_CODE = {OK: 0, AT_RISK: 1, BREACHED: 2}

_SPEC_RE = re.compile(
    r'^(?P<key>[A-Za-z_][\w.\[\]]*\.[\w.\[\]]+)\s*'
    r'(?P<op><=|>=|<|>)\s*'
    r'(?P<thr>[-+]?[0-9.]+(?:[eE][-+]?[0-9]+)?)'
    r'@(?P<win>[0-9.]+)'
    r'(?::(?P<burn>[0-9.]+))?$')

_OPS: Dict[str, Callable[[float, float], bool]] = {
    '<=': lambda v, t: v <= t,
    '>=': lambda v, t: v >= t,
    '<': lambda v, t: v < t,
    '>': lambda v, t: v > t,
}


@dataclass(frozen=True)
class SLOSpec:
    """One parsed objective (module docstring grammar)."""

    name: str
    key: str                 # '<set>.<gauge>' history spelling
    op: str                  # <=, >=, <, >
    threshold: float
    window: float            # long window seconds; 0 = per-sample
    burn: float = 1.0
    budget: float = 0.1      # violating fraction of a window = 1 burn
    kind: str = 'SLOBreachError'   # failure-log kind on breach

    @classmethod
    def parse(cls, name: str, text: str, **overrides) -> 'SLOSpec':
        m = _SPEC_RE.match(text.strip())
        if m is None:
            raise ValueError(
                f'slo.{name}: cannot parse {text!r} — expected '
                f'<set>.<key><op><threshold>@<window>[:burn]')
        burn = m.group('burn')
        return cls(name=name, key=m.group('key'), op=m.group('op'),
                   threshold=float(m.group('thr')),
                   window=float(m.group('win')),
                   burn=float(burn) if burn is not None else 1.0,
                   **overrides)

    def violates(self, value: float) -> bool:
        return not _OPS[self.op](float(value), self.threshold)

    def describe(self) -> str:
        tail = '' if self.burn == 1.0 else f':{self.burn:g}'
        return (f'{self.key}{self.op}{self.threshold:g}'
                f'@{self.window:g}{tail}')

    @property
    def alarm_fraction(self) -> float:
        return min(1.0, self.burn * self.budget)


def summary_lines(view: Dict[str, dict]) -> List[str]:
    """One human line per objective from a :meth:`SLOEngine.status_view`
    dict — THE exit-summary spelling (the CLI's ``obs:`` lines and the
    elastic launcher's ``[fleet]`` lines prefix the same text, so the
    two summaries can never drift)."""
    out = []
    for name, v in sorted(view.items()):
        tail = (' — NO DATA matched; check the key spelling against '
                '/metrics' if v.get('no_data') else '')
        out.append(f"slo {name}: {v['state']} (spec {v['spec']}, "
                   f"breaches={v['breaches']}){tail}")
    return out


class SLOEngine:
    """Evaluate :class:`SLOSpec` objectives into typed verdicts over a
    :class:`GaugeHistory` (windowed specs, driven per sampler tick) or
    directly observed samples (``window=0`` specs, the freshness path).
    Thread-safe; breach records land in the failure log OUTSIDE the
    engine lock, so a dump listener reading :meth:`status_view` can
    never deadlock against the evaluation that triggered it."""

    #: verdict records retained per spec
    KEEP_HISTORY = 64
    #: long-window samples retained in status/postmortem views
    KEEP_SAMPLES = 256
    #: short window = long window / SHORT_DIV (the SRE 1h/5m ratio)
    SHORT_DIV = 12.0

    def __init__(self, history: Optional[GaugeHistory] = None,
                 log: Optional[faults.FailureLog] = None):
        self.history = history
        self.log = faults.global_failure_log() if log is None else log
        self.stats = StatSet()
        self._lock = threading.Lock()
        self._specs: Dict[str, SLOSpec] = {}            # guarded-by: _lock
        self._factories: Dict[str, Callable] = {}       # guarded-by: _lock
        self._state: Dict[str, str] = {}                # guarded-by: _lock
        self._verdicts: Dict[str, collections.deque] = {}  # guarded-by: _lock
        self._samples: Dict[str, list] = {}             # guarded-by: _lock
        self._breaches: Dict[str, int] = {}             # guarded-by: _lock
        self._last_breach: Optional[BaseException] = None  # guarded-by: _lock
        self._hubs: List[object] = []                   # guarded-by: _lock

    # -- spec registry -------------------------------------------------------
    def add(self, spec: SLOSpec,
            err_factory: Optional[Callable] = None) -> SLOSpec:
        """Register one objective.  ``err_factory(spec, value, n, ctx)``
        (optional) builds the typed error a breach raises/logs — the
        freshness tracker supplies :class:`faults.FreshnessSLOError`;
        the default is :class:`faults.SLOBreachError`."""
        with self._lock:
            self._specs[spec.name] = spec
            if err_factory is not None:
                self._factories[spec.name] = err_factory
            self._state.setdefault(spec.name, OK)
            self._verdicts.setdefault(
                spec.name, collections.deque(maxlen=self.KEEP_HISTORY))
            self._breaches.setdefault(spec.name, 0)
        return spec

    def specs(self) -> Dict[str, SLOSpec]:
        with self._lock:
            return dict(self._specs)

    def state(self, name: str) -> str:
        with self._lock:
            return self._state.get(name, OK)

    def breached(self) -> bool:
        """Any objective currently BREACHED — what flips ``/healthz``
        to ``degraded``."""
        with self._lock:
            return any(s == BREACHED for s in self._state.values())

    def breaches(self, name: Optional[str] = None) -> int:
        with self._lock:
            if name is not None:
                return self._breaches.get(name, 0)
            return sum(self._breaches.values())

    @property
    def last_breach(self) -> Optional[BaseException]:
        with self._lock:
            return self._last_breach

    def check_strict(self) -> None:
        """Raise the most recent typed breach (run boundaries)."""
        with self._lock:
            err = self._last_breach
        if err is not None:
            raise err

    # -- evaluation ----------------------------------------------------------
    def _default_error(self, spec: SLOSpec, value, n: int,
                       ratio=None) -> faults.SLOBreachError:
        shown = 'n/a' if value is None else f'{value:g}'
        return faults.SLOBreachError(
            f'SLO {spec.name!r} breached: {spec.describe()} — measured '
            f'{shown} over the window ({n} breach(es) total)',
            name=spec.name, measure=value, threshold=spec.threshold,
            window=spec.window, ratio=ratio, breaches=n)

    def _measure(self, spec: SLOSpec, now: float):
        """``(ratio_long, ratio_short, value, samples)`` for one
        windowed spec, or None when no data is in reach.  A key that
        names sampled points directly gets violating-fraction ratios;
        a ``.rate``/quantile suffix over a sampled base key reduces
        each window to one value (ratio 1 or 0)."""
        hist = self.history
        if hist is None:
            return None
        short = max(spec.window / self.SHORT_DIV, 1e-9)
        long_pts = hist.window(spec.key, spec.window, now)
        if long_pts:
            short_pts = hist.window(spec.key, short, now) or long_pts[-1:]

            def frac(pts):
                bad = sum(1 for _t, v in pts if spec.violates(v))
                return bad / len(pts)

            return (frac(long_pts), frac(short_pts), long_pts[-1][1],
                    long_pts)
        base, _, red = spec.key.rpartition('.')
        if red in REDUCERS and hist.has(base):
            vl = hist.reduce(base, red, spec.window, now)
            vs = hist.reduce(base, red, short, now)
            if vl is None and vs is None:
                return None
            rl = 1.0 if vl is not None and spec.violates(vl) else 0.0
            rs = 1.0 if vs is not None and spec.violates(vs) else 0.0
            return (rl, rs, vl if vl is not None else vs,
                    hist.window(base, spec.window, now))
        return None

    def on_tick(self, now: float, history=None) -> None:
        """Sampler listener form of :meth:`evaluate`."""
        self.evaluate(now)

    def evaluate(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Evaluate every windowed spec at ``now``; returns the fresh
        verdict records keyed by spec name (per-sample specs keep their
        latest observed verdict)."""
        now = time.monotonic() if now is None else float(now)
        events: List[tuple] = []
        out: Dict[str, dict] = {}
        with self._lock:
            specs = [s for s in self._specs.values() if s.window > 0]
        for spec in specs:
            m = self._measure(spec, now)
            if m is None:
                state, rl, rs, value, samples = OK, None, None, None, []
            else:
                rl, rs, value, samples = m
                f = spec.alarm_fraction
                hot_long, hot_short = rl >= f, rs >= f
                state = (BREACHED if hot_long and hot_short
                         else AT_RISK if hot_long or hot_short else OK)
            # no_data is surfaced on /slos, /metrics, and the exit
            # summary: a spec whose key never matches a sampled gauge
            # (typo, gauge never registered) must read as "watching
            # nothing", not as a reassuring OK
            rec = {'t': now, 'state': state, 'ratio_long': rl,
                   'ratio_short': rs, 'value': value,
                   'samples_n': len(samples), 'no_data': m is None}
            with self._lock:
                prev = self._state.get(spec.name, OK)
                self._state[spec.name] = state
                self._verdicts[spec.name].append(rec)
                self._samples[spec.name] = [
                    [t, v] for t, v in samples[-self.KEEP_SAMPLES:]]
                if state == BREACHED and prev != BREACHED:
                    self._breaches[spec.name] += 1
                    n = self._breaches[spec.name]
                    factory = self._factories.get(spec.name)
                    err = (factory(spec, value, n, {}) if factory
                           else self._default_error(spec, value, n,
                                                    ratio=rl))
                    self._last_breach = err
                    events.append((spec.kind, err))
            out[spec.name] = rec
        # failure-log records fire listeners (flight-recorder dumps that
        # read status_view) — never while holding the engine lock
        for kind, err in events:
            self.log.record(kind, str(err))
        return out

    def observe(self, name: str, value: float, **ctx) -> str:
        """Feed one sample directly to a ``window=0`` spec (the
        freshness path: every violating sample is its own breach,
        evaluated the moment it is measured).  Returns the verdict
        state for this sample."""
        now = time.monotonic()
        event = None
        with self._lock:
            spec = self._specs[name]
            viol = spec.violates(value)
            state = BREACHED if viol else OK
            self._state[name] = state
            self._verdicts[name].append(
                {'t': now, 'state': state, 'ratio_long': 1.0 if viol
                 else 0.0, 'ratio_short': 1.0 if viol else 0.0,
                 'value': float(value), 'samples_n': 1})
            samples = self._samples.setdefault(name, [])
            samples.append([now, float(value)])
            del samples[:max(0, len(samples) - self.KEEP_SAMPLES)]
            if viol:
                self._breaches[name] += 1
                n = self._breaches[name]
                factory = self._factories.get(name)
                err = (factory(spec, value, n, ctx) if factory
                       else self._default_error(spec, value, n))
                self._last_breach = err
                event = (spec.kind, err, ctx.get('step'))
        if event is not None:
            kind, err, step = event
            self.log.record(kind, str(err), step=step)
        return state

    # -- views / hub integration --------------------------------------------
    def status_view(self) -> dict:
        """The ``/slos`` body (and the flight-dump ``slos`` section):
        per spec — the grammar line, current state, breach count, the
        long window's samples at last evaluation, and the verdict
        history.  Strictly JSON-able (None, never NaN)."""
        with self._lock:
            out = {}
            for name, spec in self._specs.items():
                hist = list(self._verdicts.get(name, ()))
                last = hist[-1] if hist else None
                out[name] = {
                    'spec': spec.describe(),
                    'state': self._state.get(name, OK),
                    'breaches': self._breaches.get(name, 0),
                    'ratio_long': last['ratio_long'] if last else None,
                    'ratio_short': last['ratio_short'] if last else None,
                    'value': last['value'] if last else None,
                    'no_data': (bool(last.get('no_data')) if last
                                else spec.window > 0),
                    'window_samples': list(self._samples.get(name, ())),
                    'history': hist,
                }
            return out

    def _refresh_gauges(self) -> None:
        """Pull-style verdict/ratio rows for ``/metrics`` renders:
        ``cxxnet_slo_verdict{tag="<name>"}`` (0 OK / 1 AT_RISK /
        2 BREACHED), the window ratios, and the breach counters."""
        with self._lock:
            rows = [(name, self._state.get(name, OK),
                     (list(self._verdicts[name]) or [None])[-1],
                     self._breaches.get(name, 0))
                    for name in self._specs]
        for name, state, last, n in rows:
            self.stats.gauge(f'verdict[{name}]', _STATE_CODE[state])
            self.stats.gauge(f'breaches[{name}]', n)
            if last is not None:
                self.stats.gauge(f'no_data[{name}]',
                                 1 if last.get('no_data') else 0)
                if last.get('ratio_long') is not None:
                    self.stats.gauge(f'ratio_long[{name}]',
                                     last['ratio_long'])
                if last.get('ratio_short') is not None:
                    self.stats.gauge(f'ratio_short[{name}]',
                                     last['ratio_short'])

    def register_into(self, hub, name: str = 'slo') -> None:
        """Join a telemetry hub: verdict/ratio gauges under ``name`` on
        ``/metrics``, the status view on ``/statusz``, and the engine
        on the hub's SLO roster (``/slos`` + ``/healthz`` degradation +
        postmortem ``slos`` section)."""
        hub.register_stats(name, self.stats, refresh=self._refresh_gauges)
        hub.register_status(name, self.status_view)
        hub.attach_slo(self)
        with self._lock:
            if (hub, name) not in self._hubs:
                self._hubs.append((hub, name))

    def unregister_from(self, hub, name: str = 'slo') -> None:
        hub.unregister_stats(name)
        hub.unregister_status(name)
        hub.detach_slo(self)
        with self._lock:
            try:
                self._hubs.remove((hub, name))
            except ValueError:
                pass

    def close(self) -> None:
        """Detach from every hub this engine registered into."""
        with self._lock:
            hubs = list(self._hubs)
        for hub, name in hubs:
            self.unregister_from(hub, name)
