"""Train-while-serve: the continuous online-learning subsystem
(doc/online.md).

``OnlinePipeline`` runs a long-lived supervised trainer and a colocated
serving stack as ONE orchestrated process: the trainer async-saves
``%04d.model`` checkpoints every N steps, a ``ModelRegistry``-backed
``PredictEngine`` watches the same directory and hot-swaps them under
live traffic, and a ``FreshnessTracker`` measures the step-to-serving
lag of every swap against a configurable SLO.
"""

from .freshness import FreshnessTracker
from .pipeline import OnlineConfig, OnlinePipeline

__all__ = ['FreshnessTracker', 'OnlineConfig', 'OnlinePipeline']
