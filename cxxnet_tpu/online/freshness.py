"""Freshness: the first-class SLO of a train-while-serve loop.

A 24/7 online-learning product is only as good as the lag between what
the trainer just learned and what the server answers with.  The tracker
measures that lag per hot swap as THREE timestamps per checkpoint step:

* ``record_step(step)``   — the optimizer step's params were snapshotted
  for checkpoint ``step`` (trainer side, supervisor ``on_save`` hook),
* ``record_swap(step)``   — the registry swapped ``step`` into the live
  engine (``ModelRegistry.on_swap``),
* ``note_served(step)``   — a request completed on ``step``'s params
  (``PredictEngine.on_serve``; only the FIRST request per version
  closes the measurement).

``freshness_s`` = first-serve time − step time, observed per swap.
Breach judgment runs through the generic SLO engine
(:mod:`~cxxnet_tpu.obs.slo` — the tracker was its first consumer): a
``window=0`` per-sample spec named ``freshness`` whose error factory
builds the typed
:class:`~cxxnet_tpu.runtime.faults.FreshnessSLOError` and whose breach
records keep the historical ``freshness_slo_breach`` failure-log kind.
Every sample above ``slo_s`` increments the breach counter and is
surfaced on the eval line — breaching the SLO degrades *observability
state*, never availability (the stale model keeps serving; strict
callers raise the typed error at run boundaries via
:meth:`check_strict`).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..obs import format_report
from ..obs.slo import SLOEngine, SLOSpec
from ..runtime import faults
from ..utils.metric import StatSet


class FreshnessTracker:
    """Thread-safe step→swap→first-serve lag tracker (module docstring).

    All three probes run on different threads (step loop, registry
    watcher, batcher worker); times are ``time.monotonic()``.
    """

    #: newest checkpoint versions retained in the step/swap stamp maps —
    #: a 24/7 run publishes forever, and only the recent tail can still
    #: close a measurement (StatSet already bounds the sample lists)
    MAX_VERSIONS = 1024

    def __init__(self, slo_s: float = 0.0,
                 log: Optional[faults.FailureLog] = None):
        self.slo_s = float(slo_s)
        self.log = faults.global_failure_log() if log is None else log
        self._lock = threading.Lock()
        self._step_t: Dict[int, float] = {}
        self._swap_t: Dict[int, float] = {}
        self._served = set()          # versions whose first serve is in
        self.stats = StatSet()
        self.swaps = 0
        # breach judgment is the generic engine's (obs/slo.py): one
        # per-sample (window=0) spec, typed-error factory, historical
        # log kind — the tracker only measures
        self.slo = SLOEngine(log=self.log)
        if self.slo_s > 0:
            self.slo.add(
                SLOSpec(name='freshness', key='online.freshness_s',
                        op='<=', threshold=self.slo_s, window=0.0,
                        kind='freshness_slo_breach'),
                err_factory=lambda spec, value, n, ctx:
                    faults.FreshnessSLOError(ctx.get('step', -1), value,
                                             self.slo_s, n))

    @property
    def breaches(self) -> int:
        return self.slo.breaches('freshness')

    @property
    def last_breach(self) -> Optional[faults.FreshnessSLOError]:
        return self.slo.last_breach

    def _prune_locked(self) -> None:
        """Bound the per-version maps to the newest MAX_VERSIONS steps
        (steps are monotone, so oldest = smallest key).  Caller holds
        the lock."""
        for d in (self._step_t, self._swap_t):
            while len(d) > self.MAX_VERSIONS:
                d.pop(min(d))
        if len(self._served) > self.MAX_VERSIONS:
            keep = set(self._swap_t)
            self._served &= keep

    # -- probes ------------------------------------------------------------
    def record_step(self, step: int, t: Optional[float] = None) -> None:
        with self._lock:
            self._step_t[int(step)] = time.monotonic() if t is None else t
            self._prune_locked()

    def record_swap(self, step: int, t: Optional[float] = None) -> None:
        now = time.monotonic() if t is None else t
        with self._lock:
            step = int(step)
            self._swap_t[step] = now
            self.swaps += 1
            t0 = self._step_t.get(step)
            self._prune_locked()
        if t0 is not None:
            # trainer-side half: optimizer step -> live swap
            self.stats.observe('swap_lag_s', now - t0)

    def note_served(self, version) -> Optional[float]:
        """Engine ``on_serve`` probe: close the freshness measurement on
        the FIRST request served per swapped version.  Returns the
        freshness sample when one was recorded (None otherwise).  The
        bootstrap version (served from process start, never swapped) is
        not a freshness sample — the SLO is a property of *swaps*."""
        try:
            version = int(version)
        except (TypeError, ValueError):
            return None
        now = time.monotonic()
        with self._lock:
            if version in self._served or version not in self._swap_t:
                return None
            self._served.add(version)
            t0 = self._step_t.get(version)
        if t0 is None:
            return None
        fresh = now - t0
        self.stats.observe('freshness_s', fresh)
        if self.slo_s > 0:
            # the generic engine judges the sample: a violation counts
            # the breach, builds the typed FreshnessSLOError, and logs
            # the historical kind — same observable behavior as the
            # deleted bespoke path, one engine for every SLO
            self.slo.observe('freshness', fresh, step=version)
        return fresh

    # -- reporting ---------------------------------------------------------
    def unserved_swaps(self) -> int:
        """Swapped versions no request has touched yet — non-zero means
        traffic is slower than the swap cadence (freshness unmeasurable,
        not necessarily breached)."""
        with self._lock:
            return len(self._swap_t) - len(self._served
                                           & set(self._swap_t))

    def report(self, stats: Optional[StatSet] = None,
               name: str = 'online') -> str:
        """Eval-line-format freshness summary; with ``stats`` given the
        gauges merge into a shared set instead."""
        own = stats is None
        stats = self.stats if own else stats
        with self._lock:
            stats.gauge('swaps', self.swaps)
            stats.gauge('slo_breaches', self.breaches)
        stats.gauge('unserved_swaps', self.unserved_swaps())
        if not own:
            # copy the distributions over so p50/p99 print with the rest
            for q, tag in ((0.5, 'p50'), (0.99, 'p99')):
                for key in ('freshness_s', 'swap_lag_s'):
                    v = self.stats.quantile(key, q)
                    if v == v:                      # has samples
                        stats.gauge(f'{key}.{tag}', v)
        return format_report(name, stats)

    def check_strict(self) -> None:
        """Raise the last typed breach (strict mode, run boundaries)."""
        self.slo.check_strict()
