"""OnlinePipeline: one process that trains, saves, swaps, and serves.

The subsystem the repo's pieces were built for (ROADMAP item 5): a
long-lived supervised trainer ingests a (streaming) batch source through
the ordinary iterator chain, async-saves a serving checkpoint every
``save_every`` optimizer steps, and a colocated serving stack —
``PredictEngine`` + ``DynamicBatcher`` + ``ModelRegistry`` — watches the
same ``model_dir`` and hot-swaps each checkpoint under live traffic.
``FreshnessTracker`` stamps every swap with its step→serving lag and
checks it against the ``freshness_slo``.

Composition is the point; the invariants all come from parts that
already hold them individually:

* the trainer side is a real :class:`~cxxnet_tpu.runtime.supervisor.
  TrainSupervisor` run — watchdog, divergence breaker, restore-last-good
  bitwise recovery, async exact-state sidecars — with the serving
  checkpoint riding the supervisor's ``on_save`` hook, so the NaN gate
  that protects recovery ALSO guarantees a poisoned model file is never
  even written,
* the serving side never trusts the trainer: every checkpoint passes
  digest verification before it can swap, a corrupt one is rejected and
  blacklisted while the previous version keeps serving, and in-flight
  requests finish on the params they started with (zero drops across
  swaps),
* a model-file write failure degrades *freshness*, never training or
  availability: the background writer's deferred error is recorded
  (``async_save_failed``) and counted, the step loop continues, and the
  server keeps the last good version.

Chaos-drill the whole loop with a recurring ``FaultPlan``
(``doc/online.md`` has the recipe); ``tests/test_online.py`` proves the
served version never regresses and the trainer ends bitwise-equal to a
fault-free twin.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..nnet import checkpoint as model_io
from ..nnet.execution import ExecutionPlan
from ..obs import get_hub, span
from ..runtime import faults
from ..runtime.async_ckpt import AsyncCheckpointer, host_tree, snapshot_tree
from ..runtime.supervisor import SupervisorConfig, TrainSupervisor
from ..serve import DynamicBatcher, ModelRegistry, PredictEngine
from ..serve.registry import load_into_trainer
from ..utils.metric import StatSet
from .freshness import FreshnessTracker

__all__ = ['OnlineConfig', 'OnlinePipeline']


@dataclass
class OnlineConfig:
    """Knobs for one train-while-serve run (``online.*`` config keys in
    main.py; doc/online.md documents each)."""

    model_dir: str = 'models'
    save_every: int = 8            # steps between serving checkpoints
    save_workers: int = 2
    freshness_slo: float = 0.0     # seconds, 0 = measure but never breach
    freshness_strict: bool = False  # raise FreshnessSLOError at run end
    reload_poll: float = 0.05      # registry watch period (s)
    buckets: Tuple[int, ...] = (1, 8, 32)
    max_queue: int = 64
    max_wait: float = 0.002
    deadline: float = 1.0
    dtype: str = 'f32'             # serve.dtype quantized-inference tier
    qps: float = 50.0              # built-in traffic driver rate
    # supervisor knobs (same semantics as train.* keys)
    watchdog_deadline: Optional[float] = 60.0
    max_restarts: int = 3
    nan_breaker: int = 3
    keep_last: int = 4
    save_async: int = 1
    steps_per_dispatch: int = 1
    net_type: int = 0
    silent: bool = False
    retry: faults.RetryPolicy = field(
        default_factory=lambda: faults.DEFAULT_IO_RETRY)


class OnlinePipeline:
    """Run trainer + server as one orchestrated process (module
    docstring).

    ``trainer`` is an initialized :class:`NetTrainer`; ``train_iter`` is
    any replay-stable iterator chain (idiomatically ``iter =
    imgbin_stream``); ``serve_factory`` builds the colocated serving
    twin — a zero-arg callable returning an UNINITIALIZED
    inference-only ``NetTrainer`` of the same architecture (the pipeline
    loads the bootstrap checkpoint into it, so trainer and server never
    share device buffers).  ``request_source`` (optional) feeds the
    built-in traffic driver: a zero-arg callable returning one request's
    float32 rows; external embedders skip it and call :meth:`submit`
    themselves.
    """

    def __init__(self, trainer, train_iter, serve_factory: Callable,
                 cfg: OnlineConfig,
                 request_source: Optional[Callable[[], np.ndarray]] = None,
                 failure_log: Optional[faults.FailureLog] = None):
        from ..io.data import ThreadBufferIterator
        self.trainer = trainer
        self.cfg = cfg
        self.serve_factory = serve_factory
        self.request_source = request_source
        self.log = (faults.global_failure_log() if failure_log is None
                    else failure_log)
        # the supervisor brings its own watchdog buffer: unwrap a
        # conf-level threadbuffer stage (same reasoning as main.py's
        # _make_supervisor — one producer, one fault-index base)
        self._it = train_iter
        if isinstance(self._it, ThreadBufferIterator):
            self._it = self._it.base
        if self._it is not None and not self._it.is_replay_stable():
            msg = ('online train iterator reshuffles per pass: recovery '
                   'restores exact params but the replayed pass is a new '
                   'permutation — the chaos bitwise contract needs a '
                   'replay-stable source (imgbin_stream is)')
            self.log.record('replay_unstable', msg)
            if not cfg.silent:
                print(f'OnlinePipeline: {msg}', flush=True)
        self.tracker = FreshnessTracker(slo_s=cfg.freshness_slo,
                                        log=self.log)
        self.engine: Optional[PredictEngine] = None
        self.batcher: Optional[DynamicBatcher] = None
        self.registry: Optional[ModelRegistry] = None
        self.supervisor: Optional[TrainSupervisor] = None
        self._plan: Optional[ExecutionPlan] = None
        self._ckpt = AsyncCheckpointer(workers=cfg.save_workers,
                                       failure_log=self.log)
        self._last_counter: Optional[int] = None
        self._served_lock = threading.Lock()   # traffic + client threads
        self._served = 0           # guarded-by: _served_lock
        self._client_errors = 0    # guarded-by: _served_lock
        self._traffic_stop = threading.Event()
        self._traffic_thread: Optional[threading.Thread] = None
        self._qps = float(cfg.qps)        # guarded-by: _served_lock
        self._train_throttle = 0.0        # guarded-by: _served_lock
        self._started = False
        self._closed = False

    # -- checkpoint publishing (trainer -> model_dir) -----------------------
    def _model_path(self, counter: int) -> str:
        return os.path.join(self.cfg.model_dir, f'{counter:04d}.model')

    def _model_header(self) -> bytes:
        return (int(self.cfg.net_type).to_bytes(4, 'little', signed=True)
                + self.trainer.model_header())

    def _publish_model(self, counter: int, sync: bool = False) -> str:
        """Publish the trainer's CURRENT params as ``%04d.model`` +
        digest sidecar — snapshot now (donation-safe device copy),
        serialize + atomic write + digest on the background writer.
        The freshness clock for ``counter`` starts here: this moment IS
        (modulo a window boundary) the optimizer step that produced the
        params."""
        from ..nnet.trainer import NetTrainer
        tr = self.trainer
        path = self._model_path(counter)
        self.tracker.record_step(counter)
        def job():
            blob = model_io.serialize_blob(net, host_tree(psnap))
            # digest-before-rename publish: the watching registry can
            # never observe this file without its sidecar (and the
            # corrupt_model chaos event is deterministically caught)
            model_io.publish_model_file(
                path,
                lambda f: NetTrainer.write_model_bytes(f, header, blob),
                retry=self.cfg.retry)
            return path

        # the span brackets what the STEP LOOP pays: the snapshot plus
        # either the whole write (sync) or the background hand-off
        with span('online.publish', 'online', step=counter,
                  sync=bool(sync or not self.cfg.save_async)):
            header = self._model_header()
            net = tr.net
            psnap = snapshot_tree(tr.params)
            if sync or not self.cfg.save_async:
                job()
            else:
                # drain (not wait): a failed PREVIOUS model save is
                # already in the failure log as async_save_failed —
                # online, a lost serving checkpoint degrades freshness,
                # never training
                self._ckpt.drain()
                self._ckpt.submit(job, step=counter,
                                  label=f'publish_model:{counter:04d}')
        return path

    def _on_train_save(self, step: int) -> None:
        """Supervisor ``on_save`` listener: every accepted exact-state
        save (NaN gate already passed) also publishes the serving
        checkpoint — one cadence, one validity gate.  Deduped per step:
        each round's anchor save re-lands on the previous final step."""
        if step == self._last_counter:
            return
        self._last_counter = step
        self._publish_model(step)

    # -- serving side -------------------------------------------------------
    def start(self) -> None:
        """Bootstrap the colocated server: publish the trainer's current
        params synchronously, load them into the serving twin, warm every
        bucket program, and start batcher + registry watch + (when a
        ``request_source`` was given) the traffic driver."""
        if self._started:
            return
        cfg = self.cfg
        os.makedirs(cfg.model_dir, exist_ok=True)
        counter = int(self.trainer.sample_counter)
        self._last_counter = counter
        boot = self._publish_model(counter, sync=True)
        serve_tr = load_into_trainer(self.serve_factory(), boot,
                                     retry=cfg.retry)
        self.engine = PredictEngine(serve_tr, cfg.buckets,
                                    dtype=cfg.dtype)
        self.engine.version = counter
        self.engine.on_serve = self.tracker.note_served
        self.engine.warm()
        self.batcher = DynamicBatcher(self.engine, max_queue=cfg.max_queue,
                                      max_wait=cfg.max_wait,
                                      deadline=cfg.deadline)
        self.registry = ModelRegistry(
            self.engine, cfg.model_dir, poll_interval=cfg.reload_poll,
            current=counter, retry=cfg.retry, log=self.log,
            on_swap=self._on_swap)
        self.registry.start()
        # register the live stat sets + status views into the telemetry
        # hub: /metrics serves the batcher/freshness/registry gauges and
        # /statusz the registry state machine while the process runs
        hub = get_hub()
        self.batcher.register_into(hub)
        hub.register_stats('online', self.tracker.stats,
                           refresh=self._refresh_online_gauges)
        self.registry.register_into(hub)
        hub.register_status('online', self.summary)
        # the freshness SLO engine joins the hub roster: its verdict
        # rides /slos + /metrics, and a breached freshness flips
        # /healthz to degraded (the stale model keeps serving — the
        # endpoint stays 200/alive)
        self.tracker.slo.register_into(hub, name='online_slo')
        if self.request_source is not None:
            self._traffic_stop.clear()
            self._traffic_thread = threading.Thread(
                target=self._traffic, daemon=True, name='online-traffic')
            self._traffic_thread.start()
        self._started = True
        if not cfg.silent:
            print(f'online: serving from step {counter} '
                  f'({len(self.engine.buckets)} bucket programs warm), '
                  f'watching {cfg.model_dir} every {cfg.reload_poll:g}s',
                  flush=True)

    def _on_swap(self, counter: int, path: str) -> None:
        self.tracker.record_swap(counter)
        if not self.cfg.silent:
            print(f'online: hot-swapped step {counter} into the live '
                  f'engine ({path})', flush=True)

    def submit(self, rows: np.ndarray,
               deadline: Optional[float] = None) -> np.ndarray:
        """One request through the live stack (typed serving errors
        propagate).  The first request to land on a freshly swapped
        version closes its freshness measurement."""
        if self.batcher is None:
            raise RuntimeError('OnlinePipeline.start() first')
        out = self.batcher.submit(np.asarray(rows, np.float32), deadline)
        with self._served_lock:
            self._served += len(rows)
        return out

    def _traffic(self) -> None:
        """Built-in constant-rate traffic driver (``qps`` requests/sec)
        over ``request_source`` rows — the CLI/bench stand-in for a
        fronting server.  Client-visible errors are counted, never
        raised: the drill's zero-drop assertion reads the counter."""
        while True:
            # re-read the rate every tick: the autoscaler retunes it
            # live through set_qps (the train/serve split surface)
            with self._served_lock:
                period = 1.0 / max(self._qps, 1e-6)
            if self._traffic_stop.wait(period):
                return
            try:
                self.submit(self.request_source())
            except faults.ServeError:
                with self._served_lock:
                    self._client_errors += 1
            except RuntimeError:
                return                       # batcher closed under us

    def set_qps(self, qps: float) -> float:
        """Live-retune the built-in traffic driver's rate (autoscaler /
        operator surface); returns the previous rate.  Takes effect on
        the next tick — no thread restart, no request dropped."""
        qps = float(qps)
        if qps <= 0:
            raise ValueError(f'qps must be > 0, got {qps}')
        with self._served_lock:
            prev, self._qps = self._qps, qps
        return prev

    def set_train_throttle(self, seconds: float) -> float:
        """Per-step training slowdown in seconds (0 = full speed) — the
        autoscaler's train/serve split knob: under serving pressure the
        train half yields device time; on sustained OK it is released.
        Bounded (capped at 1s), reversible, takes effect on the next
        step via the ``before_step`` hook.  Returns the previous value."""
        seconds = min(1.0, max(0.0, float(seconds)))
        with self._served_lock:
            prev, self._train_throttle = self._train_throttle, seconds
        return prev

    def train_throttle(self) -> float:
        with self._served_lock:
            return self._train_throttle

    # -- the training loop --------------------------------------------------
    def _make_supervisor(self) -> TrainSupervisor:
        cfg = self.cfg
        sup_cfg = SupervisorConfig(
            batch_deadline=cfg.watchdog_deadline,
            max_restarts=cfg.max_restarts,
            nan_breaker=cfg.nan_breaker,
            save_every=cfg.save_every,
            keep_last=cfg.keep_last,
            save_async=cfg.save_async,
            save_workers=cfg.save_workers,
            retry=cfg.retry,
            on_save=self._on_train_save,
            pipeline_stats=(None if self._it is None
                            else self._it.pipeline_stats()))
        return TrainSupervisor(
            self.trainer,
            os.path.join(cfg.model_dir, 'supervised_state'), sup_cfg,
            failure_log=self.log)

    def run(self, num_rounds: int = 1,
            evals: Sequence[Tuple[object, str]] = (),
            start_round: int = 1,
            before_step: Optional[Callable[[int], None]] = None,
            out=None) -> dict:
        """The long-lived loop: ``num_rounds`` supervised passes over the
        (streaming) train iterator, serving the whole time.  Each round
        ends with the reference eval line on ``out`` (default stderr)
        extended with the freshness/swap gauges (:meth:`eval_line`).
        Returns :meth:`summary`; in ``freshness_strict`` mode a breached
        SLO raises the typed ``FreshnessSLOError`` AFTER the final round
        (training and serving finish first — the SLO is an alarm, not a
        kill switch)."""
        import itertools
        out = sys.stderr if out is None else out
        self.start()
        sup = self.supervisor = self._make_supervisor()
        self._plan = ExecutionPlan.resolve(
            requested_k=self.cfg.steps_per_dispatch,
            silent=self.cfg.silent)
        it = self._it
        tr = self.trainer

        def factory(k):
            return itertools.islice(iter(it), k, None)

        def throttled(step: int) -> None:
            # the autoscaler's train/serve split: yield device time to
            # serving under pressure (sleep OFF any lock, between
            # dispatches — training math is unchanged, only its pace)
            t = self.train_throttle()
            if t > 0:
                time.sleep(t)
            if before_step is not None:
                before_step(step)

        try:
            for r in range(start_round, start_round + int(num_rounds)):
                tr.start_round(r)
                sup.run(factory, before_step=throttled,
                        make_stepper=lambda: self._plan.round_stepper(
                            tr, lookahead=0))
                tr.flush_divergence_check()
                line = f'[{r}]'
                if not evals:
                    line += tr.evaluate(None, 'train')
                for ev_it, name in evals:
                    line += tr.evaluate(ev_it, name)
                line += self.eval_line()
                out.write(line + '\n')
                out.flush()
        finally:
            sup.close()
            self._ckpt.drain()
        if self.cfg.freshness_strict:
            self.tracker.check_strict()
        return self.summary()

    # -- observability ------------------------------------------------------
    def _refresh_online_gauges(self) -> None:
        """Pull-style gauges for /metrics renders (the eval line gets
        the same values through :meth:`eval_line`)."""
        self.tracker.report()      # gauges swaps/breaches/unserved_swaps
        with self._served_lock:
            self.tracker.stats.gauge('served', self._served)
        self.tracker.stats.gauge('dropped', self.dropped())

    def dropped(self) -> int:
        """Requests that got an error instead of scores — the zero-drop
        acceptance counter (batcher sheds + engine faults + client
        abandonment + client-side typed errors from the built-in
        driver)."""
        if self.batcher is None:
            with self._served_lock:
                return self._client_errors
        s = self.batcher.stats
        return int(s.get('expired') + s.get('rejected')
                   + s.get('engine_errors') + s.get('abandoned'))

    def eval_line(self, name: str = 'online') -> str:
        """Freshness + swap gauges in eval-line format — what rides the
        round eval line (doc/online.md explains each key)."""
        stats = StatSet()
        with self._served_lock:
            stats.gauge('served', self._served)
        stats.gauge('dropped', self.dropped())
        if self.registry is not None:
            stats.gauge('last_swap_step', self.registry.last_swap_step)
            age = self.registry.last_swap_age_s()
            if age == age:
                stats.gauge('last_swap_age_s', age)
        return self.tracker.report(stats, name)

    def serve_report(self) -> str:
        """Full serving-side stats: batcher per-bucket latency ledger +
        registry swap stamps (both eval-line format)."""
        parts = []
        if self.batcher is not None:
            parts.append(self.batcher.report('serve'))
        if self.registry is not None:
            parts.append(self.registry.report(name='registry'))
        return ''.join(parts)

    def summary(self) -> dict:
        """One strictly-JSON-able dict for receipts and tests (unmeasured
        quantiles are None/null, never NaN — the summary line is an
        advertised machine-readable surface)."""
        t = self.tracker

        def q(name, p):
            v = t.stats.quantile(name, p)
            return None if v != v else v

        with self._served_lock:
            served = int(self._served)
        return {
            'steps': int(self.trainer.sample_counter),
            'swaps': int(t.swaps),
            'served': served,
            'dropped': int(self.dropped()),
            'slo_breaches': int(t.breaches),
            'freshness_p50_s': q('freshness_s', 0.5),
            'freshness_p99_s': q('freshness_s', 0.99),
            'swap_lag_p50_s': q('swap_lag_s', 0.5),
            'last_swap_step': (-1 if self.registry is None
                               else int(self.registry.last_swap_step)),
            'save_failures': len(self.log.records('async_save_failed')),
            'restarts': (0 if self.supervisor is None
                         else int(self.supervisor.restarts_total)),
        }

    # -- lifecycle ----------------------------------------------------------
    def close(self, timeout: Optional[float] = None) -> None:
        """Tear the whole loop down (idempotent): traffic, registry
        watch, batcher (drains queued requests), background writers."""
        if self._closed:
            return
        self._closed = True
        hub = get_hub()
        for name in ('serve', 'online', 'registry'):
            hub.unregister_stats(name)
        for name in ('online', 'registry'):
            hub.unregister_status(name)
        self.tracker.slo.close()
        self._traffic_stop.set()
        t = self._traffic_thread
        if t is not None:
            t.join(timeout)
        if self.registry is not None:
            self.registry.close(timeout=timeout)
        if self.batcher is not None:
            self.batcher.close(timeout=timeout)
        if self.supervisor is not None:
            self.supervisor.close()
        self._ckpt.close()
