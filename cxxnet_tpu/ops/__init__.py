"""Device compute ops: Pallas TPU kernels + XLA lowerings."""

from .pallas_kernels import (lrn_auto_mode, lrn_hybrid,
                             lrn_pallas, pallas_enabled,
                             pallas_matmul, pallas_mode)
