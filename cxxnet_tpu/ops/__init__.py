"""Device compute ops: Pallas TPU kernels + XLA lowerings."""

from .pallas_kernels import (decode_use_flash, lrn_auto_mode, lrn_hybrid,
                             lrn_pallas, paged_flash_decode,
                             pallas_enabled, pallas_int8_matmul,
                             pallas_matmul, pallas_mode)
