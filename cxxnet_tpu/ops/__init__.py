"""Device compute ops: Pallas TPU kernels + XLA lowerings."""

from .pallas_kernels import (lrn_fwd_profitable, lrn_hybrid,
                             lrn_pallas, pallas_enabled,
                             pallas_matmul, pallas_mode)
