"""Device compute ops: Pallas TPU kernels + XLA lowerings."""

from .pallas_kernels import lrn_pallas, pallas_enabled, pallas_matmul
