"""Fused Pallas CNN blocks + μ-cuDNN convolution microbatching.

Two training/CNN-tier primitives beyond ``pallas_kernels``:

* **fused conv+bias+activation** (``fused_conv_bias_act``): one Pallas
  forward block computes the convolution as an im2col GEMM *in VMEM*
  (the column tensor never touches HBM — the maxDNN/cuDNN fusion the
  reference hand-wrote in CUDA), adds the bias, and applies the
  activation (relu or identity) before the single HBM write-back.  The
  grid walks batch x output-row tiles; each step holds one padded input
  image and builds its patch matrix with static strided slices over the
  kernel taps, so the MXU contracts ``kh*kw*cin`` deep per pass.  The
  backward is a ``jax.custom_vjp`` that reuses the saved pre-activation
  tensor for the relu mask and hands dx/dw to XLA's conv transpose —
  the measured-loser Pallas backwards stay off the trainer path (the
  ``fullc`` lesson, receipts/micro_matmul.json).  The block is pinned to
  the XLA reference composition by tolerance twins (``_FUSED_RTOL`` /
  ``_FUSED_ATOL``, tests/test_cnn_fused.py): the in-VMEM GEMM reduces in
  a different order than XLA's conv, so the contract is pinned-tolerance,
  never silently looser (the PR 10 quant rule).

* **convolution microbatching** (``microbatched_conv``): μ-cuDNN's
  observation, recast for XLA — splitting a convolution's *batch* axis
  into ``micro_batch`` sequential slices bounds the layer's live
  workspace (im2col patch tensors, wide activation intermediates) at the
  cost of dispatching k smaller convs.  The forward and dx run per-slice
  under ``lax.map``; **dw is computed by the one full-batch transpose
  op**, because a slice-accumulated dw sums in a different order and is
  NOT bitwise-equal to the unsplit step (measured — see
  doc/kernels.md).  Under jit the unused full-batch primal is DCE'd, so
  the anchor costs one conv-transpose, exactly like the unsplit step.
  This makes the microbatched step a **bitwise twin** of the unsplit
  one at every declared split — the property grafttune's LedgerGate
  relies on when it prices ``micro_batch`` from ``memory_analysis``
  peak bytes (tune/space.py, ``mem_inv``).

Both paths run under ``interpret=True`` on CPU — correctness validation
without hardware; speed claims come only from on-TPU receipts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .pallas_kernels import (_block_spec, _compiler_params, _interpret,
                             pallas_mode, pltpu)

_DN = ('NHWC', 'HWIO', 'NHWC')

#: pinned fused-vs-XLA twin tolerances (f32): the VMEM im2col GEMM and
#: XLA's native conv reduce in different orders, so equality is pinned
#: here, once, and asserted everywhere (tests AND bench) — never loosened
#: at a call site.
_FUSED_RTOL = 1e-5
_FUSED_ATOL = 1e-5

#: rows-per-grid-step target for the output tile: ~512 output pixels per
#: MXU pass (same scale as pallas_kernels._ROW_TILE)
_TILE_PIXELS = 512


def conv_use_fused(explicit=None, *, spmd_devices: int = 1) -> bool:
    """Whether eligible conv(+bias)+relu pairs take the fused Pallas
    block.  ``explicit`` is the ``fuse=`` net param: ``1``/``0`` force it
    on/off (``1`` engages even in interpret mode — that is the CPU
    validation path), anything else (``'auto'``/None) defers to the
    tri-state ``pallas_mode()`` gate.  ``auto`` engages only on a real
    single-device TPU: under GSPMD a ``pallas_call`` is an opaque custom
    call with no sharding rule (same scoping as ``lrn_auto_mode``), and
    in interpret mode the kernel is a correctness tool, not a win."""
    if explicit is not None:
        text = str(explicit).strip().lower()
        if text in ('1', 'true', 'yes', 'on'):
            return True
        if text in ('0', 'false', 'no', 'off'):
            return False
        # anything else ('auto', '') falls through to the global gate
    mode = pallas_mode()
    if mode == 'on':
        return True
    if mode == 'off':
        return False
    return not _interpret() and pltpu is not None and spmd_devices == 1


def _conv_ref(x, w, strides, pad, groups=1):
    """The XLA reference lowering the fused block's backward (and its
    twin tests) anchor to.  Deliberately a local duplicate of
    ``layers.conv.conv_native`` — ops/ cannot import layers/ (the conv
    layer imports this module), and the 4 lines ARE the contract."""
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad,
        dimension_numbers=_DN, feature_group_count=groups)


# --- fused conv + bias + activation ---------------------------------------

def _conv_act_kernel(x_ref, w_ref, b_ref, y_ref, z_ref, *, kh, kw, sy, sx,
                     tile_oy, ox, groups, act):
    """One (batch image, output-row tile) grid step.

    ``x_ref`` holds the whole zero-padded image (1, Hp, Wp, cin); the
    step slices its input row window, builds the im2col patch matrix
    with static strided slices over the kernel taps (column order
    (u, v, c) — exactly ``w.reshape(kh*kw*cin_g, cout)`` row order), and
    contracts on the MXU in f32.  Grouped convs loop the (static) groups
    with static channel slices.  The pre-activation ``z`` is written as
    a second output: the custom-VJP backward reuses it as the relu mask
    instead of re-deriving it.
    """
    j = pl.program_id(1)
    x = x_ref[0]                                        # (Hp, Wp, cin)
    cin = x.shape[-1]
    iy = (tile_oy - 1) * sy + kh
    xwin = lax.dynamic_slice(
        x, (j * tile_oy * sy, 0, 0), (iy, x.shape[1], cin))
    w2 = w_ref[...]                                 # (kh*kw*cin_g, cout)
    cout = w2.shape[1]
    cin_g = cin // groups
    cout_g = cout // groups
    outs = []
    for gi in range(groups):
        xg = lax.slice_in_dim(xwin, gi * cin_g, (gi + 1) * cin_g, axis=2)
        cols = []
        for u in range(kh):
            for v in range(kw):
                tap = lax.slice(
                    xg, (u, v, 0),
                    (u + (tile_oy - 1) * sy + 1,
                     v + (ox - 1) * sx + 1, cin_g),
                    (sy, sx, 1))                     # (tile_oy, ox, cin_g)
                cols.append(tap.reshape(tile_oy * ox, cin_g))
        patches = jnp.concatenate(cols, axis=1)
        wg = lax.slice_in_dim(w2, gi * cout_g, (gi + 1) * cout_g, axis=1)
        outs.append(jnp.dot(patches, wg,
                            preferred_element_type=jnp.float32))
    z = outs[0] if groups == 1 else jnp.concatenate(outs, axis=1)
    z = z + b_ref[...]                               # (1, cout) broadcast
    y = jnp.maximum(z, 0.0) if act == 'relu' else z
    z_ref[...] = z.reshape(1, tile_oy, ox, cout)
    y_ref[...] = y.reshape(1, tile_oy, ox, cout).astype(y_ref.dtype)


def _fused_call(x, w, b, strides, padding, groups, act):
    """Launch the fused block; returns (activated out, f32 pre-act)."""
    if act not in ('relu', 'identity'):
        raise ValueError(f'fused conv: unknown act {act!r}')
    n, h, win, cin = x.shape
    kh, kw, cin_g, cout = w.shape
    sy, sx = strides
    (py_lo, py_hi), (px_lo, px_hi) = padding
    oy = (h + py_lo + py_hi - kh) // sy + 1
    ox = (win + px_lo + px_hi - kw) // sx + 1
    if oy <= 0 or ox <= 0:
        raise ValueError('fused conv: kernel larger than padded input')
    tile_oy = max(1, min(oy, -(-_TILE_PIXELS // max(1, ox))))
    oy_p = -(-oy // tile_oy) * tile_oy
    # rows padded so every tile's input window is in bounds (the extra
    # zero rows produce garbage output rows sliced off below); the width
    # pad is the conv pad alone — the kernel's static slices never read
    # past (ox-1)*sx + kw
    hp_need = (oy_p - 1) * sy + kh
    extra = max(0, hp_need - (h + py_lo + py_hi))
    xp = jnp.pad(x, ((0, 0), (py_lo, py_hi + extra),
                     (px_lo, px_hi), (0, 0)))
    w2 = w.reshape(kh * kw * cin_g, cout).astype(jnp.float32)
    bvec = (jnp.zeros((cout,), jnp.float32) if b is None
            else b.astype(jnp.float32)).reshape(1, cout)
    xp32 = xp.astype(jnp.float32)
    hp, wp = xp32.shape[1], xp32.shape[2]
    kernel = functools.partial(_conv_act_kernel, kh=kh, kw=kw, sy=sy,
                               sx=sx, tile_oy=tile_oy, ox=ox,
                               groups=groups, act=act)
    y, z = pl.pallas_call(
        kernel,
        grid=(n, oy_p // tile_oy),
        in_specs=[
            _block_spec((1, hp, wp, cin), lambda i, j: (i, 0, 0, 0)),
            _block_spec((kh * kw * cin_g, cout), lambda i, j: (0, 0)),
            _block_spec((1, cout), lambda i, j: (0, 0)),
        ],
        out_specs=[
            _block_spec((1, tile_oy, ox, cout), lambda i, j: (i, j, 0, 0)),
            _block_spec((1, tile_oy, ox, cout), lambda i, j: (i, j, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, oy_p, ox, cout), x.dtype),
            jax.ShapeDtypeStruct((n, oy_p, ox, cout), jnp.float32),
        ],
        interpret=_interpret(),
        **_compiler_params('parallel', 'parallel'),
    )(xp32, w2, bvec)
    return y[:, :oy], z[:, :oy]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_conv_bias_act(x, w, b, strides, padding, groups=1, act='relu'):
    """Fused conv + bias + activation, differentiable.

    ``b`` may be None (no-bias conv; the kernel adds a zero vector,
    which is bitwise-identity in f32, and the backward returns a None
    cotangent).  The forward is the Pallas block; the backward masks the
    upstream cotangent with the SAVED pre-activation (no recompute) and
    takes XLA's conv transposes for dx/dw.
    """
    y, _ = _fused_call(x, w, b, strides, padding, groups, act)
    return y


def _fused_fwd(x, w, b, strides, padding, groups, act):
    y, z = _fused_call(x, w, b, strides, padding, groups, act)
    return y, (x, w, b, z)


def _fused_bwd(strides, padding, groups, act, res, ct):
    x, w, b, z = res
    g = ct.astype(jnp.float32)
    if act == 'relu':
        # the saved pre-activation IS the mask — no recompute.  The
        # reference relu is jnp.maximum(x, 0), whose XLA gradient at an
        # EXACT z==0 tie is 0.5 (lax.max splits equal operands), so the
        # mask mirrors that: ties are measure-zero for continuous
        # inputs, but zero-padded integer images with zero-init bias tie
        # densely at step 0 and the twin must hold there too
        g = jnp.where(z > 0, g, jnp.where(z == 0, 0.5 * g, 0.0))
    gx = g.astype(x.dtype)
    _, vjp = jax.vjp(
        lambda xx, ww: _conv_ref(xx, ww, strides, padding, groups), x, w)
    dx, dw = vjp(gx)
    db = None if b is None else jnp.sum(gx, axis=(0, 1, 2)).astype(b.dtype)
    return dx, dw, db


fused_conv_bias_act.defvjp(_fused_fwd, _fused_bwd)


# --- μ-cuDNN-style convolution microbatching ------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def microbatched_conv(x, w, strides, padding, groups, split, conv_fn):
    """Run ``conv_fn`` over ``split`` sequential batch slices.

    ``conv_fn(x, w, strides, padding, groups)`` is a module-level
    callable (hashable, so the trace caches); the batch must divide
    evenly — callers gate on ``batch % split == 0`` and fall through to
    the unsplit op otherwise.  Bitwise contract: forward and dx are
    per-example-independent, so the slice loop reproduces the unsplit
    values exactly; dw is the one full-batch transpose op (see module
    docstring) — the whole step is a bitwise twin of ``split=1``.
    """
    return _mb_fwd_impl(x, w, strides, padding, groups, split, conv_fn)


def _mb_fwd_impl(x, w, strides, padding, groups, split, conv_fn):
    n = x.shape[0]
    xs = x.reshape((split, n // split) + x.shape[1:])
    ys = lax.map(lambda xt: conv_fn(xt, w, strides, padding, groups), xs)
    return ys.reshape((n,) + ys.shape[2:])


def _mb_fwd(x, w, strides, padding, groups, split, conv_fn):
    y = _mb_fwd_impl(x, w, strides, padding, groups, split, conv_fn)
    return y, (x, w)


def _mb_bwd(strides, padding, groups, split, conv_fn, res, g):
    x, w = res
    n = x.shape[0]
    xs = x.reshape((split, n // split) + x.shape[1:])
    gs = g.reshape((split, n // split) + g.shape[1:])

    def _slice_dx(pair):
        xt, gt = pair
        _, vjp = jax.vjp(
            lambda xx: conv_fn(xx, w, strides, padding, groups), xt)
        return vjp(gt)[0]

    dx = lax.map(_slice_dx, (xs, gs)).reshape(x.shape)
    # dw anchors on the ONE full-batch transpose op: a slice-accumulated
    # dw reduces in a different order and is NOT bitwise-equal to the
    # unsplit step (measured; doc/kernels.md).  Under jit the unused
    # primal recompute is DCE'd away.
    _, vjp_w = jax.vjp(
        lambda ww: conv_fn(x, ww, strides, padding, groups), w)
    dw = vjp_w(g)[0]
    return dx, dw


microbatched_conv.defvjp(_mb_fwd, _mb_bwd)
