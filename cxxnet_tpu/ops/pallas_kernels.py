"""Pallas TPU kernels for hot ops.

Per the north-star mapping (BASELINE.json), the reference's hand-written
CUDA/mshadow hot paths become TPU kernels.  Design notes:

* **conv / pooling** stay on XLA's native convolution/reduce-window — on
  TPU those already lower to MXU-optimal programs (the cuDNN analogy);
  a hand-written Pallas conv would have to re-derive XLA's spatial
  partitioning to break even.  Measured, not assumed: see bench notes.
* **LRN** is the real fusion win: the XLA lowering materializes the
  padded/cumsum intermediates in HBM, while the Pallas kernel computes
  ``x * (k + alpha/n * (x^2 @ band))^-beta`` in one VMEM pass — the
  channel-window sum becomes a banded matmul on the MXU, and square /
  power / multiply fuse around it.  Forward and backward are both single
  kernels wired through ``jax.custom_vjp``.
* **fullc** gets a tiled-MXU matmul (``pallas_matmul``) used when
  ``CXXNET_PALLAS=1``; XLA's dot is the default.

All kernels run under ``interpret=True`` on CPU, which is how the test
suite validates them without hardware.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def pallas_enabled() -> bool:
    """Opt-in switch for the Pallas paths (config ``use_pallas=1`` sets it
    process-wide; default off until benchmarked ahead on hardware)."""
    return os.environ.get('CXXNET_PALLAS', '0').strip().lower() \
        in ('1', 'true', 'yes', 'on')


def _interpret() -> bool:
    return jax.default_backend() != 'tpu'


def _block_spec(shape, index_map=None):
    if _VMEM is not None:
        return pl.BlockSpec(shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(shape, index_map)


def _band_matrix(c: int, nsize: int, dtype=jnp.float32):
    """(c, c) 0/1 band: column j sums channels in j's LRN window."""
    half_lo = (nsize - 1) // 2
    half_hi = nsize - 1 - half_lo
    idx = np.arange(c)
    band = ((idx[:, None] >= idx[None, :] - half_lo)
            & (idx[:, None] <= idx[None, :] + half_hi))
    return jnp.asarray(band, dtype)


def _pad_rows(x2, tile):
    rows = x2.shape[0]
    pad = (-rows) % tile
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2, rows


# --- LRN ------------------------------------------------------------------

def _lrn_fwd_kernel(x_ref, band_ref, o_ref, norm_ref, *, alpha_n, beta,
                    knorm):
    x = x_ref[:].astype(jnp.float32)
    win = jnp.dot(x * x, band_ref[:], preferred_element_type=jnp.float32)
    norm = knorm + alpha_n * win
    norm_ref[:] = norm
    o_ref[:] = (x * jnp.power(norm, -beta)).astype(o_ref.dtype)


def _lrn_bwd_kernel(x_ref, g_ref, band_ref, norm_ref, dx_ref, *, alpha_n,
                    beta):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    norm = norm_ref[:]
    npow = jnp.power(norm, -beta)
    # dL/dx = g * norm^-b - 2*b*alpha_n * x * ((g*x*norm^(-b-1)) @ band^T)
    inner = jnp.dot(g * x * npow / norm, band_ref[:],
                    preferred_element_type=jnp.float32)
    dx_ref[:] = (g * npow - 2.0 * beta * alpha_n * x * inner
                 ).astype(dx_ref.dtype)


_ROW_TILE = 512


def _lrn_call(kernel, outs, args, c, rows_padded, band_arg):
    """band_arg: index into ``args`` of the (c, c) band matrix — dispatch
    is positional because row blocks can also be (c, c) when the padded
    row count happens to equal the channel count."""
    grid = (rows_padded // _ROW_TILE,)
    row_spec = _block_spec((_ROW_TILE, c), lambda i: (i, 0))
    band_spec = _block_spec((c, c), lambda i: (0, 0))
    specs = [band_spec if i == band_arg else row_spec
             for i in range(len(args))]
    return pl.pallas_call(
        kernel,
        out_shape=outs,
        grid=grid,
        in_specs=specs,
        out_specs=[row_spec] * len(outs) if isinstance(outs, list)
        else row_spec,
        interpret=_interpret(),
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn_pallas(x, nsize: int, alpha: float, beta: float, knorm: float):
    """Cross-channel LRN over NHWC input, Pallas-fused."""
    out, _ = _lrn_fwd_impl(x, nsize, alpha, beta, knorm)
    return out


def _lrn_fwd_impl(x, nsize, alpha, beta, knorm):
    b = x.shape[:-1]
    c = x.shape[-1]
    x2, rows = _pad_rows(x.reshape(-1, c), _ROW_TILE)
    band = _band_matrix(c, nsize)
    kernel = functools.partial(_lrn_fwd_kernel, alpha_n=alpha / nsize,
                               beta=beta, knorm=knorm)
    out, norm = _lrn_call(
        kernel,
        [jax.ShapeDtypeStruct(x2.shape, x.dtype),
         jax.ShapeDtypeStruct(x2.shape, jnp.float32)],
        (x2, band), c, x2.shape[0], band_arg=1)
    return out[:rows].reshape(*b, c), norm[:rows]


def _lrn_vjp_fwd(x, nsize, alpha, beta, knorm):
    out, norm = _lrn_fwd_impl(x, nsize, alpha, beta, knorm)
    return out, (x, norm)


def _lrn_vjp_bwd(nsize, alpha, beta, knorm, res, g):
    x, norm = res
    b = x.shape[:-1]
    c = x.shape[-1]
    x2, rows = _pad_rows(x.reshape(-1, c), _ROW_TILE)
    g2, _ = _pad_rows(g.reshape(-1, c).astype(jnp.float32), _ROW_TILE)
    n2, _ = _pad_rows(norm, _ROW_TILE)
    n2 = jnp.where(n2 == 0.0, 1.0, n2)   # padded rows: avoid 0^-b
    # backward contracts the transposed band: dx_j sums over windows i
    # that contain j (identical for symmetric/odd windows)
    band = _band_matrix(c, nsize).T
    kernel = functools.partial(_lrn_bwd_kernel, alpha_n=alpha / nsize,
                               beta=beta)
    dx = _lrn_call(
        kernel, jax.ShapeDtypeStruct(x2.shape, x.dtype),
        (x2, g2, band, n2), c, x2.shape[0], band_arg=2)
    return (dx[:rows].reshape(*b, c),)


lrn_pallas.defvjp(_lrn_vjp_fwd, _lrn_vjp_bwd)


# --- tiled matmul (fullc) -------------------------------------------------

def _matmul_kernel(a_ref, b_ref, o_ref):
    o_ref[:] = jnp.dot(a_ref[:], b_ref[:],
                       preferred_element_type=jnp.float32
                       ).astype(o_ref.dtype)


@jax.custom_vjp
def pallas_matmul(a, b):
    """(m, k) @ (k, n) with an MXU-tiled Pallas kernel; differentiable
    (backward runs the same kernel on the transposed operands)."""
    return _matmul_impl(a, b)


def _matmul_vjp_fwd(a, b):
    return _matmul_impl(a, b), (a, b)


def _matmul_vjp_bwd(res, g):
    a, b = res
    return (_matmul_impl(g, b.T).astype(a.dtype),
            _matmul_impl(a.T, g).astype(b.dtype))


pallas_matmul.defvjp(_matmul_vjp_fwd, _matmul_vjp_bwd)


def _matmul_impl(a, b, tile_m: int = 256, tile_n: int = 256):
    """K is kept whole per tile (fits VMEM for fullc-sized layers)."""
    m, k = a.shape
    k2, n = b.shape
    assert k == k2
    pm, pn = (-m) % tile_m, (-n) % tile_n
    ap = jnp.pad(a, ((0, pm), (0, 0))) if pm else a
    bp = jnp.pad(b, ((0, 0), (0, pn))) if pn else b
    mm, nn = ap.shape[0], bp.shape[1]
    out = pl.pallas_call(
        _matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((mm, nn), a.dtype),
        grid=(mm // tile_m, nn // tile_n),
        in_specs=[_block_spec((tile_m, k), lambda i, j: (i, 0)),
                  _block_spec((k, tile_n), lambda i, j: (0, j))],
        out_specs=_block_spec((tile_m, tile_n), lambda i, j: (i, j)),
        interpret=_interpret(),
    )(ap, bp)
    return out[:m, :n]
