"""Pallas TPU kernels for hot ops.

Per the north-star mapping (BASELINE.json), the reference's hand-written
CUDA/mshadow hot paths become TPU kernels.  Design notes:

* **conv / pooling** stay on XLA's native convolution/reduce-window — on
  TPU those already lower to MXU-optimal programs (the cuDNN analogy);
  a hand-written Pallas conv would have to re-derive XLA's spatial
  partitioning to break even.  Measured, not assumed: see bench notes.
* **LRN** is the real fusion win: the XLA lowering materializes the
  padded/cumsum intermediates in HBM, while the Pallas kernel computes
  ``x * (k + alpha/n * (x^2 @ band))^-beta`` in one VMEM pass — the
  channel-window sum becomes a banded matmul on the MXU, and square /
  power / multiply fuse around it.  Forward and backward are both single
  kernels wired through ``jax.custom_vjp``.
* **fullc** gets a tiled-MXU matmul (``pallas_matmul``) used when
  ``CXXNET_PALLAS=1``; XLA's dot is the default.

All kernels run under ``interpret=True`` on CPU, which is how the test
suite validates them without hardware.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
# lint: allow(fault-taxonomy): import-time capability probe; absence IS the signal
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def pallas_mode() -> str:
    """Tri-state Pallas switch: ``'on'`` (config ``use_pallas=1`` /
    ``CXXNET_PALLAS=1`` forces every Pallas path), ``'off'`` (explicit 0
    disables even the measured-profitable ones), ``'auto'`` (unset: each
    op consults its own receipts-derived profitability gate — see
    ``lrn_auto_mode`` and receipts/micro_*.json)."""
    v = os.environ.get('CXXNET_PALLAS')
    if v is None or not v.strip():
        return 'auto'
    return ('on' if v.strip().lower() in ('1', 'true', 'yes', 'on')
            else 'off')


def pallas_enabled() -> bool:
    """True only when Pallas paths are explicitly forced on."""
    return pallas_mode() == 'on'




_FLASH_SCORE_BYTES = 4 << 30   # dense-score budget: ~1/4 of v5e HBM


def attn_use_flash(seq_len: int, batch: int = 1, heads: int = 1) -> bool:
    """Whether fused flash attention should replace the dense local path
    for a (local) ``batch x heads x seq x seq`` attention.  ``'on'``
    forces it; in ``'auto'`` it engages only on a real TPU (with the
    pallas TPU memory spaces importable) when the dense O(seq^2) score
    materialization — ``batch*heads*seq^2`` f32 — would blow a ~4 GiB
    budget (about a quarter of v5e HBM, leaving room for params,
    activations, and the backward's second score pass).  The gate is a
    MEMORY feasibility bound, not a speed claim: at every SPEED-measured
    shape (seq <= 4096 at small b*h, receipts/micro_attn.json) XLA's
    dense path won, so auto stays off while dense still fits."""
    mode = pallas_mode()
    if mode == 'off':
        return False
    if mode == 'on':
        return True
    score_bytes = 4.0 * batch * heads * seq_len * seq_len
    return (not _interpret() and pltpu is not None
            and score_bytes >= _FLASH_SCORE_BYTES)


def fullc_use_pallas(m: int, k: int, n: int, *, is_train: bool,
                     spmd_devices: int = 1) -> bool:
    """Whether fullc's forward matmul should take the Pallas kernel.

    Training keeps XLA everywhere: with honest (scatter-add-perturbed)
    timing the fwd+bwd kernels lose at every production shape
    (receipts/micro_matmul.json).  The exception this gate encodes is
    the EVAL path at fc8's shape class: at 256x4096x1000 the Pallas
    forward measured **4.28x** over XLA — XLA mishandles the
    non-lane-aligned N=1000 (48.7 TF/s) while the padded Pallas tiles
    don't care.  ``auto`` therefore engages only when no backward will
    run (``is_train=False`` — pred/extract/evaluate forwards), on a
    real single-device TPU program, at the measured shape class:
    lane-ragged N (``n % 128 != 0``) big enough to matter
    (m >= 128, k >= 1024, n >= 512).  Anything narrower was never
    measured and stays on XLA; ``use_pallas=1`` still forces the
    kernel everywhere, ``0`` disables it."""
    mode = pallas_mode()
    if mode == 'off':
        return False
    if mode == 'on':
        return True
    if os.environ.get('CXXNET_FULLC_PALLAS', '').strip() == '0':
        # fullc-only kill switch: lets bench.py eval_alexnet A/B THIS
        # gate in isolation — CXXNET_PALLAS=0 would also flip the LRN
        # auto winners and confound the receipt
        return False
    if is_train or _interpret() or spmd_devices != 1:
        return False
    return fullc_pallas_shape_class(m, k, n)


def fullc_pallas_shape_class(m: int, k: int, n: int) -> bool:
    """The measured fc8 shape class (receipts/micro_matmul.json):
    lane-ragged N big enough to matter."""
    return n % 128 != 0 and m >= 128 and k >= 1024 and n >= 512


def lrn_auto_mode(c: int, spmd_devices: int = 1) -> str:
    """Which LRN implementation the ``auto`` Pallas mode picks at channel
    count ``c``: ``'full'`` (Pallas fwd+bwd), ``'hybrid'`` (Pallas fwd /
    XLA bwd), or ``'xla'``.

    From receipts/micro_lrn.json (TPU v5 lite, bf16, 2026-07-30
    scatter-add-perturbation rerun — the earlier broadcast-perturbation
    numbers let XLA hoist work and are superseded):
    c=256 (AlexNet norm2): fwd 1.37x, fwd+bwd **2.16x** -> full Pallas;
    c=96  (AlexNet norm1): fwd 1.90x, fwd+bwd 0.66x -> the fused fwd
    wins even with the 96-lane underfill but the bwd loses, so the
    hybrid keeps the fwd win and hands the bwd to XLA.  The gates:
    128-lane-aligned channels run full Pallas; other sublane-aligned
    (c % 8) counts at or above the measured c=96 floor run the hybrid
    (smaller channel counts underfill the (c, c) band matmul worse than
    anything measured, so they stay on XLA); ragged counts stay on XLA.

    ``spmd_devices`` is the mesh size of the CALLING program (threaded
    through ForwardContext): auto engages only in single-device
    programs, because under GSPMD a ``pallas_call`` is an opaque custom
    call with no sharding rule — the partitioner would gather the full
    sharded activation around it, slower and memory-fatter than the XLA
    path it replaces (and the receipts are single-chip measurements).
    Explicit ``use_pallas=1`` still forces the full kernel everywhere;
    the shard_map'd paths in parallel/sequence.py run per-shard by
    construction and take no such scoping."""
    mode = pallas_mode()
    if mode == 'off':
        return 'xla'
    if mode == 'on':
        return 'full'
    if _interpret() or spmd_devices != 1:
        return 'xla'
    if c % 128 == 0:
        return 'full'
    if c % 8 == 0 and c >= 96:
        return 'hybrid'
    return 'xla'


def decode_use_flash(explicit=None) -> bool:
    """Whether the serve decode step should take the paged flash-decode
    kernel (:func:`paged_flash_decode`) instead of the gather-then-dense
    path.  ``explicit`` is the ``serve.flash_decode`` key: ``1``/``0``
    force it on/off, ``'auto'``/None defer to the tri-state
    ``pallas_mode()`` gate — ``'on'`` forces the kernel everywhere
    (interpret mode included: that is the CPU validation path), ``'off'``
    disables it, ``'auto'`` engages only on a real TPU, where reading
    pages in place actually saves the per-step dense-cache
    materialization HBM round-trip.  Always False when the TPU memory
    spaces are unimportable (the kernel needs VMEM scratch)."""
    if pltpu is None:
        return False
    if explicit is not None:
        text = str(explicit).strip().lower()
        if text in ('1', 'true', 'yes', 'on'):
            return True
        if text in ('0', 'false', 'no', 'off'):
            return False
        # anything else ('auto', '') falls through to the global gate
    mode = pallas_mode()
    if mode == 'on':
        return True
    if mode == 'off':
        return False
    return not _interpret()


def _interpret() -> bool:
    return jax.default_backend() != 'tpu'


def _block_spec(shape, index_map=None):
    if _VMEM is not None:
        return pl.BlockSpec(shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(shape, index_map)


def _compiler_params(*dimension_semantics):
    """Mark grid dims 'parallel' (independent; Mosaic can pipeline) or
    'arbitrary' (sequential — reduction dims carrying scratch state).
    Interpret mode takes no TPU compiler params."""
    if _interpret() or pltpu is None:
        return {}
    return {'compiler_params':
            pltpu.CompilerParams(dimension_semantics=dimension_semantics)}


def _band_matrix(c: int, nsize: int, dtype=jnp.float32):
    """(c, c) 0/1 band: column j sums channels in j's LRN window."""
    half_lo = (nsize - 1) // 2
    half_hi = nsize - 1 - half_lo
    idx = np.arange(c)
    band = ((idx[:, None] >= idx[None, :] - half_lo)
            & (idx[:, None] <= idx[None, :] + half_hi))
    return jnp.asarray(band, dtype)


def _pad_rows(x2, tile):
    rows = x2.shape[0]
    pad = (-rows) % tile
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2, rows


# --- LRN ------------------------------------------------------------------

def _lrn_fwd_kernel(x_ref, band_ref, o_ref, norm_ref, *, alpha_n, beta,
                    knorm):
    x = x_ref[:].astype(jnp.float32)
    win = jnp.dot(x * x, band_ref[:], preferred_element_type=jnp.float32)
    norm = knorm + alpha_n * win
    norm_ref[:] = norm
    o_ref[:] = (x * jnp.power(norm, -beta)).astype(o_ref.dtype)


def _lrn_bwd_kernel(x_ref, g_ref, band_ref, norm_ref, dx_ref, *, alpha_n,
                    beta):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    norm = norm_ref[:]
    npow = jnp.power(norm, -beta)
    # dL/dx = g * norm^-b - 2*b*alpha_n * x * ((g*x*norm^(-b-1)) @ band^T)
    inner = jnp.dot(g * x * npow / norm, band_ref[:],
                    preferred_element_type=jnp.float32)
    dx_ref[:] = (g * npow - 2.0 * beta * alpha_n * x * inner
                 ).astype(dx_ref.dtype)


_ROW_TILE = 512


def _lrn_call(kernel, outs, args, c, rows_padded, band_arg):
    """band_arg: index into ``args`` of the (c, c) band matrix — dispatch
    is positional because row blocks can also be (c, c) when the padded
    row count happens to equal the channel count."""
    grid = (rows_padded // _ROW_TILE,)
    row_spec = _block_spec((_ROW_TILE, c), lambda i: (i, 0))
    band_spec = _block_spec((c, c), lambda i: (0, 0))
    specs = [band_spec if i == band_arg else row_spec
             for i in range(len(args))]
    return pl.pallas_call(
        kernel,
        out_shape=outs,
        grid=grid,
        in_specs=specs,
        out_specs=[row_spec] * len(outs) if isinstance(outs, list)
        else row_spec,
        interpret=_interpret(),
        **_compiler_params('parallel'),
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn_pallas(x, nsize: int, alpha: float, beta: float, knorm: float):
    """Cross-channel LRN over NHWC input, Pallas-fused."""
    out, _ = _lrn_fwd_impl(x, nsize, alpha, beta, knorm)
    return out


def _lrn_fwd_impl(x, nsize, alpha, beta, knorm):
    b = x.shape[:-1]
    c = x.shape[-1]
    x2, rows = _pad_rows(x.reshape(-1, c), _ROW_TILE)
    band = _band_matrix(c, nsize)
    kernel = functools.partial(_lrn_fwd_kernel, alpha_n=alpha / nsize,
                               beta=beta, knorm=knorm)
    out, norm = _lrn_call(
        kernel,
        [jax.ShapeDtypeStruct(x2.shape, x.dtype),
         jax.ShapeDtypeStruct(x2.shape, jnp.float32)],
        (x2, band), c, x2.shape[0], band_arg=1)
    return out[:rows].reshape(*b, c), norm[:rows]


def _lrn_vjp_fwd(x, nsize, alpha, beta, knorm):
    out, norm = _lrn_fwd_impl(x, nsize, alpha, beta, knorm)
    return out, (x, norm)


def _lrn_vjp_bwd(nsize, alpha, beta, knorm, res, g):
    x, norm = res
    b = x.shape[:-1]
    c = x.shape[-1]
    x2, rows = _pad_rows(x.reshape(-1, c), _ROW_TILE)
    g2, _ = _pad_rows(g.reshape(-1, c).astype(jnp.float32), _ROW_TILE)
    n2, _ = _pad_rows(norm, _ROW_TILE)
    n2 = jnp.where(n2 == 0.0, 1.0, n2)   # padded rows: avoid 0^-b
    # backward contracts the transposed band: dx_j sums over windows i
    # that contain j (identical for symmetric/odd windows)
    band = _band_matrix(c, nsize).T
    kernel = functools.partial(_lrn_bwd_kernel, alpha_n=alpha / nsize,
                               beta=beta)
    dx = _lrn_call(
        kernel, jax.ShapeDtypeStruct(x2.shape, x.dtype),
        (x2, g2, band, n2), c, x2.shape[0], band_arg=2)
    return (dx[:rows].reshape(*b, c),)


lrn_pallas.defvjp(_lrn_vjp_fwd, _lrn_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn_hybrid(x, nsize: int, alpha: float, beta: float, knorm: float):
    """Cross-channel LRN: Pallas forward, XLA backward.

    The measured split (receipts/micro_lrn.json, 2026-07-30 rerun): the
    fused forward wins at every measured shape (1.90x at c=96, 1.37x at
    c=256), while the Pallas backward only wins at 128-lane-aligned
    channels (fwd+bwd 2.16x at c=256 — ``lrn_auto_mode`` routes those to
    the full ``lrn_pallas``) and loses below that (fwd+bwd 0.66x at
    c=96, where the bwd band matmul underfills the MXU worse than the
    fwd because it runs two elementwise chains per tile).  So this
    hybrid — the auto choice at non-128-aligned channels — keeps the
    Pallas forward and runs the backward as plain jnp ops (the cumsum
    window trick of ``layers/norm.py``) on the residuals the Pallas
    forward already produced."""
    out, _ = _lrn_fwd_impl(x, nsize, alpha, beta, knorm)
    return out


def _lrn_hybrid_fwd(x, nsize, alpha, beta, knorm):
    out, norm = _lrn_fwd_impl(x, nsize, alpha, beta, knorm)
    return out, (x, norm.reshape(x.shape))


def _lrn_hybrid_bwd(nsize, alpha, beta, knorm, res, g):
    x, norm = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    npow = jnp.power(norm, -beta)
    t = g32 * x32 * npow / norm
    n = nsize
    half_lo = (n - 1) // 2
    half_hi = n - 1 - half_lo
    c = x.shape[-1]
    # dx_j sums t_i over windows i that CONTAIN j — the transposed
    # window [j-half_hi, j+half_lo], hence the swapped pad widths
    pad = jnp.pad(t, [(0, 0)] * (x.ndim - 1) + [(half_hi + 1, half_lo)])
    cums = jnp.cumsum(pad, axis=-1)
    win = cums[..., n:n + c] - cums[..., 0:c]
    dx = g32 * npow - 2.0 * beta * (alpha / n) * x32 * win
    return (dx.astype(x.dtype),)


lrn_hybrid.defvjp(_lrn_hybrid_fwd, _lrn_hybrid_bwd)


# --- tiled matmul (fullc) -------------------------------------------------

def _matmul_kernel_wholek(a_ref, b_ref, o_ref):
    """Scratch-free whole-K tile: the fallback when TPU memory spaces are
    unavailable (interpret-mode CPU installs without pallas.tpu)."""
    o_ref[:] = jnp.dot(a_ref[:], b_ref[:],
                       preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _matmul_kernel(a_ref, b_ref, o_ref, acc_ref):
    """Grid (m, n, k): K is innermost so the f32 accumulator tile stays in
    VMEM scratch across K steps (keeping whole K per tile VMEM-OOMs at
    AlexNet's 9216-wide fc6)."""
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


# Measured-winning forward tile config (receipts/micro_matmul_tiles.log,
# TPU v5 lite, bf16): at fc6's 256x9216x4096 the (256, 1024, 512) tiling
# ran 172.6 TF/s vs XLA's 151.0 — 1.143x, the first Pallas matmul win at
# a production shape.  Not the default (the sweep was cut off by a
# tunnel drop before covering fc7; the training path's bwd kernels still
# lose) — callers opt in via _matmul_impl(a, b, *MATMUL_TILES_WIDE_N).
MATMUL_TILES_WIDE_N = (256, 1024, 512)


@jax.custom_vjp
def pallas_matmul(a, b):
    """(m, k) @ (k, n) with an MXU-tiled Pallas kernel; differentiable
    (backward runs the same kernel on the transposed operands)."""
    return _matmul_impl(a, b)


def _matmul_vjp_fwd(a, b):
    return _matmul_impl(a, b), (a, b)


def _matmul_vjp_bwd(res, g):
    a, b = res
    # transpose-free backward: da = g @ b^T and db = a^T @ g are computed
    # by kernels that contract directly against the STORED layouts of b
    # and a — a physical .T of the (9216, 4096) fc6 weight costs a ~75 MB
    # HBM round-trip per operand per step, paid before the old
    # reuse-the-forward-kernel approach even started multiplying
    return (_matmul_nt_impl(g, b).astype(a.dtype),
            _matmul_tn_impl(a, g).astype(b.dtype))


pallas_matmul.defvjp(_matmul_vjp_fwd, _matmul_vjp_bwd)


def _matmul_nt_kernel(g_ref, b_ref, o_ref, acc_ref):
    """(bm, bn) x (bk, bn) -> (bm, bk): contract the trailing axis of
    both tiles (da = g @ b^T without transposing b)."""
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        g_ref[:], b_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _matmul_tn_kernel(a_ref, g_ref, o_ref, acc_ref):
    """(bm, bk) x (bm, bn) -> (bk, bn): contract the leading axis of
    both tiles (db = a^T @ g without transposing a)."""
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        a_ref[:], g_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _pad2(x, tr, tc):
    pr, pc = (-x.shape[0]) % tr, (-x.shape[1]) % tc
    return jnp.pad(x, ((0, pr), (0, pc))) if pr or pc else x


def _clamp_tile(tile: int, dim: int, align: int = 128) -> int:
    """Shrink a default tile size to the dimension it will cover (rounded
    up to MXU lane alignment), so a dim smaller than the default tile is
    not padded up to the tile — at fullc's production m=256, the TN
    backward's old fixed tile_m=512 padded the reduction to twice its
    real size and HALVED its throughput (receipts/micro_matmul_bwd.json,
    TN 0.23-0.26x vs NT 0.49-0.54x)."""
    return min(tile, max(align, -(-dim // align) * align))


def _matmul_nt_impl(g, b, tile_m: int = 256, tile_n: int = 512,
                    tile_k: int = 256):
    """g (m, n) @ b (k, n)^T -> (m, k); reduction over n (innermost)."""
    m, n = g.shape
    k = b.shape[0]
    if pltpu is None:                    # exotic CPU-only installs
        return _matmul_impl(g, b.T)
    tile_m = _clamp_tile(tile_m, m)
    tile_n = _clamp_tile(tile_n, n)
    tile_k = _clamp_tile(tile_k, k)
    gp, bp = _pad2(g, tile_m, tile_n), _pad2(b, tile_k, tile_n)
    out = pl.pallas_call(
        _matmul_nt_kernel,
        out_shape=jax.ShapeDtypeStruct((gp.shape[0], bp.shape[0]), g.dtype),
        grid=(gp.shape[0] // tile_m, bp.shape[0] // tile_k,
              gp.shape[1] // tile_n),
        in_specs=[_block_spec((tile_m, tile_n), lambda i, j, t: (i, t)),
                  _block_spec((tile_k, tile_n), lambda i, j, t: (j, t))],
        out_specs=_block_spec((tile_m, tile_k), lambda i, j, t: (i, j)),
        scratch_shapes=[_scratch((tile_m, tile_k))],
        interpret=_interpret(),
        **_compiler_params('parallel', 'parallel', 'arbitrary'),
    )(gp, bp)
    return out[:m, :k]


def _matmul_tn_impl(a, g, tile_m: int = 512, tile_n: int = 256,
                    tile_k: int = 256):
    """a (m, k)^T @ g (m, n) -> (k, n); reduction over m (innermost)."""
    m, k = a.shape
    n = g.shape[1]
    if pltpu is None:                    # exotic CPU-only installs
        return _matmul_impl(a.T, g)
    tile_m = _clamp_tile(tile_m, m)
    tile_n = _clamp_tile(tile_n, n)
    tile_k = _clamp_tile(tile_k, k)
    ap, gp = _pad2(a, tile_m, tile_k), _pad2(g, tile_m, tile_n)
    out = pl.pallas_call(
        _matmul_tn_kernel,
        out_shape=jax.ShapeDtypeStruct((ap.shape[1], gp.shape[1]), a.dtype),
        grid=(ap.shape[1] // tile_k, gp.shape[1] // tile_n,
              ap.shape[0] // tile_m),
        in_specs=[_block_spec((tile_m, tile_k), lambda i, j, t: (t, i)),
                  _block_spec((tile_m, tile_n), lambda i, j, t: (t, j))],
        out_specs=_block_spec((tile_k, tile_n), lambda i, j, t: (i, j)),
        scratch_shapes=[_scratch((tile_k, tile_n))],
        interpret=_interpret(),
        **_compiler_params('parallel', 'parallel', 'arbitrary'),
    )(ap, gp)
    return out[:k, :n]


def _matmul_impl(a, b, tile_m: int = 256, tile_n: int = 256,
                 tile_k: int = 512):
    m, k = a.shape
    k2, n = b.shape
    assert k == k2
    if pltpu is None:
        # no TPU memory spaces (exotic CPU-only install): scratch-free
        # whole-K kernel — VMEM limits don't exist in interpret mode
        pm, pn = (-m) % tile_m, (-n) % tile_n
        ap = jnp.pad(a, ((0, pm), (0, 0))) if pm else a
        bp = jnp.pad(b, ((0, 0), (0, pn))) if pn else b
        mm, nn = ap.shape[0], bp.shape[1]
        out = pl.pallas_call(
            _matmul_kernel_wholek,
            out_shape=jax.ShapeDtypeStruct((mm, nn), a.dtype),
            grid=(mm // tile_m, nn // tile_n),
            in_specs=[_block_spec((tile_m, k), lambda i, j: (i, 0)),
                      _block_spec((k, tile_n), lambda i, j: (0, j))],
            out_specs=_block_spec((tile_m, tile_n), lambda i, j: (i, j)),
            interpret=_interpret(),
        )(ap, bp)
        return out[:m, :n]
    tile_m = _clamp_tile(tile_m, m)
    tile_n = _clamp_tile(tile_n, n)
    tile_k = _clamp_tile(tile_k, k)
    pm, pn, pk = (-m) % tile_m, (-n) % tile_n, (-k) % tile_k
    ap = jnp.pad(a, ((0, pm), (0, pk))) if pm or pk else a
    bp = jnp.pad(b, ((0, pk), (0, pn))) if pk or pn else b
    mm, nn, kk = ap.shape[0], bp.shape[1], ap.shape[1]
    out = pl.pallas_call(
        _matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((mm, nn), a.dtype),
        grid=(mm // tile_m, nn // tile_n, kk // tile_k),
        in_specs=[_block_spec((tile_m, tile_k), lambda i, j, t: (i, t)),
                  _block_spec((tile_k, tile_n), lambda i, j, t: (t, j))],
        out_specs=_block_spec((tile_m, tile_n), lambda i, j, t: (i, j)),
        scratch_shapes=[_scratch((tile_m, tile_n))],
        interpret=_interpret(),
        **_compiler_params('parallel', 'parallel', 'arbitrary'),
    )(ap, bp)
    return out[:m, :n]


# --- flash attention ------------------------------------------------------
#
# Fused online-softmax attention: the (seq_q, seq_k) score matrix never
# leaves VMEM.  Forward and both backward passes (dq; dk/dv) are blockwise
# Pallas kernels wired through jax.custom_vjp, with the standard
# log-sum-exp + delta recomputation scheme.  Layout inside the kernels is
# (batch*heads, seq, head_dim); the public API takes (b, s, h, d).

_NEG_INF = -1e30


def _causal_mask(qi, kj, bq, bk, sk_valid):
    """(bq, bk) bool mask of *allowed* positions for query block qi /
    key block kj, also masking padded keys beyond sk_valid."""
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return (q_pos >= k_pos) & (k_pos < sk_valid)


def _valid_mask(kj, bq, bk, sk_valid):
    k_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return k_pos < sk_valid



def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the varying-manual-axes of ``like`` so
    pallas_call works under shard_map(check_vma=True)."""
    vma = getattr(getattr(like, 'aval', None), 'vma', None)
    if vma is not None:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:          # older jax without the vma kwarg
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *, scale, causal, sk_valid):
    """Grid (bh, q_blocks, k_blocks): only one (block, d) tile of each
    operand is VMEM-resident at a time; the online-softmax state lives in
    VMEM scratch carried across the innermost (key) grid dimension."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # skip key blocks that are entirely masked: fully above the causal
    # diagonal, or entirely in the padded key range
    run = kj * bk < sk_valid
    if causal:
        run = jnp.logical_and(run, qi * bq + bq - 1 >= kj * bk)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        mask = (_causal_mask(qi, kj, bq, bk, sk_valid) if causal
                else _valid_mask(kj, bq, bk, sk_valid))
        s = jnp.where(mask, s, _NEG_INF)
        m = m_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        corr = jnp.exp(m - m_new)
        l_ref[:, 0] = l_ref[:, 0] * corr + jnp.sum(p, axis=-1)
        m_ref[:, 0] = m_new
        acc_ref[:] = (acc_ref[:] * corr[:, None]
                      + jnp.dot(p, v_blk, preferred_element_type=jnp.float32))

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_ref[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[:, 0] + jnp.log(l_safe))[:, None]


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dq_ref, dqacc_ref, *, scale, causal, sk_valid):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        dqacc_ref[:] = jnp.zeros_like(dqacc_ref)

    run = kj * bk < sk_valid
    if causal:
        run = jnp.logical_and(run, qi * bq + bq - 1 >= kj * bk)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, 0]
        delta = delta_ref[0, :, 0]
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        mask = (_causal_mask(qi, kj, bq, bk, sk_valid) if causal
                else _valid_mask(kj, bq, bk, sk_valid))
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dqacc_ref[:] = dqacc_ref[:] + jnp.dot(
            ds, k_blk, preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = (dqacc_ref[:] * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dkacc_ref, dvacc_ref, *, scale,
                      causal, sq_valid):
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    bk = k_ref.shape[1]
    bq = q_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dkacc_ref[:] = jnp.zeros_like(dkacc_ref)
        dvacc_ref[:] = jnp.zeros_like(dvacc_ref)

    # skip query blocks entirely below the valid range or, for causal,
    # entirely above the diagonal (no query in the block sees key block kj)
    run = qi * bq < sq_valid
    if causal:
        run = jnp.logical_and(run, qi * bq + bq - 1 >= kj * bk)

    @pl.when(run)
    def _compute():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        q_blk = q_ref[0].astype(jnp.float32)
        do_blk = do_ref[0].astype(jnp.float32)
        lse_blk = lse_ref[0, :, 0]
        delta_blk = delta_ref[0, :, 0]
        s = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32) * scale
        q_pos = (qi * bq
                 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
        k_pos = (kj * bk
                 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1))
        mask = q_pos < sq_valid
        if causal:
            mask = mask & (q_pos >= k_pos)
        p = jnp.where(mask, jnp.exp(s - lse_blk[:, None]), 0.0)
        dvacc_ref[:] = dvacc_ref[:] + jnp.dot(
            p.T, do_blk, preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk[:, None])
        dkacc_ref[:] = dkacc_ref[:] + jnp.dot(
            ds.T, q_blk, preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = (dkacc_ref[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dvacc_ref[:].astype(dv_ref.dtype)


def _pad_seq(x, block):
    pad = (-x.shape[1]) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _flash_blocks(seq, block):
    return max(1, min(block, seq))


def _scratch(shape, dtype=jnp.float32):
    if pltpu is None:          # pragma: no cover - exotic installs only
        raise RuntimeError(
            'this pallas kernel needs TPU memory spaces '
            '(jax.experimental.pallas.tpu unavailable)')
    return pltpu.VMEM(shape, dtype)


def _flash_fwd_impl(q, k, v, causal, block_q, block_k):
    """q,k,v: (bh, s, d).  Returns (out, lse) with lse over valid keys."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = _flash_blocks(sq, block_q)
    bk = _flash_blocks(sk, block_k)
    qp, kp, vp = _pad_seq(q, bq), _pad_seq(k, bk), _pad_seq(v, bk)
    sqp, skp = qp.shape[1], kp.shape[1]
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                               sk_valid=sk)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[_sds((bh, sqp, d), q.dtype, qp),
                   _sds((bh, sqp, 1), jnp.float32, qp)],
        grid=(bh, sqp // bq, skp // bk),
        in_specs=[_block_spec((1, bq, d), lambda i, j, t: (i, j, 0)),
                  _block_spec((1, bk, d), lambda i, j, t: (i, t, 0)),
                  _block_spec((1, bk, d), lambda i, j, t: (i, t, 0))],
        out_specs=[_block_spec((1, bq, d), lambda i, j, t: (i, j, 0)),
                   _block_spec((1, bq, 1), lambda i, j, t: (i, j, 0))],
        scratch_shapes=[_scratch((bq, d)), _scratch((bq, 1)),
                        _scratch((bq, 1))],
        interpret=_interpret(),
        **_compiler_params('parallel', 'parallel', 'arbitrary'),
    )(qp, kp, vp)
    return out[:, :sq], lse[:, :sq, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhsd(q, k, v, causal, block_q, block_k):
    out, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k)
    return out


def _flash_bhsd_fwd(q, k, v, causal, block_q, block_k):
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bhsd_bwd(causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = _flash_blocks(sq, block_q)
    bk = _flash_blocks(sk, block_k)
    scale = 1.0 / math.sqrt(d)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    qp, gp = _pad_seq(q, bq), _pad_seq(g, bq)
    kp, vp = _pad_seq(k, bk), _pad_seq(v, bk)
    sqp, skp = qp.shape[1], kp.shape[1]
    pad_q = sqp - sq
    lse_p = jnp.pad(lse, ((0, 0), (0, pad_q)))[..., None]
    delta_p = jnp.pad(delta, ((0, 0), (0, pad_q)))[..., None]

    dq_kernel = functools.partial(_flash_dq_kernel, scale=scale,
                                  causal=causal, sk_valid=sk)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=_sds((bh, sqp, d), q.dtype, qp),
        grid=(bh, sqp // bq, skp // bk),
        in_specs=[_block_spec((1, bq, d), lambda i, j, t: (i, j, 0)),
                  _block_spec((1, bk, d), lambda i, j, t: (i, t, 0)),
                  _block_spec((1, bk, d), lambda i, j, t: (i, t, 0)),
                  _block_spec((1, bq, d), lambda i, j, t: (i, j, 0)),
                  _block_spec((1, bq, 1), lambda i, j, t: (i, j, 0)),
                  _block_spec((1, bq, 1), lambda i, j, t: (i, j, 0))],
        out_specs=_block_spec((1, bq, d), lambda i, j, t: (i, j, 0)),
        scratch_shapes=[_scratch((bq, d))],
        interpret=_interpret(),
        **_compiler_params('parallel', 'parallel', 'arbitrary'),
    )(qp, kp, vp, gp, lse_p, delta_p)

    dkv_kernel = functools.partial(_flash_dkv_kernel, scale=scale,
                                   causal=causal, sq_valid=sq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=[_sds((bh, skp, d), k.dtype, kp),
                   _sds((bh, skp, d), v.dtype, vp)],
        grid=(bh, skp // bk, sqp // bq),
        in_specs=[_block_spec((1, bq, d), lambda i, t, j: (i, j, 0)),
                  _block_spec((1, bk, d), lambda i, t, j: (i, t, 0)),
                  _block_spec((1, bk, d), lambda i, t, j: (i, t, 0)),
                  _block_spec((1, bq, d), lambda i, t, j: (i, j, 0)),
                  _block_spec((1, bq, 1), lambda i, t, j: (i, j, 0)),
                  _block_spec((1, bq, 1), lambda i, t, j: (i, j, 0))],
        out_specs=[_block_spec((1, bk, d), lambda i, t, j: (i, t, 0)),
                   _block_spec((1, bk, d), lambda i, t, j: (i, t, 0))],
        scratch_shapes=[_scratch((bk, d)), _scratch((bk, d))],
        interpret=_interpret(),
        **_compiler_params('parallel', 'parallel', 'arbitrary'),
    )(qp, kp, vp, gp, lse_p, delta_p)

    return dq[:, :sq], dk[:, :sk], dv[:, :sk]


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128):
    """Fused attention over ``(batch, seq, heads, head_dim)`` arrays.

    Exact (online-softmax) attention; O(seq) memory — the score matrix
    stays in VMEM blocks.  Differentiable via blockwise Pallas backward
    kernels.  Oracle: ``parallel.sequence.attention_reference``.

    ``causal=True`` uses TOP-LEFT mask alignment (position counted from
    0 for both q and k), which only makes sense for ``sq == sk``; the
    bottom-right (decode) convention is not implemented, so mismatched
    lengths with ``causal`` are rejected.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if causal and sq != sk:
        raise ValueError(
            f'causal flash_attention requires q and k of equal length '
            f'(top-left mask alignment); got sq={sq} sk={sk}')

    def to_bhsd(x, s):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, x.shape[-1])

    out = _flash_bhsd(to_bhsd(q, sq), to_bhsd(k, sk), to_bhsd(v, sk),
                      causal, block_q, block_k)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


# --- paged flash-decode attention (serve/decode.py) ------------------------
#
# The decode engine's step used to GATHER every slot's KV pages into a
# dense (S, T, H, hd) cache in HBM on every token (kpool[:, table] — a
# full-pool materialization per step per stage).  This kernel reads each
# slot's pages IN PLACE: the page table is a scalar-prefetch operand, so
# the (slot, logical-page) grid cell's BlockSpec index map resolves the
# PHYSICAL page to DMA — HBM traffic per step is exactly the slot's live
# pages, once.  Per-slot positions (``pos``) and left-pad widths (``w``)
# drive the same live mask as ``transformer.decode_step``; the final
# masked softmax + weighted sum mirror the dense ops EXACTLY (same
# einsum shapes, same f32 cast points), which is what makes the kernel
# bitwise-equal to the gather-then-dense twin — pinned by
# tests/test_serve_decode.py on the CPU ``interpret=True`` path.

def _paged_decode_kernel(table_ref, pos_ref, w_ref, q_ref, k_ref, v_ref,
                         o_ref, s_scr, v_scr, *, scale, ps, pp):
    """Grid (slots, pages_per_slot): page j of slot s is DMA'd from the
    physical page ``table[s, j]``; its scores land in the score scratch
    (an exact per-page slice write — no cross-page reduction), its V rows
    in the V scratch.  The last page step applies the live mask and runs
    the one full-width softmax + value contraction."""
    s = pl.program_id(0)
    j = pl.program_id(1)
    q = q_ref[0]                                   # (H, hd)
    # per-page scores: same per-element hd-length dots as the dense
    # einsum 'bqhd,bkhd->bhqk' — slice writes are exact, so assembling
    # the (H, T) score row page-by-page loses nothing
    s_scr[:, pl.ds(j * ps, ps)] = jnp.einsum('hd,khd->hk', q, k_ref[0])
    v_scr[pl.ds(j * ps, ps)] = v_ref[0]

    @pl.when(j == pp - 1)
    def _finalize():
        t = pos_ref[s]
        wv = w_ref[s]
        ar = jax.lax.broadcasted_iota(jnp.int32, (1, pp * ps), 1)
        live = (ar <= t) & (ar >= wv)              # (1, T)
        sc = s_scr[:] * scale
        sc = jnp.where(live, sc, -jnp.inf)
        p = jax.nn.softmax(sc.astype(jnp.float32), axis=-1
                           ).astype(v_scr.dtype)
        # keep the singleton q axis: 'hqk,khd->qhd' lowers to the same
        # contraction as the dense 'bhqk,bkhd->bqhd' (dropping it pads
        # the result a ulp apart on CPU — measured, not assumed)
        o_ref[0] = jnp.einsum('hqk,khd->qhd', p[:, None], v_scr[:])[0]


def paged_flash_decode(q, kpool, vpool, table, pos, w, scale):
    """One decode step's attention for every slot, over the paged pool.

    ``q``: (S, H, hd) — each slot's single-token query.  ``kpool`` /
    ``vpool``: (P, ps, H, hd) — ONE stage's physical page pool (the
    current token's K/V must already be scattered in at ``pos``).
    ``table``: (S, pp) int32 page table (physical page 0 = scratch: its
    rows are masked dead by ``pos``/``w``).  ``pos``/``w``: (S,) int32
    per-slot write position and left-pad width.  Returns (S, H, hd)
    attention outputs, bitwise-equal to gathering ``kpool[table]`` into
    a dense cache and running ``transformer.decode_step``'s attention.
    """
    S, H, hd = q.shape
    P, ps = kpool.shape[0], kpool.shape[1]
    pp = table.shape[1]
    if pltpu is None:          # pragma: no cover - exotic installs only
        raise RuntimeError(
            'paged_flash_decode needs TPU memory spaces '
            '(jax.experimental.pallas.tpu unavailable); gate callers on '
            'decode_use_flash()')
    kernel = functools.partial(_paged_decode_kernel, scale=scale, ps=ps,
                               pp=pp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, pp),
        in_specs=[
            pl.BlockSpec((1, H, hd), lambda s, j, tr, pr, wr: (s, 0, 0)),
            pl.BlockSpec((1, ps, H, hd),
                         lambda s, j, tr, pr, wr: (tr[s, j], 0, 0, 0)),
            pl.BlockSpec((1, ps, H, hd),
                         lambda s, j, tr, pr, wr: (tr[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, hd),
                               lambda s, j, tr, pr, wr: (s, 0, 0)),
        scratch_shapes=[pltpu.VMEM((H, pp * ps), q.dtype),
                        pltpu.VMEM((pp * ps, H, hd), vpool.dtype)],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((S, H, hd), vpool.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        **_compiler_params('parallel', 'arbitrary'),
    )(table, pos, w, q, kpool, vpool)


def _paged_verify_kernel(table_ref, pos_ref, w_ref, q_ref, k_ref, v_ref,
                         o_ref, s_scr, v_scr, *, scale, ps, pp, K):
    """Grid (slots, pages_per_slot): the K-query window extension of
    :func:`_paged_decode_kernel` (speculative-decode verify / prefix-
    shared tail, serve/decode.py).  Page j of slot s is DMA'd from
    physical page ``table[s, j]``; its per-query scores land in the
    (K, H, T) score scratch as exact slice writes; the last page step
    applies the PER-QUERY live mask — window query k sees cache
    positions ``[w, pos + k]``, its own row and earlier drafts, never a
    later one — and runs one full-width softmax + value contraction per
    query, mirroring the dense ``verify_step`` ops (same einsum shapes,
    same f32 cast points) so the two legs are bitwise-equal."""
    s = pl.program_id(0)
    j = pl.program_id(1)
    q = q_ref[0]                                   # (K, H, hd)
    s_scr[:, :, pl.ds(j * ps, ps)] = jnp.einsum('qhd,khd->qhk', q,
                                                k_ref[0])
    v_scr[pl.ds(j * ps, ps)] = v_ref[0]

    @pl.when(j == pp - 1)
    def _finalize():
        t = pos_ref[s]
        wv = w_ref[s]
        ar = jax.lax.broadcasted_iota(jnp.int32, (K, 1, pp * ps), 2)
        kq = jax.lax.broadcasted_iota(jnp.int32, (K, 1, pp * ps), 0)
        live = (ar <= t + kq) & (ar >= wv)         # (K, 1, T)
        sc = s_scr[:] * scale
        sc = jnp.where(live, sc, -jnp.inf)
        p = jax.nn.softmax(sc.astype(jnp.float32), axis=-1
                           ).astype(v_scr.dtype)
        o_ref[0] = jnp.einsum('qhk,khd->qhd', p, v_scr[:])


def paged_flash_verify(q, kpool, vpool, table, pos, w, scale):
    """A K-token verify window's attention for every slot, in place over
    the paged pool — :func:`paged_flash_decode` widened to multi-query
    (serve/decode.py "Speculative decoding" / prefix-shared tail
    prefill).

    ``q``: (S, K, H, hd) — each slot's K window queries, query k at
    position ``pos[s] + k``.  ``kpool``/``vpool``: (P, ps, H, hd) — ONE
    stage's physical page pool (the window's K/V rows must already be
    scattered in at ``[pos, pos + K)``).  ``table``: (S, pp) int32 page
    table.  ``pos``/``w``: (S,) int32 per-slot window start and left-pad
    width.  Returns (S, K, H, hd), bitwise-equal to gathering
    ``kpool[table]`` dense and running ``transformer.verify_step``'s
    attention (the per-query mask is the verify-step rule:
    ``[w, pos + k]``)."""
    S, K, H, hd = q.shape
    P, ps = kpool.shape[0], kpool.shape[1]
    pp = table.shape[1]
    if pltpu is None:          # pragma: no cover - exotic installs only
        raise RuntimeError(
            'paged_flash_verify needs TPU memory spaces '
            '(jax.experimental.pallas.tpu unavailable); gate callers on '
            'decode_use_flash()')
    kernel = functools.partial(_paged_verify_kernel, scale=scale, ps=ps,
                               pp=pp, K=K)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, pp),
        in_specs=[
            pl.BlockSpec((1, K, H, hd),
                         lambda s, j, tr, pr, wr: (s, 0, 0, 0)),
            pl.BlockSpec((1, ps, H, hd),
                         lambda s, j, tr, pr, wr: (tr[s, j], 0, 0, 0)),
            pl.BlockSpec((1, ps, H, hd),
                         lambda s, j, tr, pr, wr: (tr[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, K, H, hd),
                               lambda s, j, tr, pr, wr: (s, 0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((K, H, pp * ps), q.dtype),
                        pltpu.VMEM((pp * ps, H, hd), vpool.dtype)],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((S, K, H, hd), vpool.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        **_compiler_params('parallel', 'arbitrary'),
    )(table, pos, w, q, kpool, vpool)


# --- int8 matmul (quantized inference tier, nnet/quantize.py) --------------

def _int8_matmul_kernel(a_ref, b_ref, o_ref, acc_ref):
    """``pallas_matmul``'s K-innermost tiling with int8 MXU inputs and an
    exact int32 accumulator (integer adds reassociate freely, so the
    K-split accumulation is bitwise-equal to the XLA fallback's one-shot
    dot — the scale application to f32 happens outside)."""
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.int32)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[:] = acc_ref[:]


def pallas_int8_matmul(a, b, tile_m: int = 256, tile_n: int = 256,
                       tile_k: int = 512):
    """(m, k) int8 @ (k, n) int8 -> (m, n) int32, MXU-tiled.

    The quantized-inference matmul leg (doc/serving.md "Quantized
    inference"): int8 operand tiles feed the MXU, the accumulator is
    exact int32, and the caller applies the (row-scale x col-scale) f32
    rescale.  Bitwise-equal to ``lax.dot_general`` on the same int8
    operands (integer accumulation has no rounding), so the
    Pallas-vs-XLA twin is exact, not a tolerance."""
    m, k = a.shape
    k2, n = b.shape
    assert k == k2
    if pltpu is None:                    # exotic CPU-only installs
        return jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
    tile_m = _clamp_tile(tile_m, m)
    tile_n = _clamp_tile(tile_n, n)
    tile_k = _clamp_tile(tile_k, k)
    pm, pn, pk = (-m) % tile_m, (-n) % tile_n, (-k) % tile_k
    ap = jnp.pad(a, ((0, pm), (0, pk))) if pm or pk else a
    bp = jnp.pad(b, ((0, pk), (0, pn))) if pk or pn else b
    mm, nn, kk = ap.shape[0], bp.shape[1], ap.shape[1]
    out = pl.pallas_call(
        _int8_matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((mm, nn), jnp.int32),
        grid=(mm // tile_m, nn // tile_n, kk // tile_k),
        in_specs=[_block_spec((tile_m, tile_k), lambda i, j, t: (i, t)),
                  _block_spec((tile_k, tile_n), lambda i, j, t: (t, j))],
        out_specs=_block_spec((tile_m, tile_n), lambda i, j, t: (i, j)),
        scratch_shapes=[_scratch((tile_m, tile_n), jnp.int32)],
        interpret=_interpret(),
        **_compiler_params('parallel', 'parallel', 'arbitrary'),
    )(ap, bp)
    return out[:m, :n]
