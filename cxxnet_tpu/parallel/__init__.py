"""Parallelism: device meshes, shardings, and distributed init."""

from .mesh import (batch_sharding, build_mesh, param_shardings,
                   replicated_sharding)
from .distributed import maybe_init_distributed
from .sequence import (attention_reference, ring_attention,
                       ulysses_attention)
