"""Parallelism: device meshes, shardings, distributed init, and the
elastic multi-host runtime (``elastic`` is imported lazily by its
consumers — it pulls in the runtime/supervisor stack)."""

from .mesh import (batch_sharding, build_mesh, param_shardings,
                   replicated_sharding)
from .distributed import init_distributed, maybe_init_distributed
from .sequence import (attention_reference, ring_attention,
                       ulysses_attention)
