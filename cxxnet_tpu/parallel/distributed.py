"""Multi-host (DCN) initialization.

Replaces the reference's distributed parameter-server deployment
(``param_server = dist`` + ps-lite launcher, ``src/nnet/nnet_ps_server.cpp``)
with ``jax.distributed``: every host runs the same trainer; the global mesh
spans all hosts' devices; gradients ride ICI within a slice and DCN across
hosts through the same XLA collectives.  The reference's env contract is
kept: ``PS_RANK`` (worker rank) and ``dist_num_worker`` map onto
process_id/num_processes, and the data pipeline shards input per worker
exactly as ``iter_thread_imbin-inl.hpp:189-220`` did.

Hardened surface (doc/fault_tolerance.md "Multi-host recovery"):

* misconfiguration is a typed ``faults.DistInitError`` (rank out of
  range, bad worker count) instead of a silently wrong world,
* a coordinator that is slow to come up is a **retry**, not a hang:
  ``initialize`` runs under a ``faults.RetryPolicy`` with a bounded
  per-attempt ``initialization_timeout``,
* :func:`init_distributed` may be called again with ``fresh=True`` to
  tear down and rebuild the world — the per-generation re-init the
  elastic runtime (``parallel/elastic.py``) performs after a membership
  change on a real fleet.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

from ..runtime import faults
from ..utils.config import cfg_get, cfg_get_int

#: initialize() can stall indefinitely on a half-up coordinator; each
#: attempt gets this bound and the retry policy owns the patience
DEFAULT_INIT_TIMEOUT = 60.0

#: jax.distributed.initialize raises RuntimeError flavors on connect
#: trouble, not OSError — the init policy retries both
DIST_INIT_RETRY = faults.RetryPolicy(
    retry_on=(OSError, TimeoutError, RuntimeError))


def init_distributed(coordinator: str, nproc: int, rank: int,
                     timeout: float = DEFAULT_INIT_TIMEOUT,
                     retry: Optional[faults.RetryPolicy] = None,
                     fresh: bool = False) -> None:
    """Join (or, with ``fresh=True``, re-join) a ``jax.distributed``
    world, with typed validation and a retried, time-bounded connect.

    ``fresh=True`` shuts down any live world first — the elastic
    runtime's rejoin path: after a membership change every survivor
    rebuilds the world for the new generation instead of wedging on the
    dead one."""
    if nproc < 1:
        raise faults.DistInitError(
            f'distributed world needs at least 1 process, got {nproc}')
    if not 0 <= rank < nproc:
        raise faults.DistInitError(
            f'worker rank {rank} out of range for a {nproc}-process '
            'world (check PS_RANK / dist_worker_rank vs '
            'CXXNET_NUM_WORKER / dist_num_worker)')
    import jax
    retry = DIST_INIT_RETRY if retry is None else retry

    def attempt():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator, num_processes=nproc,
                process_id=rank, initialization_timeout=int(timeout))
        except RuntimeError:
            # a failed/stale half-initialized client must be torn down
            # before the retry, or every later attempt fails on
            # "already initialized"
            try:
                jax.distributed.shutdown()
            except RuntimeError:
                pass
            raise

    if fresh:
        try:
            jax.distributed.shutdown()
        except RuntimeError:
            pass                     # no live world: nothing to tear down
    try:
        retry.call(attempt, op_name='jax_distributed_init')
    except faults.RetryError as e:
        raise faults.DistInitError(
            f'jax.distributed world ({coordinator}, rank {rank}/'
            f'{nproc}) failed to initialize: {e}') from e


def maybe_init_distributed(cfg_pairs) -> bool:
    """Initialize jax.distributed when the config/environment asks for it.

    Triggers on ``param_server = dist`` (reference spelling) or the
    presence of standard cluster env vars.  Returns True if distributed
    mode was initialized.
    """
    want = cfg_get(cfg_pairs, 'param_server') == 'dist'
    coord = os.environ.get('CXXNET_COORDINATOR',
                           os.environ.get('COORDINATOR_ADDRESS'))
    if not want and coord is None:
        return False
    env_nproc = os.environ.get('CXXNET_NUM_WORKER')
    nproc = (int(env_nproc) if env_nproc
             else cfg_get_int(cfg_pairs, 'dist_num_worker', 1))
    env_rank = os.environ.get('PS_RANK')
    rank = (int(env_rank) if env_rank
            else cfg_get_int(cfg_pairs, 'dist_worker_rank', 0))
    if nproc <= 1:
        if coord is not None:
            # a coordinator address with a 1-process world is almost
            # always a mis-set CXXNET_NUM_WORKER — say so instead of
            # silently training solo
            print('distributed: coordinator address set but '
                  f'num_workers={nproc} — running single-process '
                  '(set CXXNET_NUM_WORKER / dist_num_worker)',
                  file=sys.stderr, flush=True)
        return False
    if coord is None:
        raise faults.DistInitError(
            'param_server=dist needs a coordinator address '
            '(CXXNET_COORDINATOR / COORDINATOR_ADDRESS)')
    timeout = float(os.environ.get('CXXNET_DIST_INIT_TIMEOUT',
                                   str(DEFAULT_INIT_TIMEOUT)))
    init_distributed(coord, nproc, rank, timeout=timeout)
    return True
