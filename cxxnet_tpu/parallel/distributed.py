"""Multi-host (DCN) initialization.

Replaces the reference's distributed parameter-server deployment
(``param_server = dist`` + ps-lite launcher, ``src/nnet/nnet_ps_server.cpp``)
with ``jax.distributed``: every host runs the same trainer; the global mesh
spans all hosts' devices; gradients ride ICI within a slice and DCN across
hosts through the same XLA collectives.  The reference's env contract is
kept: ``PS_RANK`` (worker rank) and ``dist_num_worker`` map onto
process_id/num_processes, and the data pipeline shards input per worker
exactly as ``iter_thread_imbin-inl.hpp:189-220`` did.
"""

from __future__ import annotations

import os


def maybe_init_distributed(cfg_pairs) -> bool:
    """Initialize jax.distributed when the config/environment asks for it.

    Triggers on ``param_server = dist`` (reference spelling) or the
    presence of standard cluster env vars.  Returns True if distributed
    mode was initialized.
    """
    want = any(k == 'param_server' and v == 'dist' for k, v in cfg_pairs)
    coord = os.environ.get('CXXNET_COORDINATOR',
                           os.environ.get('COORDINATOR_ADDRESS'))
    if not want and coord is None:
        return False
    import jax
    nproc = int(os.environ.get('CXXNET_NUM_WORKER',
                               _cfg_get(cfg_pairs, 'dist_num_worker', '1')))
    rank = int(os.environ.get('PS_RANK',
                              _cfg_get(cfg_pairs, 'dist_worker_rank', '0')))
    if nproc <= 1:
        return False
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=rank)
    return True


def _cfg_get(cfg_pairs, name, default):
    val = default
    for k, v in cfg_pairs:
        if k == name:
            val = v
    return val
